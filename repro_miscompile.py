"""Standalone repro for the axon-TPU XLA miscompile on large protocol
graphs (DEVELOP.md "Known issue").

Builds the LOWERED secure-softmax computation (~10k host-level integer
ops over ring128) and executes it twice from IDENTICAL PRF keys (the
lowered graph is fully deterministic given its runtime key inputs —
every seed-derivation nonce is a baked graph attribute):

  1. eagerly, op by op (the exact reference — per-op XLA programs are
     measured correct at every size), and
  2. as jitted XLA program(s) of ``--segment`` ops each (0 = the whole
     graph as ONE program),

and reports the max |difference| per segment.  The two paths compute the
same integer math from the same randomness, so ANY difference is a
backend miscompile, not protocol noise.

Expected results:
  - CPU backend: PASS at every segment size.
  - axon TPU backend: FAIL for large programs (historically: one
    ~500-op window inside exp's b2a/polynomial region diverges with
    err ~5e13; 50-op segments all pass; returning every intermediate as
    an output also passes — an output-set-sensitive whole-program bug,
    not a kernel bug).

Usage:
  python repro_miscompile.py                  # whole graph, equal keys
  python repro_miscompile.py --segment 500    # bisect: per-segment diff
  python repro_miscompile.py --keys random    # value-dependence probe
  python repro_miscompile.py --platform cpu   # control run
  python repro_miscompile.py --xla-bisect     # XLA-flag sweep over the
                                              # stacked fx_sigmoid repro
  python repro_miscompile.py --sigmoid-probe  # one jit-vs-eager sigmoid
                                              # check under current env

Exit code 0 = paths agree (bug not reproduced), 1 = divergence.

``--xla-bisect`` (VERDICT r5 Weak #3) targets the sharpest known
reproducer — a single jitted ``spmd_math.fx_sigmoid`` at fixed(24,40)
diverges from its own eager execution on the axon TPU backend — and
sweeps ``--xla_disable_hlo_passes`` / fusion / scheduler toggles
hunting a flag set under which it compiles correctly.  XLA reads
``XLA_FLAGS`` once at backend init, so every configuration probes in a
fresh subprocess (``--sigmoid-probe``).  The baseline probe also dumps
the program's HLO (``--dump-hlo``) — with the sweep summary, that file
IS the sharpened upstream repro when no flag set helps.  Outcomes are
recorded in DEVELOP.md ("Known issue" section).
"""

import argparse
import os
import subprocess
import sys

import numpy as np

# XLA_FLAGS configurations the bisect sweeps, coarsest lever first.
# All use --xla_disable_hlo_passes (present on every backend; unknown
# pass NAMES in the list are ignored, unknown FLAGS would abort), so
# one sweep runs identically on cpu (control) and tpu (the target).
XLA_BISECT_CONFIGS = (
    ("baseline", ""),
    ("no-fusion", "--xla_disable_hlo_passes=fusion"),
    (
        "no-fusion-family",
        "--xla_disable_hlo_passes=fusion,fusion_merger,"
        "multi_output_fusion,horizontal_loop_fusion,"
        "horizontal_input_fusion",
    ),
    ("no-algsimp", "--xla_disable_hlo_passes=algsimp"),
    (
        "no-scheduler",
        "--xla_disable_hlo_passes=latency-hiding-scheduler,"
        "rematerialization",
    ),
    (
        "no-fusion-no-scheduler",
        "--xla_disable_hlo_passes=fusion,fusion_merger,"
        "multi_output_fusion,latency-hiding-scheduler",
    ),
)


def sigmoid_probe(precision, batch: int, dump_hlo=None,
                  pallas: bool = False) -> int:
    """One jit-vs-eager comparison of the stacked protocol sigmoid
    under the CURRENT process environment (XLA_FLAGS already applied).
    The computation is deterministic given the fixed master key, so any
    difference is a miscompile.  Returns the exit code.

    ``pallas=True`` forces the ring128 Pallas kernels on (ISSUE 9): the
    hot primitives become opaque Mosaic programs XLA cannot re-fuse, so
    this probe doubles as the regression guard that the kernel path is
    bit-exact under whole-graph jit — the sidestep for the very
    miscompile this file reproduces."""
    import moose_tpu  # noqa: F401  (x64 + plugin setup)
    import jax

    from moose_tpu.parallel import spmd
    from moose_tpu.parallel import spmd_math as sm

    if pallas:
        from moose_tpu.native import ring128_kernels

        ring128_kernels.set_enabled(True)

    integ, frac = precision
    # Goldschmidt division inside the protocol sigmoid needs
    # 2*(integ+frac) <= ring width (same rule as bench.py's gate)
    width = 64 if 2 * (integ + frac) <= 64 else 128
    rng = np.random.default_rng(0)
    x = rng.normal(size=(batch, 4)) * 2.0
    mk = np.arange(4, dtype=np.uint32) + 21

    def forward(master_key, x_f):
        sess = spmd.SpmdSession(master_key)
        xs = spmd.fx_encode_share(sess, x_f, integ, frac, width)
        return spmd.fx_reveal_decode(sm.fx_sigmoid(sess, xs))

    print(f"backend: {jax.default_backend()}  fixed({integ},{frac}) "
          f"ring{width}  XLA_FLAGS={os.environ.get('XLA_FLAGS', '')!r}",
          flush=True)
    if pallas:
        from moose_tpu.native import ring128_kernels

        print(f"pallas kernels: {ring128_kernels.report()}", flush=True)
    eager = np.asarray(forward(mk, x))
    jfn = jax.jit(forward)
    if dump_hlo:
        with open(dump_hlo, "w") as fh:
            fh.write(jfn.lower(mk, x).as_text())
        print(f"HLO written to {dump_hlo}")
    jitted = np.asarray(jfn(mk, x))
    if pallas:
        # guard against a vacuous pass: if every kernel fell back, this
        # probe re-tested the plain XLA path and proves nothing about
        # the Pallas route it exists to guard
        from moose_tpu.native import ring128_kernels

        verdicts = ring128_kernels.report()["kernels"]
        bad = {k: v for k, v in verdicts.items() if v != "ok"}
        if not verdicts or bad:
            print(
                "FAIL: --pallas requested but the kernel path did not "
                f"run cleanly: {bad or 'no kernel dispatched'}"
            )
            return 1
    if np.array_equal(eager, jitted):
        print("PASS: jitted fx_sigmoid bit-identical to eager")
        return 0
    err = float(np.abs(eager - jitted).max())
    print(f"FAIL: jitted fx_sigmoid diverges, max|diff|={err:.3e}")
    return 1


def xla_bisect(precision, batch: int, platform=None) -> int:
    """Sweep XLA_BISECT_CONFIGS over the fx_sigmoid repro in fresh
    subprocesses; print a verdict table and return 0 when either the
    bug does not reproduce (control backend) or a working flag set was
    found, 1 when every configuration diverges (the dumped HLO + this
    table are the upstream repro)."""
    integ, frac = precision
    hlo_path = os.path.abspath(f"fx_sigmoid_fixed{integ}_{frac}.hlo.txt")
    results = []
    for name, flags in XLA_BISECT_CONFIGS:
        env = dict(os.environ)
        base = env.get("XLA_FLAGS", "")
        env["XLA_FLAGS"] = f"{base} {flags}".strip()
        cmd = [
            sys.executable, os.path.abspath(__file__),
            "--sigmoid-probe", "--precision", f"{integ},{frac}",
            "--batch", str(batch),
        ]
        if platform:
            cmd += ["--platform", platform]
        if name == "baseline":
            cmd += ["--dump-hlo", hlo_path]
        print(f"--- {name}: XLA_FLAGS={env['XLA_FLAGS']!r}", flush=True)
        try:
            proc = subprocess.run(
                cmd, env=env, timeout=900, capture_output=True, text=True,
            )
            ok = proc.returncode == 0
            tail = (proc.stdout or proc.stderr).strip().splitlines()
            print("    " + (tail[-1] if tail else "(no output)"))
        except subprocess.TimeoutExpired:
            ok = False
            print("    TIMEOUT (counted as FAIL)")
        results.append((name, ok))

    print("\n=== xla-bisect summary ===")
    for name, ok in results:
        print(f"  {'PASS' if ok else 'FAIL':4}  {name}")
    baseline_ok = results[0][1]
    fixes = [n for n, ok in results[1:] if ok]
    if baseline_ok:
        print("\nbaseline PASSES: the miscompile does not reproduce on "
              "this backend (control run)")
        return 0
    if fixes:
        print(f"\nWORKING FLAG SET(S): {', '.join(fixes)} — record in "
              "DEVELOP.md and consider pinning for worker deployments")
        return 0
    print(f"\nNO flag set fixes the divergence: {hlo_path} plus this "
          "table is the sharpened upstream repro")
    return 1


def build_lowered_softmax(arguments, classes=4, precision=(24, 40)):
    import moose_tpu as pm
    from moose_tpu.compilation import DEFAULT_PASSES, compile_computation
    from moose_tpu.compilation.lowering import arg_specs_from_arguments
    from moose_tpu.edsl import tracer

    alice = pm.host_placement("alice")
    bob = pm.host_placement("bob")
    carole = pm.host_placement("carole")
    rep = pm.replicated_placement("rep", players=[alice, bob, carole])

    @pm.computation
    def comp(x: pm.Argument(placement=alice, dtype=pm.float64)):
        with alice:
            xf = pm.cast(x, dtype=pm.fixed(*precision))
        with rep:
            y = pm.softmax(xf, axis=1, upmost_index=classes)
        with carole:
            out = pm.cast(y, dtype=pm.float64)
        return out

    # local execution: keep the graph unnetworked (no Send/Recv pairs)
    passes = [p for p in DEFAULT_PASSES if p != "networking"]
    return compile_computation(
        tracer.trace(comp), passes,
        arg_specs=arg_specs_from_arguments(arguments),
    )


def main():
    parser = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("--segment", type=int, default=0,
                        help="ops per jitted segment (0 = one program)")
    parser.add_argument("--keys", choices=["equal", "random"],
                        default="equal",
                        help="equal = deterministic repro keys; random = "
                        "fresh keys (failure is value-dependent)")
    parser.add_argument("--platform", default=None,
                        help="force a JAX platform (e.g. cpu) before init")
    parser.add_argument("--batch", type=int, default=4)
    parser.add_argument("--classes", type=int, default=4,
                        help="softmax width (fewer classes = smaller "
                        "graph; CI uses 2 as a reduced regression guard)")
    parser.add_argument("--precision", default="24,40",
                        help="fixed-point 'i,f' — e.g. 8,17 selects the "
                        "64-bit ring for a much smaller lowered graph")
    parser.add_argument("--xla-bisect", action="store_true",
                        help="sweep XLA pass-disable flag sets over the "
                        "jitted fx_sigmoid repro (fresh subprocess per "
                        "config; XLA_FLAGS is read once at init)")
    parser.add_argument("--sigmoid-probe", action="store_true",
                        help="one jit-vs-eager fx_sigmoid check under "
                        "the current XLA_FLAGS (the bisect child mode)")
    parser.add_argument("--dump-hlo", default=None, metavar="PATH",
                        help="with --sigmoid-probe: write the jitted "
                        "program's HLO text to PATH")
    parser.add_argument("--pallas", action="store_true",
                        help="with --sigmoid-probe: force the ring128 "
                        "Pallas kernels on (MOOSE_TPU_PALLAS override) "
                        "— the regression guard for the kernel "
                        "sidestep of this miscompile")
    args = parser.parse_args()
    integ, frac = (int(p) for p in args.precision.split(","))

    if args.platform and (args.sigmoid_probe or args.xla_bisect):
        os.environ["JAX_PLATFORMS"] = args.platform
    if args.sigmoid_probe:
        return sigmoid_probe(
            (integ, frac), args.batch, args.dump_hlo, pallas=args.pallas
        )
    if args.xla_bisect:
        return xla_bisect((integ, frac), args.batch, args.platform)

    import moose_tpu  # noqa: F401  (x64 + plugin setup)
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    print(f"backend: {jax.default_backend()}")

    from moose_tpu.execution import physical
    from moose_tpu.execution.interpreter import plan_segments

    rng = np.random.default_rng(0)
    x = rng.normal(size=(args.batch, args.classes)) * 2.0
    arguments = {"x": x}
    comp = build_lowered_softmax(
        arguments, classes=args.classes, precision=(integ, frac)
    )

    plan = physical._build_plan(comp, arguments, False)
    order, key_ops, dyn_names, static_env, _ = plan
    n_ops = len(order)
    limit = args.segment if args.segment > 0 else n_ops + 1
    recv_src = physical._recv_sources(comp, order)

    def effective_inputs(n):
        op = comp.operations[n]
        if op.kind == "Receive":
            return [recv_src[op.name]]
        return op.inputs

    chunks, in_names, out_names = plan_segments(
        order, static_env, effective_inputs, limit
    )
    print(f"{n_ops} ops, {len(chunks)} segment(s) of <= {limit}")

    # identical PRF keys for both paths (this is the determinism pin the
    # localization used: the lowered graph has no other entropy source)
    if args.keys == "equal":
        keys = {
            n: np.zeros(4, dtype=np.uint32) + 7 for n in key_ops
        }
    else:
        keys = {n: physical._fresh_key_words() for n in key_ops}
    dyn_all = {n: np.asarray(arguments[n]) for n in dyn_names}
    dyn_set = set(dyn_names)
    key_set = set(key_ops)

    from moose_tpu.execution.session import EagerSession

    def seg_callable(si, names):
        outs = list(out_names[si])

        def seg(ks, dyn, env_in):
            sess = EagerSession()
            env = dict(static_env)
            env.update(env_in)
            outputs, saves = {}, {}
            physical._run_physical_ops(
                sess, comp, names, static_env, env, outputs, saves,
                ks, dyn, recv_src,
            )
            return {n: env[n] for n in outs}, outputs

        return seg

    divergent = []
    env = {}  # lockstep: both paths continue from the REFERENCE values
    for si, names in enumerate(chunks):
        seg = seg_callable(si, names)
        import jax as _jax

        seg_jit = _jax.jit(seg)
        ks_i = {n: keys[n] for n in names if n in key_set}
        dyn_i = {n: dyn_all[n] for n in names if n in dyn_set}
        env_in = {n: env[n] for n in in_names[si]}

        ref_env, ref_out = seg(ks_i, dyn_i, env_in)
        jit_env, jit_out = seg_jit(ks_i, dyn_i, env_in)

        worst = 0.0
        for tree_a, tree_b in ((ref_env, jit_env), (ref_out, jit_out)):
            la = _jax.tree_util.tree_leaves(tree_a)
            lb = _jax.tree_util.tree_leaves(tree_b)
            for a, b in zip(la, lb):
                a = np.asarray(a)
                b = np.asarray(b)
                if not np.array_equal(a, b):
                    d = np.abs(
                        a.astype(np.float64) - b.astype(np.float64)
                    ).max()
                    worst = max(worst, float(d))
        status = "OK " if worst == 0.0 else "DIVERGED"
        lo_idx = sum(len(c) for c in chunks[:si])
        print(
            f"segment {si:4d} ops[{lo_idx}:{lo_idx + len(names)}]"
            f" ({names[0]}..{names[-1]}): {status}"
            + (f" max|diff|={worst:.3e}" if worst else ""),
            flush=True,
        )
        if worst:
            divergent.append((si, worst))
        env.update(ref_env)

    if divergent:
        print(f"\nFAIL: {len(divergent)} divergent segment(s): "
              + ", ".join(f"#{si} (|diff|~{d:.1e})" for si, d in divergent))
        return 1
    print("\nPASS: jitted path bit-identical to eager reference")
    return 0


if __name__ == "__main__":
    sys.exit(main())
