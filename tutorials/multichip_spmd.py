"""Use case: scaling the 3-party protocol over a TPU device mesh.

The TPU-native execution layout this framework adds beyond the
reference: instead of three worker processes exchanging shares over gRPC
(`/root/reference/moose/src/choreography/`), a single-controller XLA
program runs all three parties as a ``parties`` axis of a
``jax.sharding.Mesh``, with resharing lowered to ``collective-permute``
over ICI links and the batch dimension data-parallel over the remaining
devices.  The protocol math is identical — the network is the mesh.

What this demonstrates, end to end:

1. party-stacked sharings (``spmd.SpmdRep``: one array, leading axes
   ``(party=3, slot=2)``) and the fixed-point layer on top;
2. building a ``(parties, data)`` mesh and constraining shares to it;
3. a secure logistic-regression training step AND a secure softmax
   (bit-decomposition comparisons, Goldschmidt division — the nonlinear
   protocol library of ``parallel/spmd_math.py``) jitted over the mesh;
4. inspecting the compiled HLO to verify the collective mix: party
   exchanges ride ``collective-permute`` (neighbor hops), sharded
   contractions reduce with ``all-reduce``, and nothing degenerates to
   ``all-to-all``.

Run on any machine (8 virtual CPU devices stand in for a TPU slice):

    python tutorials/multichip_spmd.py

On a real v5e-8 the same code runs unchanged: 6 of the 8 chips form a
(3, 2) mesh — each party owns two chips, shares never co-reside.
"""

import os
import pathlib as _pathlib
import sys as _sys

_sys.path.insert(0, str(_pathlib.Path(__file__).resolve().parents[1]))

N_DEVICES = 6

# the mesh must exist before jax initializes its backend
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={N_DEVICES}"
    ).strip()

import numpy as np

import moose_tpu  # noqa: F401  (x64 + dialect registration)
import jax
import jax.numpy as jnp

jax.config.update("jax_platforms", "cpu")  # tutorial: virtual devices

from moose_tpu.parallel import spmd
from moose_tpu.parallel import spmd_math as sm

I, F, W = 14, 23, 128


def main():
    # ---- 1. a (parties=3, data=2) mesh over 6 devices -----------------
    mesh = spmd.make_mesh(N_DEVICES)
    print(f"mesh: {dict(zip(mesh.axis_names, mesh.devices.shape))}")

    rng = np.random.default_rng(0)
    batch = 8 * mesh.devices.shape[1]
    x = rng.normal(size=(batch, 16)) * 0.4
    true_w = rng.normal(size=(16, 1))
    y = (x @ true_w > 0).astype(np.float64)
    w0 = np.zeros((16, 1))
    mk = np.frombuffer(b"tutorial-masterk", dtype=np.uint32)

    # ---- 2+3. secure training step + softmax, jitted over the mesh ----
    def train_and_infer(master_key, x_f, y_f, w_f):
        sess = spmd.SpmdSession(master_key)
        xs = spmd.fx_encode_share(sess, x_f, I, F, W)
        ys = spmd.fx_encode_share(sess, y_f, I, F, W)
        ws = spmd.fx_encode_share(sess, w_f, I, F, W)
        # shares are CONSTRAINED onto the mesh: party axis -> 'parties',
        # batch axis -> 'data' (spmd.rep_sharding builds the spec)
        w1 = spmd.logreg_train_step(sess, xs, ys, ws, lr=0.5, mesh=mesh)
        logits = spmd.fx_dot(sess, xs, w1)
        two_col = sm.fx_softmax(
            sess,
            spmd.SpmdFixed(
                spmd.concat([logits.tensor, spmd.neg(logits.tensor)], 1),
                I, F,
            ),
            axis=1,
        )
        return spmd.fx_reveal_decode(w1), spmd.fx_reveal_decode(two_col)

    with mesh:
        compiled = jax.jit(train_and_infer).lower(mk, x, y, w0).compile()
        w1, probs = compiled(mk, x, y, w0)
    w1, probs = np.asarray(w1), np.asarray(probs)

    # the revealed results match the same step on plaintext floats
    z = x @ w0
    preds = 0.5 + 0.19828547 * z - 0.00446928 * z**3  # protocol sigmoid
    w_plain = w0 - 0.5 * x.T @ (preds - y) / batch
    assert np.abs(w1 - w_plain).max() < 1e-3, "training step diverged"
    corr = np.corrcoef(w1.ravel(), true_w.ravel())[0, 1]
    print(f"one secure SGD step: max |Δw vs plaintext| = "
          f"{np.abs(w1 - w_plain).max():.2e}, corr(w, w_true) = {corr:.2f}")

    logits1 = x @ w1
    want = np.asarray(
        jax.nn.softmax(np.concatenate([logits1, -logits1], 1), axis=1)
    )
    print(f"secure softmax: max err vs plaintext = "
          f"{np.abs(probs - want).max():.2e}")
    assert np.abs(probs - want).max() < 5e-2

    # ---- 4. the collective mix is the proof of the layout -------------
    hlo = (
        "\n".join(
            m.to_string() for m in compiled.runtime_executable().hlo_modules()
        )
        if hasattr(compiled, "runtime_executable")
        else compiled.as_text()
    )
    counts = {
        name: hlo.count(name)
        for name in (
            "collective-permute", "all-reduce", "all-gather", "all-to-all"
        )
    }
    print(f"collective mix: {counts}")
    assert counts["collective-permute"] > 0, "party axis must neighbor-hop"
    assert counts["all-to-all"] == 0, "layout regressed to all-to-all"
    print("multichip SPMD tutorial OK")


if __name__ == "__main__":
    main()
