"""Use case: encrypted machine-learning inference from an ONNX model.

Executable-doc port of the reference tutorial
``/root/reference/tutorials/ml-inference-with-onnx.ipynb``: a healthcare
AI startup trained a diagnosis model; a hospital wants predictions on
patient data that is too sensitive to share.  The model is exported to
ONNX, imported as a moose_tpu predictor, and evaluated under 3-party
replicated secret sharing: the hospital never sees the weights, the
startup never sees the patients.

The reference tutorial exports with skl2onnx/onnxmltools; this repo
ships its own sklearn->ONNX encoder
(``moose_tpu.predictors.sklearn_export``) so no extra dependencies are
needed — ``from_onnx`` also accepts any standard ONNX
LinearClassifier/TreeEnsemble/MLP proto produced by those tools.

    python tutorials/ml_inference_with_onnx.py
"""

import argparse

import pathlib as _pathlib
import sys as _sys

_sys.path.insert(0, str(_pathlib.Path(__file__).resolve().parents[1]))

import numpy as np

import moose_tpu as pm
from moose_tpu import predictors
from moose_tpu.runtime import LocalMooseRuntime


def train_model(n_samples=300, n_features=10, seed=14):
    """Train a logistic-regression 'heart disease' classifier (sklearn,
    exactly like the reference tutorial)."""
    from sklearn.linear_model import LogisticRegression
    from sklearn.model_selection import train_test_split

    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n_samples, n_features))
    w_true = rng.normal(size=(n_features,))
    y = (x @ w_true + 0.3 * rng.normal(size=n_samples) > 0).astype(int)
    x_train, x_test, y_train, _ = train_test_split(
        x, y, test_size=0.2, random_state=0
    )
    model = LogisticRegression().fit(x_train, y_train)
    return model, x_test


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--batch", type=int, default=8)
    args = parser.parse_args(argv)

    sk_model, x_test = train_model()
    x = x_test[: args.batch]

    # 1. Export the trained model to ONNX (the startup does this once).
    from moose_tpu.predictors import sklearn_export as ox

    onnx_proto = ox.logistic_regression_onnx(sk_model, x.shape[1])

    # 2. Import the ONNX model as a predictor: this builds the
    #    @pm.computation that loads the input on one host, secret-shares
    #    it, runs dot + sigmoid ON SHARES, and reveals only the scores.
    predictor = predictors.from_onnx(onnx_proto)
    print(f"predictor: {type(predictor).__name__}")
    comp = predictor.predictor_factory()

    # 3. Evaluate under the local runtime (one process simulating the
    #    three parties; swap in GrpcMooseRuntime for real deployment —
    #    see scientific_computing_multiple_players.py --grpc).
    runtime = LocalMooseRuntime(["alice", "bob", "carole"])
    outputs = runtime.evaluate_computation(
        comp, arguments={"x": x.astype(np.float64)}
    )
    (scores,) = outputs.values()
    scores = np.asarray(scores)

    expected = sk_model.predict_proba(x)
    print("encrypted scores[:3]:", np.round(scores[:3], 5).tolist())
    print("sklearn  scores[:3]:", np.round(expected[:3], 5).tolist())
    np.testing.assert_allclose(scores, expected, atol=1e-2)
    print("OK — encrypted inference matches sklearn")
    return scores


if __name__ == "__main__":
    main()
