"""Use case: scientific computing with multiple players.

Executable-doc port of the reference tutorial
``/root/reference/tutorials/scientific-computing-multiple-players.ipynb``:
two government departments each hold a private column of data (alcohol
consumption, student grades); a data scientist wants the Pearson
correlation between them WITHOUT any party revealing its column.  The
whole statistic — means, centered products, the variance product, its
square root, and the final division — is computed on secret-shared
values under 3-party replicated secret sharing; only the single
correlation coefficient is revealed.

Run locally (one process simulating all parties):

    python tutorials/scientific_computing_multiple_players.py

Run across three real worker processes over gRPC (the reference's comet
deployment; workers are spawned for you):

    python tutorials/scientific_computing_multiple_players.py --grpc
"""

import argparse

import pathlib as _pathlib
import sys as _sys

_sys.path.insert(0, str(_pathlib.Path(__file__).resolve().parents[1]))

import numpy as np

import moose_tpu as pm

FIXED = pm.fixed(24, 40)

# One placement per real-world party.  The replicated placement is the
# "virtual encrypted machine" spanned by the three of them: values that
# move onto it are secret-shared, computation on it runs on shares.
pub_health_dpt = pm.host_placement(name="pub_health_dpt")
education_dpt = pm.host_placement(name="education_dpt")
data_scientist = pm.host_placement(name="data_scientist")
encrypted_government = pm.replicated_placement(
    name="encrypted_government",
    players=[pub_health_dpt, education_dpt, data_scientist],
)


def generate_synthetic_correlated_data(n_samples):
    """Synthetic (alcohol, grades) columns with a known anticorrelation
    (same construction as the reference tutorial)."""
    mu = np.array([10.0, 0.0])
    r = np.array([[3.40, -2.75], [-2.75, 5.50]])
    rng = np.random.default_rng(12)
    x = rng.multivariate_normal(mu, r, size=n_samples)
    return x[:, 0:1], x[:, 1:2]


def pearson_correlation_coefficient(x, y):
    """corr = sum((x-mx)(y-my)) / sqrt(sum((x-mx)^2) * sum((y-my)^2)),
    every op below runs on secret shares (sqrt is the secure
    2^(log2/2) protocol, div the Goldschmidt protocol)."""
    x_mean = pm.mean(x, 0)
    y_mean = pm.mean(y, 0)
    stdv_x = pm.sum(pm.square(pm.sub(x, x_mean)))
    stdv_y = pm.sum(pm.square(pm.sub(y, y_mean)))
    corr_num = pm.sum(pm.mul(pm.sub(x, x_mean), pm.sub(y, y_mean)))
    corr_denom = pm.sqrt(pm.mul(stdv_x, stdv_y))
    return pm.div(corr_num, corr_denom)


@pm.computation
def multiparty_correlation():
    # Each department loads ITS OWN data from ITS OWN storage, in
    # plaintext, then casts to the fixed-point encoding the protocol
    # computes over.
    with pub_health_dpt:
        alcohol = pm.load("alcohol_data", dtype=pm.float64)
        alcohol = pm.cast(alcohol, dtype=FIXED)

    with education_dpt:
        grades = pm.load("grades_data", dtype=pm.float64)
        grades = pm.cast(grades, dtype=FIXED)

    # Crossing from a host placement into the replicated placement
    # secret-shares the values; nothing in this block ever exists in
    # the clear on any single machine.
    with encrypted_government:
        correlation = pearson_correlation_coefficient(alcohol, grades)

    # Only the final scalar is revealed, and only to the data scientist.
    with data_scientist:
        correlation = pm.cast(correlation, dtype=pm.float64)
        correlation = pm.save("correlation", correlation)

    return correlation


def run_local(alcohol, grades):
    from moose_tpu.runtime import LocalMooseRuntime

    runtime = LocalMooseRuntime(
        identities=["pub_health_dpt", "education_dpt", "data_scientist"],
        storage_mapping={
            "pub_health_dpt": {"alcohol_data": alcohol},
            "education_dpt": {"grades_data": grades},
        },
    )
    runtime.set_default()
    multiparty_correlation()
    return np.asarray(
        runtime.read_value_from_storage("data_scientist", "correlation")
    )


def run_grpc(alcohol, grades, base_port=23500):
    """The same computation across three real worker processes over gRPC
    — the reference's `comet` deployment shape.  Workers are spawned
    here for convenience; in a real deployment each party runs its own.
    """
    import subprocess
    import sys

    sys.path.insert(0, "benchmarks")
    import distributed_grpc as dg

    dg.BASE_PORT = base_port
    procs, endpoints = dg.spawn_workers(base_port)
    try:
        from moose_tpu.runtime import GrpcMooseRuntime

        runtime = GrpcMooseRuntime(endpoints)
        runtime.set_default()
        # workers hold no storage here, so feed the columns as inputs
        alice, bob, carole = (
            pm.host_placement("alice"),
            pm.host_placement("bob"),
            pm.host_placement("carole"),
        )
        rep = pm.replicated_placement("rep", players=[alice, bob, carole])

        @pm.computation
        def corr_inputs(
            a: pm.Argument(placement=alice, dtype=pm.float64),
            g: pm.Argument(placement=bob, dtype=pm.float64),
        ):
            with alice:
                af = pm.cast(a, dtype=FIXED)
            with bob:
                gf = pm.cast(g, dtype=FIXED)
            with rep:
                c = pearson_correlation_coefficient(af, gf)
            with carole:
                out = pm.cast(c, dtype=pm.float64)
            return out

        outputs, _timings = runtime.evaluate_computation(
            corr_inputs, {"a": alcohol, "g": grades}
        )
        (val,) = outputs.values()
        return np.asarray(val)
    finally:
        dg._teardown(procs)


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--grpc", action="store_true",
                        help="run across 3 spawned gRPC workers")
    parser.add_argument("--samples", type=int, default=100)
    args = parser.parse_args(argv)

    alcohol, grades = generate_synthetic_correlated_data(args.samples)
    if args.grpc:
        moose_corr = run_grpc(alcohol, grades)
    else:
        moose_corr = run_local(alcohol, grades)

    np_corr = np.corrcoef(alcohol.ravel(), grades.ravel())[1, 0]
    print(f"Correlation with moose_tpu: {float(np.ravel(moose_corr)[0]):.6f}")
    print(f"Correlation with numpy:     {np_corr:.6f}")
    assert abs(float(np.ravel(moose_corr)[0]) - np_corr) < 1e-2
    print("OK — secure result matches the plaintext statistic")
    return float(np.ravel(moose_corr)[0])


if __name__ == "__main__":
    main()
