"""Advanced usage: interfacing Python computations with the CLI tools.

Executable-doc port of the reference tutorial
``/root/reference/tutorials/interfacing-moose-with-pymoose.ipynb``: a
``@pm.computation`` is traced, serialized, compiled by the elk compiler,
written out in the line-per-op TEXTUAL format (``.moose``), inspected
with ``elk stats``, and executed from the file by ``dasher`` (the
single-process all-roles simulator) — the workflow for driving the
runtime without Python in the loop.

    python tutorials/interfacing_textual_and_cli.py
"""

import argparse
import json
import pathlib
import subprocess
import sys
import tempfile

import pathlib as _pathlib
import sys as _sys

_sys.path.insert(0, str(_pathlib.Path(__file__).resolve().parents[1]))

import numpy as np

import moose_tpu as pm
from moose_tpu import elk_compiler, serde, textual
from moose_tpu.edsl import tracer

FIXED = pm.fixed(24, 40)

player0 = pm.host_placement("player0")
player1 = pm.host_placement("player1")
player2 = pm.host_placement("player2")
repl = pm.replicated_placement("replicated", players=[player0, player1, player2])


@pm.computation
def my_computation():
    # (Constants embedded like this are NOT secret — they live in the
    # graph in plaintext.  Pedagogical example, as in the reference.)
    with player0:
        x = pm.constant(np.array([1.0, 2.0, 3.0]), dtype=pm.float64)
        x = pm.cast(x, dtype=FIXED)
    with player1:
        y = pm.constant(np.array([4.0, 5.0, 6.0]), dtype=pm.float64)
        y = pm.cast(y, dtype=FIXED)
    with repl:
        z = pm.dot(x, y)
    with player2:
        out = pm.cast(z, dtype=pm.float64)
    return out


def comp_to_moose(abstract_comp, filepath):
    """Trace -> msgpack -> elk compile (no passes: keep it logical) ->
    textual form, written to ``filepath`` (mirrors the reference's
    ``comp_to_moose`` helper, which calls the Rust elk through
    ``pm.elk_compiler.compile_computation``)."""
    traced = tracer.trace(abstract_comp)
    comp_bin = serde.serialize_computation(traced)
    compiled_bin = elk_compiler.compile_computation(comp_bin, passes=[])
    comp = serde.deserialize_computation(compiled_bin)
    text = textual.to_textual(comp)
    pathlib.Path(filepath).write_text(text)
    return text


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.parse_args(argv)

    with tempfile.TemporaryDirectory() as tmp:
        moose_file = pathlib.Path(tmp) / "dotprod.moose"

        # 1. Python -> textual .moose file
        text = comp_to_moose(my_computation, moose_file)
        print("-- first 5 lines of the textual computation --")
        print("\n".join(text.splitlines()[:5]))

        import os

        repo_root = str(pathlib.Path(__file__).resolve().parents[1])
        env = {
            **os.environ,
            # PREPEND the repo root — replacing PYTHONPATH would drop
            # site hooks the environment may rely on (e.g. accelerator
            # plugin registration)
            "PYTHONPATH": os.pathsep.join(
                [repo_root, os.environ.get("PYTHONPATH", "")]
            ).rstrip(os.pathsep),
            # dasher runs real role-filtered workers, which (rightly)
            # refuse to derive share masks from the non-cryptographic
            # default PRF
            "MOOSE_TPU_PRF": "threefry",
        }

        # 2. Inspect with `elk stats` (op histogram)
        hist = subprocess.run(
            [sys.executable, "-m", "moose_tpu.bin.elk", "stats",
             "op_hist", str(moose_file)],
            capture_output=True, text=True, check=True, env=env,
        )
        print("-- elk stats op_hist --")
        print(hist.stdout.strip())

        # 3. Fully compile: lower the replicated ops to host ops and
        #    insert Send/Recv on cross-host edges (with no --passes, elk
        #    only converts formats — same contract as the reference elk)
        compiled_file = pathlib.Path(tmp) / "dotprod-compiled.moose"
        subprocess.run(
            [sys.executable, "-m", "moose_tpu.bin.elk", "compile",
             str(moose_file), "-o", str(compiled_file), "--passes",
             "typing,lowering,prune,networking,toposort"],
            check=True, env=env,
        )
        n_lowered = len(compiled_file.read_text().splitlines())
        print(f"compiled graph: {n_lowered} textual ops")
        assert n_lowered > 50, "lowering should expand the secure dot"

        # 4. Execute the FILE with dasher (all roles in one process)
        run = subprocess.run(
            [sys.executable, "-m", "moose_tpu.bin.dasher", str(moose_file)],
            capture_output=True, text=True, check=True, env=env,
        )
        print("-- dasher output --")
        print(run.stdout.strip())

        out_line = [
            ln for ln in run.stdout.splitlines() if "output" in ln
        ][-1]
        value = float(json.loads(out_line.split(":", 1)[1])
                      if out_line.strip().startswith("{")
                      else out_line.split()[-1])
        expected = float(np.dot([1.0, 2.0, 3.0], [4.0, 5.0, 6.0]))
        assert abs(value - expected) < 1e-3, (value, expected)
        print(f"OK — dasher computed {value} == {expected}")


if __name__ == "__main__":
    main()
