"""ONNX model import + encrypted inference through the predictor zoo
(reference pymoose/pymoose/predictors): train with sklearn, export to
ONNX, score under 3-party replicated sharing.

  python examples/onnx_predictor.py
"""

import numpy as np

import pathlib as _pathlib
import sys as _sys

_sys.path.insert(0, str(_pathlib.Path(__file__).resolve().parents[1]))

import moose_tpu as pm
from moose_tpu import predictors
from moose_tpu.predictors.sklearn_export import logistic_regression_onnx
from moose_tpu.runtime import LocalMooseRuntime


def main():
    from sklearn.linear_model import LogisticRegression

    rng = np.random.default_rng(2)
    x_train = rng.normal(size=(200, 20))
    y_train = (rng.uniform(size=200) > 0.5).astype(int)
    sk = LogisticRegression().fit(x_train, y_train)

    onnx_bytes = logistic_regression_onnx(sk, n_features=20).encode()
    model = predictors.from_onnx(onnx_bytes)
    comp = model.predictor_factory()

    runtime = LocalMooseRuntime(["alice", "bob", "carole"])
    x = rng.normal(size=(16, 20))
    (probs,) = runtime.evaluate_computation(
        comp, arguments={"x": x}
    ).values()
    gap = np.abs(probs - sk.predict_proba(x)).max()
    print(f"max |secure - sklearn| probability gap: {gap:.2e}")
    assert gap < 5e-3
    print("OK")


if __name__ == "__main__":
    main()
