"""Encrypted-input inference: the client AES-GCM-encrypts its features;
the 3 compute parties decrypt *under MPC* (the plaintext never exists on
any single machine) and score an ONNX model (reference AesWrapper,
pymoose/pymoose/predictors/predictor.py:49-85).

  python examples/aes_inference.py          # fused local simulation
  python examples/aes_inference.py --grpc   # 3 real worker processes:
      # the ciphertext lowers through the compile pipeline and the AES
      # circuit executes role-filtered over gRPC (slow: the decrypt
      # circuit is ~200k host ops walked eagerly per worker)
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import numpy as np

import moose_tpu as pm
from moose_tpu.dialects import aes
from moose_tpu.runtime import LocalMooseRuntime

alice = pm.host_placement("alice")
bob = pm.host_placement("bob")
carole = pm.host_placement("carole")
rep = pm.replicated_placement("rep", players=[alice, bob, carole])

FIXED = pm.fixed(14, 23)


@pm.computation
def secure_score(
    aes_data: pm.Argument(placement=alice,
                          vtype=pm.AesTensorType(dtype=FIXED)),
    aes_key: pm.Argument(placement=rep, vtype=pm.AesKeyType()),
    w: pm.Argument(placement=bob, dtype=pm.float64),
):
    with rep:
        x = pm.decrypt(aes_key, aes_data)  # AES-128 evaluated on shares
    with bob:
        wf = pm.cast(w, dtype=FIXED)
    with rep:
        score = pm.sigmoid(pm.dot(x, wf))
    with carole:
        out = pm.cast(score, dtype=pm.float64)
    return out


def main():
    rng = np.random.default_rng(1)
    grpc_mode = "--grpc" in sys.argv
    shape = (1, 2) if grpc_mode else (2, 4)
    features = rng.normal(size=shape)
    w = rng.normal(size=(shape[1], 1))

    # the data owner encrypts client-side with any AES-GCM implementation
    key = bytes(range(16))
    nonce = bytes([7] * 12)
    wire = aes.encrypt_fixed_array(key, nonce, features, frac_precision=23)
    arguments = {
        "aes_data": np.asarray(wire),
        "aes_key": np.asarray(aes.bytes_to_bits_be(key)),
        "w": w,
    }

    if grpc_mode:
        import os
        import pathlib

        sys.path.insert(0, str(
            pathlib.Path(__file__).resolve().parent.parent / "benchmarks"
        ))
        os.environ.setdefault("MOOSE_TPU_PRF", "threefry")
        from moose_tpu.dialects import ring

        ring.set_prf_impl("threefry")  # real share masks between workers
        from distributed_grpc import _teardown, spawn_workers

        from moose_tpu.runtime import GrpcMooseRuntime

        procs, endpoints = spawn_workers(base_port=22500)
        try:
            runtime = GrpcMooseRuntime(endpoints)
            outputs, timings = runtime.evaluate_computation(
                secure_score, arguments, timeout=900.0
            )
            (scores,) = outputs.values()
            print("per-role micros:", timings)
        finally:
            _teardown(procs)
    else:
        # party-stacked layout: the AES-GCM circuit evaluates as SpmdBits
        # banks and the whole decrypt+score program jits into one XLA
        # program (dialects/aes.py StackedBitOps) — seconds instead of
        # the per-host eager walk
        import time

        runtime = LocalMooseRuntime(
            ["alice", "bob", "carole"], layout="stacked", use_jit=True
        )
        t0 = time.perf_counter()
        (scores,) = runtime.evaluate_computation(
            secure_score, arguments
        ).values()
        t_first = time.perf_counter() - t0
        t0 = time.perf_counter()
        (scores,) = runtime.evaluate_computation(
            secure_score, arguments
        ).values()
        print(
            f"decrypt+score: first call {t_first:.1f}s (compile), "
            f"steady {time.perf_counter() - t0:.2f}s"
        )
    plain = 1 / (1 + np.exp(-(features @ w)))
    print("secure scores:   ", np.ravel(scores))
    print("plaintext scores:", np.ravel(plain))
    assert np.abs(scores - plain).max() < 5e-3
    print("OK")


if __name__ == "__main__":
    main()
