"""Encrypted-input inference: the client AES-GCM-encrypts its features;
the 3 compute parties decrypt *under MPC* (the plaintext never exists on
any single machine) and score an ONNX model (reference AesWrapper,
pymoose/pymoose/predictors/predictor.py:49-85).

  python examples/aes_inference.py
"""

import numpy as np

import moose_tpu as pm
from moose_tpu.dialects import aes
from moose_tpu.runtime import LocalMooseRuntime

alice = pm.host_placement("alice")
bob = pm.host_placement("bob")
carole = pm.host_placement("carole")
rep = pm.replicated_placement("rep", players=[alice, bob, carole])

FIXED = pm.fixed(14, 23)


@pm.computation
def secure_score(
    aes_data: pm.Argument(placement=alice,
                          vtype=pm.AesTensorType(dtype=FIXED)),
    aes_key: pm.Argument(placement=rep, vtype=pm.AesKeyType()),
    w: pm.Argument(placement=bob, dtype=pm.float64),
):
    with rep:
        x = pm.decrypt(aes_key, aes_data)  # AES-128 evaluated on shares
    with bob:
        wf = pm.cast(w, dtype=FIXED)
    with rep:
        score = pm.sigmoid(pm.dot(x, wf))
    with carole:
        out = pm.cast(score, dtype=pm.float64)
    return out


def main():
    rng = np.random.default_rng(1)
    features = rng.normal(size=(2, 4))
    w = rng.normal(size=(4, 1))

    # the data owner encrypts client-side with any AES-GCM implementation
    key = bytes(range(16))
    nonce = bytes([7] * 12)
    wire = aes.encrypt_fixed_array(key, nonce, features, frac_precision=23)

    runtime = LocalMooseRuntime(["alice", "bob", "carole"], use_jit=False)
    (scores,) = runtime.evaluate_computation(
        secure_score,
        arguments={
            "aes_data": wire,
            "aes_key": aes.bytes_to_bits_be(key),
            "w": w,
        },
    ).values()
    plain = 1 / (1 + np.exp(-(features @ w)))
    print("secure scores:   ", np.ravel(scores))
    print("plaintext scores:", np.ravel(plain))
    assert np.abs(scores - plain).max() < 5e-3
    print("OK")


if __name__ == "__main__":
    main()
