"""Encrypted ResNet-style inference (north-star config from
BASELINE.json: "ONNX MLP / small ResNet encrypted inference").

A miniature residual convnet (Conv+BN+Relu+MaxPool, a residual block,
GlobalAveragePool, Gemm head, Softmax) is imported from ONNX and
evaluated under 3-party replicated secret sharing: the inputs are
secret-shared, every conv runs as an exact ring convolution (im2col +
int8-MXU limb matmul), BatchNorm folds into public mirrored affine
constants, and only the final class probabilities are revealed.

Run:  python examples/resnet_inference.py

Note: the default whole-computation jit fuses the entire model into one
XLA program; the MaxPool tournament (secure compares over ring128 bit
decompositions) makes that graph large and slow to compile.  For quick
runs use MOOSE_TPU_JIT=0 (eager per-op execution), or prefer
AveragePool-only architectures for the fused path.
"""

import numpy as np

import pathlib as _pathlib
import sys as _sys

_sys.path.insert(0, str(_pathlib.Path(__file__).resolve().parents[1]))

import moose_tpu as pm
from moose_tpu import predictors
from moose_tpu.predictors.sklearn_export import resnet_block_onnx
from moose_tpu.runtime import LocalMooseRuntime


def main():
    model_proto, _ = resnet_block_onnx(seed=7, in_ch=3, mid_ch=4, size=8,
                                       n_classes=3)
    model = predictors.from_onnx(model_proto.encode())
    print(f"imported: {type(model).__name__}")

    comp = model.predictor_factory(fixedpoint_dtype=pm.fixed(24, 40))
    runtime = LocalMooseRuntime(["alice", "bob", "carole"])

    rng = np.random.default_rng(0)
    x = rng.normal(size=(4, 3, 8, 8)) * 0.5  # NCHW, like the ONNX export
    (probs,) = runtime.evaluate_computation(
        comp, arguments={"x": x}
    ).values()
    print("encrypted class probabilities:")
    print(np.round(probs, 4))
    print("rows sum to", np.round(probs.sum(axis=1), 4))


if __name__ == "__main__":
    main()
