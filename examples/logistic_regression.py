"""Encrypted logistic-regression: train on secret-shared data, then run
encrypted inference (the reference's flagship example,
pymoose/examples/logreg).

  python examples/logistic_regression.py
"""

import numpy as np

import pathlib as _pathlib
import sys as _sys

_sys.path.insert(0, str(_pathlib.Path(__file__).resolve().parents[1]))

import moose_tpu as pm
from moose_tpu.runtime import LocalMooseRuntime

alice = pm.host_placement("alice")
bob = pm.host_placement("bob")
carole = pm.host_placement("carole")
rep = pm.replicated_placement("rep", players=[alice, bob, carole])
mirr = pm.mirrored_placement("mirr", players=[alice, bob, carole])

FIXED = pm.fixed(24, 40)
N_FEATURES = 10
BATCH = 64
STEPS = 8
LR = 0.25


@pm.computation
def train(
    x: pm.Argument(placement=alice, dtype=pm.float64),
    y: pm.Argument(placement=alice, dtype=pm.float64),
):
    """alice holds the training data; the model is learned under MPC and
    revealed to bob."""
    with alice:
        xf = pm.cast(x, dtype=FIXED)
        yf = pm.cast(y, dtype=FIXED)

    with bob:
        w = pm.cast(
            pm.constant(np.zeros((N_FEATURES, 1)), dtype=pm.float64),
            dtype=FIXED,
        )
        lr = pm.cast(pm.constant(LR, dtype=pm.float64), dtype=FIXED)

    with mirr:
        inv_batch = pm.constant(1.0 / BATCH, dtype=FIXED)

    with rep:
        xs = pm.identity(xf)  # share once
        ys = pm.identity(yf)
        xT = pm.transpose(xs)
        for _ in range(STEPS):
            y_hat = pm.sigmoid(pm.dot(xs, w))
            grad = pm.mul(pm.dot(xT, y_hat - ys), inv_batch)
            w = w - grad * lr

    with bob:
        w_out = pm.cast(w, dtype=pm.float64)
    return w_out


@pm.computation
def predict(
    x: pm.Argument(placement=carole, dtype=pm.float64),
    w: pm.Argument(placement=bob, dtype=pm.float64),
):
    """carole's query is scored against bob's model without either party
    seeing the other's data."""
    with carole:
        xf = pm.cast(x, dtype=FIXED)
    with bob:
        wf = pm.cast(w, dtype=FIXED)
    with rep:
        score = pm.sigmoid(pm.dot(xf, wf))
    with carole:
        out = pm.cast(score, dtype=pm.float64)
    return out


def main():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(BATCH, N_FEATURES))
    true_w = rng.normal(size=(N_FEATURES, 1))
    y = (x @ true_w > 0).astype(np.float64)

    # eager execution: the unrolled training loop is a large graph and
    # per-op execution starts instantly (use_jit=True amortizes the
    # XLA compile when a computation is evaluated repeatedly)
    runtime = LocalMooseRuntime(["alice", "bob", "carole"], use_jit=False)
    (w_fit,) = runtime.evaluate_computation(
        train, arguments={"x": x, "y": y}
    ).values()
    corr = np.corrcoef(np.ravel(w_fit), np.ravel(true_w))[0, 1]
    print(f"weight correlation with generator: {corr:.3f}")

    x_test = rng.normal(size=(8, N_FEATURES))
    (scores,) = runtime.evaluate_computation(
        predict, arguments={"x": x_test, "w": np.asarray(w_fit)}
    ).values()
    plain = 1 / (1 + np.exp(-(x_test @ np.asarray(w_fit))))
    print("max |secure - plaintext| score gap:",
          float(np.abs(scores - plain).max()))


if __name__ == "__main__":
    main()
