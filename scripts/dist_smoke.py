"""CI distributed throughput smoke (ISSUE 5 acceptance): 3 local comet
workers over TCP (in-process WorkerServers on 127.0.0.1 gRPC ports, the
same server class the comet daemon runs) execute logreg inference
through the client supervisor with the compiled worker fast path ON.

Asserts:

1. every worker reaches a **segmented/full-jit plan mode** on the clean
   graph with ZERO eager pinning (a pin here means a jit candidate
   diverged from its eager reference on CPU — a real regression);
2. a **repeat session performs zero validating evaluations** — the
   worker-side plan cache (weak-keyed on (computation, role), memoized
   by computation bytes) serves the resolved plan warm;
3. the distributed outputs **match the in-process path**
   (LocalMooseRuntime over the identical traced computation) and
   sklearn's own predict_proba;
4. (ISSUE 6 observability) with OTLP configured, one session exports
   **one stitched trace id** shared by the client spans and every
   worker's execute_role span; each worker's HTTP metrics port serves
   **non-empty Prometheus text** carrying worker-plan and networking
   counters; and a chaos-killed session's report attaches the killed
   party's **flight-recorder events** (plus retry/chaos counters on
   /metrics);
5. (ISSUE 7 static analysis) **predicted-vs-measured**: the static cost
   model's per-party tx/rx byte and ``send_many`` envelope/payload
   predictions for one warm session equal the metrics-registry counter
   deltas EXACTLY — the analyzer can never silently drift from the
   runtime; and a **deliberately deadlocking segmented plan** is
   rejected at ``worker_plan.get_plan`` time with an MSA5xx diagnostic
   (flight ``plan_rejected`` event, legacy-eager fallback, typed
   failure in seconds instead of a hang).
6. (ISSUE 12 observability) **profile smoke**: one profiled warm
   3-worker session emits a loadable Perfetto/Chrome-trace JSON whose
   named phases cover >=95% of the measured session wall time and
   stitch to ONE session trace id, with the distributed phase taxonomy
   (``execute_role`` / ``worker_segment`` / ``net_send`` /
   ``net_receive`` / ``serde``) present; and the **cost-drift
   watchdog** screened every warm planned session with ZERO
   ``cost_drift`` flight events (the continuous per-session mirror of
   gate 5).

Prints one JSON summary line (the CI log artifact).

    JAX_PLATFORMS=cpu python scripts/dist_smoke.py
"""

import json
import os
import sys
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# the smoke IS the fast-path check: force it on regardless of the
# suite-wide eager default, with a 1-session validation budget so the
# second session is already warm
os.environ["MOOSE_TPU_WORKER_JIT"] = "1"
os.environ["MOOSE_TPU_JIT_SELFCHECK"] = "1"
# validation cost on the CI box is ~4s of trace+XLA-compile per
# candidate segment (measured: 71 segments -> ~300s first session at
# the default min-seg of 4); validating only >=48-op segments keeps the
# smoke's contract — segmented plan, zero pins, warm second session —
# while compiling ~17 candidates instead of 71.  TPU deployments keep
# the default: there validation amortizes across serving sessions.
os.environ.setdefault("MOOSE_TPU_WORKER_MIN_SEG", "48")
# workers refuse the non-cryptographic default PRF
os.environ.setdefault("MOOSE_TPU_PRF", "threefry")

CLIENTS_SESSIONS = 3
FEATURES = 8
BATCH = 16


class _Collector:
    """Minimal in-process OTLP/HTTP collector capturing POSTed spans."""

    def __init__(self):
        import http.server
        import threading

        collector = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                length = int(self.headers["Content-Length"])
                collector.requests.append(
                    json.loads(self.rfile.read(length))
                )
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.end_headers()
                self.wfile.write(b"{}")

            def log_message(self, *args):
                pass

        self.requests = []
        self.server = http.server.HTTPServer(("127.0.0.1", 0), Handler)
        self.endpoint = f"http://127.0.0.1:{self.server.server_port}"
        threading.Thread(
            target=self.server.serve_forever, daemon=True
        ).start()

    def spans(self):
        out = []
        for payload in self.requests:
            for rs in payload["resourceSpans"]:
                for ss in rs["scopeSpans"]:
                    out.extend(ss["spans"])
        return out

    def close(self):
        self.server.shutdown()
        self.server.server_close()


def check_stitched_trace(collector) -> dict:
    """Exactly one trace id shared by the client's run_computation tree
    and all three workers' execute_role roots (ISSUE 6 acceptance)."""
    spans = collector.spans()
    by_name = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s)
    roots = by_name.get("run_computation", [])
    assert len(roots) == 1, (
        f"expected 1 client root span, saw {len(roots)}"
    )
    trace_id = roots[0]["traceId"]
    workers = by_name.get("execute_role", [])
    parties = set()
    for s in workers:
        attrs = {a["key"]: a["value"] for a in s["attributes"]}
        parties.add(attrs["party"]["stringValue"])
        assert s["traceId"] == trace_id, (
            f"worker span in foreign trace: {s['traceId']} != {trace_id}"
        )
    assert parties == {"alice", "bob", "carole"}, parties
    trace_ids = {
        s["traceId"] for s in spans
        if s["name"] in (
            "run_computation", "attempt", "launch", "retrieve",
            "execute_role", "worker_segment",
        )
    }
    assert trace_ids == {trace_id}, (
        f"session spans span {len(trace_ids)} traces, want 1"
    )
    return {"trace_id": trace_id, "parties": sorted(parties)}


def check_metrics_scrape(server) -> dict:
    """A worker's metrics port serves non-empty Prometheus text with
    worker-plan and networking counters (retry/chaos counters join
    after the chaos run — same process-global registry)."""
    import urllib.request

    port = server.metrics_server.port
    text = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=10
    ).read().decode()
    assert text.strip(), "empty Prometheus scrape"
    for needle in (
        "moose_tpu_worker_plans_built_total",
        "moose_tpu_net_tx_bytes_total",
        "moose_tpu_net_send_many_total",
    ):
        assert needle in text, f"scrape missing {needle}"
    return {"port": port, "bytes": len(text)}


def run_chaos_kill_flight(traced, x) -> dict:
    """Kill one party mid-session under the deterministic chaos layer;
    the terminal report must attach the killed party's flight events,
    and retry/chaos counters must land on the registry."""
    from moose_tpu import metrics
    from moose_tpu.distributed.chaos import ChaosConfig
    from moose_tpu.distributed.choreography import start_local_cluster
    from moose_tpu.distributed.client import GrpcClientRuntime

    retries_before = metrics.REGISTRY.value(
        "moose_tpu_client_retries_total"
    )
    chaos = ChaosConfig(seed=1, kill_after_ops=1, party="carole")
    servers = {}
    # eager workers: this run is about failure propagation + flight
    # capture, not the compiled plan — skip the fresh cluster's
    # re-validation compiles
    os.environ["MOOSE_TPU_WORKER_JIT"] = "0"
    try:
        servers, endpoints = start_local_cluster(
            ("alice", "bob", "carole"), ping_interval=0.25,
            ping_misses=2, startup_grace=5.0, receive_timeout=30.0,
            chaos=chaos, metrics_port=0,
        )
        runtime = GrpcClientRuntime(
            endpoints, max_attempts=2, backoff_base_s=0.05,
            backoff_cap_s=0.2,
        )
        failed = False
        try:
            runtime.run_computation(traced, {"x": x}, timeout=60.0)
        except Exception:
            failed = True
        assert failed, "chaos-killed session unexpectedly succeeded"
        report = runtime.last_session_report
        events = report.get("flight") or []
        assert events, "terminal failure attached no flight events"
        parties = {e.get("party") for e in events}
        assert "carole" in parties, (
            f"killed party's events missing from flight: {parties}"
        )
        carole_kinds = {
            e["kind"] for e in events if e.get("party") == "carole"
        }
        assert "chaos_kill" in carole_kinds, carole_kinds
        assert metrics.REGISTRY.value(
            "moose_tpu_chaos_injections_total", kind="kill"
        ) >= 1
        assert metrics.REGISTRY.value(
            "moose_tpu_client_retries_total"
        ) > retries_before, "retry counter did not advance"
        # the acceptance wording in full: a worker scrape AFTER the
        # failure carries retry and chaos counters too (alice is alive;
        # the registry is process-global)
        import urllib.request

        text = urllib.request.urlopen(
            "http://127.0.0.1:"
            f"{servers['alice'].metrics_server.port}/metrics",
            timeout=10,
        ).read().decode()
        for needle in (
            "moose_tpu_client_retries_total",
            'moose_tpu_chaos_injections_total{kind="kill"}',
        ):
            assert needle in text, f"post-chaos scrape missing {needle}"
        return {
            "flight_events": len(events),
            "killed_party_events": sum(
                1 for e in events if e.get("party") == "carole"
            ),
            "attempts": report["n_attempts"],
        }
    finally:
        os.environ["MOOSE_TPU_WORKER_JIT"] = "1"
        for srv in servers.values():
            srv.stop()


def _wire_snapshot() -> dict:
    from moose_tpu import metrics

    v = metrics.REGISTRY.value
    return {
        "tx_bytes": v("moose_tpu_net_tx_bytes_total", transport="grpc"),
        "rx_bytes": v("moose_tpu_net_rx_bytes_total", transport="grpc"),
        "sends": v("moose_tpu_net_sends_total", transport="grpc"),
        "send_many_envelopes": v(
            "moose_tpu_net_send_many_total", transport="grpc"
        ),
        "send_many_payloads": v(
            "moose_tpu_net_send_many_payloads_total", transport="grpc"
        ),
        "receives": v(
            "moose_tpu_net_receives_total", transport="grpc"
        ),
    }


def check_predicted_vs_measured(runtime, traced, x) -> dict:
    """ISSUE 7 acceptance: run ONE warm session and require the static
    cost model's predictions (per-party, summed onto the registry's
    per-transport counters) to equal the measured deltas EXACTLY —
    bytes, single sends, coalesced envelopes, coalesced payloads,
    receives.  Any drift between the analyzer and the runtime wire
    path fails CI here."""
    from moose_tpu.compilation.analysis import cost_report

    before = _wire_snapshot()
    runtime.run_computation(traced, {"x": x}, timeout=300.0)
    measured = {
        k: int(after - before[k])
        for k, after in _wire_snapshot().items()
    }
    # the computation the workers actually ran: the client's compiled
    # cache (lowering bakes nonces, so predicting from a recompile
    # would still match — keys are deterministic — but the cached
    # object is the ground truth)
    per_specs = runtime._compile_cache[traced]
    compiled, _comp_bytes = next(iter(per_specs.values()))
    session_id = runtime.last_session_report["attempts"][-1]["session_id"]
    report = cost_report(compiled, session_id=session_id,
                         transport="grpc")
    assert report["resolved"], (
        "cost model left sends unresolved: "
        f"{ {p: s['unresolved_sends'] for p, s in report['per_party'].items()} }"
    )
    t = report["totals"]
    predicted = {
        "tx_bytes": t["tx_bytes"],
        "rx_bytes": t["rx_bytes"],
        "sends": t["sends"],
        "send_many_envelopes": t["send_many_envelopes"],
        "send_many_payloads": t["send_many_payloads"],
        "receives": t["receives"],
    }
    assert predicted == measured, (
        f"static cost model drifted from the runtime:\n"
        f"predicted {predicted}\nmeasured  {measured}"
    )
    return {
        "predicted": predicted,
        "measured": measured,
        "per_party": {
            p: {
                k: s[k] for k in (
                    "tx_bytes", "rx_bytes", "sends",
                    "send_many_envelopes", "send_many_payloads",
                    "receives",
                )
            }
            for p, s in report["per_party"].items()
        },
        "exact_match": True,
    }


def build_deadlock_comp():
    """A deliberately would-hang computation the schedule analyzer must
    reject at plan-build time: rendezvous key ``dup-k`` is consumed by
    TWO Receives on alice but sent once — single-delivery cell-store
    semantics can serve only the first wait, so the sequential plan
    (and the legacy scheduler) would sit in a blocked receive until the
    timeout.  Toposort accepts the graph (no dataflow cycle), so only
    the MSA5xx plan-level analysis catches it before execution."""
    from moose_tpu.computation import (
        Computation,
        HostFloat64TensorTy,
        HostPlacement,
        Operation,
        Signature,
        UnitTy,
    )

    f64 = HostFloat64TensorTy
    comp = Computation()
    for name in ("alice", "bob", "carole"):
        comp.add_placement(HostPlacement(name))
    comp.add_operation(Operation(
        "c_b", "Constant", [], "bob", Signature((), f64),
        {"value": np.zeros((2,))},
    ))
    comp.add_operation(Operation(
        "s_b", "Send", ["c_b"], "bob", Signature((f64,), UnitTy),
        {"rendezvous_key": "dup-k", "receiver": "alice"},
    ))
    for i in (1, 2):
        comp.add_operation(Operation(
            f"r_a{i}", "Receive", [], "alice", Signature((), f64),
            {"rendezvous_key": "dup-k", "sender": "bob"},
        ))
    comp.add_operation(Operation(
        "out", "Output", ["r_a2"], "alice", Signature((f64,), f64),
    ))
    return comp


def check_deadlock_plan_rejected() -> dict:
    """ISSUE 7 acceptance: the deadlocking plan is rejected at
    ``get_plan`` time with an MSA5xx diagnostic and a flight
    ``plan_rejected`` event, and executing the role anyway (worker jit
    on) demotes to the legacy eager scheduler whose failure mode is a
    TYPED receive timeout within seconds — never a hang."""
    import time

    from moose_tpu import flight
    from moose_tpu.distributed import worker_plan
    from moose_tpu.distributed.networking import (
        LocalNetworking,
        ProgressClock,
    )
    from moose_tpu.distributed.worker import execute_role
    from moose_tpu.errors import PlanRejectedError, ReceiveTimeoutError

    comp = build_deadlock_comp()
    rejected = False
    try:
        worker_plan.get_plan(comp, "alice", session_id="smoke-deadlock")
    except PlanRejectedError as e:
        rejected = True
        rules = {d.rule for d in e.diagnostics}
        assert any(r.startswith("MSA5") for r in rules), rules
        assert "MSA501" in str(e), str(e)
    assert rejected, "deadlocking plan was NOT rejected at build time"
    events = flight.get_recorder().events(session="smoke-deadlock")
    assert any(e["kind"] == "plan_rejected" for e in events), events

    # run the role end-to-end with the fast path ON: the rejection must
    # demote to the legacy scheduler and surface a typed timeout fast
    stats_before = worker_plan.plan_stats()
    net = LocalNetworking()
    t0 = time.monotonic()
    typed = False
    try:
        execute_role(
            comp, "alice", {}, {}, net, "smoke-deadlock-2",
            timeout=2.0, progress=ProgressClock(),
        )
    except ReceiveTimeoutError:
        typed = True
    elapsed = time.monotonic() - t0
    assert typed, "expected a typed ReceiveTimeoutError from the " \
                  "legacy fallback"
    assert elapsed < 30.0, f"fallback took {elapsed:.1f}s — a hang"
    stats = worker_plan.plan_stats()
    assert stats["plans_rejected"] >= (
        stats_before["plans_rejected"] + 1
    ), (stats_before, stats)
    return {
        "rejected_at_build_time": True,
        "flight_plan_rejected": True,
        "fallback_elapsed_s": round(elapsed, 2),
        "plans_rejected_total": stats["plans_rejected"],
    }


def run_profile_smoke(runtime, traced, x) -> dict:
    """ISSUE 12 acceptance: profile one warm 3-worker session.  The
    Perfetto JSON must load, its phase events must cover >=95% of the
    measured session wall time (merged-interval union across threads),
    the distributed phase taxonomy must be present, and the session's
    spans must stitch to ONE trace id."""
    import tempfile
    import time

    from moose_tpu import profiling

    fd, path = tempfile.mkstemp(prefix="moose_profile_", suffix=".json")
    os.close(fd)
    profiling.start(path=path)
    try:
        t0 = time.perf_counter()
        runtime.run_computation(traced, {"x": x}, timeout=300.0)
        wall_s = time.perf_counter() - t0
    finally:
        profiling.stop()
    with open(path) as fh:
        trace = json.load(fh)  # loadable-JSON gate
    events = [
        e for e in trace["traceEvents"] if e.get("ph") == "X"
    ]
    assert events, "profiled session produced no phase events"

    # named-phase taxonomy: the distributed path's phases all present
    names = {e["name"] for e in events}
    for needle in (
        "run_computation", "attempt", "execute_role", "worker_segment",
        "net_send", "net_receive", "serde",
    ):
        assert needle in names, f"profile missing phase {needle!r}: " \
                                f"{sorted(names)}"

    # coverage: merged union of phase intervals vs measured wall time
    intervals = sorted(
        (e["ts"], e["ts"] + e.get("dur", 0.0)) for e in events
    )
    covered = 0.0
    cur_start, cur_end = intervals[0]
    for start, end in intervals[1:]:
        if start <= cur_end:
            cur_end = max(cur_end, end)
        else:
            covered += cur_end - cur_start
            cur_start, cur_end = start, end
    covered += cur_end - cur_start
    coverage = covered / (wall_s * 1e6)
    assert coverage >= 0.95, (
        f"profile phases cover {coverage:.1%} of session wall time, "
        "want >= 95%"
    )

    # stitching: the client root and every worker span share ONE id
    trace_ids = {
        e["args"].get("trace_id")
        for e in events
        if e["name"] in (
            "run_computation", "attempt", "launch", "retrieve",
            "execute_role", "worker_segment",
        )
    }
    trace_ids.discard(None)
    assert len(trace_ids) == 1, (
        f"profiled session spans {len(trace_ids)} trace ids, want 1"
    )
    return {
        "events": len(events),
        "coverage": round(coverage, 4),
        "phases": sorted(names),
        "trace_id": next(iter(trace_ids)),
        "wall_s": round(wall_s, 3),
    }


def check_cost_watchdog_clean() -> dict:
    """ISSUE 12 acceptance: the continuous cost-drift watchdog screened
    the warm planned sessions above and found NOTHING — zero
    ``cost_drift`` flight events, with the ``ok`` outcome counter
    proving it actually ran (a silently-skipped watchdog would pass
    vacuously)."""
    from moose_tpu import flight, metrics

    drift_events = [
        e for e in flight.get_recorder().events()
        if e["kind"] == "cost_drift"
    ]
    assert not drift_events, (
        f"cost-drift watchdog flagged {len(drift_events)} session(s): "
        f"{drift_events[:2]}"
    )
    screened_ok = metrics.REGISTRY.value(
        "moose_tpu_cost_watchdog_sessions_total", outcome="ok"
    )
    assert screened_ok > 0, (
        "the cost-drift watchdog never screened a session — the "
        "zero-drift gate would be vacuous"
    )
    return {"sessions_ok": int(screened_ok), "drift_events": 0}


def build_logreg():
    from sklearn.linear_model import LogisticRegression

    from moose_tpu import predictors
    from moose_tpu.predictors.sklearn_export import (
        logistic_regression_onnx,
    )

    rng = np.random.default_rng(5)
    x_train = rng.normal(size=(96, FEATURES))
    y_train = (rng.uniform(size=96) > 0.5).astype(int)
    sk = LogisticRegression().fit(x_train, y_train)
    model = predictors.from_onnx(
        logistic_regression_onnx(sk, FEATURES).encode()
    )
    return model, sk


def main() -> int:
    from moose_tpu.distributed import worker_plan
    from moose_tpu.distributed.choreography import start_local_cluster
    from moose_tpu.distributed.client import GrpcClientRuntime
    from moose_tpu.edsl import tracer
    from moose_tpu.runtime import LocalMooseRuntime

    model, sk = build_logreg()
    traced = tracer.trace(model.predictor_factory())
    rng = np.random.default_rng(11)
    x = rng.normal(size=(BATCH, FEATURES))
    want = sk.predict_proba(x)

    servers = {}
    summary = {}
    try:
        servers, endpoints = start_local_cluster(
            ("alice", "bob", "carole"), metrics_port=0
        )

        runtime = GrpcClientRuntime(endpoints)
        outputs = None
        stats_before_last = None
        for session in range(CLIENTS_SESSIONS):
            stats_before_last = worker_plan.plan_stats()
            outputs, _ = runtime.run_computation(
                traced, {"x": x}, timeout=300.0
            )
        report = runtime.last_session_report
        modes = report.get("plan_modes", {})
        assert set(modes) == {"alice", "bob", "carole"}, modes
        for party, mode in modes.items():
            assert mode["plan_mode"] in ("segmented", "full-jit"), (
                f"{party} did not reach a compiled plan: {mode}"
            )
            assert mode["pinned_segments"] == [], (
                f"{party} pinned segments on a clean graph: {mode} — a "
                "jit candidate diverged from its eager reference"
            )
        # warm-cache promise: the LAST session validated nothing
        stats_after = worker_plan.plan_stats()
        validating_last = (
            stats_after["validating_evaluations"]
            - stats_before_last["validating_evaluations"]
        )
        assert validating_last == 0, (
            f"warm repeat session re-validated: {stats_before_last} -> "
            f"{stats_after}"
        )

        (got,) = outputs.values()
        got = np.asarray(got)
        err_sk = np.abs(got - want).max()
        assert err_sk < 5e-3, f"distributed vs sklearn: {err_sk}"

        # the in-process path over the identical traced computation —
        # eagerly: the local runtime's own validated-jit ladder would
        # spend ~3.5 min compiling the 7k-op graph (measured on the CI
        # box) for one reference value; the distributed sessions above
        # are the jit under test here, the local run is just the oracle
        os.environ["MOOSE_TPU_JIT"] = "0"
        local = LocalMooseRuntime(["alice", "bob", "carole"])
        local_out = np.asarray(next(iter(
            local.evaluate_computation(traced, arguments={"x": x}).values()
        )))
        err_local = np.abs(got - local_out).max()
        # both paths run the same protocol with independent randomness;
        # they agree to protocol precision, not bitwise
        assert err_local < 1e-2, f"distributed vs in-process: {err_local}"

        # --- ISSUE 6 observability gates --------------------------------
        # one more session with OTLP export on: the plan caches are warm,
        # so this session's spans are purely the trace under test
        from moose_tpu import telemetry

        collector = _Collector()
        try:
            exporter = telemetry.configure_otlp(collector.endpoint)
            runtime.run_computation(traced, {"x": x}, timeout=300.0)
            assert exporter.flush(timeout_s=15.0), "otlp flush timed out"
            assert exporter.dropped == 0, (
                f"exporter dropped spans: {exporter.last_error}"
            )
            stitched = check_stitched_trace(collector)
        finally:
            telemetry.disable_otlp()
            collector.close()

        # Prometheus scrape off a worker's metrics port
        scrape = check_metrics_scrape(servers["alice"])

        # --- ISSUE 7 static-analysis gate -------------------------------
        # predicted-vs-measured: one more warm session, counter deltas
        # must equal the static cost model exactly
        cost_gate = check_predicted_vs_measured(runtime, traced, x)

        # --- ISSUE 12 observability gates -------------------------------
        # one profiled warm session: loadable Perfetto JSON, >=95% wall
        # coverage, stitched trace id, distributed phase taxonomy
        profile_gate = run_profile_smoke(runtime, traced, x)
        # the continuous cost-drift watchdog screened every planned
        # session above and flagged nothing
        watchdog_gate = check_cost_watchdog_clean()
    finally:
        for srv in servers.values():
            srv.stop()

    # deadlocking-plan rejection gate (standalone: in-process worker)
    deadlock_gate = check_deadlock_plan_rejected()

    # chaos-kill postmortem: flight events of the killed party reach
    # last_session_report["flight"] (fresh cluster; the clean one above
    # is already stopped so its ports/ids can't interfere)
    flight_summary = run_chaos_kill_flight(traced, x)

    summary = {
        "ok": True,
        "plan_modes": {p: m["plan_mode"] for p, m in modes.items()},
        "validating_last_session": validating_last,
        "plan_stats": stats_after,
        "max_err_vs_sklearn": float(err_sk),
        "max_err_vs_inprocess": float(err_local),
        "stitched_trace": stitched,
        "metrics_scrape": scrape,
        "chaos_flight": flight_summary,
        "cost_predicted_vs_measured": cost_gate,
        "deadlock_plan_rejected": deadlock_gate,
        "profile_smoke": profile_gate,
        "cost_watchdog": watchdog_gate,
    }
    print(json.dumps(summary), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
