"""CI distributed throughput smoke (ISSUE 5 acceptance): 3 local comet
workers over TCP (in-process WorkerServers on 127.0.0.1 gRPC ports, the
same server class the comet daemon runs) execute logreg inference
through the client supervisor with the compiled worker fast path ON.

Asserts:

1. every worker reaches a **segmented/full-jit plan mode** on the clean
   graph with ZERO eager pinning (a pin here means a jit candidate
   diverged from its eager reference on CPU — a real regression);
2. a **repeat session performs zero validating evaluations** — the
   worker-side plan cache (weak-keyed on (computation, role), memoized
   by computation bytes) serves the resolved plan warm;
3. the distributed outputs **match the in-process path**
   (LocalMooseRuntime over the identical traced computation) and
   sklearn's own predict_proba.

Prints one JSON summary line (the CI log artifact).

    JAX_PLATFORMS=cpu python scripts/dist_smoke.py
"""

import json
import os
import sys
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# the smoke IS the fast-path check: force it on regardless of the
# suite-wide eager default, with a 1-session validation budget so the
# second session is already warm
os.environ["MOOSE_TPU_WORKER_JIT"] = "1"
os.environ["MOOSE_TPU_JIT_SELFCHECK"] = "1"
# validation cost on the CI box is ~4s of trace+XLA-compile per
# candidate segment (measured: 71 segments -> ~300s first session at
# the default min-seg of 4); validating only >=48-op segments keeps the
# smoke's contract — segmented plan, zero pins, warm second session —
# while compiling ~17 candidates instead of 71.  TPU deployments keep
# the default: there validation amortizes across serving sessions.
os.environ.setdefault("MOOSE_TPU_WORKER_MIN_SEG", "48")
# workers refuse the non-cryptographic default PRF
os.environ.setdefault("MOOSE_TPU_PRF", "threefry")

CLIENTS_SESSIONS = 3
FEATURES = 8
BATCH = 16


def build_logreg():
    from sklearn.linear_model import LogisticRegression

    from moose_tpu import predictors
    from moose_tpu.predictors.sklearn_export import (
        logistic_regression_onnx,
    )

    rng = np.random.default_rng(5)
    x_train = rng.normal(size=(96, FEATURES))
    y_train = (rng.uniform(size=96) > 0.5).astype(int)
    sk = LogisticRegression().fit(x_train, y_train)
    model = predictors.from_onnx(
        logistic_regression_onnx(sk, FEATURES).encode()
    )
    return model, sk


def main() -> int:
    from moose_tpu.distributed import worker_plan
    from moose_tpu.distributed.choreography import start_local_cluster
    from moose_tpu.distributed.client import GrpcClientRuntime
    from moose_tpu.edsl import tracer
    from moose_tpu.runtime import LocalMooseRuntime

    model, sk = build_logreg()
    traced = tracer.trace(model.predictor_factory())
    rng = np.random.default_rng(11)
    x = rng.normal(size=(BATCH, FEATURES))
    want = sk.predict_proba(x)

    servers = {}
    summary = {}
    try:
        servers, endpoints = start_local_cluster(
            ("alice", "bob", "carole")
        )

        runtime = GrpcClientRuntime(endpoints)
        outputs = None
        stats_before_last = None
        for session in range(CLIENTS_SESSIONS):
            stats_before_last = worker_plan.plan_stats()
            outputs, _ = runtime.run_computation(
                traced, {"x": x}, timeout=300.0
            )
        report = runtime.last_session_report
        modes = report.get("plan_modes", {})
        assert set(modes) == {"alice", "bob", "carole"}, modes
        for party, mode in modes.items():
            assert mode["plan_mode"] in ("segmented", "full-jit"), (
                f"{party} did not reach a compiled plan: {mode}"
            )
            assert mode["pinned_segments"] == [], (
                f"{party} pinned segments on a clean graph: {mode} — a "
                "jit candidate diverged from its eager reference"
            )
        # warm-cache promise: the LAST session validated nothing
        stats_after = worker_plan.plan_stats()
        validating_last = (
            stats_after["validating_evaluations"]
            - stats_before_last["validating_evaluations"]
        )
        assert validating_last == 0, (
            f"warm repeat session re-validated: {stats_before_last} -> "
            f"{stats_after}"
        )

        (got,) = outputs.values()
        got = np.asarray(got)
        err_sk = np.abs(got - want).max()
        assert err_sk < 5e-3, f"distributed vs sklearn: {err_sk}"

        # the in-process path over the identical traced computation —
        # eagerly: the local runtime's own validated-jit ladder would
        # spend ~3.5 min compiling the 7k-op graph (measured on the CI
        # box) for one reference value; the distributed sessions above
        # are the jit under test here, the local run is just the oracle
        os.environ["MOOSE_TPU_JIT"] = "0"
        local = LocalMooseRuntime(["alice", "bob", "carole"])
        local_out = np.asarray(next(iter(
            local.evaluate_computation(traced, arguments={"x": x}).values()
        )))
        err_local = np.abs(got - local_out).max()
        # both paths run the same protocol with independent randomness;
        # they agree to protocol precision, not bitwise
        assert err_local < 1e-2, f"distributed vs in-process: {err_local}"

        summary = {
            "ok": True,
            "plan_modes": {p: m["plan_mode"] for p, m in modes.items()},
            "validating_last_session": validating_last,
            "plan_stats": stats_after,
            "max_err_vs_sklearn": float(err_sk),
            "max_err_vs_inprocess": float(err_local),
        }
        print(json.dumps(summary), flush=True)
        return 0
    finally:
        for srv in servers.values():
            srv.stop()


if __name__ == "__main__":
    sys.exit(main())
