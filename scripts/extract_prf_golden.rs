//! One-command golden-vector extraction for PRF bit-identity
//! (VERDICT r4 #7: the composed aes-prng stream has no golden vectors
//! because this build environment has no Rust toolchain).
//!
//! Run on ANY machine with cargo:
//!
//! ```sh
//! cargo new prf-golden --bin && cd prf-golden
//! cat >> Cargo.toml <<'EOF'
//! aes-prng = "~0.2"
//! blake3 = "=1.3.0"
//! rand = "0.8"
//! EOF
//! cp /path/to/repo/scripts/extract_prf_golden.rs src/main.rs
//! cargo run --release > prf_golden_rust.json
//! # then, back in the repo:
//! python scripts/check_prf_golden.py prf_golden_rust.json
//! ```
//!
//! It prints one JSON object with the exact streams the reference's
//! kernels consume (moose/src/host/ops.rs:1959-2040 draw orders;
//! moose/src/host/prim.rs:113-133 seed derivation):
//!   - next_u64 stream for a fixed 16-byte seed (AesRng::from_seed)
//!   - ring128 draws: HIGH limb first, then low (ring128_kernel)
//!   - get_bit stream (bit_kernel / max_value == 1 sampling)
//!   - fill_bytes stream (serialization-adjacent consumers)
//!   - DeriveSeed: blake3::derive_key("Derive Seed", key) then keyed
//!     hash of session_id_bytes || sync_key_bytes, first 16 bytes
//!
//! The repo-side checker (scripts/check_prf_golden.py) compares every
//! stream against crypto/aes_prng.py and pins down any divergence to
//! the exact consumption rule (word order / bit granularity), so the
//! BASELINE "bit-identical outputs" claim is one cargo run from closed.

use aes_prng::AesRng;
use rand::{RngCore, SeedableRng};

fn hex(bytes: &[u8]) -> String {
    bytes.iter().map(|b| format!("{:02x}", b)).collect()
}

fn main() {
    let seed: [u8; 16] = *b"moose-prf-golden";

    // 1) raw next_u64 stream
    let mut rng = AesRng::from_seed(seed);
    let u64s: Vec<String> = (0..32).map(|_| format!("{}", rng.next_u64())).collect();

    // 2) ring128 element draws: high limb first (host/ops.rs:2001)
    let mut rng = AesRng::from_seed(seed);
    let ring128: Vec<String> = (0..16)
        .map(|_| {
            let v = ((rng.next_u64() as u128) << 64) + rng.next_u64() as u128;
            format!("{}", v)
        })
        .collect();

    // 3) bit draws (host/ops.rs bit_kernel / get_bit)
    let mut rng = AesRng::from_seed(seed);
    let bits: Vec<u8> = (0..256).map(|_| rng.get_bit()).collect();

    // 4) fill_bytes stream
    let mut rng = AesRng::from_seed(seed);
    let mut buf = [0u8; 64];
    rng.fill_bytes(&mut buf);

    // 5) DeriveSeed (host/prim.rs:113-133): nonce = sid || sync_key
    let key_bytes: [u8; 16] = *b"moose-prfkey-16b";
    let sid_bytes: [u8; 16] = *b"session-id-16byt";
    let sync_key_bytes: [u8; 16] = *b"sync-key-16bytes";
    let derived_key = blake3::derive_key("Derive Seed", &key_bytes);
    let mut hasher = blake3::Hasher::new_keyed(&derived_key);
    hasher.update(&sid_bytes);
    hasher.update(&sync_key_bytes);
    let mut okr = hasher.finalize_xof();
    let mut seed_out = [0u8; 16];
    okr.fill(&mut seed_out);

    println!(
        "{{\n  \"seed\": \"{}\",\n  \"next_u64\": [{}],\n  \"ring128_hi_first\": [{}],\n  \"bits\": {:?},\n  \"fill_bytes\": \"{}\",\n  \"derive_seed\": {{\"key\": \"{}\", \"sid\": \"{}\", \"sync_key\": \"{}\", \"seed_out\": \"{}\"}}\n}}",
        hex(&seed),
        u64s.iter().map(|s| format!("\"{}\"", s)).collect::<Vec<_>>().join(", "),
        ring128.iter().map(|s| format!("\"{}\"", s)).collect::<Vec<_>>().join(", "),
        bits,
        hex(&buf),
        hex(&key_bytes),
        hex(&sid_bytes),
        hex(&sync_key_bytes),
        hex(&seed_out),
    );
}
