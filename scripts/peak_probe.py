"""Measure achievable dense matmul throughput on this chip (int8/bf16),
to calibrate MFU claims. Forces execution via scalar readback."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import moose_tpu  # noqa: F401
import jax
import jax.numpy as jnp


def bench(m, k, n, dtype, acc, iters=30):
    rng = np.random.default_rng(0)
    if dtype == jnp.int8:
        a = jax.device_put(rng.integers(-128, 127, (m, k), np.int8))
        b = jax.device_put(rng.integers(-128, 127, (k, n), np.int8))
    else:
        a = jax.device_put(rng.normal(size=(m, k)).astype(dtype))
        b = jax.device_put(rng.normal(size=(k, n)).astype(dtype))

    @jax.jit
    def f(a, b):
        p = jax.lax.dot_general(
            a, b, (((1,), (0,)), ((), ())), preferred_element_type=acc
        )
        return jnp.sum(p.astype(jnp.float32) if acc != jnp.float32 else p)

    float(f(a, b))
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            s = f(a, b)
        float(s)
        times.append((time.perf_counter() - t0) / iters)
    t = min(times)
    tops = 2 * m * k * n / t / 1e12
    print(f"{m}x{k}x{n} {np.dtype(dtype).name}->{np.dtype(acc).name}: "
          f"{t*1e3:.3f} ms  {tops:.1f} TOP/s")


for sz in (1000, 1024, 4096):
    bench(sz, sz, sz, jnp.int8, jnp.int32)
    bench(sz, sz, sz, jnp.bfloat16, jnp.float32)
bench(8192, 8192, 8192, jnp.int8, jnp.int32, iters=10)
bench(8192, 8192, 8192, jnp.bfloat16, jnp.float32, iters=10)
bench(1000, 16000, 1000, jnp.int8, jnp.int32)
bench(3000, 16000, 3000, jnp.int8, jnp.int32, iters=10)
