"""Measure achievable HBM bandwidth + PRF sampling rate on this chip
(scan-chained, scalar readback)."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import moose_tpu  # noqa: F401
import jax
import jax.numpy as jnp


def chain(body, init, T=50):
    @jax.jit
    def run():
        c, _ = jax.lax.scan(body, init, None, length=T)
        return jnp.sum(c)

    float(run())
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        s = run()
        float(s)
        times.append(time.perf_counter() - t0)
    return min(times) / T


n = 3000  # 72 MB u64
x = jax.device_put(np.random.default_rng(0).integers(0, 1 << 63, (n, n), dtype=np.uint64))

# elementwise add: reads 2*72, writes 72 => 216 MB per iter
t = chain(lambda c, _: (c + x, None), x)
print(f"u64 add: {t*1e3:.3f} ms  {216e6/t/1e9:.0f} GB/s")

# u64 mul (emulated 32-bit on TPU)
t = chain(lambda c, _: (c * x, None), x)
print(f"u64 mul: {t*1e3:.3f} ms  {216e6/t/1e9:.0f} GB/s")

# f32 add, same footprint in elements (36 MB arrays => 108 MB)
xf = jax.device_put(np.random.default_rng(0).random((n, n), np.float32))
t = chain(lambda c, _: (c + xf, None), xf)
print(f"f32 add: {t*1e3:.3f} ms  {108e6/t/1e9:.0f} GB/s")

# rbg draw of (2,3,n,n) u64 = 144 MB + xor fold into carry (reads+writes ~288MB)
from moose_tpu.dialects import ring


def body(c, _):
    seed = ring.mix_seed(
        jnp.asarray([1, 2, 3, 4], jnp.uint32),
        jnp.stack([c[0, 0, 0].astype(jnp.uint32), jnp.uint32(1), jnp.uint32(2), jnp.uint32(3)]),
    )
    lo, hi = ring.sample_uniform_seeded((3, n, n), seed, 128)
    return c ^ lo ^ hi, None


t = chain(body, x[None].repeat(3, 0).reshape(3, n, n), T=20)
mb = 3 * n * n * 8 * 2
print(f"rbg 128-bit bank draw ({mb/1e6:.0f} MB): {t*1e3:.3f} ms  {mb/t/1e9:.1f} GB/s")

ring.set_prf_impl("threefry")
t = chain(body, x[None].repeat(3, 0).reshape(3, n, n), T=20)
print(f"threefry 128-bit bank draw ({mb/1e6:.0f} MB): {t*1e3:.3f} ms  {mb/t/1e9:.1f} GB/s")

from moose_tpu.dialects import pallas_prf


def body_pallas(c, _):
    seed = jnp.stack([c[0, 0, 0].astype(jnp.uint32), jnp.uint32(1),
                      jnp.uint32(2), jnp.uint32(3)])
    bits = pallas_prf.random_bits_u64(seed, (2, 3, n, n))
    return c ^ bits[0] ^ bits[1], None


t = chain(body_pallas, x[None].repeat(3, 0).reshape(3, n, n), T=20)
print(f"pallas threefry 128-bit bank draw ({mb/1e6:.0f} MB): {t*1e3:.3f} ms  {mb/t/1e9:.1f} GB/s")
