"""mypy --strict gate over the typed core (CI).

The static analyzer judges other code; it must itself be type-clean.
The training storage layer (checkpoints, sessions, export) crosses
trust boundaries and is in scope for the same reason.  Scope and the
per-flag relaxations for gradually-typed neighbor modules
(follow_imports=silent, untyped calls permitted) live in
``pyproject.toml`` ``[tool.mypy]`` — this wrapper only adds the
--strict baseline and a friendly skip when mypy is not installed (dev
boxes; CI installs it).

    python scripts/typecheck_analysis.py
"""

import pathlib
import subprocess
import sys

ROOT = pathlib.Path(__file__).resolve().parents[1]

TARGETS = [
    "moose_tpu/compilation/analysis",
    "moose_tpu/training",
    # the PRF construction the keystream analysis (MSA8xx) models —
    # drift between the two is a silent-secrecy bug, so both sides of
    # the contract sit under the same gate
    "moose_tpu/crypto",
]


def main() -> int:
    try:
        import mypy  # noqa: F401 — availability probe only
    except ModuleNotFoundError:
        print(
            "mypy not installed; skipping the analysis type gate "
            "(CI installs it — `pip install mypy` to run locally)"
        )
        return 0
    cmd = [
        sys.executable, "-m", "mypy", "--strict",
        # the strict baseline, minus the gradual-typing relaxations in
        # pyproject (CLI flags would override the config, so restate
        # the two that --strict turns back on)
        "--allow-untyped-calls", "--no-warn-return-any",
        "--allow-any-generics",
        *(str(ROOT / target) for target in TARGETS),
    ]
    print("$", " ".join(cmd))
    return subprocess.call(cmd, cwd=ROOT)


if __name__ == "__main__":
    sys.exit(main())
