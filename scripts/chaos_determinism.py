"""Chaos determinism check (CI): run the 3-party distributed secure-dot
TWICE under one fixed MOOSE_TPU_CHAOS schedule and fail on ANY
divergence between the two runs — fault schedule (drop/dup/kill
decisions), supervisor outcome (ok / final error class / attempts
used), and, for successful runs, the output bytes.

    python scripts/chaos_determinism.py --chaos "seed:85,drop_send:0.2"
    python scripts/chaos_determinism.py \
        --chaos "seed:7,kill_after_ops:2,party:carole,fail_ping:0.2"

The chaos layer's whole contract is that a seed IS the fault schedule;
this script is the regression guard for that contract (the same check
the tier-1 suite makes once, made twice and compared).  Keys and
trace-time nonces are pinned so outputs are bit-comparable (weak-PRF
escape hatch: this is a single-process test cluster, not a deployment).
"""

import argparse
import hashlib
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["MOOSE_TPU_ALLOW_WEAK_PRF"] = "1"
os.environ["MOOSE_TPU_FIXED_KEYS"] = "chaos-determinism"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

# decisions whose occurrence count is schedule, not timing (fail_ping
# entries scale with how many detector rounds ran — excluded)
_SCHEDULE_KINDS = {"drop_send", "dup_send", "kill"}


def _secure_dot():
    import moose_tpu as pm

    alice = pm.host_placement("alice")
    bob = pm.host_placement("bob")
    carole = pm.host_placement("carole")
    rep = pm.replicated_placement("rep", players=[alice, bob, carole])

    @pm.computation
    def comp(
        x: pm.Argument(placement=alice, dtype=pm.float64),
        w: pm.Argument(placement=bob, dtype=pm.float64),
    ):
        with alice:
            xf = pm.cast(x, dtype=pm.fixed(14, 23))
        with bob:
            wf = pm.cast(w, dtype=pm.fixed(14, 23))
        with rep:
            y = pm.dot(xf, wf)
        with carole:
            out = pm.cast(y, dtype=pm.float64)
        return out

    return comp


def run_once(chaos_spec: str) -> dict:
    """One fresh cluster + client run under a fresh schedule; returns
    the comparable outcome."""
    import numpy as np

    from moose_tpu.dialects import host as host_dialect
    from moose_tpu.distributed.chaos import ChaosConfig
    from moose_tpu.distributed.choreography import WorkerServer
    from moose_tpu.distributed.client import GrpcClientRuntime
    from moose_tpu.edsl import tracer

    chaos = ChaosConfig.from_env(chaos_spec)
    if chaos is None:
        raise SystemExit("--chaos spec parsed to no chaos; nothing to check")
    servers, endpoints = {}, {}
    for i in ("alice", "bob", "carole"):
        srv = WorkerServer(
            i, 0, {}, ping_interval=0.25, ping_misses=2,
            startup_grace=5.0, receive_timeout=4.0, stall_grace=0.5,
            chaos=chaos,
        ).start()
        servers[i] = srv
        endpoints[i] = f"127.0.0.1:{srv.port}"
    for srv in servers.values():
        srv.endpoints.update(endpoints)
        srv.networking._endpoints.update(endpoints)

    rng = np.random.default_rng(0)
    args = {"x": rng.normal(size=(4, 3)), "w": rng.normal(size=(3, 2))}
    outcome = {"ok": False, "error": None, "n_attempts": 0}
    try:
        runtime = GrpcClientRuntime(
            endpoints, max_attempts=3, backoff_base_s=0.05,
            backoff_cap_s=0.2,
        )
        with host_dialect.deterministic_sync_keys(1234):
            try:
                outputs, _ = runtime.run_computation(
                    tracer.trace(_secure_dot()), args, timeout=30.0
                )
                outcome["ok"] = True
                digest = hashlib.blake2b(digest_size=16)
                for name in sorted(outputs):
                    digest.update(name.encode())
                    digest.update(np.ascontiguousarray(
                        np.asarray(outputs[name])
                    ).tobytes())
                outcome["outputs"] = digest.hexdigest()
            except Exception as e:  # noqa: BLE001 — outcome, not crash
                # the exact class is race-dependent under kill chaos
                # (own-detector PeerUnreachable vs adopted abort vs raw
                # UNAVAILABLE may each win); what IS schedule-determined
                # is that the run failed and how the supervisor
                # classified it
                from moose_tpu.distributed.client import _retryable

                outcome["error"] = (
                    "retryable" if _retryable(e) else "permanent"
                )
        outcome["n_attempts"] = runtime.last_session_report.get(
            "n_attempts", 0
        )
        outcome["schedule"] = chaos.schedule_digest(kinds=_SCHEDULE_KINDS)
        outcome["faults"] = sorted(
            (f["kind"], f.get("key", f.get("party", "")))
            for f in chaos.faults if f["kind"] in _SCHEDULE_KINDS
        )
    finally:
        for srv in servers.values():
            srv.stop()
    return outcome


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--chaos", required=True,
        help="MOOSE_TPU_CHAOS spec, e.g. 'seed:85,drop_send:0.2'",
    )
    args = parser.parse_args(argv)

    first = run_once(args.chaos)
    second = run_once(args.chaos)
    print(json.dumps({"run1": first, "run2": second}, indent=2))
    if first != second:
        print(
            f"NON-DETERMINISTIC outcome under chaos spec "
            f"{args.chaos!r}", file=sys.stderr,
        )
        return 1
    print(f"deterministic under {args.chaos!r}: "
          f"schedule={first['schedule']}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
