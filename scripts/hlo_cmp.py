import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import collections
import re
import sys
import numpy as np
import moose_tpu
import jax, jax.numpy as jnp
from moose_tpu.dialects import ring

n = 1000
rng = np.random.default_rng(0)
a = rng.integers(0, 1<<64, (n,n), dtype=np.uint64)
b = rng.integers(0, 1<<64, (n,n), dtype=np.uint64)

f = jax.jit(lambda w,x,y,z: ring._matmul_u128(w,x,y,z))
txt = f.lower(a,a,b,b).compile().as_text()
ops = collections.Counter(re.findall(r"= \S+ (\w+)\(", txt))
print(sys.argv[1] if len(sys.argv)>1 else "?", dict(ops.most_common(12)))
print("lines:", len(txt.splitlines()), "fusions:",
      sum(1 for l in txt.splitlines() if "fusion(" in l))
