"""CI autotuner smoke (ISSUE 20): the cost-driven plan autotuner on the
two north-star predictor graphs, at both ring widths.

Asserts, for logreg + MLP at ring64 (fixed(8,17)) and ring128
(fixed(24,40)):

1. **decisions are recorded** — every evaluation surfaces the full
   decision table (`segment_limit` / `worker_min_seg` / `coalesce` /
   `pallas` / `pallas_dot` / `transport`, each with a valid provenance)
   in ``runtime.last_plan["autotune"]``;
2. **decisions are deterministic** — a fresh runtime over a fresh trace
   of the same model resolves the IDENTICAL table (the decision engine
   is a pure function of (computation, measurements, env));
3. **the chosen plan is bit-exact** — under ``MOOSE_TPU_FIXED_KEYS``
   the autotuned validated-jit evaluation equals the eager oracle
   bit-for-bit (the autotuner picks among exact plans only);
4. **the sigmoid sidestep still holds with kernels selected** —
   ``repro_miscompile.py --sigmoid-probe --pallas`` (the regression
   guard for the Pallas sidestep of the known TPU miscompile) passes in
   a subprocess.

Prints one JSON summary line (the CI log artifact).

    JAX_PLATFORMS=cpu python scripts/autotune_smoke.py
"""

import json
import os
import subprocess
import sys
import time
from pathlib import Path

ROOT = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(ROOT))

# fixed keys: bit-exactness across evaluations needs reproducible PRF
# masks (test-only knob; requires the weak-PRF acknowledgement)
os.environ.setdefault("MOOSE_TPU_FIXED_KEYS", "autotune-smoke")
os.environ.setdefault("MOOSE_TPU_ALLOW_WEAK_PRF", "1")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

import moose_tpu as pm  # noqa: E402


def _models(features: int):
    from sklearn.linear_model import LogisticRegression
    from sklearn.neural_network import MLPClassifier

    from moose_tpu import predictors
    from moose_tpu.predictors.sklearn_export import (
        logistic_regression_onnx,
        mlp_onnx,
    )

    rng = np.random.default_rng(7)
    x_train = rng.normal(size=(128, features))
    y_train = (rng.uniform(size=128) > 0.5).astype(int)

    logreg = predictors.from_onnx(
        logistic_regression_onnx(
            LogisticRegression().fit(x_train, y_train), features
        ).encode()
    )
    mlp = predictors.from_onnx(
        mlp_onnx(
            MLPClassifier(
                hidden_layer_sizes=(16,), activation="relu", max_iter=20
            ).fit(x_train, y_train),
            features, classifier=True,
        ).encode()
    )
    return {"logreg": logreg, "mlp": mlp}


def _evaluate(comp, args):
    """(outputs, decision table) of one evaluation on a FRESH runtime."""
    from moose_tpu.runtime import LocalMooseRuntime

    runtime = LocalMooseRuntime(
        ["alice", "bob", "carole"], use_jit=True,
    )
    out = next(iter(
        runtime.evaluate_computation(comp, arguments=args).values()
    ))
    table = runtime.last_plan.get("autotune")
    assert table is not None, "no autotune table in last_plan"
    return np.asarray(out), table


def _eager_oracle(comp, args):
    from moose_tpu.runtime import LocalMooseRuntime

    runtime = LocalMooseRuntime(
        ["alice", "bob", "carole"], use_jit=False,
    )
    return np.asarray(next(iter(
        runtime.evaluate_computation(comp, arguments=args).values()
    )))


KNOBS = {
    "segment_limit", "worker_min_seg", "coalesce",
    "pallas", "pallas_dot", "transport",
}
SOURCES = {"override", "measured", "predicted", "default"}


def main() -> int:
    from moose_tpu.edsl import tracer

    features, batch = 20, 16
    rng = np.random.default_rng(3)
    x = rng.normal(size=(batch, features))
    args = {"x": x}
    summary = {"cases": {}, "widths": {}}

    models = _models(features)
    t0 = time.time()
    for width, dtype in ((64, pm.fixed(8, 17)), (128, pm.fixed(24, 40))):
        for name, model in models.items():
            case = f"{name}/ring{width}"
            print(f"[autotune-smoke] {case} ...", file=sys.stderr, flush=True)
            t_case = time.time()
            comp = tracer.trace(model.predictor_factory(dtype))

            out, table = _evaluate(comp, args)

            # 1. decisions recorded, every knob with valid provenance
            decisions = table["decisions"]
            missing = KNOBS - set(decisions)
            assert not missing, f"{case}: knobs missing decisions: {missing}"
            for knob, entry in decisions.items():
                assert entry["source"] in SOURCES, (
                    f"{case}: {knob} has bad source {entry['source']!r}"
                )
                assert entry.get("why"), f"{case}: {knob} has no why"

            # 2. deterministic: fresh runtime + fresh trace -> same table
            comp2 = tracer.trace(model.predictor_factory(dtype))
            out2, table2 = _evaluate(comp2, args)
            assert table2["decisions"] == decisions, (
                f"{case}: autotune decisions diverged across processes' "
                f"worth of fresh state:\n{table2['decisions']}\nvs\n"
                f"{decisions}"
            )

            # 3. chosen plan bit-exact vs the eager oracle (fixed keys)
            oracle = _eager_oracle(comp, args)
            assert np.array_equal(out, oracle), (
                f"{case}: autotuned plan diverged from the eager oracle "
                f"(max|diff|={np.abs(out - oracle).max():.3e})"
            )
            assert np.array_equal(out2, oracle), (
                f"{case}: repeat evaluation diverged from the oracle"
            )

            summary["cases"][case] = {
                "bit_exact_vs_eager": True,
                "deterministic": True,
                "seconds": round(time.time() - t_case, 2),
                "decisions": {
                    k: {"choice": v["choice"], "source": v["source"]}
                    for k, v in decisions.items()
                },
            }
            print(
                f"[autotune-smoke] {case} ok "
                f"({summary['cases'][case]['seconds']}s)",
                file=sys.stderr, flush=True,
            )
    summary["predictor_seconds"] = round(time.time() - t0, 2)

    # 4. the Pallas sigmoid sidestep guard, kernels forced + verified
    t0 = time.time()
    # reduced ring64 precision + tiny batch: the same cheap every-commit
    # configuration the CI kernel step runs (full fixed(24,40) coverage
    # lives in the slow-marked kernel suite)
    probe = subprocess.run(
        [sys.executable, str(ROOT / "repro_miscompile.py"),
         "--sigmoid-probe", "--pallas", "--platform",
         os.environ.get("JAX_PLATFORMS", "cpu"),
         "--precision", "8,17", "--batch", "2"],
        capture_output=True, text=True, timeout=1800, cwd=str(ROOT),
    )
    summary["sigmoid_probe_pallas"] = {
        "returncode": probe.returncode,
        "seconds": round(time.time() - t0, 2),
        "tail": probe.stdout.strip().splitlines()[-1:],
    }
    assert probe.returncode == 0, (
        "repro_miscompile.py --sigmoid-probe --pallas FAILED — the "
        f"kernel sidestep regressed:\n{probe.stdout}\n{probe.stderr}"
    )

    summary["ok"] = True
    print(json.dumps(summary), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
