"""train_smoke: the ISSUE-13 CI gate — fault-tolerant secure training
end to end, on REAL subprocess workers.

1. **Cluster**: three comet daemons (gRPC choreography + networking,
   filesystem storage wrapped in a CheckpointStore via ``--checkpoint``)
   train logistic regression for 3 epochs as successive distributed
   sessions driven by the TrainingSession supervisor.
2. **Kill/resume**: the moment carole commits epoch 1, she is SIGKILLed
   (a real process death mid-epoch-2) and restarted ~2 s later from her
   durable storage.  The supervisor must ride it out: the epoch session
   fails retryably, the restarted worker reopens its CheckpointStore
   (durable pin + CURRENT), and training completes — with
   ``epoch_resumed`` flight evidence and
   ``moose_tpu_training_resumes_total >= 1`` proving the recovery path
   actually ran, and every party's final checkpoint at epoch 3.
3. **Oracle**: the distributed final weights must match BOTH the
   in-process (LocalMooseRuntime) training oracle and the float64
   numpy reference chain.
4. **Hot-swap**: the trained weights export to ONNX and roll into a
   RUNNING blitzen through the PR-9 snapshot/drain path (write the new
   artifact, SIGTERM-drain, restart) under continuous client load —
   ZERO dropped requests (every logical request ends 2xx), and the
   served predictions flip to the trained model.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

ENV = dict(
    os.environ,
    JAX_PLATFORMS="cpu",
    MOOSE_TPU_ALLOW_WEAK_PRF="1",
    MOOSE_TPU_FIXED_KEYS="train-smoke",
    MOOSE_TPU_JIT="0",
)
os.environ.update(ENV)

PARTIES = ["alice", "bob", "carole"]
EPOCHS = 3
FEATURES = 3


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class Proc:
    def __init__(self, name, argv):
        self.name = name
        self.argv = argv
        self.lines: list = []
        self._lock = threading.Lock()
        self.popen = subprocess.Popen(
            argv, env=ENV, cwd=ROOT, text=True,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        threading.Thread(target=self._pump, daemon=True).start()

    def _pump(self):
        for line in self.popen.stdout:
            with self._lock:
                self.lines.append(line.rstrip())

    def tail(self, n=15):
        with self._lock:
            return "\n".join(self.lines[-n:])

    def sigkill(self):
        self.popen.send_signal(signal.SIGKILL)
        self.popen.wait(timeout=30)

    def sigterm(self):
        self.popen.send_signal(signal.SIGTERM)


def wait_until(predicate, timeout_s, what):
    deadline = time.perf_counter() + timeout_s
    while time.perf_counter() < deadline:
        result = predicate()
        if result:
            return result
        time.sleep(0.25)
    raise AssertionError(f"timed out after {timeout_s}s: {what}")


def http_get(url, timeout=10):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()
    except Exception:
        return None, b""


def http_post(url, payload, timeout=60):
    request = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    try:
        with urllib.request.urlopen(request, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()
    except Exception as e:
        return None, type(e).__name__.encode()


def start_worker(identity, port, endpoints_spec, storage_dir):
    return Proc(identity, [
        sys.executable, "-m", "moose_tpu.bin.comet",
        "--identity", identity, "--port", str(port),
        "--endpoints", endpoints_spec,
        "--storage-dir", str(storage_dir),
        "--checkpoint",
        "--receive-timeout", "5",
    ])


def wait_worker_up(port, timeout_s=60):
    def probe():
        s = socket.socket()
        s.settimeout(0.5)
        try:
            s.connect(("127.0.0.1", port))
            return True
        except OSError:
            return False
        finally:
            s.close()

    wait_until(probe, timeout_s, f"worker port {port} accepting")


def main():
    import moose_tpu  # noqa: F401 — initialize jax config before use
    from moose_tpu import flight as flight_mod
    from moose_tpu import metrics as metrics_mod
    from moose_tpu.distributed.client import GrpcClientRuntime
    from moose_tpu.predictors.trainers import LogregSGDTrainer
    from moose_tpu.runtime import LocalMooseRuntime
    from moose_tpu.storage import FilesystemStorage
    from moose_tpu.training import (
        CheckpointStore,
        TrainingConfig,
        TrainingSession,
    )
    from moose_tpu.training.export import logreg_onnx_bytes
    from moose_tpu.training.session import (
        GrpcTrainingCluster,
        LocalTrainingCluster,
    )

    tmp = tempfile.mkdtemp(prefix="train-smoke-")
    rng = np.random.default_rng(1)
    x = rng.normal(size=(8, FEATURES)) * 0.5
    y = (rng.uniform(size=(8, 1)) > 0.5).astype(np.float64)

    ports = {p: free_port() for p in PARTIES}
    endpoints_spec = ",".join(
        f"{p}=127.0.0.1:{ports[p]}" for p in PARTIES
    )
    storage_dirs = {p: os.path.join(tmp, p) for p in PARTIES}
    procs = {
        p: start_worker(p, ports[p], endpoints_spec, storage_dirs[p])
        for p in PARTIES
    }
    blitzen = None
    try:
        for p in PARTIES:
            wait_worker_up(ports[p])
        print(f"[train_smoke] 3 comet workers up ({endpoints_spec})")

        # ---- killer: SIGKILL carole the moment she commits epoch 1 —
        # a real process death mid-epoch-2 — restart her ~2 s later
        kill_done = threading.Event()
        killer_error: list = []
        epoch1_manifest = os.path.join(
            storage_dirs["carole"], "_ckpt", "gen-00000001",
            "MANIFEST.npy",
        )

        def killer():
            # generous budget: on a loaded box one eager MPC epoch can
            # take minutes; a silent killer-thread death would make the
            # assertion below blame the wrong thing
            try:
                wait_until(
                    lambda: os.path.exists(epoch1_manifest), 420,
                    "carole's epoch-1 checkpoint commit",
                )
                print("[train_smoke] SIGKILL carole (mid-epoch-2)")
                procs["carole"].sigkill()
                time.sleep(2.0)
                procs["carole"] = start_worker(
                    "carole", ports["carole"], endpoints_spec,
                    storage_dirs["carole"],
                )
                wait_worker_up(ports["carole"])
                print(
                    "[train_smoke] carole restarted from durable storage"
                )
                kill_done.set()
            except BaseException as e:  # noqa: BLE001 — surfaced below
                killer_error.append(e)
                raise

        killer_thread = threading.Thread(target=killer, daemon=True)
        killer_thread.start()

        # ---- distributed supervised training
        client = GrpcClientRuntime(
            dict(zip(
                PARTIES,
                (f"127.0.0.1:{ports[p]}" for p in PARTIES),
            )),
            max_attempts=3, backoff_base_s=0.2, backoff_cap_s=1.0,
        )
        trainer = LogregSGDTrainer(n_features=FEATURES, learning_rate=0.1)
        session = TrainingSession(
            trainer, GrpcTrainingCluster(client),
            TrainingConfig(
                epochs=EPOCHS, session_timeout_s=90,
                max_epoch_attempts=10, backoff_base_s=0.3,
                backoff_cap_s=2.0,
            ),
        )
        t0 = time.perf_counter()
        report = session.run(x, y)
        train_s = time.perf_counter() - t0
        assert report["ok"], report
        assert not killer_error, f"kill harness failed: {killer_error}"
        assert kill_done.is_set(), (
            "training finished before the kill fired — not a "
            "mid-epoch recovery"
        )
        assert report["resumes"] >= 1, report
        resumed = [
            e for e in flight_mod.get_recorder().events()
            if e.get("kind") == "epoch_resumed"
        ]
        assert resumed, "no epoch_resumed flight event recorded"
        assert metrics_mod.REGISTRY.value(
            "moose_tpu_training_resumes_total"
        ) >= 1
        queries = {
            p: session.cluster.control(p, "query") for p in PARTIES
        }
        assert all(q["latest"] == EPOCHS for q in queries.values()), (
            queries
        )
        w_dist = report["weights"]["w"]
        print(
            f"[train_smoke] distributed training OK in {train_s:.1f}s "
            f"(resumes={report['resumes']}, attempts="
            f"{report['attempts']})"
        )

        # ---- oracle 1: in-process training over CheckpointStores
        local_rt = LocalMooseRuntime(
            identities=PARTIES,
            storage_mapping={
                p: CheckpointStore(
                    FilesystemStorage(os.path.join(tmp, "local", p)),
                    party=p,
                )
                for p in PARTIES
            },
            use_jit=False,
        )
        local_report = TrainingSession(
            LogregSGDTrainer(n_features=FEATURES, learning_rate=0.1),
            LocalTrainingCluster(local_rt, PARTIES),
            TrainingConfig(epochs=EPOCHS),
        ).run(x, y)
        w_local = local_report["weights"]["w"]
        np.testing.assert_allclose(w_dist, w_local, atol=1e-5)
        # ---- oracle 2: the float64 numpy chain
        state = {"w": session._initial_value("w", (FEATURES, 1))}
        for _ in range(EPOCHS):
            state = trainer.reference_epoch(state, x, y)
        np.testing.assert_allclose(w_dist, state["w"], atol=1e-3)
        print("[train_smoke] final weights match in-process + numpy "
              "oracles")

        # ---- hot-swap into a running blitzen (snapshot/drain path)
        w_stale = session._initial_value("w", (FEATURES, 1))
        model_path = os.path.join(tmp, "logreg.onnx")
        with open(model_path, "wb") as f:
            f.write(logreg_onnx_bytes(w_stale))
        snapshot_dir = os.path.join(tmp, "snapshot")
        bport = free_port()
        base = f"http://127.0.0.1:{bport}"

        def start_blitzen():
            return Proc("blitzen", [
                sys.executable, "-m", "moose_tpu.bin.blitzen",
                f"logreg={model_path}",
                "--features", f"logreg={FEATURES}",
                "--host", "127.0.0.1", "--port", str(bport),
                "--snapshot-dir", snapshot_dir,
                "--drain-timeout-s", "60",
            ])

        blitzen = start_blitzen()
        wait_until(
            lambda: http_get(base + "/readyz")[0] == 200, 600,
            "blitzen ready",
        )
        probe = x[:1].tolist()
        stop = threading.Event()
        dropped: list = []
        served = [0]

        def open_loop():
            while not stop.is_set():
                # one LOGICAL request: retried on retryable failures
                # (503 drain, connection refused during restart) until
                # it lands — a request that never lands is a DROP
                deadline = time.perf_counter() + 120
                while True:
                    status, _ = http_post(
                        base + "/v1/models/logreg:predict",
                        {"x": probe}, timeout=10,
                    )
                    if status == 200:
                        served[0] += 1
                        break
                    if time.perf_counter() > deadline:
                        dropped.append(status)
                        break
                    time.sleep(0.2)
                time.sleep(0.05)

        client_threads = [
            threading.Thread(target=open_loop, daemon=True)
            for _ in range(4)
        ]
        for t in client_threads:
            t.start()
        time.sleep(2.0)

        # the swap: new artifact over the model path, graceful drain,
        # restart — the snapshot invalidates on the model-source digest
        # change and the daemon registers the trained weights fresh
        with open(model_path, "wb") as f:
            f.write(logreg_onnx_bytes(w_dist))
        blitzen.sigterm()
        code = blitzen.popen.wait(timeout=120)
        assert code == 0, f"drain exit code {code}\n{blitzen.tail()}"
        blitzen = start_blitzen()
        wait_until(
            lambda: http_get(base + "/readyz")[0] == 200, 600,
            "blitzen ready after hot-swap restart",
        )
        time.sleep(2.0)
        stop.set()
        for t in client_threads:
            t.join(timeout=130)
        assert not dropped, (
            f"{len(dropped)} requests dropped across the hot swap "
            f"(statuses: {dropped[:5]})"
        )
        assert served[0] > 0
        status, body = http_post(
            base + "/v1/models/logreg:predict", {"x": probe},
        )
        assert status == 200, (status, body)
        got = np.asarray(json.loads(body)["y"]).ravel()[-1]
        want = 1.0 / (1.0 + np.exp(-(x[:1] @ w_dist)))
        assert abs(got - want.ravel()[0]) < 2e-2, (got, want)
        print(
            f"[train_smoke] hot-swap OK: {served[0]} requests served, "
            "0 dropped, served predictions follow the trained weights"
        )
        print("[train_smoke] PASS")
    except BaseException:
        for name, proc in {**procs, "blitzen": blitzen}.items():
            if proc is not None:
                print(f"--- {name} tail ---\n{proc.tail()}")
        raise
    finally:
        for proc in list(procs.values()) + [blitzen]:
            if proc is not None and proc.popen.poll() is None:
                proc.popen.kill()


if __name__ == "__main__":
    main()
