"""SLO / regression gate over bench records (ISSUE 12 tentpole #3).

``bench.py`` has recorded the repo's whole perf trajectory for five
rounds — and nothing failed when the headline slid 69x -> 51x between
BENCH_r03 and BENCH_r05.  This gate is the tripwire: it diffs a fresh
bench record against the committed ``BENCH_r*.json`` trajectory with
per-metric thresholds and exits nonzero on regression.  Future BENCH
rounds must pass it (see DEVELOP.md "Profiling" / "Benchmarks").

    python scripts/bench_gate.py --record fresh.json          # gate it
    python scripts/bench_gate.py --self-test                  # CI step

Record inputs accepted, in order of preference:

- a driver-style ``BENCH_r*.json`` wrapper (``{"parsed": {...}}``);
- a raw bench JSON record (the dict ``bench.py`` prints);
- raw ``bench.py`` stdout (the LAST parseable JSON line wins — the
  progressive-emission convention).

Threshold file (``benchmarks/bench_thresholds.json``)::

    {
      "vs_baseline": {
        "direction": "higher",          # higher|lower is better
        "max_regression_frac": 0.20,    # tolerated fractional slide
        "reference": "latest",          # latest|best over the trajectory
        "required": false,              # fail when the fresh record
                                        # lacks the metric (only once the
                                        # trajectory has established it)
        "floor": 69.0                   # absolute bound EVERY fresh
      },                                # record must meet, regardless of
      ...                               # how far the trajectory slid
    }

Per metric: ``reference`` resolves against every committed BENCH round
(``latest`` = the newest record carrying the metric, ``best`` = the best
value ever recorded); the fresh value fails when it regresses past
``reference * (1 -/+ max_regression_frac)``.  Metrics the trajectory has
never carried pass vacuously — the fresh record establishes their
baseline.

``floor`` is the escape from ratchet decay: relative thresholds follow
the trajectory down (69x -> 51x passed five rounds of "within 20% of
latest"), a floor does not move.  Floors bind FRESH records only — they
are the target the next committed round must clear, not a retroactive
judgment of the trajectory (``--self-test`` evaluates the committed
trajectory with floors disabled, then separately proves a below-floor
record trips).  ``--self-test`` proves the gate's own teeth: the merged
latest trajectory record must PASS, a synthetically regressed copy
(every gated metric pushed to 2x its tolerated slide) must FAIL, and
every floored metric must FAIL a record pushed just past its floor.
"""

from __future__ import annotations

import argparse
import glob
import json
import re
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parents[1]
DEFAULT_THRESHOLDS = ROOT / "benchmarks" / "bench_thresholds.json"


def load_thresholds(path) -> dict:
    with open(path, encoding="utf-8") as fh:
        thresholds = json.load(fh)
    for metric, spec in thresholds.items():
        if spec.get("direction") not in ("higher", "lower"):
            raise ValueError(
                f"{metric}: direction must be 'higher' or 'lower'"
            )
        frac = spec.get("max_regression_frac")
        if not isinstance(frac, (int, float)) or frac < 0:
            raise ValueError(
                f"{metric}: max_regression_frac must be a number >= 0"
            )
        if spec.get("reference", "latest") not in ("latest", "best"):
            raise ValueError(
                f"{metric}: reference must be 'latest' or 'best'"
            )
        floor = spec.get("floor")
        if floor is not None and not isinstance(floor, (int, float)):
            raise ValueError(f"{metric}: floor must be a number")
    return thresholds


def trajectory_records(root=ROOT) -> list:
    """(round_name, parsed_record) for every committed BENCH_r*.json,
    oldest first."""
    out = []
    for path in sorted(
        glob.glob(str(root / "BENCH_r*.json")),
        key=lambda p: [int(t) for t in re.findall(r"\d+", Path(p).name)],
    ):
        try:
            with open(path, encoding="utf-8") as fh:
                doc = json.load(fh)
        except (OSError, json.JSONDecodeError):
            continue
        parsed = doc.get("parsed") if isinstance(doc, dict) else None
        if isinstance(parsed, dict):
            out.append((Path(path).stem, parsed))
    return out


def load_record(path) -> dict:
    """One fresh bench record from a wrapper / raw record / stdout."""
    text = Path(path).read_text(encoding="utf-8")
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        doc = None
    if isinstance(doc, dict):
        parsed = doc.get("parsed")
        if isinstance(parsed, dict):
            return parsed
        return doc
    # bench.py stdout: progressive emission re-prints supersets, so the
    # LAST parseable JSON line is the fullest record
    record = None
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            candidate = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(candidate, dict):
            record = candidate
    if record is None:
        raise ValueError(f"no bench record found in {path}")
    return record


def resolve_reference(metric: str, spec: dict, trajectory) -> tuple:
    """(reference_value, source_round) over the trajectory, or
    (None, None) when no committed round ever carried the metric."""
    carried = [
        (name, record[metric])
        for name, record in trajectory
        if isinstance(record.get(metric), (int, float))
    ]
    if not carried:
        return None, None
    if spec.get("reference", "latest") == "best":
        pick = (
            max(carried, key=lambda nv: nv[1])
            if spec["direction"] == "higher"
            else min(carried, key=lambda nv: nv[1])
        )
        return pick[1], pick[0]
    return carried[-1][1], carried[-1][0]


def bound_for(spec: dict, reference: float) -> float:
    frac = float(spec["max_regression_frac"])
    if spec["direction"] == "higher":
        return reference * (1.0 - frac)
    return reference * (1.0 + frac)


def gate(record: dict, thresholds: dict, trajectory,
         enforce_floors: bool = True) -> dict:
    """Evaluate every thresholded metric; returns the machine-readable
    verdict ({"ok": bool, "results": {metric: {...}}}).

    ``enforce_floors=False`` skips the absolute-floor checks — used by
    ``--self-test`` when judging the committed trajectory, where floors
    are forward-looking targets rather than retroactive failures."""
    results = {}
    ok = True
    for metric, spec in sorted(thresholds.items()):
        reference, source = resolve_reference(metric, spec, trajectory)
        fresh = record.get(metric)
        entry = {
            "direction": spec["direction"],
            "reference": reference,
            "reference_round": source,
            "fresh": fresh,
        }
        floor = spec.get("floor")
        if floor is not None:
            entry["floor"] = floor
        if (
            enforce_floors
            and floor is not None
            and isinstance(fresh, (int, float))
        ):
            below = (
                fresh < floor
                if spec["direction"] == "higher"
                else fresh > floor
            )
            if below:
                entry["verdict"] = "FAIL(floor)"
                ok = False
                results[metric] = entry
                continue
        if reference is None:
            # the trajectory never carried it: the fresh record (if it
            # has the metric) ESTABLISHES the baseline — by design a
            # brand-new metric cannot fail its first gate
            entry["verdict"] = (
                "baseline-established"
                if isinstance(fresh, (int, float))
                else "no-data"
            )
        elif not isinstance(fresh, (int, float)):
            if spec.get("required", False):
                entry["verdict"] = "FAIL(missing)"
                ok = False
            else:
                entry["verdict"] = "missing"
        else:
            bound = bound_for(spec, float(reference))
            entry["bound"] = bound
            regressed = (
                fresh < bound
                if spec["direction"] == "higher"
                else fresh > bound
            )
            if regressed:
                entry["verdict"] = "FAIL(regressed)"
                ok = False
            else:
                entry["verdict"] = "pass"
        results[metric] = entry
    return {"ok": ok, "results": results}


def _print_verdict(verdict: dict, file=sys.stdout) -> None:
    for metric, entry in verdict["results"].items():
        ref = entry["reference"]
        fresh = entry["fresh"]
        bound = entry.get("bound")
        parts = [
            f"{entry['verdict']:<22}",
            f"{metric:<44}",
            f"fresh={fresh if fresh is not None else '-'}",
            f"ref={ref if ref is not None else '-'}",
        ]
        if entry.get("reference_round"):
            parts.append(f"({entry['reference_round']})")
        if bound is not None:
            parts.append(f"bound={bound:.6g}")
        if entry.get("floor") is not None:
            parts.append(f"floor={entry['floor']:.6g}")
        print(" ".join(parts), file=file)
    print(
        ("BENCH GATE: PASS" if verdict["ok"] else "BENCH GATE: FAIL"),
        file=file,
    )


def self_test(thresholds: dict, trajectory) -> int:
    """The gate must pass the real trajectory, fail a synthetically
    regressed copy of it, and fail a below-floor record — proof it has
    teeth, runnable in CI with no fresh bench."""
    if not trajectory:
        print("bench_gate --self-test: no BENCH_r*.json trajectory found")
        return 1
    # merged latest record: per metric, the newest round's value — the
    # "real one" of the acceptance criterion.  Floors are disabled for
    # THIS check: a floor is the bar the next round must clear, and
    # raising one above the current trajectory (e.g. vs_baseline back
    # to the r03 69x) must not brick CI retroactively.
    merged: dict = {}
    for _, record in trajectory:
        for key, value in record.items():
            if isinstance(value, (int, float)):
                merged[key] = value
    verdict = gate(merged, thresholds, trajectory, enforce_floors=False)
    if not verdict["ok"]:
        print("self-test FAILED: the real trajectory record was rejected")
        _print_verdict(verdict)
        return 1

    regressed = dict(merged)
    gated = 0
    for metric, spec in thresholds.items():
        reference, _ = resolve_reference(metric, spec, trajectory)
        if reference is None:
            continue
        gated += 1
        frac = 2.0 * float(spec["max_regression_frac"]) + 0.01
        if spec["direction"] == "higher":
            regressed[metric] = reference * max(0.0, 1.0 - frac)
        else:
            regressed[metric] = reference * (1.0 + frac)
    if gated == 0:
        print("self-test FAILED: no metric had a trajectory reference")
        return 1
    verdict_bad = gate(regressed, thresholds, trajectory)
    failed = [
        m for m, e in verdict_bad["results"].items()
        if e["verdict"].startswith("FAIL")
    ]
    if verdict_bad["ok"] or len(failed) < gated:
        print(
            "self-test FAILED: the synthetically regressed record "
            f"passed ({len(failed)}/{gated} metrics tripped)"
        )
        _print_verdict(verdict_bad)
        return 1

    # floor teeth: for every floored metric, a record sitting just past
    # the floor (but otherwise healthy) must trip FAIL(floor)
    floored = {
        m: spec for m, spec in thresholds.items()
        if spec.get("floor") is not None
    }
    floor_trips = 0
    for metric, spec in floored.items():
        probe = dict(merged)
        nudge = 0.99 if spec["direction"] == "higher" else 1.01
        probe[metric] = float(spec["floor"]) * nudge
        entry = gate(probe, thresholds, trajectory)["results"][metric]
        if entry["verdict"] != "FAIL(floor)":
            print(
                f"self-test FAILED: {metric} below its floor "
                f"{spec['floor']} got verdict {entry['verdict']!r}"
            )
            return 1
        floor_trips += 1
    print(json.dumps({
        "self_test": "ok",
        "gated_metrics": gated,
        "tripped_on_synthetic_regression": len(failed),
        "floored_metrics": floor_trips,
        "passing_real_record_metrics": sorted(
            m for m, e in verdict["results"].items()
            if e["verdict"] == "pass"
        ),
    }))
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="bench_gate", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument(
        "--record", default=None,
        help="fresh bench record to gate (wrapper / raw record / "
        "bench.py stdout)",
    )
    parser.add_argument(
        "--thresholds", default=str(DEFAULT_THRESHOLDS),
        help=f"threshold file (default {DEFAULT_THRESHOLDS})",
    )
    parser.add_argument(
        "--baseline-dir", default=str(ROOT),
        help="directory holding the committed BENCH_r*.json trajectory",
    )
    parser.add_argument(
        "--self-test", action="store_true",
        help="prove the gate passes the real trajectory and fails a "
        "synthetic regression (CI step; no fresh record needed)",
    )
    parser.add_argument(
        "--json", action="store_true",
        help="print the machine-readable verdict instead of the table",
    )
    args = parser.parse_args(argv)

    thresholds = load_thresholds(args.thresholds)
    trajectory = trajectory_records(Path(args.baseline_dir))
    if args.self_test:
        return self_test(thresholds, trajectory)
    if not args.record:
        parser.error("--record is required (or use --self-test)")
    record = load_record(args.record)
    verdict = gate(record, thresholds, trajectory)
    if args.json:
        print(json.dumps(verdict))
    else:
        _print_verdict(verdict)
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
