"""CI load-smoke for the serving layer (`moose_tpu/serving/`).

Drives the in-process InferenceServer the way the blitzen daemon does:

1. LOW LOAD — 64 concurrent closed-loop client threads over a logreg
   predictor, generous deadlines.  Asserts: every request completes
   with the right answer, ZERO deadline misses, zero re-traces and zero
   ladder (validating) evaluations after warmup, and batch-fill metrics
   present in the telemetry snapshot.
2. OVERLOAD — the evaluation lock is held so the dispatcher stalls,
   then submissions continue until the bounded queue rejects one.
   Asserts the rejection is a typed ServerOverloadedError raised
   synchronously (never a hang: the whole phase runs under a watchdog
   budget), and that every admitted request still completes once the
   lock is released.

Prints one JSON summary line (the CI log artifact).

    JAX_PLATFORMS=cpu python scripts/serve_smoke.py
"""

import json
import os
import sys
import threading
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

# The smoke validates SCHEDULING semantics (coalescing, deadlines,
# backpressure, metrics) — eager execution keeps the CI step fast and
# deterministic; per-bucket compiled-plan performance is bench.py's
# concern on real hardware.
os.environ.setdefault("MOOSE_TPU_JIT", "0")

CLIENTS = 64
REQUESTS_PER_CLIENT = 4
FEATURES = 12


def build_logreg():
    from sklearn.linear_model import LogisticRegression

    from moose_tpu import predictors
    from moose_tpu.predictors.sklearn_export import (
        logistic_regression_onnx,
    )

    rng = np.random.default_rng(3)
    x = rng.normal(size=(96, FEATURES))
    y = (rng.uniform(size=96) > 0.5).astype(int)
    sk = LogisticRegression().fit(x, y)
    model = predictors.from_onnx(
        logistic_regression_onnx(sk, FEATURES).encode()
    )
    return model, sk


def low_load_phase(server, sk) -> dict:
    rng = np.random.default_rng(17)
    rows = rng.normal(size=(CLIENTS, REQUESTS_PER_CLIENT, FEATURES))
    errors = []
    max_err = [0.0]
    lock = threading.Lock()

    def client(ci: int):
        try:
            for ri in range(REQUESTS_PER_CLIENT):
                x = rows[ci, ri]
                got = server.predict(
                    "logreg", x, deadline_ms=120_000.0, timeout_s=300.0
                )
                want = sk.predict_proba(x[np.newaxis])
                err = float(np.abs(got - want).max())
                with lock:
                    max_err[0] = max(max_err[0], err)
        except Exception as e:  # noqa: BLE001 — collected + re-raised
            errors.append((ci, repr(e)))

    threads = [
        threading.Thread(target=client, args=(ci,))
        for ci in range(CLIENTS)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600)
    elapsed = time.perf_counter() - t0
    assert not errors, f"client failures: {errors[:5]}"
    assert max_err[0] < 5e-3, f"serving results diverged: {max_err[0]}"

    snap = server.metrics_snapshot()
    total = CLIENTS * REQUESTS_PER_CLIENT
    assert snap["rows_served"] == total, snap
    assert snap["deadline_misses"] == 0, snap
    assert snap["deadline_drops"] == 0, snap
    # the warm-registry promise: serving traffic NEVER re-traces or
    # lands on a validating (ladder) evaluation
    assert snap["retraces_after_warm"] == 0, snap
    assert snap["validating_after_warm"] == 0, snap
    # batch-fill telemetry must be present and sane
    assert snap["batch_fill_ratio"] is not None, snap
    assert 0.0 < snap["batch_fill_ratio"] <= 1.0, snap
    assert snap["batch_size_hist"], snap
    assert snap["request_latency_p99_s"] is not None, snap
    # 64 concurrent clients must coalesce: far fewer batches than rows
    assert snap["batches"] < total, snap
    return {
        "elapsed_s": elapsed,
        "requests_per_sec": total / elapsed,
        "batches": snap["batches"],
        "batch_fill_ratio": snap["batch_fill_ratio"],
        "p99_s": snap["request_latency_p99_s"],
    }


def overload_phase(server) -> dict:
    """The queue bound must REJECT (typed), not hang."""
    from moose_tpu.errors import ServerOverloadedError

    x = np.zeros(FEATURES)
    admitted = []
    rejected = 0
    budget_s = 30.0
    with server.registry.eval_lock:  # dispatcher stalls mid-batch
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < budget_s:
            try:
                admitted.append(
                    server.submit("logreg", x, deadline_ms=600_000.0)
                )
            except ServerOverloadedError:
                rejected += 1
                break
        assert rejected, (
            f"queue bound {server.config.queue_bound} never rejected "
            f"within {budget_s}s ({len(admitted)} admitted)"
        )
    for future in admitted:  # released: every admitted request completes
        future.result(timeout=300)
    snap = server.metrics_snapshot()
    assert snap["overloads"] >= 1, snap
    return {"admitted": len(admitted), "rejections": snap["overloads"]}


def main():
    from moose_tpu.serving import InferenceServer, ServingConfig

    model, sk = build_logreg()
    # queue_bound sits ABOVE the closed-loop in-flight ceiling (64
    # clients x 1 outstanding request each) so phase 1 is genuinely
    # low-load, while staying small enough that phase 2 hits the bound
    # (and drains) quickly
    config = ServingConfig.from_env(
        max_batch=32, max_wait_ms=4.0, queue_bound=96
    )
    t0 = time.perf_counter()
    with InferenceServer(config=config) as server:
        server.register_model("logreg", model, row_shape=(FEATURES,))
        register_s = time.perf_counter() - t0
        summary = {"register_s": register_s}
        summary["low_load"] = low_load_phase(server, sk)
        summary["overload"] = overload_phase(server)
    print(json.dumps(summary), flush=True)
    print("serve_smoke: OK", file=sys.stderr)


if __name__ == "__main__":
    main()
