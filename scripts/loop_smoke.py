"""CI continuous-training-loop smoke (ISSUE 18): 2 blitzen replicas
(--admin) + the donner router (--admin) + the in-process ControlPlane
driving a REAL resumable TrainingSession — train -> canary -> promote,
then train -> poisoned canary -> auto-rollback, all under sustained
open-loop multi-tenant load.

What it proves (the acceptance gates):

1. **The loop closes**: a TrainingSession generation (epoch 1) is
   staged onto every replica over the admin wire, canaried at 50%
   through donner's deterministic tenant hash buckets, watched against
   its SLOs, and PROMOTED — the base model flip is atomic and the new
   weights provably serve.
2. **Auto-rollback fires on a real SLO breach**: generation 2 (epoch 2,
   trained by the same resumable session) is poisoned via the replicas'
   chaos knob (every request to its serving name stalls past the p99
   SLO); the control plane detects the breach from donner's sliding
   per-generation window and rolls back — ``generation_rolled_back``
   flight event with ``reason == "latency"`` plus the
   ``moose_tpu_controlplane_*`` counters asserted from a Prometheus
   scrape.
3. **Zero dropped requests**: the open-loop tenant stream sees EVERY
   request end 2xx across staging, canary split installs, the promote
   flip, the poisoned canary, and the rollback flip.
4. **Last-good is bit-identical**: after the rollback, quiet-phase
   probes on every replica answer byte-identically to the promoted
   generation's quiet-phase probe (MOOSE_TPU_FIXED_KEYS).

MOOSE_TPU_JIT=0 like the other smokes: this validates loop SEMANTICS;
compiled-path promote/rollback timing is bench.py's concern.

    JAX_PLATFORMS=cpu python scripts/loop_smoke.py
"""

import json
import os
import re
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np

ROOT = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(ROOT))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["MOOSE_TPU_JIT"] = "0"
os.environ["MOOSE_TPU_FIXED_KEYS"] = "loop-smoke"
os.environ["MOOSE_TPU_ALLOW_WEAK_PRF"] = "1"

FEATURES = 4
PARTIES = ["alice", "bob", "carole"]
# eager CPU service time is ~2-3s/request, so the open loop must stay
# well under saturation or the GOOD generation breaches its own SLO
# from queueing alone (observed at 0.75 rps: p99 > 2.5s, queue-wait
# p99 ~4s)
REQUESTS_PER_SECOND = 0.3
CHAOS_DELAY_MS = 10_000.0  # poisoned generation: +10s per request
P99_SLO_S = 8.0  # strict canary SLO: above baseline noise, below chaos

ENV = {
    **os.environ,
    "MOOSE_TPU_SERVE_MAX_BATCH": "4",
    "MOOSE_TPU_SERVE_MAX_WAIT_MS": "5",
    "PYTHONPATH": str(ROOT),
    "PYTHONUNBUFFERED": "1",
}


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class Proc:
    """A replica/router subprocess with captured, greppable stdout."""

    def __init__(self, name, argv):
        self.name = name
        self.lines = []
        self._lock = threading.Lock()
        self.popen = subprocess.Popen(
            argv, env=ENV, cwd=ROOT, text=True,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        threading.Thread(target=self._pump, daemon=True).start()

    def _pump(self):
        for line in self.popen.stdout:
            with self._lock:
                self.lines.append(line.rstrip())

    def grep(self, pattern):
        with self._lock:
            for line in self.lines:
                m = re.search(pattern, line)
                if m:
                    return m
        return None

    def tail(self, n=15):
        with self._lock:
            return "\n".join(self.lines[-n:])


def wait_until(predicate, timeout_s, what):
    deadline = time.perf_counter() + timeout_s
    while time.perf_counter() < deadline:
        result = predicate()
        if result:
            return result
        time.sleep(0.25)
    raise AssertionError(f"timed out after {timeout_s}s waiting for {what}")


def http_get(url, timeout=10):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()
    except Exception:
        return None, b""


def http_post(url, payload, timeout=120, headers=None):
    request = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST",
    )
    try:
        with urllib.request.urlopen(request, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()
    except Exception as e:
        return None, type(e).__name__.encode()


def prom_value(text, name):
    value = None
    for line in text.splitlines():
        if line.startswith(name) and not line.startswith("#"):
            value = float(line.rsplit(" ", 1)[1])
    return value


def main():
    # heavyweight imports AFTER env pinning
    from moose_tpu import flight
    from moose_tpu import metrics as metrics_mod
    from moose_tpu.bin.donner import _assign_generation
    from moose_tpu.predictors.trainers import LogregSGDTrainer
    from moose_tpu.runtime import LocalMooseRuntime
    from moose_tpu.serving import (
        CanaryConfig,
        ControlPlane,
        HttpFleetClient,
        SessionGenerationProducer,
    )
    from moose_tpu.storage import FilesystemStorage
    from moose_tpu.training import (
        CheckpointStore,
        TrainingConfig,
        TrainingSession,
    )
    from moose_tpu.training.export import logreg_onnx_bytes

    rng = np.random.default_rng(18)
    workdir = Path(tempfile.mkdtemp(prefix="loop_smoke_"))
    onnx_path = workdir / "base.onnx"
    onnx_path.write_bytes(
        logreg_onnx_bytes(rng.normal(size=(FEATURES, 1)) * 0.5)
    )
    snapshot_dir = workdir / "snapshots"

    # the long-lived training session: 3 parties, durable secret-shared
    # checkpoints, in THIS process (the control-plane process)
    stores = {
        p: CheckpointStore(
            FilesystemStorage(str(workdir / "ckpt" / p)),
            party=p, retain=2,
        )
        for p in PARTIES
    }
    runtime = LocalMooseRuntime(
        identities=PARTIES, storage_mapping=stores, use_jit=False
    )
    from moose_tpu.training.session import LocalTrainingCluster

    x_train = rng.normal(size=(8, FEATURES)) * 0.5
    y_train = (rng.uniform(size=(8, 1)) > 0.5).astype(np.float64)
    session = TrainingSession(
        LogregSGDTrainer(n_features=FEATURES, learning_rate=0.1),
        LocalTrainingCluster(runtime, PARTIES),
        TrainingConfig(epochs=1),
    )
    producer = SessionGenerationProducer(
        session, x_train, y_train, epochs_per_generation=1
    )

    ports = {"a": free_port(), "b": free_port()}
    bases = {k: f"http://127.0.0.1:{p}" for k, p in ports.items()}
    procs = {}
    summary = {}
    stop_load = threading.Event()
    outcomes = []
    outcomes_lock = threading.Lock()
    t_all = time.perf_counter()

    # 2 base-bucket + 2 canary-bucket tenants ('base' sorts first, so
    # [0, 0.5) of the hash ring is base at every 50/50 split)
    probe_split = {"base": 0.5, "zzz": 0.5}
    base_tenants = [
        t for t in (f"tenant-{i}" for i in range(10_000))
        if _assign_generation("m", t, probe_split) == "base"
    ][:2]
    canary_tenants = [
        t for t in (f"tenant-{i}" for i in range(10_000))
        if _assign_generation("m", t, probe_split) != "base"
    ][:2]
    tenants = base_tenants + canary_tenants

    try:
        # ---- phase 1: the fleet comes up (A fresh, B from snapshot)
        t0 = time.perf_counter()
        procs["a"] = Proc("a", [
            sys.executable, "-m", "moose_tpu.bin.blitzen",
            f"m={onnx_path}", "--features", f"m={FEATURES}",
            "--host", "127.0.0.1", "--port", str(ports["a"]),
            "--snapshot-dir", str(snapshot_dir),
            "--drain-timeout-s", "60", "--admin",
        ])
        wait_until(
            lambda: http_get(bases["a"] + "/readyz")[0] == 200,
            600, "replica a ready",
        )
        summary["fresh_register_s"] = time.perf_counter() - t0
        procs["b"] = Proc("b", [
            sys.executable, "-m", "moose_tpu.bin.blitzen",
            f"m={onnx_path}", "--features", f"m={FEATURES}",
            "--host", "127.0.0.1", "--port", str(ports["b"]),
            "--snapshot-dir", str(snapshot_dir),
            "--drain-timeout-s", "60", "--admin",
        ])
        wait_until(
            lambda: http_get(bases["b"] + "/readyz")[0] == 200,
            600, "replica b ready",
        )

        procs["donner"] = Proc("donner", [
            sys.executable, "-m", "moose_tpu.bin.donner",
            "--replica", bases["a"], "--replica", bases["b"],
            "--host", "127.0.0.1", "--port", "0",
            "--probe-interval-ms", "200", "--retries", "6", "--admin",
        ])
        m = wait_until(
            lambda: procs["donner"].grep(
                r"donner: routing .* on http://127\.0\.0\.1:(\d+)"
            ),
            30, "donner startup banner",
        )
        donner = f"http://127.0.0.1:{m.group(1)}"
        wait_until(
            lambda: http_get(donner + "/readyz")[0] == 200,
            30, "donner ready",
        )

        client = HttpFleetClient(
            donner, [bases["a"], bases["b"]], timeout_s=600.0
        )
        # two planes over the SAME producer/fleet: the good plane gets
        # a latency SLO the eager CPU path can actually meet; the
        # strict plane is the one the poisoned generation must breach
        plane_good = ControlPlane(client, "m", CanaryConfig(
            fraction=0.5, watch_s=3.0, min_requests=2,
            p99_slo_s=60.0, error_rate_slo=0.5, poll_s=0.25,
            timeout_s=600.0, cost_drift_max=1000,
        ))
        plane_strict = ControlPlane(client, "m", CanaryConfig(
            fraction=0.5, watch_s=3.0, min_requests=2,
            p99_slo_s=P99_SLO_S, error_rate_slo=0.5, poll_s=0.25,
            timeout_s=600.0, cost_drift_max=1000,
        ))

        def probe(base_url):
            status, body = http_post(
                base_url + "/v1/models/m:predict",
                {"x": [[0.25, -0.1, 0.3, 0.05]]},
            )
            assert status == 200, (base_url, status, body)
            return body

        y_seed = probe(bases["a"])
        assert probe(bases["b"]) == y_seed, "fleet disagrees at start"

        # ---- open-loop load: requests fire on the clock across the
        # tenant ring; missed ticks are dropped, never replayed
        def one_request(i, tenant):
            t = time.perf_counter()
            status, body = http_post(
                donner + "/v1/models/m:predict",
                {"x": [[0.1, 0.2, -0.3, 0.4]]},
                timeout=120, headers={"X-Moose-Tenant": tenant},
            )
            with outcomes_lock:
                outcomes.append({
                    "i": i, "tenant": tenant, "status": status,
                    "latency_s": time.perf_counter() - t,
                    "body": body[:120].decode(errors="replace"),
                })

        def open_loop():
            i = 0
            period = 1.0 / REQUESTS_PER_SECOND
            next_t = time.perf_counter()
            while not stop_load.is_set():
                threading.Thread(
                    target=one_request,
                    args=(i, tenants[i % len(tenants)]), daemon=True,
                ).start()
                i += 1
                next_t = max(next_t + period, time.perf_counter())
                time.sleep(max(0.0, next_t - time.perf_counter()))

        loader = threading.Thread(target=open_loop, daemon=True)
        loader.start()

        # ---- phase 2: train generation 1 -> canary -> PROMOTE
        t0 = time.perf_counter()
        report1 = plane_good.run_loop(producer, generations=1)[0]
        summary["generation1_s"] = time.perf_counter() - t0
        assert report1["promoted"], report1
        assert report1["generation"] == "g0001", report1
        summary["promote_s"] = report1["promote_s"]
        assert session.last_report["final_epoch"] == 1

        # ---- phase 3: poison generation 2, train it -> AUTO-ROLLBACK
        for base_url in bases.values():
            status, body = http_post(
                base_url + "/admin/chaos",
                {"match": "@g0002", "delay_ms": CHAOS_DELAY_MS},
            )
            assert status == 200, (base_url, body)
        t0 = time.perf_counter()
        report2 = plane_strict.run_loop(producer, generations=1)[0]
        summary["generation2_s"] = time.perf_counter() - t0
        assert not report2["promoted"], report2
        assert report2["generation"] == "g0002", report2
        assert report2["reason"] == "latency", report2
        assert report2["observed"]["p99_s"] > P99_SLO_S, report2
        summary["rollback_s"] = report2["rollback_s"]
        assert session.last_report["final_epoch"] == 2

        # ---- phase 4: stop the load, settle, judge
        stop_load.set()
        loader.join(timeout=10)

        def settled():
            with outcomes_lock:
                count = len(outcomes)
            time.sleep(2.0)
            with outcomes_lock:
                if len(outcomes) != count:
                    return False
            fleet = json.loads(http_get(donner + "/fleet")[1])
            return all(
                r["in_flight"] == 0 for r in fleet["replicas"]
            )

        wait_until(settled, 180, "open-loop stragglers to land")

        with outcomes_lock:
            done = list(outcomes)
        total = len(done)
        non_2xx = [o for o in done if o["status"] != 200]
        assert total >= 10, f"open loop under-delivered: {total}"
        assert not non_2xx, (
            f"{len(non_2xx)}/{total} requests dropped "
            f"(first: {non_2xx[:5]})"
        )

        # last-good is bit-identical on every replica: the fleet serves
        # the PROMOTED generation-1 weights, not the seed, not g0002
        y_good = probe(bases["a"])
        assert probe(bases["b"]) == y_good, "fleet disagrees after loop"
        assert y_good != y_seed, "generation 1 never actually served"

        # route table clean, staging names retired
        fleet_view = json.loads(http_get(donner + "/fleet")[1])
        assert not fleet_view["routes"].get("m", {}).get("weights")
        for base_url in bases.values():
            status, body = http_post(
                base_url + "/v1/models/m@g0002:predict",
                {"x": [[0.0, 0.0, 0.0, 0.0]]},
            )
            assert status == 404, (base_url, status, body)
            assert json.loads(body)["error"] == "ModelNotFoundError"

        # the WHY, from the flight recorder and a Prometheus scrape of
        # the control-plane process
        events = flight.get_recorder().events(party="controlplane")
        kinds = {
            (e["kind"], e.get("generation")) for e in events
        }
        assert ("generation_promoted", "g0001") in kinds, kinds
        assert ("generation_rolled_back", "g0002") in kinds, kinds
        rolled = [
            e for e in events
            if e["kind"] == "generation_rolled_back"
        ][-1]
        assert rolled["reason"] == "latency", rolled
        scrape = metrics_mod.render_prometheus()
        assert prom_value(
            scrape,
            'moose_tpu_controlplane_generations_total{'
            'outcome="promoted"}',
        ) == 1.0, "promoted counter missing from scrape"
        assert prom_value(
            scrape,
            'moose_tpu_controlplane_generations_total{'
            'outcome="rolled_back"}',
        ) == 1.0, "rolled_back counter missing from scrape"
        assert prom_value(
            scrape,
            'moose_tpu_controlplane_slo_breaches_total{'
            'reason="latency"}',
        ) == 1.0, "breach counter missing from scrape"
        # ... and donner's per-generation accounting on ITS scrape
        donner_prom = http_get(donner + "/metrics")[1].decode()
        assert "moose_tpu_donner_generation_requests_total" in (
            donner_prom
        ), "per-generation request counter missing from donner scrape"

        latencies = sorted(o["latency_s"] for o in done)
        summary.update({
            "requests": total,
            "dropped": 0,
            "generations": 2,
            "promoted": 1,
            "rolled_back": 1,
            "resumes": session.last_report["resumes"],
            "p50_s": latencies[len(latencies) // 2],
            "p99_s": latencies[min(
                len(latencies) - 1, int(len(latencies) * 0.99)
            )],
            "elapsed_s": time.perf_counter() - t_all,
        })
        print("LOOP_SMOKE_OK " + json.dumps(summary))
    except BaseException:
        for name, proc in procs.items():
            print(f"---- {name} tail ----\n{proc.tail()}", flush=True)
        raise
    finally:
        stop_load.set()
        for proc in procs.values():
            if proc.popen.poll() is None:
                proc.popen.kill()


if __name__ == "__main__":
    main()
