"""Scratch probe: compare u128 limb-matmul inner-loop variants on TPU.

Variants (single (n,n) x (n,n) u128 contraction, 16 centered int8 limbs):
  pairs     per-pair dot_generals, s32 diagonal accumulation (the r3 path)
  slab      one dot_general per diagonal over concat slices (unpadded)
  slab_pad  same, with k padded to a multiple of 512 so slices are aligned
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import moose_tpu  # noqa: F401
import jax
import jax.numpy as jnp

n = int(sys.argv[1]) if len(sys.argv) > 1 else 1000
rng = np.random.default_rng(0)
a = rng.integers(0, 1 << 64, size=(n, n), dtype=np.uint64)
b = rng.integers(0, 1 << 64, size=(n, n), dtype=np.uint64)


def limbs(x):
    return [
        (((x >> np.uint64(8 * i)) & np.uint64(0xFF)).astype(jnp.int32) - 128)
        .astype(jnp.int8)
        for i in range(8)
    ]


def diags_pairs(la, lb, k):
    ra = [jnp.sum(x.astype(jnp.int32), axis=-1) for x in la]
    cb = [jnp.sum(x.astype(jnp.int32), axis=0) for x in lb]
    L = len(la)
    out = []
    for s in range(L):
        ps = None
        for i in range(min(s + 1, L)):
            j = s - i
            p = jax.lax.dot_general(
                la[i], lb[j], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )
            p = p + (
                jnp.int32(128) * (ra[i][:, None] + cb[j][None, :])
                + jnp.int32(128 * 128 * k)
            )
            ps = p if ps is None else ps + p
        out.append(ps.astype(jnp.int64).astype(jnp.uint64))
    return out


def diags_slab(la, lb, k, pad_to=0):
    ra = [jnp.sum(x.astype(jnp.int32), axis=-1) for x in la]
    cb = [jnp.sum(x.astype(jnp.int32), axis=0) for x in lb]
    L = len(la)
    kp = k if not pad_to else -(-k // pad_to) * pad_to
    if kp != k:
        la = [jnp.pad(x, ((0, 0), (0, kp - k))) for x in la]
        lb = [jnp.pad(x, ((0, kp - k), (0, 0))) for x in lb]
    afull = jnp.concatenate(la, axis=-1)
    brev = jnp.concatenate(lb[::-1], axis=0)
    out = []
    for s in range(L):
        i0, i1 = max(0, s - (L - 1)), min(s, L - 1)
        npairs = i1 - i0 + 1
        a_sl = afull[:, i0 * kp:(i1 + 1) * kp]
        b0 = (L - 1 - s + i0) * kp
        b_sl = brev[b0:b0 + npairs * kp, :]
        ps = jax.lax.dot_general(
            a_sl, b_sl, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        tra = sum(ra[i] for i in range(i0, i1 + 1))
        tcb = sum(cb[s - i] for i in range(i0, i1 + 1))
        ps = ps + (
            jnp.int32(128) * (tra[:, None] + tcb[None, :])
            + jnp.int32(128 * 128 * k * npairs)
        )
        out.append(ps.astype(jnp.int64).astype(jnp.uint64))
    return out


def recombine(diags):
    acc = jnp.zeros_like(diags[0])
    for s, d in enumerate(diags):
        acc = acc + (d << np.uint64(8 * s))
    return acc


da, db = None, None


def run(name, fn):
    global da, db
    if da is None:
        da, db = jax.device_put(a), jax.device_put(b)
    f = jax.jit(fn)
    out = jax.block_until_ready(f(da, db))
    ref = (a.astype(object) @ b.astype(object)) % (1 << 64) if n <= 256 else None
    if ref is not None:
        assert np.array_equal(np.asarray(out), ref.astype(np.uint64)), name
    g = jax.jit(lambda x, y: jnp.sum(fn(x, y)))
    float(g(da, db))  # compile + warm
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(50):
            s = g(da, db)
        float(s)  # scalar readback forces true execution on the tunnel
        times.append((time.perf_counter() - t0) / 50)
    print(f"{name}: {min(times)*1e3:.3f} ms")


run("pairs    ", lambda x, y: recombine(diags_pairs(limbs(x), limbs(y), n)))
run("slab     ", lambda x, y: recombine(diags_slab(limbs(x), limbs(y), n)))
run("slab_512 ", lambda x, y: recombine(diags_slab(limbs(x), limbs(y), n, 512)))
run("slab_128 ", lambda x, y: recombine(diags_slab(limbs(x), limbs(y), n, 128)))
