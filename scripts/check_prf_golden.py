"""Verify crypto/aes_prng.py bit-for-bit against Rust-extracted golden
vectors (the output of ``scripts/extract_prf_golden.rs`` run on any
machine with a cargo toolchain — see that file's header).

    python scripts/check_prf_golden.py prf_golden_rust.json

On full agreement this CLOSES the BASELINE "bit-identical outputs"
claim.  On a mismatch it pins down WHICH consumption rule diverges
(word order, bit granularity, counter layout) so the fix is mechanical:

- ``next_u64`` mismatch at index 0 → counter/endianness of the CTR
  keystream itself (crypto/aes_prng.py:_refill).
- ``next_u64`` ok but ``bits`` mismatch → get_bit granularity: this
  repo consumes one keystream BYTE per bit draw (aes_prng.get_bit); if
  the crate consumes a u32 per draw, patch get_bit accordingly.
- ``ring128_hi_first`` mismatch with next_u64 ok → limb draw order
  (uniform_u128 swaps high/low).
- ``derive_seed`` mismatch → blake3 layer (crypto/blake3.py) or the
  session-id hashing rule (host/prim.rs SessionId::as_bytes).
"""

import json
import sys

sys.path.insert(0, ".")

from moose_tpu.crypto.aes_prng import AesCtrRng, derive_seed  # noqa: E402
from moose_tpu.crypto.blake3 import derive_key, keyed_hash  # noqa: E402


def main(path: str) -> int:
    golden = json.load(open(path))
    seed = bytes.fromhex(golden["seed"])
    failures = []

    rng = AesCtrRng(seed)
    got = [rng.next_u64() for _ in range(len(golden["next_u64"]))]
    want = [int(v) for v in golden["next_u64"]]
    if got != want:
        i = next(i for i, (a, b) in enumerate(zip(got, want)) if a != b)
        failures.append(
            f"next_u64 diverges at index {i}: got {got[i]}, want {want[i]}"
            + (" (keystream/counter layout)" if i == 0 else "")
        )

    rng = AesCtrRng(seed)
    got = [
        (rng.next_u64() << 64) + rng.next_u64()
        for _ in range(len(golden["ring128_hi_first"]))
    ]
    want = [int(v) for v in golden["ring128_hi_first"]]
    if got != want:
        failures.append("ring128 high-limb-first order diverges")

    rng = AesCtrRng(seed)
    got = [rng.get_bit() for _ in range(len(golden["bits"]))]
    if got != list(golden["bits"]):
        failures.append(
            "get_bit stream diverges (bit-draw granularity: this repo "
            "burns one keystream byte per bit)"
        )

    rng = AesCtrRng(seed)
    got = rng.next_bytes(len(golden["fill_bytes"]) // 2).hex()
    if got != golden["fill_bytes"]:
        failures.append("fill_bytes stream diverges")

    ds = golden["derive_seed"]
    # raw 16-byte sid (the Rust extractor feeds sid BYTES directly;
    # derive_seed() in-repo hashes the sid STRING per SessionId::new —
    # compare at the keyed-hash layer to isolate the blake3 chain)
    derived = derive_key("Derive Seed", bytes.fromhex(ds["key"]))
    got = keyed_hash(
        derived,
        bytes.fromhex(ds["sid"]) + bytes.fromhex(ds["sync_key"]),
        out_len=16,
    ).hex()
    if got != ds["seed_out"]:
        failures.append("derive_seed blake3 chain diverges")

    if failures:
        print("PRF GOLDEN MISMATCH:")
        for f in failures:
            print(" -", f)
        return 1
    print(
        "PRF golden vectors match bit-for-bit: next_u64, ring128 limb "
        "order, get_bit, fill_bytes, derive_seed — BASELINE bit-identity "
        "claim closed."
    )
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1]))
