"""CI fabric transport smoke (ISSUE 19 acceptance): a 3-party logreg
SGD training step runs over 3 in-process workers on 127.0.0.1 gRPC
ports whose parties opt into ONE FabricDomain — every inter-party value
moves as a collective permute over the shared (CPU virtual-device)
mesh, the gRPC path staying as the trust-boundary fallback.

Asserts:

1. **zero gRPC sends intra-fabric**: across two fabric sessions (cold +
   warm) the ``moose_tpu_net_sends_total{transport="grpc"}`` counter
   never moves — every inter-party edge of the training step lowered
   to a permute;
2. **bit-identity**: the fabric epoch's revealed weights equal the
   plain gRPC cluster's BIT-exactly under ``MOOSE_TPU_FIXED_KEYS`` (the
   fabric moves the very tensors the wire would have serialized);
3. **predicted == measured EXACTLY**: the warm session's fabric counter
   deltas (permutes, batched permutes, permute payloads, device bytes,
   singleton sends) equal the MSA6xx cost model's fabric prediction —
   the analyzer can never silently drift from the runtime;
4. **mixed sessions green**: with carole OUTSIDE the trust domain, the
   session completes bit-identically, exactly the crossing edges fall
   back to gRPC (``trust_boundary`` fallback tally equals the model's
   per-party ``fallback_sends``), and the session report says
   ``transport: mixed``;
5. the session report carries ``transport``/``trust_model`` for bench
   rows and postmortems.

Prints one JSON summary line (the CI log artifact).

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python scripts/fabric_smoke.py
"""

import json
import os
import sys
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# the fabric needs one lead device per party: 8 virtual CPU devices
# unless the caller already forced a device count
if "xla_force_host_platform_device_count" not in os.environ.get(
    "XLA_FLAGS", ""
):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
# replicated truncation noise is share-dependent: bit-exact
# cross-CLUSTER comparisons need the session PRF keys pinned (a
# testing knob — it voids inter-party secrecy, hence the explicit
# weak-PRF acknowledgement; this smoke is one process anyway)
os.environ.setdefault("MOOSE_TPU_FIXED_KEYS", "fabric-smoke")
os.environ.setdefault("MOOSE_TPU_ALLOW_WEAK_PRF", "1")
os.environ.setdefault("MOOSE_TPU_PRF", "threefry")
# pin the worker to eager numerics: the jit plan ladder warms across
# sessions (validating -> jit), and the two modes differ by one
# fixed(14,23) LSB — bit-identity across clusters needs ONE mode.
# Eager workers issue singleton sends, so predictions use
# ``coalesce=False`` below.
os.environ.setdefault("MOOSE_TPU_JIT", "0")

IDENTITIES = ["alice", "bob", "carole"]


def build_traced():
    """One logreg SGD step (the ISSUE 19 acceptance workload: a
    training epoch's worth of per-step cross-party traffic)."""
    from moose_tpu.predictors.trainers import LogregSGDTrainer

    trainer = LogregSGDTrainer(n_features=2, steps_per_epoch=1)
    return trainer.step_computation(4)


def run_session(traced, args, fabric_domain=None, session_tag=""):
    """One client-supervised session over a fresh in-process gRPC
    cluster; returns (outputs, last_session_report)."""
    from moose_tpu.dialects import host as host_dialect
    from moose_tpu.distributed.choreography import start_local_cluster
    from moose_tpu.distributed.client import GrpcClientRuntime

    servers, endpoints = start_local_cluster(
        IDENTITIES, receive_timeout=30.0, startup_grace=10.0,
        fabric_domain=fabric_domain,
    )
    try:
        runtime = GrpcClientRuntime(endpoints, max_attempts=1)
        # each compile draws fresh seed-derivation nonces, and
        # replicated truncation noise is mask-dependent: bit-exact
        # cross-CLUSTER comparisons need the same nonce sequence in
        # every compilation
        with host_dialect.deterministic_sync_keys(1234):
            outputs, _ = runtime.run_computation(
                traced, args, timeout=120.0
            )
        return outputs, runtime.last_session_report
    finally:
        for srv in servers.values():
            srv.stop()


def metric(name, **labels):
    from moose_tpu import metrics

    return metrics.REGISTRY.value(name, **labels)


def main() -> int:
    from moose_tpu.compilation import DEFAULT_PASSES, compile_computation
    from moose_tpu.compilation.analysis.cost import cost_report
    from moose_tpu.compilation.lowering import arg_specs_from_arguments
    from moose_tpu.distributed.fabric import FabricDomain

    summary = {}
    rng = np.random.default_rng(0)
    args = {
        "x": rng.normal(size=(4, 2)),
        "y": (rng.random(size=(4, 1)) > 0.5).astype(np.float64),
        "w": np.zeros((2, 1)),
    }
    traced = build_traced()

    # ---- baseline: plain gRPC cluster --------------------------------
    base_out, base_report = run_session(traced, args)
    assert base_report["ok"], base_report
    assert base_report["transport"] == "grpc", base_report["transport"]
    summary["grpc_ok"] = True

    # ---- one FabricDomain: cold + warm, zero gRPC intra-fabric -------
    domain = FabricDomain.default(IDENTITIES, trust_model="simulation")
    fabric_counters = {
        "sends": lambda: metric(
            "moose_tpu_net_sends_total", transport="fabric"
        ),
        "fabric_permutes": lambda: metric(
            "moose_tpu_fabric_permutes_total"
        ),
        "fabric_batched_permutes": lambda: metric(
            "moose_tpu_fabric_batched_permutes_total"
        ),
        "fabric_permute_payloads": lambda: metric(
            "moose_tpu_fabric_permute_payloads_total"
        ),
        "fabric_tx_bytes": lambda: metric(
            "moose_tpu_fabric_tx_bytes_total"
        ),
    }
    grpc_before = metric("moose_tpu_net_sends_total", transport="grpc")
    fab_out, fab_report = run_session(
        traced, args, fabric_domain=domain
    )  # cold: compiles every (edge, shape-set) permute program
    before = {k: f() for k, f in fabric_counters.items()}
    warm_out, warm_report = run_session(
        traced, args, fabric_domain=domain
    )
    measured = {
        k: int(f() - before[k]) for k, f in fabric_counters.items()
    }
    grpc_sends = int(
        metric("moose_tpu_net_sends_total", transport="grpc")
        - grpc_before
    )
    assert grpc_sends == 0, (
        f"{grpc_sends} gRPC sends leaked out of the fabric"
    )
    assert measured["fabric_permutes"] > 0, measured
    assert warm_report["transport"] == "fabric", warm_report
    assert warm_report["trust_model"] == "simulation"
    summary["grpc_sends_intra_fabric"] = grpc_sends
    summary["warm_measured"] = measured

    # bit-identity: fabric vs wire under pinned keys
    for name in base_out:
        if not np.array_equal(
            np.asarray(base_out[name]), np.asarray(warm_out[name])
        ):
            raise AssertionError(
                f"fabric output {name} diverged from the gRPC run"
            )
    summary["bit_identical"] = True

    # predicted == measured EXACTLY (the workers compile the same
    # computation bytes with the same passes: the client-side compile
    # reproduces their rendezvous schedule)
    compiled = compile_computation(
        traced, DEFAULT_PASSES,
        arg_specs=arg_specs_from_arguments(args),
    )
    session_id = warm_report["attempts"][-1]["session_id"]
    report = cost_report(
        compiled, session_id=session_id, transport="fabric",
        fabric_parties=tuple(IDENTITIES), coalesce=False,
    )
    assert report["resolved"], report
    predicted = {k: int(report["totals"][k]) for k in measured}
    assert measured == predicted, (measured, predicted)
    assert report["totals"]["fallback_sends"] == 0
    summary["predicted"] = predicted
    summary["exact_match"] = True

    # ---- mixed session: carole outside the trust domain --------------
    mixed_domain = FabricDomain.default(
        ["alice", "bob"], trust_model="colocated_tee"
    )
    crossing_before = metric(
        "moose_tpu_fabric_fallbacks_total", reason="trust_boundary"
    )
    mixed_out, mixed_report = run_session(
        traced, args, fabric_domain=mixed_domain
    )
    crossed = int(
        metric(
            "moose_tpu_fabric_fallbacks_total", reason="trust_boundary"
        )
        - crossing_before
    )
    assert mixed_report["ok"], mixed_report
    assert mixed_report["transport"] == "mixed", mixed_report
    for name in base_out:
        if not np.array_equal(
            np.asarray(base_out[name]), np.asarray(mixed_out[name])
        ):
            raise AssertionError(
                f"mixed output {name} diverged from the gRPC run"
            )
    mixed_cost = cost_report(
        compiled,
        session_id=mixed_report["attempts"][-1]["session_id"],
        transport="fabric", fabric_parties=("alice", "bob"),
        coalesce=False,
    )
    predicted_crossing = sum(
        mixed_cost["per_party"][p]["fallback_sends"]
        for p in ("alice", "bob")
    )
    assert crossed == predicted_crossing, (crossed, predicted_crossing)
    summary["mixed_ok"] = True
    summary["mixed_crossing_sends"] = crossed
    summary["transports"] = mixed_report["transports"]

    print(json.dumps({"fabric_smoke": summary}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
