"""Verification driver: user-style flows on the real TPU backend."""
import time

import numpy as np

import moose_tpu as pm
from moose_tpu.runtime import LocalMooseRuntime

import jax

print("backend:", jax.default_backend(), jax.devices(), flush=True)

alice = pm.host_placement("alice")
bob = pm.host_placement("bob")
carole = pm.host_placement("carole")
rep = pm.replicated_placement("rep", players=[alice, bob, carole])

# -- Flow 1: secure dot (ring64 and ring128) via the user entrypoint, jitted
# ring64 needs 2*(i+f) + 10 (accumulation headroom) <= 61 (dtypes.fixed)
for prec, label in [((8, 17), "ring64"), ((24, 40), "ring128")]:
    fx = pm.fixed(*prec)
    assert (label == "ring64") == (fx.name == "fixed64"), (label, fx.name)

    @pm.computation
    def dot_comp(
        x: pm.Argument(placement=alice, dtype=pm.float64),
        w: pm.Argument(placement=bob, dtype=pm.float64),
    ):
        with alice:
            xf = pm.cast(x, dtype=fx)
        with bob:
            wf = pm.cast(w, dtype=fx)
        with rep:
            y = pm.dot(xf, wf)
        with carole:
            out = pm.cast(y, dtype=pm.float64)
        return out

    rng = np.random.default_rng(7)
    x = rng.normal(size=(16, 8))
    w = rng.normal(size=(8, 4))
    rt = LocalMooseRuntime(["alice", "bob", "carole"], use_jit=True)
    t0 = time.time()
    (got,) = rt.evaluate_computation(
        dot_comp, arguments={"x": x, "w": w}
    ).values()
    t1 = time.time()
    (got2,) = rt.evaluate_computation(
        dot_comp, arguments={"x": x, "w": w}
    ).values()
    t2 = time.time()
    err = np.abs(got - x @ w).max()
    print(
        f"dot {label}: err={err:.2e} first={t1 - t0:.1f}s cached={t2 - t1:.3f}s",
        flush=True,
    )
    assert err < 1e-4, err

# -- Flow 2: secure comparison + mux, jitted on TPU
fx = pm.fixed(8, 20)


@pm.computation
def relu_comp(x: pm.Argument(placement=alice, dtype=pm.float64)):
    with alice:
        xf = pm.cast(x, dtype=fx)
    with rep:
        y = pm.relu(xf)
    with alice:
        out = pm.cast(y, dtype=pm.float64)
    return out


x = np.array([[-1.5, 2.25], [0.0, -0.125]])
rt = LocalMooseRuntime(["alice", "bob", "carole"], use_jit=True)
(got,) = rt.evaluate_computation(relu_comp, arguments={"x": x}).values()
err = np.abs(got - np.maximum(x, 0)).max()
print("relu (msb+mux) jit: err", err, flush=True)
assert err < 1e-5

# -- Flow 3: full logreg inference (dot+sigmoid) eagerly on TPU
fx = pm.fixed(8, 27)


@pm.computation
def logreg(
    x_uri: pm.Argument(placement=alice, vtype=pm.StringType()),
    w: pm.Argument(placement=bob, dtype=pm.float64),
):
    with alice:
        x = pm.load(x_uri, dtype=pm.float64)
        xf = pm.cast(x, dtype=fx)
    with bob:
        wf = pm.cast(w, dtype=fx)
    with rep:
        y = pm.sigmoid(pm.dot(xf, wf))
    with carole:
        out = pm.cast(y, dtype=pm.float64)
        res = pm.save("pred", out)
    return res


rng = np.random.default_rng(3)
x = rng.normal(size=(32, 10)) * 0.4
w = rng.normal(size=(10,)) * 0.4
rt = LocalMooseRuntime(
    ["alice", "bob", "carole"],
    storage_mapping={"alice": {"xs": x}},
    use_jit=False,
)
t0 = time.time()
rt.evaluate_computation(logreg, arguments={"x_uri": "xs", "w": w})
got = rt.read_value_from_storage("carole", "pred")
want = 1 / (1 + np.exp(-(x @ w)))
err = np.abs(got - want).max()
print(f"logreg eager TPU: err={err:.2e} time={time.time() - t0:.1f}s", flush=True)
assert err < 1e-2

# -- Edge probes: scalar and values near the trunc bound
@pm.computation
def square(x: pm.Argument(placement=alice, dtype=pm.float64)):
    with alice:
        xf = pm.cast(x, dtype=pm.fixed(8, 20))
    with rep:
        y = pm.mul(xf, xf)
    with alice:
        return pm.cast(y, dtype=pm.float64)


rt = LocalMooseRuntime(["alice", "bob", "carole"], use_jit=True)
(got,) = rt.evaluate_computation(square, arguments={"x": np.float64(3.5)}).values()
assert abs(got - 12.25) < 1e-4, got
print("scalar mul:", got, flush=True)

big = np.array([100.0, -100.0, 127.0])  # near 2^(i_p-1) = 128 bound
(got,) = rt.evaluate_computation(square, arguments={"x": big}).values()
print("near-bound square (wraps expected beyond 2^7):", got, flush=True)

print("ALL VERIFY FLOWS PASSED", flush=True)
