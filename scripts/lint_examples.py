"""Lint the shipped example/tutorial computations with prancer (the CI
gate for the static analyzer: every graph we ship must be free of
error-severity diagnostics).

Each target computation is traced, written to a temp ``.moose`` file,
and linted through the prancer CLI — the same path a user takes with a
serialized computation.  The tutorial dot product (constants only, so no
arg specs needed) is additionally run through the full compile pipeline
and linted post-networking, exercising the MSA2xx communication rules
AND the MSA5xx/MSA6xx plan rules on a real Send/Receive graph.

When the reference checkout is present (``/root/reference``, or
``MOOSE_REFERENCE_DIR``), every ``.moose`` artifact the reference ships
is linted too — the first machine-checked tie to the ROADMAP's interop
anchor: graphs the reference runtime executes must be clean under our
analyzer as well.

    python scripts/lint_examples.py
"""

import glob
import os
import pathlib
import sys
import tempfile

ROOT = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(ROOT))

REFERENCE_DIR = os.environ.get("MOOSE_REFERENCE_DIR", "/root/reference")

# (label, module, attribute) — module-level @pm.computation objects
TARGETS = [
    ("tutorial dot product",
     "tutorials.interfacing_textual_and_cli", "my_computation"),
    ("logistic regression training", "examples.logistic_regression",
     "train"),
    ("logistic regression inference", "examples.logistic_regression",
     "predict"),
    ("AES encrypted inference", "examples.aes_inference", "secure_score"),
]


def trainer_graphs():
    """(label, computation, extra prancer flags) for every trainer graph
    shape at BOTH shipped precisions — logreg/MLP init, epoch, and
    standalone step — with the trainer's real shapes and declared
    feature/weight/label ranges passed via --arg-shape/--arg-range, so
    the MSA7xx overflow checks (and the MSA105 storage taint rules on
    the checkpoint boundary ops) are armed, not just advisory."""
    import moose_tpu as pm
    from moose_tpu.predictors.trainers import (
        LogregSGDTrainer,
        MLPSGDTrainer,
    )

    n_rows = 16
    out = []
    for fx, tag in (
        (pm.fixed(8, 17), "fixed(8,17)/ring64"),
        (pm.fixed(24, 40), "fixed(24,40)/ring128"),
    ):
        trainers = [
            ("logreg", LogregSGDTrainer(4, fixedpoint_dtype=fx,
                                        steps_per_epoch=2)),
            ("mlp", MLPSGDTrainer(4, 3, fixedpoint_dtype=fx,
                                  steps_per_epoch=2)),
        ]
        for mname, trainer in trainers:
            graphs = [
                ("init", trainer.init_computation(), None),
                ("epoch", trainer.epoch_computation(n_rows), n_rows),
                ("step", trainer.step_computation(n_rows), n_rows),
            ]
            for gname, comp, rows in graphs:
                arg_specs, arg_ranges = trainer.range_specs(rows)
                flags = [
                    f"--arg-shape={name}="
                    + "x".join(str(d) for d in shape)
                    for name, shape in sorted(arg_specs.items())
                ] + [
                    f"--arg-range={name}={lo}:{hi}"
                    for name, (lo, hi) in sorted(arg_ranges.items())
                ]
                out.append(
                    (f"{mname} trainer {gname} @ {tag}", comp, flags)
                )
    return out


def build_resnet_computation():
    import moose_tpu as pm
    from moose_tpu import predictors
    from moose_tpu.predictors.sklearn_export import resnet_block_onnx

    proto, _ = resnet_block_onnx(seed=7, in_ch=3, mid_ch=4, size=8,
                                 n_classes=3)
    model = predictors.from_onnx(proto.encode())
    return model.predictor_factory(fixedpoint_dtype=pm.fixed(24, 40))


def main() -> int:
    import importlib

    from moose_tpu.bin.prancer import main as prancer
    from moose_tpu.compilation import DEFAULT_PASSES, compile_computation
    from moose_tpu.edsl import tracer
    from moose_tpu.textual import to_textual

    graphs = []
    for label, modname, attr in TARGETS:
        comp_fn = getattr(importlib.import_module(modname), attr)
        graphs.append((label, tracer.trace(comp_fn), []))
    graphs.append(
        ("resnet predictor", tracer.trace(build_resnet_computation()), [])
    )

    # full pipeline on the constants-only tutorial graph: lowering,
    # pruning, networking — the graph the workers would execute
    logical = graphs[0][1]
    graphs.append((
        "tutorial dot product (lowered + networked)",
        compile_computation(logical, passes=DEFAULT_PASSES),
        [],
    ))

    # every trainer graph at both shipped precisions, with declared
    # ranges armed — MSA105/MSA7xx regressions on training graphs fail
    # here
    graphs.extend(trainer_graphs())

    failures = 0
    linted = 0
    with tempfile.TemporaryDirectory() as tmp:
        for i, (label, comp, flags) in enumerate(graphs):
            path = pathlib.Path(tmp) / f"comp_{i}.moose"
            path.write_text(to_textual(comp))
            rc = prancer([str(path), *flags])
            status = "clean" if rc == 0 else "FAILED"
            print(f"[{status}] {label} ({len(comp.operations)} ops)")
            failures += rc != 0
            linted += 1

    # the reference's own shipped artifacts (ROADMAP item 5's interop
    # anchor): every .moose graph the reference executes must also be
    # clean under prancer — including the MSA5xx schedule rules on the
    # pre-networked *-networked/-compiled artifacts
    artifacts = sorted(
        glob.glob(f"{REFERENCE_DIR}/**/*.moose", recursive=True)
    )
    if artifacts:
        for path in artifacts:
            rc = prancer([path])
            status = "clean" if rc == 0 else "FAILED"
            rel = os.path.relpath(path, REFERENCE_DIR)
            print(f"[{status}] reference artifact {rel}")
            failures += rc != 0
            linted += 1
    else:
        print(
            f"# reference artifacts not present under {REFERENCE_DIR}; "
            "skipping (CI runs them when the checkout is mounted)"
        )

    if failures:
        print(f"{failures} computation(s) failed lint", file=sys.stderr)
        return 1
    print(f"all {linted} computations lint clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
