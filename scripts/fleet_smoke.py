"""CI fleet smoke (ISSUE 11): 3 blitzen replicas + the donner router,
open-loop clients, one chaos-kill + restart-from-snapshot and one
graceful rolling restart mid-run.

What it proves (the acceptance gates):

1. **Warm snapshots work end-to-end**: replica A registers fresh and
   writes the durable snapshot; replicas B and C cold-start FROM it
   (their stdout reports the restore and its duration, bounded below);
   after the chaos kill, B restarts from the snapshot again and its
   very first served request does not re-trace or re-validate —
   asserted from its /metrics Prometheus scrape
   (``retraces_after_warm_total == 0``,
   ``validating_after_warm_total == 0``) and /v1/metrics JSON.
2. **Zero dropped requests**: an open-loop client stream runs through
   donner for the whole scenario — SIGKILL of replica B mid-traffic,
   ejection, restart, readmission, then a SIGTERM rolling restart of
   replica C — and EVERY request ends 2xx (donner resolves all
   retryable failures on other replicas).
3. **Routing state machine**: donner's metrics show >= 1 ejection and
   >= 1 readmission; its /fleet view tracks the kill and the recovery.
4. **Bit-exactness across the fleet**: under MOOSE_TPU_FIXED_KEYS a
   canned single request answers bit-identically on every replica,
   fresh or snapshot-restored (quiet-phase probes: batching position
   affects share noise, so the probe never races open-loop traffic).
5. **Graceful drain**: the SIGTERM'd replica answers 503+Retry-After
   during its drain, exits 0, and leaves a refreshed snapshot behind.
6. **AOT-execute knob**: a final restore with
   ``MOOSE_TPU_SNAPSHOT_AOT_EXEC=0`` re-warms bit-identically, reports
   zero executed artifacts, and the summary carries the re-warm delta
   between the exec and no-exec paths.

Run time is dominated by replica A's fresh registration; B/C restore
from the snapshot in seconds (MOOSE_TPU_JIT=0 here, like
serve_smoke.py: this validates fleet SEMANTICS — compiled-path re-warm
performance is bench.py's concern on real hardware).

    JAX_PLATFORMS=cpu python scripts/fleet_smoke.py
"""

import json
import os
import re
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np

ROOT = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(ROOT))

FEATURES = 12
REWARM_BOUND_S = 300.0  # generous CI bound; bench.py measures for real
LOAD_SECONDS = 30.0
# an eager logreg batch costs ~1 CPU-second: the open-loop rate must
# stay sustainable on a small CI box (3 replica processes share its
# cores) or the smoke measures scheduler thrash, not fleet semantics
REQUESTS_PER_SECOND = 1.0

ENV = {
    **os.environ,
    "JAX_PLATFORMS": "cpu",
    "MOOSE_TPU_JIT": "0",
    "MOOSE_TPU_FIXED_KEYS": "fleet-smoke",
    "MOOSE_TPU_ALLOW_WEAK_PRF": "1",
    "MOOSE_TPU_SERVE_MAX_BATCH": "4",
    "MOOSE_TPU_SERVE_MAX_WAIT_MS": "5",
    "PYTHONPATH": str(ROOT),
    "PYTHONUNBUFFERED": "1",
}


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class Proc:
    """A replica/router subprocess with captured, greppable stdout."""

    def __init__(self, name, argv, extra_env=None):
        self.name = name
        self.lines = []
        self._lock = threading.Lock()
        self.popen = subprocess.Popen(
            argv, env={**ENV, **(extra_env or {})}, cwd=ROOT, text=True,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        threading.Thread(target=self._pump, daemon=True).start()

    def _pump(self):
        for line in self.popen.stdout:
            with self._lock:
                self.lines.append(line.rstrip())

    def grep(self, pattern):
        with self._lock:
            for line in self.lines:
                m = re.search(pattern, line)
                if m:
                    return m
        return None

    def tail(self, n=15):
        with self._lock:
            return "\n".join(self.lines[-n:])

    def kill(self):
        self.popen.kill()
        self.popen.wait(timeout=30)

    def sigterm(self):
        self.popen.send_signal(signal.SIGTERM)


def wait_until(predicate, timeout_s, what):
    deadline = time.perf_counter() + timeout_s
    while time.perf_counter() < deadline:
        result = predicate()
        if result:
            return result
        time.sleep(0.25)
    raise AssertionError(f"timed out after {timeout_s}s waiting for {what}")


def http_get(url, timeout=10):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()
    except Exception:
        return None, b""


def http_post(url, payload, timeout=60):
    request = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    try:
        with urllib.request.urlopen(request, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()
    except Exception as e:
        return None, type(e).__name__.encode()


def wait_ready(base, timeout_s=600):
    wait_until(
        lambda: http_get(base + "/readyz")[0] == 200,
        timeout_s, f"{base}/readyz == 200",
    )


def start_replica(name, port, onnx_path, snapshot_dir, extra_env=None):
    return Proc(name, [
        sys.executable, "-m", "moose_tpu.bin.blitzen",
        f"logreg={onnx_path}", "--features", f"logreg={FEATURES}",
        "--host", "127.0.0.1", "--port", str(port),
        "--snapshot-dir", str(snapshot_dir),
        "--drain-timeout-s", "60",
    ], extra_env=extra_env)


def prom_value(text, name):
    """Last sample of ``name`` in a Prometheus exposition (None when
    the series is absent — an absent counter means zero events)."""
    value = None
    for line in text.splitlines():
        if line.startswith(name) and not line.startswith("#"):
            value = float(line.rsplit(" ", 1)[1])
    return value


def main():
    from sklearn.linear_model import LogisticRegression

    from moose_tpu.predictors.sklearn_export import (
        logistic_regression_onnx,
    )

    rng = np.random.default_rng(3)
    x_train = rng.normal(size=(96, FEATURES))
    y_train = (rng.uniform(size=96) > 0.5).astype(int)
    sk = LogisticRegression().fit(x_train, y_train)

    workdir = Path(tempfile.mkdtemp(prefix="fleet_smoke_"))
    onnx_path = workdir / "logreg.onnx"
    onnx_path.write_bytes(
        logistic_regression_onnx(sk, FEATURES).encode()
    )
    snapshot_dir = workdir / "snapshots"

    ports = {"a": free_port(), "b": free_port(), "c": free_port()}
    bases = {k: f"http://127.0.0.1:{p}" for k, p in ports.items()}
    procs = {}
    summary = {}
    stop_load = threading.Event()
    t_all = time.perf_counter()
    try:
        # ---- phase 1: replica A registers fresh, writes the snapshot
        t0 = time.perf_counter()
        procs["a"] = start_replica(
            "a", ports["a"], onnx_path, snapshot_dir
        )
        wait_ready(bases["a"])
        summary["fresh_register_s"] = time.perf_counter() - t0
        assert (snapshot_dir / "CURRENT").exists(), (
            "replica A never wrote the warm-state snapshot"
        )

        # ---- phase 2: B and C cold-start FROM the snapshot
        t0 = time.perf_counter()
        for key in ("b", "c"):
            procs[key] = start_replica(
                key, ports[key], onnx_path, snapshot_dir
            )
        for key in ("b", "c"):
            wait_ready(bases[key])
            m = wait_until(
                lambda k=key: procs[k].grep(
                    r"restored warm state from .* in ([0-9.]+)s"
                ),
                30, f"replica {key} restore banner",
            )
            rewarm_s = float(m.group(1))
            assert rewarm_s < REWARM_BOUND_S, (
                f"replica {key} re-warm {rewarm_s}s "
                f"exceeds {REWARM_BOUND_S}s"
            )
            summary[f"rewarm_{key}_s"] = rewarm_s

        # ---- phase 3: quiet-phase bit-exactness probe across replicas
        probe_x = rng.normal(size=(1, FEATURES)).tolist()
        probe_bytes = {}
        for key, base in bases.items():
            status, body = http_post(
                base + "/v1/models/logreg:predict", {"x": probe_x}
            )
            assert status == 200, (key, status, body)
            probe_bytes[key] = body
        assert len(set(probe_bytes.values())) == 1, (
            "replicas disagree bitwise under MOOSE_TPU_FIXED_KEYS: "
            f"{probe_bytes}"
        )
        want = sk.predict_proba(np.asarray(probe_x))
        got = np.asarray(json.loads(probe_bytes["a"])["y"])
        assert float(np.abs(got - want).max()) < 5e-3

        # ---- phase 4: donner up, open-loop load through it
        procs["donner"] = Proc("donner", [
            sys.executable, "-m", "moose_tpu.bin.donner",
            "--replica", bases["a"], "--replica", bases["b"],
            "--replica", bases["c"],
            "--host", "127.0.0.1", "--port", "0",
            "--probe-interval-ms", "200", "--eject-after", "2",
            "--readmit-after", "2", "--retries", "6",
        ])
        m = wait_until(
            lambda: procs["donner"].grep(
                r"donner: routing .* on http://127\.0\.0\.1:(\d+)"
            ),
            30, "donner startup banner",
        )
        donner = f"http://127.0.0.1:{m.group(1)}"
        wait_ready(donner, timeout_s=30)

        outcomes = []
        outcomes_lock = threading.Lock()

        def one_request(i):
            x = rng.normal(size=(1, FEATURES)).tolist()
            t = time.perf_counter()
            status, body = http_post(
                donner + "/v1/models/logreg:predict", {"x": x},
                timeout=90,
            )
            with outcomes_lock:
                outcomes.append({
                    "i": i, "status": status,
                    "latency_s": time.perf_counter() - t,
                    "body": body[:120].decode(errors="replace"),
                })

        def open_loop():
            # OPEN loop: requests fire on the clock, never gated on
            # earlier completions — exactly the traffic shape that
            # exposes dropped requests during kill/eject windows.
            # Missed ticks are DROPPED, not replayed: on a slow CI box
            # a replay burst after a long phase would turn the open
            # loop into a thundering herd of catch-up threads
            i = 0
            period = 1.0 / REQUESTS_PER_SECOND
            next_t = time.perf_counter()
            while not stop_load.is_set():
                threading.Thread(
                    target=one_request, args=(i,), daemon=True
                ).start()
                i += 1
                next_t = max(
                    next_t + period, time.perf_counter()
                )
                time.sleep(max(0.0, next_t - time.perf_counter()))

        loader = threading.Thread(target=open_loop, daemon=True)
        t_load = time.perf_counter()
        loader.start()

        # ---- phase 5: chaos-kill replica B mid-traffic
        time.sleep(6)
        procs["b"].kill()
        wait_until(
            lambda: any(
                r["url"] == bases["b"] and r["ejected"]
                for r in json.loads(
                    http_get(donner + "/fleet")[1]
                )["replicas"]
            ),
            20, "donner ejecting the killed replica",
        )

        # ---- phase 6: restart B from the snapshot, wait readmission
        time.sleep(2)
        t0 = time.perf_counter()
        procs["b2"] = start_replica(
            "b2", ports["b"], onnx_path, snapshot_dir
        )
        wait_ready(bases["b"])
        summary["restart_to_ready_s"] = time.perf_counter() - t0
        m = wait_until(
            lambda: procs["b2"].grep(
                r"restored warm state from .* in ([0-9.]+)s"
            ),
            30, "restarted replica restore banner",
        )
        summary["rewarm_after_kill_s"] = float(m.group(1))
        assert summary["rewarm_after_kill_s"] < REWARM_BOUND_S
        wait_until(
            lambda: all(
                not r["ejected"]
                for r in json.loads(
                    http_get(donner + "/fleet")[1]
                )["replicas"]
            ),
            30, "donner readmitting the restarted replica",
        )

        # the restarted replica must actually serve from warm state:
        # wait until it has taken traffic, then hold its after-warm
        # counters to zero — scraped from /metrics, not in-process
        wait_until(
            lambda: (
                prom_value(
                    http_get(bases["b"] + "/metrics")[1].decode(),
                    "moose_tpu_serving_rows_total",
                ) or 0
            ) > 0,
            60, "restarted replica serving traffic",
        )
        prom = http_get(bases["b"] + "/metrics")[1].decode()
        assert not prom_value(
            prom, "moose_tpu_serving_retraces_after_warm_total"
        ), "restarted replica re-traced after its snapshot restore"
        assert not prom_value(
            prom, "moose_tpu_serving_validating_after_warm_total"
        ), "restarted replica re-validated after its snapshot restore"
        rewarm_gauge = prom_value(
            prom, "moose_tpu_serving_rewarm_seconds"
        )
        assert rewarm_gauge is not None and rewarm_gauge < REWARM_BOUND_S
        snap_json = json.loads(
            http_get(bases["b"] + "/v1/metrics")[1]
        )
        assert snap_json["retraces_after_warm"] == 0, snap_json
        assert snap_json["validating_after_warm"] == 0, snap_json

        # ---- phase 7: rolling restart — SIGTERM replica C (graceful)
        procs["c"].sigterm()
        # during the drain the replica answers 503 + Retry-After on
        # predicts and 503 on readiness; donner routes around it
        status, body = http_post(
            bases["c"] + "/v1/models/logreg:predict", {"x": probe_x},
            timeout=30,
        )
        if status is not None:  # it may already have exited
            assert status in (200, 503), (status, body)
            if status == 503:
                assert json.loads(body)["retryable"] is True
        assert procs["c"].popen.wait(timeout=300) == 0, (
            "graceful drain must exit 0"
        )
        assert procs["c"].grep(r"blitzen: drained \(clean\)"), (
            procs["c"].tail()
        )
        procs["c2"] = start_replica(
            "c2", ports["c"], onnx_path, snapshot_dir
        )
        wait_ready(bases["c"])

        # ---- phase 8: stop the load, settle, judge
        remaining = LOAD_SECONDS - (time.perf_counter() - t_load)
        if remaining > 0:
            time.sleep(remaining)
        stop_load.set()
        loader.join(timeout=10)

        # wait for REAL quiet: no outcome recorded for 2 consecutive
        # seconds AND the router reports zero in-flight forwards —
        # a straggler still bouncing through retries would co-batch
        # with the bit-exactness probe below and shift its share noise
        def settled():
            with outcomes_lock:
                count = len(outcomes)
            time.sleep(2.0)
            with outcomes_lock:
                if len(outcomes) != count:
                    return False
            fleet = json.loads(http_get(donner + "/fleet")[1])
            return all(
                r["in_flight"] == 0 for r in fleet["replicas"]
            )

        wait_until(settled, 120, "open-loop stragglers to land")

        # quiet-phase bit-exactness, again: with the open loop stopped
        # (co-batched rows shift batch positions, and share noise is
        # position-dependent), the snapshot-restored replica must still
        # answer the canned probe with the exact bytes the fleet agreed
        # on before the kill
        status, body = http_post(
            bases["b"] + "/v1/models/logreg:predict", {"x": probe_x}
        )
        assert status == 200 and body == probe_bytes["a"], (
            "snapshot-restored replica diverged bitwise: "
            f"{body!r} != {probe_bytes['a']!r}"
        )

        with outcomes_lock:
            done = list(outcomes)
        total = len(done)
        non_2xx = [o for o in done if o["status"] != 200]
        assert total >= LOAD_SECONDS * REQUESTS_PER_SECOND * 0.5, (
            f"open loop under-delivered: {total} requests"
        )
        assert not non_2xx, (
            f"{len(non_2xx)}/{total} requests dropped "
            f"(first: {non_2xx[:5]})"
        )

        donner_prom = http_get(donner + "/metrics")[1].decode()
        ejections = prom_value(
            donner_prom, "moose_tpu_donner_ejections_total"
        )
        readmissions = prom_value(
            donner_prom, "moose_tpu_donner_readmissions_total"
        )
        assert ejections and ejections >= 1, donner_prom
        assert readmissions and readmissions >= 1, donner_prom

        # ---- phase 9: AOT-execute re-warm delta — restart replica B
        # once more with the restored-artifact execution path disabled
        # (MOOSE_TPU_SNAPSHOT_AOT_EXEC=0) and compare re-warm times.
        # Under MOOSE_TPU_JIT=0 both restores are compile-free and the
        # delta is noise; on the compiled path the exec'd artifact
        # skips even the cached compile (tests/test_fleet.py proves the
        # "executed" verdict + bit-exactness; bench.py measures it on
        # real hardware).  Either way the knob and both restore paths
        # are exercised end-to-end here.
        procs["b2"].sigterm()
        procs["b2"].popen.wait(timeout=300)
        procs["b3"] = start_replica(
            "b3", ports["b"], onnx_path, snapshot_dir,
            extra_env={"MOOSE_TPU_SNAPSHOT_AOT_EXEC": "0"},
        )
        wait_ready(bases["b"])
        m = wait_until(
            lambda: procs["b3"].grep(
                r"restored warm state from .* in ([0-9.]+)s "
                r"\((\d+) probe digest\(s\) verified, (\d+) AOT "
                r"bucket\(s\) executed\)"
            ),
            60, "aot-exec-disabled restore banner",
        )
        summary["rewarm_aot_exec_s"] = summary["rewarm_after_kill_s"]
        summary["rewarm_aot_noexec_s"] = float(m.group(1))
        summary["rewarm_aot_delta_s"] = (
            summary["rewarm_aot_noexec_s"]
            - summary["rewarm_aot_exec_s"]
        )
        assert int(m.group(3)) == 0, (
            "MOOSE_TPU_SNAPSHOT_AOT_EXEC=0 must disable artifact "
            "execution"
        )
        status, body = http_post(
            bases["b"] + "/v1/models/logreg:predict", {"x": probe_x}
        )
        assert status == 200 and body == probe_bytes["a"], (
            "aot-exec-disabled restore diverged bitwise"
        )

        latencies = sorted(o["latency_s"] for o in done)
        summary.update({
            "requests": total,
            "dropped": 0,
            "ejections": ejections,
            "readmissions": readmissions,
            "p50_s": latencies[len(latencies) // 2],
            "p99_s": latencies[min(
                len(latencies) - 1, int(len(latencies) * 0.99)
            )],
            "elapsed_s": time.perf_counter() - t_all,
        })
        print("FLEET_SMOKE_OK " + json.dumps(summary))
    except BaseException:
        for name, proc in procs.items():
            print(f"---- {name} tail ----\n{proc.tail()}", flush=True)
        raise
    finally:
        stop_load.set()
        for proc in procs.values():
            if proc.popen.poll() is None:
                proc.popen.kill()


if __name__ == "__main__":
    main()
