"""Ready-to-run multi-chip benchmark for a v5e-8 (or any >=3-chip) slice.

The repo's dev harness has ONE tunneled v5e chip, so multi-chip numbers
cannot be produced here — this script is the one-command config for the
moment real hardware appears (VERDICT r4 #8):

    python benchmarks/v5e8_bench.py [--batch 4096] [--features 256]

It builds the (parties=3, data=n//3) mesh over the real devices
(`spmd.make_mesh`), runs the chained secure logreg training step and the
chained secure dot with the party/batch axes sharded, and prints one
JSON line per metric (same schema as bench.py).  On a single chip it
degenerates to the unsharded bench (parties co-located), so it can be
smoke-tested anywhere; the numbers become multi-chip evidence exactly
when `jax.devices()` grows.
"""

import argparse
import json
import time

import numpy as np

import moose_tpu  # noqa: F401  (enables x64)
import jax
import jax.numpy as jnp

from moose_tpu.parallel import spmd

I, F, W = 14, 23, 128


def _bench(fn, args, iters=10):
    float(fn(*args))  # compile + warm
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        float(fn(*args))
        times.append(time.perf_counter() - t0)
    return float(np.median(times)), float(np.min(times))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=4096)
    ap.add_argument("--features", type=int, default=256)
    ap.add_argument("--steps", type=int, default=10,
                    help="training steps chained in one program")
    ap.add_argument("--dot-n", type=int, default=1000)
    args = ap.parse_args()

    devices = jax.devices()
    mesh = spmd.make_mesh(len(devices))
    p, d = mesh.devices.shape
    print(f"# devices={len(devices)} mesh=(parties={p}, data={d}) "
          f"backend={jax.default_backend()}")

    rng = np.random.default_rng(0)
    mk = np.arange(4, dtype=np.uint32) + 1
    batch = (args.batch // d) * d or d
    x = rng.normal(size=(batch, args.features)) * 0.3
    y = (rng.uniform(size=(batch, 1)) > 0.5).astype(np.float64)
    w0 = rng.normal(size=(args.features, 1)) * 0.1

    @jax.jit
    def train(master_key, x_f, y_f, w_f):
        sess = spmd.SpmdSession(master_key)
        xs = spmd.fx_encode_share(sess, x_f, I, F, W)
        ys = spmd.fx_encode_share(sess, y_f, I, F, W)
        ws = spmd.fx_encode_share(sess, w_f, I, F, W)
        keys = spmd.derive_step_keys(
            jnp.asarray(master_key, jnp.uint32), args.steps
        )

        def body(wc, k):
            s = spmd.SpmdSession(k)
            return spmd.logreg_train_step(s, xs, ys, wc, 0.1, mesh=mesh), None

        ws, _ = jax.lax.scan(body, ws, keys)
        return jnp.sum(spmd.fx_reveal_decode(ws))

    with mesh:
        med, mn = _bench(train, (mk, x, y, w0))
    print(json.dumps({
        "metric": f"v5e8_logreg_train_step_batch{batch}_f{args.features}",
        "value": med / args.steps, "min_s": mn / args.steps,
        "unit": "s/step", "mesh": [int(p), int(d)],
    }), flush=True)

    a = rng.normal(size=(args.dot_n, args.dot_n))
    b = rng.normal(size=(args.dot_n, args.dot_n))

    @jax.jit
    def dot(master_key, x_f, y_f):
        sess = spmd.SpmdSession(master_key)
        xs = spmd.fx_encode_share(sess, x_f, I, F, W)
        xs = spmd.SpmdFixed(spmd.constrain(xs.tensor, mesh, 0), I, F)
        ys = spmd.fx_encode_share(sess, y_f, I, F, W)
        z = spmd.fx_dot(sess, xs, ys)
        return jnp.sum(spmd.fx_reveal_decode(z))

    with mesh:
        med, mn = _bench(dot, (mk, a, b))
    print(json.dumps({
        "metric": f"v5e8_secure_dot_{args.dot_n}x{args.dot_n}_ring128",
        "value": med, "min_s": mn, "unit": "s",
        "mesh": [int(p), int(d)],
    }), flush=True)


if __name__ == "__main__":
    main()
