"""3-worker gRPC cluster benchmark: the reference's ACTUAL deployment
shape (3 comet processes + a coordinating client,
/root/reference/benchmarks/README.md:1-24) — genuinely-distrusting
parties, per-party processes, real serde + gRPC on every cross-party
edge, parallel dependency-counted execution inside each worker.

The reference's headline 1000x1000 secure dot is 5.910 s in this shape
(3x c5.9xlarge).  Workers here are CPU-pinned (several processes cannot
share the one tunneled TPU chip) and colocated on one host, which is
honest-to-pessimistic: all three parties contend for the same cores,
whereas the reference gave each party 36 dedicated vCPUs.

  python benchmarks/distributed_grpc.py --mode dot --size 1000
  python benchmarks/distributed_grpc.py --all
"""

import argparse
import json
import os
import statistics
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BASE_PORT = int(os.environ.get("MOOSE_TPU_BENCH_PORT", "22300"))
IDENTITIES = ["alice", "bob", "carole"]


def _worker_env() -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.setdefault("MOOSE_TPU_PRF", "threefry")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    return env


def spawn_workers(base_port=BASE_PORT):
    endpoints = {
        name: f"127.0.0.1:{base_port + i}"
        for i, name in enumerate(IDENTITIES)
    }
    ep_spec = ",".join(f"{k}={v}" for k, v in endpoints.items())
    env = _worker_env()
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "moose_tpu.bin.comet",
             "--identity", name, "--port", str(base_port + i),
             "--endpoints", ep_spec],
            stdout=subprocess.DEVNULL, stderr=subprocess.PIPE, env=env,
        )
        for i, name in enumerate(IDENTITIES)
    ]
    import grpc

    try:
        deadline = time.time() + 60
        for ep in endpoints.values():
            while True:
                ch = grpc.insecure_channel(ep)
                try:
                    grpc.channel_ready_future(ch).result(timeout=5)
                    break
                except Exception:
                    if time.time() > deadline:
                        raise RuntimeError(
                            f"worker at {ep} failed to start"
                        )
                finally:
                    ch.close()
    except BaseException:
        _teardown(procs)  # don't leak spawned workers on startup failure
        raise
    return procs, endpoints


def _teardown(procs):
    for p in procs:
        p.terminate()
    for p in procs:
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()


def _transport_fields(runtime) -> dict:
    """Bench-row hygiene (ISSUE 19): every distributed row says which
    transport it rode and under which trust-model attestation, straight
    from the session report (subprocess comet workers cannot share a
    device mesh, so these rows always say grpc — the field makes that
    explicit instead of implied)."""
    report = getattr(runtime, "last_session_report", None) or {}
    return {
        "transport": report.get("transport"),
        "trust_model": report.get("trust_model"),
    }


def build_dot_comp(pm, n_seq):
    alice = pm.host_placement("alice")
    bob = pm.host_placement("bob")
    carole = pm.host_placement("carole")
    rep = pm.replicated_placement(name="rep", players=[alice, bob, carole])
    fixed = pm.fixed(8, 27)

    @pm.computation
    def dot_product_comp(
        x_arg: pm.Argument(placement=alice, dtype=pm.float64),
        y_arg: pm.Argument(placement=bob, dtype=pm.float64),
    ):
        with alice:
            x = pm.cast(x_arg, dtype=fixed)
        with bob:
            y = pm.cast(y_arg, dtype=fixed)
        with rep:
            z = pm.dot(x, y)
            for _ in range(n_seq - 1):
                z = pm.dot(x, z)
        with carole:
            res = pm.cast(z, dtype=pm.float64)
        return res

    return dot_product_comp


def bench_dot(runtime, pm, size, n_seq, iters):
    comp = build_dot_comp(pm, n_seq)
    rng = np.random.default_rng(42)
    # square x so chained dots keep their shapes; normalize to avoid
    # fixed-point overflow over the chain
    x = rng.uniform(0.5, 1.5, size=(size, size)) / max(size, 1)
    y = rng.uniform(0.5, 1.5, size=(size, size))
    args = {"x_arg": x, "y_arg": y}
    runtime.evaluate_computation(comp, args)  # warm XLA caches everywhere
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        outputs, _ = runtime.evaluate_computation(comp, args)
        times.append(time.perf_counter() - t0)
    (out,) = outputs.values()
    expected = x @ y
    for _ in range(n_seq - 1):
        expected = x @ expected
    err = float(np.max(np.abs(np.asarray(out) - expected)))
    assert err < 1e-2 * max(1.0, float(np.max(np.abs(expected)))), err
    return {
        "metric": f"grpc_dot_{size}x{size}_seq{n_seq}",
        "value": round(statistics.median(times), 4),
        "unit": "s",
        "min": round(min(times), 4),
        "max": round(max(times), 4),
        "iters": iters,
        **_transport_fields(runtime),
    }


def bench_logreg(runtime, pm, batch_size, n_iter, iters):
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import logreg as lr

    comp = lr.build_train(batch_size, 1)
    rng = np.random.default_rng(7)
    x = rng.normal(size=(batch_size, lr.N_FEATURES))
    w_true = rng.normal(size=(lr.N_FEATURES, 1))
    y = (1 / (1 + np.exp(-(x @ w_true))) > 0.5).astype(np.float64)
    w0 = np.zeros((lr.N_FEATURES, 1))
    b0 = np.zeros((1,))
    args = {"x": x, "y": y, "w_0": w0, "b_0": b0}
    # n_iter epochs are driven by re-running the one-batch step graph:
    # the distributed walk executes ops eagerly, so a 10-iteration
    # unrolled graph and 10 runs of the step graph cost the same ops;
    # the step graph keeps launch payloads small
    runtime.evaluate_computation(comp, args)  # warmup
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        for _ in range(n_iter):
            outputs, _ = runtime.evaluate_computation(comp, args)
        times.append(time.perf_counter() - t0)
    # Gate the revealed weights on the plaintext trajectory (each run
    # re-feeds w_0 = 0, so every run is the same single momentum step):
    # wrong-but-fast numbers must not be publishable (ADVICE r3).
    w_ref = lr._plaintext_sgd_momentum(
        x, y, batch_size, 1, lr.LEARNING_RATE, lr.MOMENTUM
    )
    w_out = next(
        np.asarray(v) for v in outputs.values()
        if np.asarray(v).shape == w_ref.shape
    )
    lr._check_trajectory(w_out, w_ref, w_true)
    return {
        "metric": f"grpc_logreg_b{batch_size}_i{n_iter}",
        "value": round(statistics.median(times), 4),
        "unit": "s",
        "min": round(min(times), 4),
        "max": round(max(times), 4),
        "iters": iters,
        **_transport_fields(runtime),
    }


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--mode", choices=["dot", "logreg"], default="dot")
    parser.add_argument("--size", type=int, default=1000)
    parser.add_argument("--n_seq", type=int, default=1)
    parser.add_argument("--batch_size", type=int, default=128)
    parser.add_argument("--n_iter", type=int, default=10)
    parser.add_argument("--iters", type=int, default=3)
    parser.add_argument("--all", action="store_true",
                        help="reproduce the reference's table cells")
    args = parser.parse_args()

    # the client compiles/serializes only — CPU is fine and avoids
    # fighting the workers for the tunneled chip
    os.environ.setdefault("MOOSE_TPU_PRF", "threefry")
    import jax

    jax.config.update("jax_platforms", "cpu")
    import moose_tpu as pm
    from moose_tpu.runtime import GrpcMooseRuntime

    procs, endpoints = spawn_workers()
    try:
        runtime = GrpcMooseRuntime(endpoints)
        rows = []
        if args.all:
            for size in (1, 10, 100, 1000):
                rows.append(bench_dot(runtime, pm, size, 1, args.iters))
                print(json.dumps(rows[-1]), flush=True)
            for size in (1, 10, 100):
                rows.append(bench_dot(runtime, pm, size, 10, args.iters))
                print(json.dumps(rows[-1]), flush=True)
            rows.append(bench_logreg(runtime, pm, 128, 10, args.iters))
            print(json.dumps(rows[-1]), flush=True)
        elif args.mode == "dot":
            rows.append(bench_dot(
                runtime, pm, args.size, args.n_seq, args.iters
            ))
            print(json.dumps(rows[-1]), flush=True)
        else:
            rows.append(bench_logreg(
                runtime, pm, args.batch_size, args.n_iter, args.iters
            ))
            print(json.dumps(rows[-1]), flush=True)
    finally:
        _teardown(procs)


if __name__ == "__main__":
    main()
