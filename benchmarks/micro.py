"""Executor / networking / runtime micro-benchmarks.

The counterpart of the reference's criterion benches
(``moose/benches/exec.rs`` — deep op chains through the executors,
``moose/benches/networking.rs`` — transport round-trips,
``moose/benches/runtime.rs`` — whole-session overhead): fast regression
tripwires for the scheduler, dispatch, serde, and transport layers, as
opposed to the macro benchmarks (dot_product.py / logreg.py) that track
protocol throughput.

  python benchmarks/micro.py            # all suites, one JSON line each
  python benchmarks/micro.py --suite exec --depth 200
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import moose_tpu as pm
from moose_tpu.runtime import LocalMooseRuntime


def _emit(record):
    print(json.dumps(record), flush=True)
    return record


def _median_time(fn, reps):
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


# ---------------------------------------------------------------------------
# exec: deep sequential op chain through both executors (benches/exec.rs)
# ---------------------------------------------------------------------------


def _chain_comp(depth):
    alice = pm.host_placement("alice")

    @pm.computation
    def comp(
        x: pm.Argument(placement=alice, vtype=pm.TensorType(pm.float64))
    ):
        with alice:
            y = x
            for _ in range(depth):
                y = pm.add(y, x)
        return y

    return comp


def bench_exec(depth=200, reps=5):
    """Per-op dispatch cost of the eager interpreter vs the jitted plan
    on a depth-N Add chain (the executor's scheduling overhead, isolated
    from math: the adds are scalar-ish)."""
    comp = _chain_comp(depth)
    x = np.ones((16,))
    out = []
    for use_jit, name in ((False, "eager"), (True, "jit")):
        runtime = LocalMooseRuntime(["alice"], use_jit=use_jit)
        run = lambda: runtime.evaluate_computation(comp, arguments={"x": x})
        first_s = _median_time(run, 1)  # includes trace+compile (cached after)
        t = _median_time(run, reps)
        out.append(
            _emit(
                {
                    "metric": f"exec_chain_{name}_ops_per_sec",
                    "value": round(depth / t, 1),
                    "unit": "ops/s",
                    "depth": depth,
                    "steady_latency_s": round(t, 6),
                    "first_call_s": round(first_s, 6),
                }
            )
        )
    return out


# ---------------------------------------------------------------------------
# runtime: whole-session overhead for a trivial graph (benches/runtime.rs)
# ---------------------------------------------------------------------------


def bench_runtime(reps=20):
    alice = pm.host_placement("alice")

    @pm.computation
    def tiny(
        x: pm.Argument(placement=alice, vtype=pm.TensorType(pm.float64))
    ):
        with alice:
            y = pm.add(x, x)
        return y

    runtime = LocalMooseRuntime(["alice"], use_jit=False)
    x = np.ones((4,))
    runtime.evaluate_computation(tiny, arguments={"x": x})  # warm caches
    t = _median_time(
        lambda: runtime.evaluate_computation(tiny, arguments={"x": x}), reps
    )
    return _emit(
        {
            "metric": "runtime_session_evaluations_per_sec",
            "value": round(1.0 / t, 1),
            "unit": "sessions/s",
            "steady_latency_s": round(t, 6),
        }
    )


# ---------------------------------------------------------------------------
# serde + networking transports (benches/networking.rs)
# ---------------------------------------------------------------------------


def bench_serde(nbytes=8 << 20, reps=10):
    from moose_tpu.serde import deserialize_value, serialize_value

    value = np.random.default_rng(0).random(nbytes // 8)
    blob = serialize_value(value)
    t_ser = _median_time(lambda: serialize_value(value), reps)
    t_de = _median_time(lambda: deserialize_value(blob, "alice"), reps)
    return _emit(
        {
            "metric": "serde_roundtrip_gbytes_per_sec",
            "value": round(nbytes / (t_ser + t_de) / 1e9, 3),
            "unit": "GB/s",
            "serialize_gbps": round(nbytes / t_ser / 1e9, 3),
            "deserialize_gbps": round(nbytes / t_de / 1e9, 3),
            "payload_mb": nbytes >> 20,
        }
    )


def bench_networking_inmem(reps=200):
    from moose_tpu.distributed.networking import LocalNetworking

    net = LocalNetworking()
    small = np.ones((8,))
    big = np.random.default_rng(1).random(1 << 20)  # 8 MB

    # sessions never reuse a rendezvous key (the cell store DROPS a
    # duplicate delivery of a consumed key), so each rep gets a fresh
    # key — exactly what a real session's per-edge keys look like
    seq = iter(range(10_000_000))

    def roundtrip(value, prefix):
        key = f"{prefix}-{next(seq)}"
        net.send(value, "bob", key, "bench-sess")
        return net.receive("alice", key, "bench-sess", "bob", timeout=5.0)

    t_small = _median_time(lambda: roundtrip(small, "k-small"), reps)
    t_big = _median_time(lambda: roundtrip(big, "k-big"), max(3, reps // 20))
    return _emit(
        {
            "metric": "networking_inmem_roundtrips_per_sec",
            "value": round(1.0 / t_small, 1),
            "unit": "roundtrips/s",
            "big_payload_gbps": round(big.nbytes / t_big / 1e9, 3),
        }
    )


def bench_networking_tcp(reps=100):
    """Loopback round-trips through the native C++ TCP transport
    (native/tcp_transport.cpp; reference networking/tcpstream.rs)."""
    from moose_tpu.distributed.networking import TcpNetworking

    import socket

    def free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    endpoints = {
        "alice": f"127.0.0.1:{free_port()}",
        "bob": f"127.0.0.1:{free_port()}",
    }
    a = TcpNetworking("alice", endpoints).start()
    b = TcpNetworking("bob", endpoints).start()
    try:
        small = np.ones((8,))
        big = np.random.default_rng(2).random(1 << 20)  # 8 MB
        seq = [0]

        def roundtrip(value):
            seq[0] += 1
            key = f"k{seq[0]}"
            a.send(value, "bob", key, "bench-sess")
            return b.receive("alice", key, "bench-sess", "bob", timeout=10.0)

        roundtrip(small)  # connection warmup
        t_small = _median_time(lambda: roundtrip(small), reps)
        t_big = _median_time(lambda: roundtrip(big), max(3, reps // 20))
        return _emit(
            {
                "metric": "networking_tcp_roundtrips_per_sec",
                "value": round(1.0 / t_small, 1),
                "unit": "roundtrips/s",
                "big_payload_gbps": round(big.nbytes / t_big / 1e9, 3),
            }
        )
    finally:
        a.stop()
        b.stop()


SUITES = {
    "exec": bench_exec,
    "runtime": bench_runtime,
    "serde": bench_serde,
    "net-inmem": bench_networking_inmem,
    "net-tcp": bench_networking_tcp,
}


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--suite", choices=sorted(SUITES), default=None)
    parser.add_argument("--depth", type=int, default=200)
    args = parser.parse_args(argv)
    if args.suite == "exec":
        bench_exec(depth=args.depth)
    elif args.suite:
        SUITES[args.suite]()
    else:
        bench_exec(depth=args.depth)
        bench_runtime()
        bench_serde()
        bench_networking_inmem()
        bench_networking_tcp()


if __name__ == "__main__":
    main()
