"""Secure dot-product benchmarks: the reference's two tables
(benchmarks/README.md:15-36 — sequential chains and parallel batches of
replicated dots at several sizes), through the real user path
(@pm.computation -> LocalMooseRuntime, whole graph fused by XLA).

  python benchmarks/dot_product.py --c seq --n 100 --size 1000
  python benchmarks/dot_product.py --all   # reproduce every table row
"""

import argparse
import json
import statistics
import time

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import moose_tpu as pm
from moose_tpu.dialects import ring as _ring
from moose_tpu.runtime import LocalMooseRuntime

alice = pm.host_placement("alice")
bob = pm.host_placement("bob")
carole = pm.host_placement("carole")
rep = pm.replicated_placement(name="rep", players=[alice, bob, carole])

FIXED = pm.fixed(8, 27)


def setup_par_dot_computation(n_parallel):
    @pm.computation
    def dot_product_comp(
        x_arg: pm.Argument(placement=alice, dtype=pm.float64),
        y_arg: pm.Argument(placement=bob, dtype=pm.float64),
    ):
        with alice:
            x = pm.cast(x_arg, dtype=FIXED)
        with bob:
            y = pm.cast(y_arg, dtype=FIXED)
        with rep:
            x_rep = pm.identity(x)
            y_rep = pm.identity(y)
            z_dots = [pm.dot(x_rep, y_rep) for _ in range(n_parallel)]
            z = pm.add_n(z_dots) if n_parallel > 1 else z_dots[0]
        with carole:
            res = pm.cast(z, dtype=pm.float64)
        return res

    return dot_product_comp


def setup_seq_dot_computation(n_seq):
    @pm.computation
    def dot_product_comp(
        x_arg: pm.Argument(placement=alice, dtype=pm.float64),
        y_arg: pm.Argument(placement=bob, dtype=pm.float64),
    ):
        with alice:
            x = pm.cast(x_arg, dtype=FIXED)
        with bob:
            y = pm.cast(y_arg, dtype=FIXED)
        with rep:
            y_rep = pm.identity(y)
            z = pm.dot(x, y_rep)
            for _ in range(1, n_seq):
                z = pm.dot(z, y_rep)
        with carole:
            res = pm.cast(z, dtype=pm.float64)
        return res

    return dot_product_comp


def run_one_spmd(comp_type, n, size, n_exp=5):
    """The same dot workloads through the party-stacked SPMD kernels:
    shares stay on device between chained dots (matching the reference's
    in-protocol chains), the whole chain is one fused XLA program, and a
    scalar checksum forces true end-to-end execution per iteration."""
    import time as _time

    import jax
    import jax.numpy as jnp

    from moose_tpu.parallel import spmd

    I, F, W = 8, 27, 128
    rng = np.random.default_rng(42)
    scale = (0.9 / size) ** 0.5
    x = rng.uniform(0.5, 1.0, size=(size, size)) * scale
    y = rng.uniform(0.5, 1.0, size=(size, size)) * scale
    mk = np.frombuffer(b"moose-tpu-bench!", dtype=np.uint32)

    def chain(master_key, x_f, y_f):
        sess = spmd.SpmdSession(master_key)
        xs = spmd.fx_encode_share(sess, x_f, I, F, W)
        ys = spmd.fx_encode_share(sess, y_f, I, F, W)
        z0 = spmd.fx_dot(sess, xs, ys)
        if n == 1:
            return jnp.sum(spmd.fx_reveal_decode(z0))
        # the remaining n-1 dots run under lax.scan — ONE compiled step
        # regardless of chain length (unrolling 100 dot+trunc protocols
        # overwhelms the compiler).  Each step gets its own session key so
        # masks are fresh per iteration, exactly as an unrolled chain.
        step_keys = spmd.derive_step_keys(master_key, n)[1:]
        if comp_type == "seq":

            def body(z, k):
                s = spmd.SpmdSession(k)
                return spmd.fx_dot(s, z, ys), None

        else:
            # parallel dots must NOT reuse one sharing: XLA would CSE n
            # identical dots into one.  Fresh sharing per step keeps all
            # n dot protocols genuinely executed (the accumulation into
            # one sum mirrors the reference's add_n of the dot results).
            def body(z, k):
                s = spmd.SpmdSession(k)
                xi = spmd.fx_encode_share(s, x_f, I, F, W)
                zi = spmd.fx_dot(s, xi, ys)
                return spmd.fx_add(z, zi), None

        z, _ = jax.lax.scan(body, z0, step_keys)
        return jnp.sum(spmd.fx_reveal_decode(z))

    fn = jax.jit(chain)
    da, db = jax.device_put(x), jax.device_put(y)
    float(fn(mk, da, db))  # compile + warm
    times = []
    for _ in range(n_exp):
        t0 = _time.perf_counter()
        float(fn(mk, da, db))
        times.append(_time.perf_counter() - t0)
    return {
        "bench": f"{comp_type}_dot",
        "engine": "spmd",
        "n": n,
        "size": size,
        "median_s": statistics.median(times),
        "min_s": min(times),
        "max_s": max(times),
    }


def run_one(comp_type, n, size, n_exp=5, chunk=10):
    """Time n secure dots of (size x size).

    Long sequential chains are executed as n/chunk compiled chains of
    length ``chunk``, feeding each chunk's revealed output back in as the
    next chunk's argument — unrolling hundreds of dot+TruncPr protocols
    into one XLA program exhausts the compiler, and chunking adds work
    (an extra share/reveal per chunk boundary), never removes it."""
    rng = np.random.default_rng(42)
    # keep magnitudes small so a chain of n dots stays in fixed(8, 27)
    scale = (0.9 / size) ** 0.5
    x = rng.uniform(0.5, 1.0, size=(size, size)) * scale
    y = rng.uniform(0.5, 1.0, size=(size, size)) * scale
    runtime = LocalMooseRuntime(["alice", "bob", "carole"], use_jit=True)

    chunks = 1
    if comp_type == "seq" and n > chunk:
        # largest divisor of n not exceeding the requested chunk length,
        # so any n works (n=25 -> 5 chunks of 5)
        chunk = max(d for d in range(1, chunk + 1) if n % d == 0)
        chunks = n // chunk
        comp = setup_seq_dot_computation(chunk)
    elif comp_type == "seq":
        comp = setup_seq_dot_computation(n)
    else:
        comp = setup_par_dot_computation(n)

    def run():
        args = {"x_arg": x, "y_arg": y}
        for _ in range(chunks):
            (out,) = runtime.evaluate_computation(
                comp, arguments=args
            ).values()
            args = {"x_arg": np.asarray(out), "y_arg": y}
        return out

    run()  # compile
    times = []
    for _ in range(n_exp):
        t0 = time.perf_counter()
        run()
        times.append(time.perf_counter() - t0)
    result = {
        "bench": f"{comp_type}_dot",
        "n": n,
        "size": size,
        "median_s": statistics.median(times),
        "min_s": min(times),
        "max_s": max(times),
    }
    if chunks > 1:
        result["chunked"] = f"{chunks}x{chunk}"
    return result


# reference tables (moose column, 3x c5.9xlarge over gRPC,
# benchmarks/README.md:19-36)
REFERENCE_ROWS = [
    ("seq", 1, 1000, 5.910),
    ("seq", 100, 100, 0.675),
    ("seq", 100, 1000, 545.675),
    ("parallel", 100, 1000, 163.098),
    ("parallel", 1, 1, 0.039),
]


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--c", dest="comp_type", default="parallel",
                        choices=["seq", "parallel"])
    parser.add_argument("--n", type=int, default=1)
    parser.add_argument("--size", type=int, default=1000)
    parser.add_argument("--n_exp", type=int, default=5)
    parser.add_argument(
        "--engine", choices=["runtime", "spmd"], default="spmd",
        help="runtime = full eDSL/LocalMooseRuntime path (per-op protocol "
        "graphs; slow to XLA-compile for big chains); spmd = party-stacked "
        "kernels, shares device-resident across the chain (default)",
    )
    parser.add_argument(
        "--prf", choices=["rbg", "threefry", "threefry-pallas", "aes-ctr"], default=None,
        help="PRF for mask generation (default: the library default; "
        "threefry is the cryptographic mode distributed workers require)",
    )
    parser.add_argument("--all", action="store_true",
                        help="run every reference table row")
    args = parser.parse_args()
    if args.prf:
        _ring.set_prf_impl(args.prf)


    rows = (
        [(c, n, s, ref) for c, n, s, ref in REFERENCE_ROWS]
        if args.all
        else [(args.comp_type, args.n, args.size, None)]
    )
    for comp_type, n, size, ref in rows:
        if args.engine == "spmd":
            result = run_one_spmd(comp_type, n, size, args.n_exp)
        else:
            result = run_one(comp_type, n, size, args.n_exp)
        if ref is not None:
            result["reference_s"] = ref
            result["speedup"] = ref / result["median_s"]
        result["prf"] = _ring.get_prf_impl()
        print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
