"""Secure softmax/argmax on TPU: stacked-SPMD fused vs per-op eager.

VERDICT r3 weak-point 1 / task 3: heavy protocol graphs (secure softmax
lowers to ~10k host ops) used to be gated to per-op eager dispatch on
TPU because of the known axon-backend fusion miscompile.  Two escapes
now exist and this bench measures both against the eager floor:

  spmd    the party-stacked nonlinear library (parallel/spmd_math.py):
          softmax/argmax as ONE small fused XLA program per step —
          the layout that sidesteps the miscompile by construction
          (regular kernels instead of a 10k-op lowered graph).
  jit     the logical-graph path under the validated-jit self-check
          (interpreter.py: segmented candidate promoted only after
          bit-exact agreement with a structure-identical eager run).
  eager   the library-default safe path on TPU (per-op dispatch).

Run: python benchmarks/softmax_bench.py [--rows 64] [--classes 10]
Prints one JSON line per mode; correctness is asserted against jax.nn
softmax/argmax on the plaintext within fixed-point tolerance.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import moose_tpu  # noqa: F401
import jax
import jax.numpy as jnp

jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

I, F, W = 14, 23, 128


def bench_spmd(rows, classes, t_iters=5, reps=3):
    from moose_tpu.parallel import spmd
    from moose_tpu.parallel import spmd_math as sm

    rng = np.random.default_rng(5)
    x = rng.normal(size=(rows, classes)) * 2.0
    mk = np.frombuffer(b"moose-tpu-bench!", dtype=np.uint32)

    @jax.jit
    def one(master_key, x_f):
        sess = spmd.SpmdSession(master_key)
        xs = spmd.fx_encode_share(sess, x_f, I, F, W)
        probs = sm.fx_softmax(sess, xs, 1)
        am = sm.fx_argmax(sess, xs, 1)
        return (
            spmd.fx_reveal_decode(probs),
            spmd.reveal(am)[0],
        )

    da = jax.device_put(x)
    probs, am = one(mk, da)
    probs, am = np.asarray(probs), np.asarray(am)
    want = np.asarray(jax.nn.softmax(x, axis=1))
    err = np.abs(probs - want).max()
    assert err < 2e-2, f"softmax mismatch: {err}"
    am_want = x.argmax(axis=1)
    agree = (am == am_want).mean()
    assert agree > 0.99, f"argmax agreement: {agree}"

    @jax.jit
    def chained(master_key, x_f):
        keys = spmd.derive_step_keys(
            jnp.asarray(master_key, jnp.uint32), t_iters
        )

        def body(c, k):
            sess = spmd.SpmdSession(k)
            xs = spmd.fx_encode_share(sess, x_f + c, I, F, W)
            probs = sm.fx_softmax(sess, xs, 1)
            return jnp.sum(spmd.fx_reveal_decode(probs)) * 1e-9, None

        c, _ = jax.lax.scan(body, jnp.float64(0), keys)
        return c

    float(chained(mk, da))
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        s = chained(mk, da)
        float(s)
        times.append(time.perf_counter() - t0)
    per_iter = min(times) / t_iters
    return {
        "metric": "secure_softmax_spmd_latency",
        "value": round(per_iter, 4),
        "unit": "s",
        "rows": rows,
        "classes": classes,
        "softmax_max_err": float(err),
        "argmax_agreement": float(agree),
    }


def _runtime_softmax(rows, classes, use_jit, heavy_jit, reps=3):
    import moose_tpu as pm
    from moose_tpu.runtime import LocalMooseRuntime

    if heavy_jit:
        os.environ["MOOSE_TPU_TPU_JIT_HEAVY"] = "1"
    else:
        os.environ.pop("MOOSE_TPU_TPU_JIT_HEAVY", None)

    alice = pm.host_placement("alice")
    bob = pm.host_placement("bob")
    carole = pm.host_placement("carole")
    rep = pm.replicated_placement(name="rep", players=[alice, bob, carole])
    fixed = pm.fixed(I, F)

    @pm.computation
    def comp(x: pm.Argument(placement=alice, dtype=pm.float64)):
        with alice:
            xf = pm.cast(x, dtype=fixed)
        with rep:
            probs = pm.softmax(xf, axis=1, upmost_index=classes)
        with carole:
            out = pm.cast(probs, dtype=pm.float64)
        return out

    rng = np.random.default_rng(5)
    x = rng.normal(size=(rows, classes)) * 2.0
    runtime = LocalMooseRuntime(
        ["alice", "bob", "carole"], use_jit=use_jit
    )
    t0 = time.perf_counter()
    (out,) = runtime.evaluate_computation(comp, arguments={"x": x}).values()
    first_s = time.perf_counter() - t0
    want = np.asarray(jax.nn.softmax(x, axis=1))
    err = np.abs(np.asarray(out) - want).max()
    assert err < 2e-2, f"softmax mismatch: {err}"
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        runtime.evaluate_computation(comp, arguments={"x": x})
        times.append(time.perf_counter() - t0)
    return float(np.median(times)), first_s, float(err)


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--rows", type=int, default=64)
    parser.add_argument("--classes", type=int, default=10)
    parser.add_argument(
        "--modes", default="spmd,jit,eager",
        help="comma-set of spmd,jit,eager",
    )
    args = parser.parse_args()
    modes = set(args.modes.split(","))

    results = {}
    if "spmd" in modes:
        rec = bench_spmd(args.rows, args.classes)
        results["spmd"] = rec["value"]
        print(json.dumps(rec), flush=True)
    if "jit" in modes:
        lat, first, err = _runtime_softmax(
            args.rows, args.classes, use_jit=True, heavy_jit=True
        )
        results["jit"] = lat
        print(
            json.dumps(
                {
                    "metric": "secure_softmax_validated_jit_latency",
                    "value": round(lat, 4),
                    "unit": "s",
                    "rows": args.rows,
                    "classes": args.classes,
                    "first_call_s": round(first, 2),
                    "max_err": err,
                }
            ),
            flush=True,
        )
    if "eager" in modes:
        lat, first, err = _runtime_softmax(
            args.rows, args.classes, use_jit=False, heavy_jit=False
        )
        results["eager"] = lat
        print(
            json.dumps(
                {
                    "metric": "secure_softmax_eager_latency",
                    "value": round(lat, 4),
                    "unit": "s",
                    "rows": args.rows,
                    "classes": args.classes,
                    "first_call_s": round(first, 2),
                    "max_err": err,
                }
            ),
            flush=True,
        )
    if "eager" in results:
        speedups = {
            f"{m}_speedup_vs_eager": round(results["eager"] / results[m], 1)
            for m in ("spmd", "jit")
            if m in results
        }
        print(json.dumps({"metric": "secure_softmax_speedups", **speedups}),
              flush=True)


if __name__ == "__main__":
    main()
