"""Per-phase roofline breakdown of the headline secure dot.

Answers "where do the milliseconds go" for the party-stacked secure
matmul (``spmd.fx_dot``).  The dev harness reaches the TPU through a
tunnel with a multi-millisecond *serialized per-call* dispatch floor
(scripts/peak_probe.py: a 1000^3 matmul and a 4096^3 matmul both take
~3.5 ms per call), so per-call timing measures the harness, not the
chip.  Every number here is therefore measured as T iterations chained
*inside one jitted program* via ``lax.scan`` (carry-fed so nothing can
be hoisted out of the loop), with one scalar readback at the end —
amortized per-iteration time approximates true device time.

Phases (matching replicated/arith.rs:317-454 + additive/trunc.rs):
  encode+share   fixed-point encode + PRF share of both operands
  cross-products regrouped local contractions x_i(y_i+y_{i+1}) + x_{i+1}y_i
  reshare        zero-share bank draw + add + pair roll
  trunc_pr       probabilistic truncation (mask, reveal c, recombine)
  reveal+decode  share sum + fixed-point decode

Run: python benchmarks/roofline.py [N] [T]
Prints one JSON line with per-phase amortized ms and an MFU estimate
against this chip's *achievable* int8 matmul rate (scripts/peak_probe.py
measures ~113 TOP/s at 8192^3 through this harness).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import moose_tpu  # noqa: F401
import jax
import jax.numpy as jnp

jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from moose_tpu.dialects import ring
from moose_tpu.parallel import spmd

I, F, W = 14, 23, 128

# measured achievable dense int8 rate on this chip+harness (peak_probe)
ACHIEVABLE_INT8_OPS = 113e12


def _chain_time(make_body, init_carry, t_iters, reps=3):
    """Amortized per-iteration seconds of body chained under lax.scan in
    ONE jit call; the carry threads through every iteration so the loop
    body cannot be hoisted, and the final scalar readback forces true
    execution through the async tunnel."""

    @jax.jit
    def run():
        c, _ = jax.lax.scan(
            make_body, init_carry, None, length=t_iters
        )
        leaves = jax.tree_util.tree_leaves(c)
        return sum(jnp.sum(x).astype(jnp.float64) for x in leaves)

    float(run())  # compile + warm
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        s = run()
        float(s)
        times.append(time.perf_counter() - t0)
    # subtract nothing: one dispatch amortized over t_iters is noise
    return float(np.min(times)) / t_iters


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1000
    t_iters = int(sys.argv[2]) if len(sys.argv) > 2 else 20
    rng = np.random.default_rng(3)
    a = rng.normal(size=(n, n))
    b = rng.normal(size=(n, n))
    mk = np.frombuffer(b"moose-tpu-bench!", dtype=np.uint32)

    da, db = jax.device_put(a), jax.device_put(b)

    def fresh_sess(c):
        # fold the loop carry into the master key: each iteration draws a
        # distinct PRF stream AND the scan body stays carry-dependent
        return spmd.SpmdSession(
            jnp.asarray(mk, jnp.uint32) ^ c.astype(jnp.uint32)
        )

    # --- materialized intermediates for phase isolation ---
    @jax.jit
    def stage(x_f, y_f):
        sess = spmd.SpmdSession(mk)
        xs = spmd.fx_encode_share(sess, x_f, I, F, W)
        ys = spmd.fx_encode_share(sess, y_f, I, F, W)
        v_lo, v_hi = spmd._cross_terms(xs.tensor, ys.tensor, _contract)
        z = spmd._reshare(sess, v_lo, v_hi, W)
        zt = spmd.trunc_pr(sess, z, F)
        return xs, ys, v_lo, v_hi, z, zt

    def _contract(a_lo, a_hi, b_lo, b_hi):
        f = jax.vmap(lambda p, ph, q, qh: ring.matmul(p, ph, q, qh))
        return f(a_lo, a_hi, b_lo, b_hi)

    xs, ys, v_lo, v_hi, z, zt = jax.block_until_ready(stage(da, db))

    def inject(rep, c):
        # carry-dependence without changing cost class: one cheap xor
        lo = rep.lo ^ c
        return spmd.SpmdRep(lo, rep.hi, rep.width)

    c0 = jnp.uint64(0)

    def body_share(c, _):
        sess = fresh_sess(c)
        xs_ = spmd.fx_encode_share(sess, da + c.astype(jnp.float64) * 0, I, F, W)
        ys_ = spmd.fx_encode_share(sess, db, I, F, W)
        return xs_.tensor.lo[0, 0, 0, 0] + ys_.tensor.lo[0, 0, 0, 0], None

    def body_cross(c, _):
        xt = inject(xs.tensor, c)
        v_lo_, v_hi_ = spmd._cross_terms(xt, ys.tensor, _contract)
        return v_lo_[0, 0, 0], None

    def body_reshare(c, _):
        sess = fresh_sess(c)
        z_ = spmd._reshare(sess, v_lo ^ c, v_hi, W)
        return z_.lo[0, 0, 0, 0], None

    def body_trunc(c, _):
        sess = fresh_sess(c)
        zt_ = spmd.trunc_pr(sess, inject(z, c), F)
        return zt_.lo[0, 0, 0, 0], None

    def body_reveal(c, _):
        out = ring.fixedpoint_decode(*spmd.reveal(inject(zt, c)), F)
        return c + jnp.sum(out).astype(jnp.uint64), None

    def body_full(c_rep, _):
        # carry the FULL output tensor (a scalar carry would let XLA
        # dead-code-eliminate work not feeding it, flattering the number)
        sess = fresh_sess(c_rep.lo[0, 0, 0, 0])
        z_ = spmd.fx_dot(
            sess, spmd.SpmdFixed(c_rep, I, F),
            spmd.SpmdFixed(ys.tensor, I, F),
        )
        return z_.tensor, None

    phases = {
        "share_ms": _chain_time(body_share, c0, t_iters),
        "cross_products_ms": _chain_time(body_cross, c0, t_iters),
        "reshare_ms": _chain_time(body_reshare, c0, t_iters),
        "trunc_pr_ms": _chain_time(body_trunc, c0, t_iters),
        "reveal_decode_ms": _chain_time(body_reveal, c0, t_iters),
        "full_chained_ms": _chain_time(body_full, xs.tensor, t_iters),
    }
    phases = {k: round(v * 1e3, 3) for k, v in phases.items()}

    # sanity: full secure dot still correct end to end
    @jax.jit
    def full(x_f, y_f):
        sess = spmd.SpmdSession(mk)
        xs_ = spmd.fx_encode_share(sess, x_f, I, F, W)
        ys_ = spmd.fx_encode_share(sess, y_f, I, F, W)
        zz = spmd.fx_dot(sess, xs_, ys_)
        return spmd.fx_reveal_decode(zz)

    out = np.asarray(full(da, db))
    err = np.abs(out - a @ b).max()
    assert err < 2e-4, f"secure dot mismatch: {err}"

    # MFU estimate for the cross-product phase: the regrouped secure dot
    # does 2 contractions x 3 parties; each u128 limb_int8 matmul is 136
    # s8xs8->s32 (n, n, n)-MAC slabs (pairs i+j < 16 of 16 limbs)
    strat = ring.get_matmul_strategy()
    record = {
        "metric": "secure_dot_phase_breakdown",
        "n": n,
        "t_iters": t_iters,
        "prf": ring.get_prf_impl(),
        "matmul_strategy": strat,
        "int8_diag": os.environ.get("MOOSE_TPU_INT8_DIAG", "pairs"),
        **phases,
        "sum_of_phases_ms": round(
            sum(v for k, v in phases.items() if k != "full_chained_ms"), 3
        ),
    }
    if strat == "limb_int8":
        ops = 2 * 2 * 3 * 136 * n * n * n  # 2 ops/MAC
        t_cross = phases["cross_products_ms"] / 1e3
        record["cross_mxu_ops"] = ops
        record["cross_mfu_vs_achievable_int8"] = round(
            (ops / t_cross) / ACHIEVABLE_INT8_OPS, 3
        )
        record["achievable_int8_roofline_ms"] = round(
            ops / ACHIEVABLE_INT8_OPS * 1e3, 3
        )
    print(json.dumps(record), flush=True)


if __name__ == "__main__":
    main()
