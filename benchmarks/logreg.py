"""Encrypted logistic-regression training benchmark: the reference's third
table (benchmarks/README.md:41-60 — SGD+momentum over replicated sharing,
fixed(24, 40), batches of a 100-feature dataset), same computation
structure, through LocalMooseRuntime with the whole training graph fused
by XLA.

  python benchmarks/logreg.py --batch_size 128 --n_iter 10
"""

import argparse
import json
import statistics
import time

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import moose_tpu as pm
from moose_tpu.dialects import ring as _ring
from moose_tpu.runtime import LocalMooseRuntime

alice = pm.host_placement("alice")
bob = pm.host_placement("bob")
carole = pm.host_placement("carole")
repl = pm.replicated_placement(name="rep", players=[alice, bob, carole])
mirr = pm.mirrored_placement(name="mirr", players=[alice, bob, carole])

N_FEATURES = 100
LEARNING_RATE = 0.1
MOMENTUM = 0.9
FIXED_DTYPE = pm.fixed(24, 40)


def build_train(batch_size, n_batches):
    @pm.computation
    def train(
        x: pm.Argument(placement=alice, dtype=pm.float64),
        y: pm.Argument(placement=alice, dtype=pm.float64),
        w_0: pm.Argument(placement=bob, dtype=pm.float64),
        b_0: pm.Argument(placement=bob, dtype=pm.float64),
    ):
        with alice:
            xf = pm.cast(x, dtype=FIXED_DTYPE)
            yf = pm.cast(y, dtype=FIXED_DTYPE)
            x_batches = [
                xf[i * batch_size:(i + 1) * batch_size, :]
                for i in range(n_batches)
            ]
            y_batches = [
                yf[i * batch_size:(i + 1) * batch_size, :]
                for i in range(n_batches)
            ]

        with bob:
            w = pm.cast(w_0, dtype=FIXED_DTYPE)
            b = pm.cast(b_0, dtype=FIXED_DTYPE)
            lr = pm.cast(
                pm.constant(LEARNING_RATE, dtype=pm.float64),
                dtype=FIXED_DTYPE,
            )
            mom = pm.cast(
                pm.constant(MOMENTUM, dtype=pm.float64),
                dtype=FIXED_DTYPE,
            )

        with mirr:
            # public 1/batch_size pinned to the mirrored placement so the
            # public-private scaling is a cheap mul (reference logreg.py)
            batch_size_inv = pm.constant(
                1.0 / batch_size, dtype=FIXED_DTYPE
            )

        with repl:
            x_batches = [pm.identity(xb) for xb in x_batches]
            grad_cache = None
            for xb, yb in zip(x_batches, y_batches):
                y_hat = pm.sigmoid(pm.dot(xb, w) + b)
                dy = y_hat - yb
                xT = pm.transpose(xb)
                dW = pm.mul(pm.dot(xT, dy), batch_size_inv)
                db = pm.mul(pm.sum(dy, axis=0), batch_size_inv)
                deltaW = dW * lr
                deltab = db * lr
                if grad_cache is not None:
                    deltaW_0, deltab_0 = grad_cache
                    deltaW = deltaW + deltaW_0 * mom
                    deltab = deltab + deltab_0 * mom
                grad_cache = (deltaW, deltab)
                w = w - deltaW
                b = b - deltab

        with bob:
            w_out = pm.cast(w, dtype=pm.float64)
            b_out = pm.cast(b, dtype=pm.float64)

        return w_out, b_out

    return train




def _plaintext_sgd(x, y, batch_size, n_batches, lr):
    """Float64 replica of spmd.logreg_train_step's exact math (degree-3
    polynomial sigmoid, plain SGD) — the elementwise reference
    trajectory the secure run must track to fixed-point noise."""
    w = np.zeros((N_FEATURES, 1))
    xb = x.reshape(n_batches, batch_size, N_FEATURES)
    yb = y.reshape(n_batches, batch_size, 1)
    for i in range(n_batches):
        t = xb[i] @ w
        preds = 0.5 + 0.19828547 * t - 0.00446928 * (t ** 3)
        grad = xb[i].T @ (preds - yb[i])
        w = w - (lr / batch_size) * grad
    return w


def _plaintext_sgd_momentum(x, y, batch_size, n_batches, lr, mom):
    """Float64 replica of build_train's exact math (protocol sigmoid is
    accurate to ~1e-9, so numpy's exact sigmoid is a valid reference):
    SGD + momentum over the unrolled batches."""
    w = np.zeros((N_FEATURES, 1))
    b = np.zeros((1,))
    xb = x.reshape(n_batches, batch_size, N_FEATURES)
    yb = y.reshape(n_batches, batch_size, 1)
    dW_prev = db_prev = None
    for i in range(n_batches):
        y_hat = 1.0 / (1.0 + np.exp(-(xb[i] @ w + b)))
        dy = y_hat - yb[i]
        dW = (xb[i].T @ dy) / batch_size * lr
        db = dy.sum(axis=0) / batch_size * lr
        if dW_prev is not None:
            dW = dW + dW_prev * mom
            db = db + db_prev * mom
        dW_prev, db_prev = dW, db
        w = w - dW
        b = b - db
    return w


def _check_trajectory(w_fit, w_ref, true_w, atol=1e-3):
    """Elementwise gate: the secure weights must match the plaintext
    trajectory to fixed-point noise (a corr>0.2 floor would pass a
    badly broken trainer); correlation is reported, not asserted."""
    w_fit = np.ravel(np.asarray(w_fit))
    err = float(np.abs(w_fit - np.ravel(w_ref)).max())
    assert err < atol, (
        f"secure training diverged from the plaintext trajectory "
        f"(max |dw|={err:.2e}, gate {atol})"
    )
    return float(np.corrcoef(w_fit, np.ravel(true_w))[0, 1]), err


def run_spmd(batch_size, n_batches, n_exp):
    """Same workload through the party-stacked SPMD kernels: the batch
    loop is a lax.scan of logreg_train_step (one compiled step for any
    iteration count; per-step session keys keep masks fresh)."""
    import jax
    import jax.numpy as jnp

    from moose_tpu.parallel import spmd

    I, F, W = 24, 40, 128
    n_instances = batch_size * n_batches
    rng = np.random.default_rng(7)
    x = rng.normal(size=(n_instances, N_FEATURES)) * 0.1
    true_w = rng.normal(size=(N_FEATURES, 1))
    y = (x @ true_w + 0.05 * rng.normal(size=(n_instances, 1)) > 0)
    y = y.astype(np.float64)
    mk = np.frombuffer(b"moose-tpu-logreg", dtype=np.uint32)

    def train(master_key, x_f, y_f):
        sess = spmd.SpmdSession(master_key)
        # batches scan over their leading axis as raw floats and are
        # shared inside the step (the party axes of SpmdFixed lead, so a
        # pre-shared batch stack cannot be a scan input; per-batch sharing
        # is a strict superset of the reference's share-once work)
        xb = x_f.reshape(n_batches, batch_size, N_FEATURES)
        yb = y_f.reshape(n_batches, batch_size, 1)
        w0 = spmd.fx_encode_share(
            sess, jnp.zeros((N_FEATURES, 1)), I, F, W
        )
        step_keys = spmd.derive_step_keys(master_key, n_batches)

        def body(w, inputs):
            k, xi, yi = inputs
            s = spmd.SpmdSession(k)
            xs = spmd.fx_encode_share(s, xi, I, F, W)
            ys = spmd.fx_encode_share(s, yi, I, F, W)
            return spmd.logreg_train_step(
                s, xs, ys, w, LEARNING_RATE
            ), None

        w, _ = jax.lax.scan(body, w0, (step_keys, xb, yb))
        return jnp.sum(spmd.fx_reveal_decode(w)), spmd.fx_reveal_decode(w)

    fn = jax.jit(train)
    da, db = jax.device_put(x), jax.device_put(y)
    _, w_fit = fn(mk, da, db)
    w_ref = _plaintext_sgd(x, y, batch_size, n_batches, LEARNING_RATE)
    corr, traj_err = _check_trajectory(w_fit, w_ref, true_w)

    times = []
    for _ in range(n_exp):
        t0 = time.perf_counter()
        float(fn(mk, da, db)[0])
        times.append(time.perf_counter() - t0)
    print(json.dumps({
        "bench": "logreg_train",
        "engine": "spmd",
        "batch_size": batch_size,
        "n_iter": n_batches,
        "median_s": statistics.median(times),
        "min_s": min(times),
        "max_s": max(times),
        "weight_corr": float(corr),
        "trajectory_max_abs_err": traj_err,
        "prf": _ring.get_prf_impl(),
    }))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--n_exp", type=int, default=3)
    parser.add_argument("--batch_size", type=int, default=128)
    parser.add_argument("--n_iter", type=int, default=10)
    parser.add_argument(
        "--engine", choices=["runtime", "spmd"], default="spmd",
        help="runtime = eDSL/LocalMooseRuntime (SGD+momentum, unrolled "
        "graph); spmd = party-stacked kernels with the batch loop under "
        "lax.scan (plain SGD; default)",
    )
    parser.add_argument(
        "--prf", choices=["rbg", "threefry", "threefry-pallas", "aes-ctr"], default=None,
        help="PRF for mask generation (default: the library default; "
        "threefry is the cryptographic mode distributed workers require)",
    )
    args = parser.parse_args()
    if args.prf:
        _ring.set_prf_impl(args.prf)

    if args.engine == "spmd":
        run_spmd(args.batch_size, args.n_iter, args.n_exp)
        return

    batch_size, n_batches = args.batch_size, args.n_iter
    n_instances = batch_size * n_batches

    rng = np.random.default_rng(7)
    x = rng.normal(size=(n_instances, N_FEATURES)) * 0.1
    true_w = rng.normal(size=(N_FEATURES, 1))
    y = (x @ true_w + 0.05 * rng.normal(size=(n_instances, 1)) > 0)
    y = y.astype(np.float64)
    w0 = np.zeros((N_FEATURES, 1))
    b0 = np.zeros((1,))

    train = build_train(batch_size, n_batches)
    runtime = LocalMooseRuntime(["alice", "bob", "carole"], use_jit=True)
    arguments = {"x": x, "y": y, "w_0": w0, "b_0": b0}

    outs = runtime.evaluate_computation(train, arguments=arguments)
    w_fit = next(iter(outs.values()))
    w_ref = _plaintext_sgd_momentum(
        x, y, batch_size, n_batches, LEARNING_RATE, MOMENTUM
    )
    corr, traj_err = _check_trajectory(w_fit, w_ref, true_w)

    times = []
    for _ in range(args.n_exp):
        t0 = time.perf_counter()
        runtime.evaluate_computation(train, arguments=arguments)
        times.append(time.perf_counter() - t0)

    print(json.dumps({
        "bench": "logreg_train",
        "batch_size": batch_size,
        "n_iter": n_batches,
        "median_s": statistics.median(times),
        "min_s": min(times),
        "max_s": max(times),
        "weight_corr": float(corr),
        "trajectory_max_abs_err": traj_err,
        "prf": _ring.get_prf_impl(),
    }))


if __name__ == "__main__":
    main()
