"""Durable warm-state snapshots for the serving registry.

A blitzen replica's warm state is expensive: tracing each predictor,
compiling every batch bucket, and driving the validated-jit ladder to
steady state takes minutes, during which the replica cannot serve.  A
snapshot persists everything that survives a process restart so a new
replica cold-starts warm in seconds:

- the **traced computation** of every registered model (reference
  serde msgpack — the same bytes ``elk``/``dasher`` exchange);
- the **resolved plan state** of the validated-jit ladder per plan key
  (ladder level, settled mode, pinned ops), lifted straight from the
  interpreter's plan registry — a restored plan re-enters at its
  settled rung, so the first post-restore evaluation jit-compiles but
  NEVER re-validates (no eager reference run, ``validating_after_warm``
  stays 0);
- the **lowered computations** the runtime auto-compiled during warmup
  (per-host routed models), keyed exactly as the runtime's compiled
  cache keys them, each with its own plan state;
- the **Pallas kernel verdicts** (per ``(kernel, width)`` first-use
  bit-exactness outcomes) — fallback pins always restore (skipping a
  doomed kernel is safe anywhere); ``ok`` verdicts restore only when
  the snapshot was taken on the SAME jax backend;
- **AOT-exported compiled batch buckets** where ``jax.export`` supports
  the resolved plan (a promoted whole-graph jit): serialized StableHLO
  artifacts, verdict-tagged per bucket, verified loadable at restore
  (``unsupported:*`` verdicts record exactly why a bucket could not be
  exported — segmented/per-op plans compose multiple XLA programs in
  Python and are rebuilt from plan state + the persistent compilation
  cache instead);
- under ``MOOSE_TPU_FIXED_KEYS``, a per-bucket **probe digest**: the
  blake2b of a canned deterministic evaluation, recomputed at load so a
  restored replica is proven BIT-IDENTICAL to the replica that wrote
  the snapshot before it serves traffic.

Layout (versioned, atomic)::

    <dir>/snapshot-<n>/MANIFEST.json      # format, versions, checksums
    <dir>/snapshot-<n>/<model>.comp       # serde computation bytes
    <dir>/snapshot-<n>/<model>.lowered.<i>  # auto-lowered graphs
    <dir>/snapshot-<n>/<model>.aot.<bucket> # jax.export artifacts
    <dir>/CURRENT                         # points at the live snapshot

Writers stage a complete ``snapshot-<n>`` directory, fsync it, then
atomically repoint ``CURRENT`` — a crash mid-write leaves the previous
snapshot live and the orphan staging directory is pruned on the next
save.  Readers resolve ``CURRENT``, verify the manifest checksum chain,
and fall back to fresh registration on ANY validation failure (typed
:class:`~moose_tpu.errors.SnapshotError` — never serve suspect state).

Invalidation rules (any mismatch rejects the snapshot): snapshot format
version, package version, per-file blake2b checksums, the model-source
digests the caller passes (blitzen digests the ONNX bytes + feature
count + dtype), and the fixed-keys probe digests.  A jax backend
mismatch only drops the kernel ``ok`` verdicts (re-checked on first
use) — the rest of the snapshot stays usable.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import tempfile
import time
from pathlib import Path
from typing import Dict, Optional, Tuple

import numpy as np

from .. import __version__ as _pkg_version
from ..errors import SnapshotError
from ..logger import get_logger

SNAPSHOT_FORMAT = 1
_CURRENT = "CURRENT"


# -- helpers ----------------------------------------------------------------


def _blake(data: bytes) -> str:
    return hashlib.blake2b(data, digest_size=16).hexdigest()


def _freeze(obj):
    """Recursively convert JSON lists back into the tuples the runtime
    cache keys are made of.  Every sequence inside a plan-cache key is a
    tuple of (bool | int | float | str | tuple), so a blanket
    list->tuple restore reproduces the exact key object."""
    if isinstance(obj, list):
        return tuple(_freeze(x) for x in obj)
    return obj


def _probe_rows(bucket: int, row_shape: Tuple[int, ...]) -> np.ndarray:
    """The canned deterministic probe input for one bucket — the same
    generator discipline registry warmup uses, so probe evaluations
    replay a shape the plan already compiled."""
    rng = np.random.default_rng(bucket)
    return rng.normal(size=(bucket, *row_shape))


def _fixed_keys_active() -> bool:
    return bool(os.environ.get("MOOSE_TPU_FIXED_KEYS"))


def _result_digest(arr: np.ndarray) -> str:
    arr = np.asarray(arr)
    meta = f"{arr.shape}|{arr.dtype}".encode()
    return _blake(meta + np.ascontiguousarray(arr).tobytes())


@contextlib.contextmanager
def _fleet_lock(directory: Path, exclusive: bool):
    """Cross-process advisory lock on the snapshot directory: replicas
    legitimately SHARE a snapshot dir (that is the fleet warm-start
    story), so concurrent writers (two replicas draining at once) must
    serialize publication, and a reader mid-restore must never see its
    snapshot pruned out from under it.  Writers take the lock
    exclusively around publish+prune; readers take it shared while
    slurping blobs into memory (never across the re-warm)."""
    import fcntl

    directory.mkdir(parents=True, exist_ok=True)
    with open(directory / ".lock", "a+b") as fd:
        fcntl.flock(
            fd, fcntl.LOCK_EX if exclusive else fcntl.LOCK_SH
        )
        try:
            yield
        finally:
            fcntl.flock(fd, fcntl.LOCK_UN)


def enable_compilation_cache(directory) -> None:
    """Point jax's persistent compilation cache at ``directory`` so a
    restored replica's per-bucket re-jit replays on-disk XLA binaries
    instead of recompiling.  Idempotent; safe to call before any jit."""
    import jax

    path = Path(directory) / "xla_cache"
    path.mkdir(parents=True, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", str(path))
    # cache everything: the serving buckets are exactly the small
    # programs the default 1s threshold would skip
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)


# -- plan-state capture -----------------------------------------------------


def _plan_states_of(comp) -> Dict[str, dict]:
    """JSON-able copy of the interpreter plan registry's entry for one
    computation: {plan_key: {level, mode, pinned}}."""
    from ..execution.interpreter import _registry

    out = {}
    for plan_key, state in (_registry().get(comp) or {}).items():
        out[plan_key] = {
            "level": int(state["level"]),
            "mode": state["mode"],
            "pinned": sorted(state["pinned"] or ()),
        }
    return out


def _restore_plan_states(comp, states: Dict[str, dict]) -> None:
    from ..execution.interpreter import _registry

    entry = _registry().setdefault(comp, {})
    for plan_key, state in states.items():
        entry[plan_key] = {
            "level": int(state["level"]),
            "mode": state["mode"],
            "pinned": frozenset(state["pinned"] or ()),
        }


def _kernel_verdicts() -> Dict[str, str]:
    from ..native import ring128_kernels

    return dict(ring128_kernels.report().get("kernels") or {})


def _restore_kernel_verdicts(verdicts: Dict[str, str],
                             same_backend: bool) -> int:
    """Reinstall per-(kernel, width) verdicts.  ``fallback:*`` pins are
    always safe to restore (they only route a primitive to its XLA
    twin); ``ok`` verdicts skip the first-use bit-exactness check, so
    they restore only when the snapshot's jax backend matches."""
    from ..native import ring128_kernels

    restored = 0
    with ring128_kernels._STATE_LOCK:
        for key, verdict in verdicts.items():
            kernel, _, width = key.partition("/")
            try:
                state_key = (kernel, int(width))
            except ValueError:
                continue
            if verdict == "ok" and not same_backend:
                continue
            if state_key not in ring128_kernels._STATE:
                ring128_kernels._STATE[state_key] = verdict
                restored += 1
    return restored


# -- AOT export (best-effort) ----------------------------------------------


def _resolved_runners(runtime, comp):
    """Yield (bucket_binding_key, runner) for every _SelfCheckRunner the
    runtime's interpreters cached for ``comp``."""
    from ..execution.interpreter import _SelfCheckRunner

    for interp in (
        getattr(runtime, "_stacked", None),
        getattr(runtime, "_interpreter", None),
    ):
        if interp is None:
            continue
        for key, entry in (interp._cache.get(comp) or {}).items():
            fn = entry[1] if isinstance(entry, tuple) else entry
            runner = getattr(fn, "__self__", None)
            if isinstance(runner, _SelfCheckRunner):
                yield key, runner


def _bucket_of_binding(key, input_name: str) -> Optional[int]:
    """Recover the batch-bucket size from a binding cache key: the
    leading dim of the input's recorded shape."""
    for part in key:
        if (
            isinstance(part, tuple)
            and len(part) == 3
            and part[0] == input_name
            and isinstance(part[1], tuple)
            and part[1]
        ):
            return int(part[1][0])
    return None


def _export_aot_buckets(
    runtime, model
) -> Dict[int, Tuple[bytes, str, str]]:
    """Try to AOT-serialize each bucket's resolved executable via
    ``jax.export``.  Only a plan promoted to whole-graph jit is a
    single exportable XLA program; everything else (segmented, per-op,
    eager, still-validating) records an ``unsupported:*`` verdict and
    relies on plan-state restore + the persistent compilation cache.
    Each value is ``(blob, verdict, plan_key)`` — the plan key lets the
    restore side stash the artifact under the binding the runner will
    actually look it up by."""
    out: Dict[int, Tuple[bytes, str, str]] = {}
    if os.environ.get("MOOSE_TPU_SNAPSHOT_AOT", "1") == "0":
        return out
    try:
        from jax import export as jax_export
    except Exception:  # pragma: no cover - ancient jax
        return out
    from ..execution.interpreter import master_key_words

    for key, runner in _resolved_runners(runtime, model.comp):
        bucket = _bucket_of_binding(key, model.input_name)
        if bucket is None or bucket in out:
            continue
        plan_key = getattr(runner, "_plan_key", "logical")
        if runner.mode != "jit" or runner.plan_mode != "whole-graph":
            out[bucket] = (
                b"",
                f"unsupported:plan-{runner.plan_mode}-{runner.mode}",
                plan_key,
            )
            continue
        try:
            import jax
            import jax.numpy as jnp

            probe = _probe_rows(bucket, model.row_shape)
            dyn = {model.input_name: jnp.asarray(probe)}
            # the plan returns runtime-value pytrees (HostTensor, ...)
            # jax.export cannot serialize; export a wrapper yielding
            # the flat leaves instead — the artifact is a raw compute
            # program, not a runtime-value producer
            inner = runner._jit_fn
            flat_fn = jax.jit(
                lambda mk, args: jax.tree_util.tree_leaves(
                    inner(mk, args)
                )
            )
            exported = jax_export.export(flat_fn)(
                master_key_words("logical"), dyn
            )
            out[bucket] = (exported.serialize(), "exported", plan_key)
        except Exception as e:  # noqa: BLE001 — best-effort by contract
            out[bucket] = (
                b"", f"unsupported:{type(e).__name__}", plan_key
            )
    return out


def verify_aot_artifact(blob: bytes):
    """Deserialize one exported bucket back into a callable (raises on
    a corrupt/incompatible artifact).  Callers may invoke the result as
    ``fn(master_key, {input_name: rows})`` on the platform the artifact
    was exported for."""
    from jax import export as jax_export

    exported = jax_export.deserialize(blob)
    return exported.call


# -- save -------------------------------------------------------------------


def save_snapshot(
    server_or_registry,
    directory,
    source_digests: Optional[Dict[str, str]] = None,
    only: Optional[set] = None,
) -> Path:
    """Write a complete warm-state snapshot of every registered model to
    ``directory`` and atomically repoint ``CURRENT`` at it.  Returns the
    new snapshot path.  ``source_digests`` (model name -> opaque digest
    of whatever the caller registered from, e.g. the ONNX bytes) become
    load-time invalidation keys.  ``only`` restricts the snapshot to the
    named models — a replica with ephemeral control-plane generations
    loaded snapshots just its durable set, so the restore side's
    source-digest set-equality check still holds."""
    from ..serde import serialize_computation

    registry = getattr(server_or_registry, "registry", server_or_registry)
    runtime = registry.runtime
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    t0 = time.perf_counter()

    # the stage is private (unique temp name): blob writes and the
    # probe evaluations run UNLOCKED; only the publish below (sequence
    # number, rename, CURRENT repoint, prune) needs the fleet lock
    stage = Path(tempfile.mkdtemp(
        dir=directory, prefix="snapshot-staging."
    ))
    try:
        manifest = {
            "format": SNAPSHOT_FORMAT,
            "package_version": _pkg_version,
            "jax_backend": _jax_backend(),
            "fixed_keys": _fixed_keys_active(),
            "kernel_verdicts": _kernel_verdicts(),
            "models": {},
            "files": {},
        }
        for name in registry.names():
            if only is not None and name not in only:
                continue
            model = registry.get(name)
            entry = {
                "input_name": model.input_name,
                "row_shape": list(model.row_shape),
                "buckets": list(model.buckets),
                "warmup_report": {
                    str(b): dict(r)
                    for b, r in model.warmup_report.items()
                },
                "plan_states": _plan_states_of(model.comp),
                "stacked_rejected": model.comp in getattr(
                    runtime, "_stacked_rejected", ()
                ),
                "lowered": [],
                "aot": {},
                "probe_digests": {},
            }
            if source_digests and name in source_digests:
                entry["source_digest"] = source_digests[name]
            _write_blob(
                stage, manifest, f"{name}.comp",
                serialize_computation(model.comp),
            )
            entry["comp_file"] = f"{name}.comp"
            # auto-lowered graphs (per-host routed models) with their
            # own resolved plan states, keyed as the runtime keys them
            per_comp = getattr(runtime, "_compiled_cache", {}).get(
                model.comp
            ) or {}
            for i, (key, compiled) in enumerate(per_comp.items()):
                lowered = (
                    compiled[0] if isinstance(compiled, tuple) else compiled
                )
                fname = f"{name}.lowered.{i}"
                _write_blob(
                    stage, manifest, fname,
                    serialize_computation(lowered),
                )
                entry["lowered"].append({
                    "key": key,
                    "file": fname,
                    "plan_states": _plan_states_of(lowered),
                })
            for bucket, (blob, verdict, plan_key) in _export_aot_buckets(
                runtime, model
            ).items():
                record = {"verdict": verdict, "plan_key": plan_key}
                if blob:
                    fname = f"{name}.aot.{bucket}"
                    _write_blob(stage, manifest, fname, blob)
                    record["file"] = fname
                entry["aot"][str(bucket)] = record
            if _fixed_keys_active():
                # bit-exactness anchors: one canned evaluation per
                # bucket, digested — the load side must reproduce every
                # digest before the restored replica serves traffic
                for bucket in model.buckets:
                    result, _ = registry.evaluate(
                        model, _probe_rows(bucket, model.row_shape)
                    )
                    entry["probe_digests"][str(bucket)] = (
                        _result_digest(result)
                    )
            manifest["models"][name] = entry
        body = json.dumps(manifest, indent=1, sort_keys=True).encode()
        (stage / "MANIFEST.json").write_bytes(body)
        _fsync_dir_tree(stage)
        with _fleet_lock(directory, exclusive=True):
            final = directory / f"snapshot-{_next_seq(directory)}"
            os.rename(stage, final)
            _repoint_current(directory, final.name)
            _prune(directory, keep=final.name)
    except BaseException:
        _rmtree(stage)
        raise
    get_logger().info(
        "snapshot: wrote %s (%d model(s)) in %.2fs",
        final, len(manifest["models"]), time.perf_counter() - t0,
    )
    return final


def _write_blob(stage: Path, manifest: dict, fname: str,
                data: bytes) -> None:
    (stage / fname).write_bytes(data)
    manifest["files"][fname] = {
        "bytes": len(data), "blake2b": _blake(data),
    }


def _jax_backend() -> str:
    try:
        import jax

        return jax.default_backend()
    except Exception:  # pragma: no cover - jax always importable here
        return "unknown"


def _next_seq(directory: Path) -> int:
    seqs = [0]
    for p in directory.glob("snapshot-*"):
        tail = p.name.split("-", 1)[1].split(".", 1)[0]
        if tail.isdigit():
            seqs.append(int(tail))
    return max(seqs) + 1


def _repoint_current(directory: Path, name: str) -> None:
    tmp = directory / (_CURRENT + ".tmp")
    tmp.write_text(name + "\n")
    os.replace(tmp, directory / _CURRENT)


def _prune(directory: Path, keep: str, history: int = 1) -> None:
    """Drop crash-orphaned staging leftovers and all but ``history``
    predecessors.  A staging dir is only an orphan when it is OLD —
    a recent one may belong to another replica mid-save (staging is
    deliberately done outside the fleet lock)."""
    snaps = [
        p for p in directory.glob("snapshot-*")
        if p.is_dir() and p.name != keep
    ]
    now = time.time()
    stale = [
        p for p in snaps
        if "staging" in p.name and now - p.stat().st_mtime > 3600
    ]
    # numeric sort: lexicographic ordering would rank snapshot-10
    # before snapshot-9 and delete the true predecessor
    numbered = sorted(
        (
            p for p in snaps
            if "staging" not in p.name
            and p.name.split("-")[-1].isdigit()
        ),
        key=lambda p: int(p.name.split("-")[-1]),
    )
    stale += numbered[:-history] if history else numbered
    for p in stale:
        _rmtree(p)


def _rmtree(path: Path) -> None:
    import shutil

    with contextlib.suppress(OSError):
        shutil.rmtree(path)


def _fsync_dir_tree(stage: Path) -> None:
    with contextlib.suppress(OSError):
        for p in stage.iterdir():
            with open(p, "rb") as f:
                os.fsync(f.fileno())
        fd = os.open(stage, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)


# -- load -------------------------------------------------------------------


def current_snapshot_path(directory) -> Optional[Path]:
    """Resolve ``CURRENT`` to the live snapshot directory (None when no
    snapshot has ever been written)."""
    directory = Path(directory)
    pointer = directory / _CURRENT
    if not pointer.exists():
        return None
    name = pointer.read_text().strip()
    path = directory / name
    return path if path.is_dir() else None


def read_manifest(snapshot_path: Path) -> dict:
    """Parse + checksum-verify a snapshot's manifest.  Raises
    :class:`SnapshotError` on any validation failure."""
    return _read_verified(snapshot_path)[0]


def _read_verified(snapshot_path: Path):
    """(manifest, {fname: bytes}) with every blob checksum-verified —
    the blobs come back IN MEMORY so the caller can release the fleet
    lock before the (slow) re-warm, immune to concurrent pruning."""
    try:
        manifest = json.loads(
            (snapshot_path / "MANIFEST.json").read_text()
        )
    except (OSError, json.JSONDecodeError) as e:
        raise SnapshotError(f"unreadable manifest in {snapshot_path}: {e}")
    if manifest.get("format") != SNAPSHOT_FORMAT:
        raise SnapshotError(
            f"snapshot format {manifest.get('format')!r} != supported "
            f"{SNAPSHOT_FORMAT}"
        )
    if manifest.get("package_version") != _pkg_version:
        raise SnapshotError(
            f"snapshot written by moose_tpu "
            f"{manifest.get('package_version')!r}, this build is "
            f"{_pkg_version!r}"
        )
    blobs: Dict[str, bytes] = {}
    for fname, spec in (manifest.get("files") or {}).items():
        try:
            data = (snapshot_path / fname).read_bytes()
        except OSError as e:
            raise SnapshotError(f"snapshot blob {fname} unreadable: {e}")
        if _blake(data) != spec.get("blake2b"):
            raise SnapshotError(
                f"snapshot blob {fname} failed its checksum"
            )
        blobs[fname] = data
    return manifest, blobs


def restore_registry(
    registry,
    directory,
    source_digests: Optional[Dict[str, str]] = None,
    rewarm: bool = True,
) -> dict:
    """Restore every model in the live snapshot under ``directory`` into
    ``registry`` (which must be empty of those names).  Returns a report
    ``{models, rewarm_s, probe_checked, aot}``.

    Restore order per model: deserialize the traced computation,
    reinstall its resolved plan states (and those of every lowered
    graph) in the interpreter plan registry, reinstall lowered graphs in
    the runtime's compiled cache, then — when ``rewarm`` — run ONE
    evaluation per bucket.  That evaluation jit-compiles (from the
    persistent compilation cache when enabled) but never validates: the
    ladder re-enters at its settled mode.  Under MOOSE_TPU_FIXED_KEYS
    the rewarm doubles as the bit-exactness proof against the writer's
    probe digests; any divergence raises :class:`SnapshotError` before
    the model is installed."""
    from ..serde import deserialize_computation
    from .registry import RegisteredModel

    directory = Path(directory)
    if not directory.is_dir():
        raise SnapshotError(f"no snapshot under {directory}")
    with _fleet_lock(directory, exclusive=False):
        snapshot_path = current_snapshot_path(directory)
        if snapshot_path is None:
            raise SnapshotError(f"no snapshot under {directory}")
        manifest, blobs = _read_verified(snapshot_path)
    models = manifest.get("models") or {}
    if not models:
        raise SnapshotError(f"snapshot {snapshot_path} holds no models")
    if source_digests is not None:
        if set(source_digests) != set(models):
            raise SnapshotError(
                f"snapshot models {sorted(models)} != requested "
                f"{sorted(source_digests)}"
            )
        for name, digest in source_digests.items():
            if models[name].get("source_digest") != digest:
                raise SnapshotError(
                    f"model {name!r}: source digest mismatch (the "
                    "model file changed since the snapshot was written)"
                )
    restored_kernels = _restore_kernel_verdicts(
        manifest.get("kernel_verdicts") or {},
        same_backend=manifest.get("jax_backend") == _jax_backend(),
    )
    check_probes = _fixed_keys_active() and manifest.get("fixed_keys")
    report = {
        "snapshot": str(snapshot_path),
        "models": [],
        "rewarm_s": 0.0,
        "probe_checked": 0,
        "kernel_verdicts_restored": restored_kernels,
        "aot": {},
    }
    t0 = time.perf_counter()
    runtime = registry.runtime
    # staged install: nothing lands in registry._models until EVERY
    # model restored and proved out — a failure on the Nth model must
    # leave the registry empty so the caller's fresh-registration
    # fallback can re-register all names without collisions
    staged: Dict[str, object] = {}
    for name, entry in models.items():
        comp = deserialize_computation(blobs[entry["comp_file"]])
        _restore_plan_states(comp, entry.get("plan_states") or {})
        if entry.get("stacked_rejected") and hasattr(
            runtime, "_stacked_rejected"
        ):
            runtime._stacked_rejected.add(comp)
        compiled_cache = getattr(runtime, "_compiled_cache", None)
        if compiled_cache is not None and entry.get("lowered"):
            per_comp = compiled_cache.setdefault(comp, {})
            for item in entry["lowered"]:
                lowered = deserialize_computation(blobs[item["file"]])
                per_comp[_freeze(item["key"])] = lowered
                _restore_plan_states(
                    lowered, item.get("plan_states") or {}
                )
        model = RegisteredModel(
            name=name,
            comp=comp,
            input_name=entry["input_name"],
            row_shape=tuple(entry["row_shape"]),
            buckets=tuple(int(b) for b in entry["buckets"]),
            warmup_report={
                int(b): dict(r)
                for b, r in (entry.get("warmup_report") or {}).items()
            },
        )
        aot_verdicts = {}
        aot_exec = os.environ.get(
            "MOOSE_TPU_SNAPSHOT_AOT_EXEC", "1"
        ) != "0"
        for bucket, record in (entry.get("aot") or {}).items():
            verdict = record.get("verdict", "")
            if verdict == "exported" and record.get("file"):
                try:
                    verify_aot_artifact(blobs[record["file"]])
                    verdict = "restored"
                    if aot_exec:
                        # stash the artifact so the restored runner's
                        # first call executes the exported program
                        # outright (skipping even the cached compile);
                        # the rewarm below proves bit-exactness against
                        # the writer's probe digests as usual
                        from ..execution.interpreter import (
                            preload_aot_artifact,
                        )

                        preload_aot_artifact(
                            comp,
                            record.get("plan_key", "logical"),
                            blobs[record["file"]],
                        )
                        verdict = "preloaded"
                except Exception as e:  # noqa: BLE001 — degrade, never
                    # fail the whole snapshot over an optional artifact
                    verdict = f"unloadable:{type(e).__name__}"
            aot_verdicts[bucket] = verdict
        report["aot"][name] = aot_verdicts
        if rewarm:
            for bucket in model.buckets:
                result, eval_report = registry.evaluate(
                    model, _probe_rows(bucket, model.row_shape)
                )
                if eval_report["validating"]:
                    raise SnapshotError(
                        f"model {name!r} bucket {bucket}: restored plan "
                        "re-entered validation — plan state did not "
                        "survive the snapshot"
                    )
                want = (entry.get("probe_digests") or {}).get(str(bucket))
                if check_probes and want is not None:
                    got = _result_digest(result)
                    if got != want:
                        raise SnapshotError(
                            f"model {name!r} bucket {bucket}: probe "
                            f"digest {got} != snapshot {want} — restored "
                            "state is not bit-identical"
                        )
                    report["probe_checked"] += 1
            # the rewarm just drove each bucket's first call: any
            # preloaded artifact that bound is now the executing
            # program — upgrade its verdict so callers can assert the
            # exported program (not a recompile) served the probe
            if "preloaded" in aot_verdicts.values():
                for key, runner in _resolved_runners(runtime, comp):
                    bucket = _bucket_of_binding(key, model.input_name)
                    if (
                        bucket is None
                        or aot_verdicts.get(str(bucket)) != "preloaded"
                    ):
                        continue
                    state = getattr(runner, "aot_state", None)
                    if state == "adopted":
                        aot_verdicts[str(bucket)] = "executed"
                    elif state == "fallback":
                        aot_verdicts[str(bucket)] = "restored"
        staged[name] = model
        report["models"].append(name)
    registry._models.update(staged)
    report["rewarm_s"] = time.perf_counter() - t0
    from ..metrics import counter

    aot_counter = counter(
        "moose_tpu_serving_aot_buckets_total",
        "AOT bucket artifacts by restore verdict",
        labels=("verdict",),
    )
    executed = 0
    for verdicts in report["aot"].values():
        for verdict in verdicts.values():
            aot_counter.inc(verdict=verdict.split(":", 1)[0])
            executed += verdict == "executed"
    get_logger().info(
        "snapshot: restored %d model(s) from %s in %.2fs "
        "(%d probe digest(s) verified, %d kernel verdict(s), "
        "%d AOT bucket(s) executing)",
        len(report["models"]), snapshot_path, report["rewarm_s"],
        report["probe_checked"], restored_kernels, executed,
    )
    return report
