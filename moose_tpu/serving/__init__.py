"""Secure inference serving: warm model registry + dynamic micro-batching.

The single-request user path pays trace + compile + self-check-ladder
cost per call and runs batches of one; the TPU path is ~an order of
magnitude faster at the batch sizes XLA fuses well (BENCH_r05: logreg
~9070 infer/s at batch 1024 vs ~1191 single-request).  This subsystem
closes that gap for serving traffic:

- :mod:`registry` — traces a predictor once per (model, fixedpoint
  dtype), compiles each batch bucket through the existing pipeline, and
  drives the validated-jit ladder to steady state at REGISTRATION time,
  so requests never pay trace/compile/ladder cost;
- :mod:`batcher` — per-model bounded queues; the scheduler coalesces
  pending requests up to ``max_batch`` rows or ``max_wait_ms``
  (whichever first), pads to power-of-two buckets (no recompiles),
  evaluates once, scatters per-row results to callers, and enforces
  deadlines + typed ``ServerOverloadedError`` backpressure;
- :mod:`server` — the in-process :class:`InferenceServer` API (the
  ``blitzen`` CLI daemon wraps it with an HTTP front end);
- :mod:`metrics` — queue depth, batch-size histogram, batch-fill ratio,
  p50/p99 request latency, deadline misses, plus the warm-path
  acceptance counters (no re-trace / no ladder re-run after warmup);
- :mod:`snapshot` — durable warm-state snapshots (traced computations,
  resolved plan states, lowered graphs, kernel verdicts, AOT bucket
  artifacts — executed outright on restore — fixed-keys probe digests)
  so a replica cold-starts warm in seconds; the fleet layer above this
  package is ``bin/blitzen`` (graceful drain, ``/readyz``) +
  ``bin/donner`` (the routing front door) — DEVELOP.md "Fleet serving";
- :mod:`controlplane` — the continuous train -> canary -> promote /
  auto-rollback loop over the fleet (DEVELOP.md "Continuous training
  loop").

Knobs: ``MOOSE_TPU_SERVE_MAX_BATCH`` / ``MOOSE_TPU_SERVE_MAX_WAIT_MS``
/ ``MOOSE_TPU_SERVE_QUEUE`` / ``MOOSE_TPU_SERVE_DEADLINE_MS`` (see
:mod:`config`), ``MOOSE_TPU_SNAPSHOT_DIR`` / ``MOOSE_TPU_SNAPSHOT_AOT``
/ ``MOOSE_TPU_SNAPSHOT_AOT_EXEC`` (see :mod:`snapshot`),
``MOOSE_TPU_CANARY_*`` (see :mod:`controlplane`).
"""

from .config import ServingConfig
from .metrics import ServingMetrics
from .registry import (
    ModelRegistry,
    RegisteredModel,
    bucket_for,
    power_of_two_buckets,
)
from .batcher import ModelQueue
from .controlplane import (
    CanaryConfig,
    ControlPlane,
    HttpFleetClient,
    LocalFleetClient,
    SessionGenerationProducer,
)
from .server import InferenceServer
from .snapshot import (
    current_snapshot_path,
    restore_registry,
    save_snapshot,
)

__all__ = [
    "CanaryConfig",
    "ControlPlane",
    "HttpFleetClient",
    "InferenceServer",
    "LocalFleetClient",
    "ModelQueue",
    "ModelRegistry",
    "RegisteredModel",
    "ServingConfig",
    "ServingMetrics",
    "SessionGenerationProducer",
    "bucket_for",
    "current_snapshot_path",
    "power_of_two_buckets",
    "restore_registry",
    "save_snapshot",
]
