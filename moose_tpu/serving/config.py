"""Serving knobs (env-overridable, flag-overridable in ``blitzen``).

- ``MOOSE_TPU_SERVE_MAX_BATCH`` — largest batch one evaluation carries
  (also the largest padding bucket); default 256.
- ``MOOSE_TPU_SERVE_MAX_WAIT_MS`` — how long the micro-batcher holds an
  open batch for more requests before dispatching; default 2.0 ms.
  Coalescing stops at ``max_batch`` rows or ``max_wait_ms`` elapsed,
  whichever comes first.
- ``MOOSE_TPU_SERVE_QUEUE`` — per-model pending-request bound; a full
  queue REJECTS new submissions with ``ServerOverloadedError`` (never
  blocks); default 1024.
- ``MOOSE_TPU_SERVE_DEADLINE_MS`` — default per-request deadline; unset
  means no deadline unless the request carries one.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

from ..errors import ConfigurationError


def _env_number(name: str, default, cast):
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return cast(raw)
    except ValueError as e:
        raise ConfigurationError(
            f"{name} must be a number, got {raw!r}"
        ) from e


@dataclasses.dataclass(frozen=True)
class ServingConfig:
    max_batch: int = 256
    max_wait_ms: float = 2.0
    queue_bound: int = 1024
    default_deadline_ms: Optional[float] = None

    @classmethod
    def from_env(cls, **overrides) -> "ServingConfig":
        """Env-derived config; keyword overrides win (CLI flags)."""
        values = {
            "max_batch": _env_number(
                "MOOSE_TPU_SERVE_MAX_BATCH", cls.max_batch, int
            ),
            "max_wait_ms": _env_number(
                "MOOSE_TPU_SERVE_MAX_WAIT_MS", cls.max_wait_ms, float
            ),
            "queue_bound": _env_number(
                "MOOSE_TPU_SERVE_QUEUE", cls.queue_bound, int
            ),
            "default_deadline_ms": _env_number(
                "MOOSE_TPU_SERVE_DEADLINE_MS", None, float
            ),
        }
        values.update(
            {k: v for k, v in overrides.items() if v is not None}
        )
        config = cls(**values)
        if config.max_batch < 1:
            raise ConfigurationError(
                f"max_batch must be >= 1, got {config.max_batch}"
            )
        if config.queue_bound < 1:
            raise ConfigurationError(
                f"queue_bound must be >= 1, got {config.queue_bound}"
            )
        if config.max_wait_ms < 0:
            raise ConfigurationError(
                f"max_wait_ms must be >= 0, got {config.max_wait_ms}"
            )
        if (
            config.default_deadline_ms is not None
            and config.default_deadline_ms <= 0
        ):
            # a non-positive deadline expires every request at dispatch
            # (blitzen would answer 504 for ALL traffic) — fail at
            # startup like the other knobs
            raise ConfigurationError(
                "default_deadline_ms must be > 0, got "
                f"{config.default_deadline_ms}"
            )
        return config
