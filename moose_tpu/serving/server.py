"""In-process secure-inference server: registry + per-model batchers.

The programmatic API behind the ``blitzen`` daemon, used directly by
tests, ``scripts/serve_smoke.py``, and ``bench.py``::

    from moose_tpu.serving import InferenceServer

    server = InferenceServer()
    server.register_model("logreg", model, row_shape=(100,))
    y = server.predict("logreg", x_row)          # sync helper
    fut = server.submit("logreg", x_rows)        # async: a Future
    print(server.metrics_snapshot())

Lifecycle: ``register_model`` pays trace + per-bucket compile + ladder
warmup once; ``submit``/``predict`` only ever replay warm plans.  See
``moose_tpu/serving/batcher.py`` for the dispatch/backpressure policy
and ``config.ServingConfig`` for the knobs.
"""

from __future__ import annotations

from concurrent.futures import Future
from concurrent.futures import TimeoutError as FutureTimeoutError
from typing import Dict, Optional, Tuple

import numpy as np

from ..errors import ConfigurationError
from .batcher import ModelQueue
from .config import ServingConfig
from .metrics import ServingMetrics
from .registry import ModelRegistry


class InferenceServer:
    """Micro-batching secure-inference server over one shared runtime."""

    def __init__(self, config: Optional[ServingConfig] = None,
                 runtime=None):
        self.config = config or ServingConfig.from_env()
        self.registry = ModelRegistry(runtime=runtime, config=self.config)
        self.metrics = ServingMetrics()
        self._queues: Dict[str, ModelQueue] = {}
        self._closed = False
        self._draining = False

    # -- lifecycle ---------------------------------------------------------

    def register_model(
        self,
        name: str,
        model,
        row_shape: Tuple[int, ...],
        buckets: Tuple[int, ...] = (),
        fixedpoint_dtype=None,
        input_name: Optional[str] = None,
        arg_ranges=None,
    ):
        """Register + warm a model and start its micro-batch scheduler.
        Buckets default to powers of two up to ``config.max_batch``.
        ``arg_ranges`` declares real-space input bounds and arms the
        MSA7xx overflow gate at registration (see
        ``ModelRegistry.register``)."""
        if self._closed:
            raise ConfigurationError("server is shut down")
        registered = self.registry.register(
            name,
            model,
            row_shape=row_shape,
            buckets=buckets,
            fixedpoint_dtype=fixedpoint_dtype,
            input_name=input_name,
            arg_ranges=arg_ranges,
        )
        self._queues[name] = ModelQueue(
            model=registered,
            registry=self.registry,
            config=self.config,
            metrics=self.metrics,
        )
        return registered

    def replace_model(
        self,
        name: str,
        model,
        row_shape: Tuple[int, ...],
        buckets: Tuple[int, ...] = (),
        fixedpoint_dtype=None,
        input_name: Optional[str] = None,
    ):
        """Hot-swap a live model with ZERO dropped requests: the
        replacement warms fully under the registry's staging name while
        the old version answers everything, then the queue's model
        reference flips atomically — in-flight batches finish against
        the old object (its plans stay cached), new batches bucket
        against the new one."""
        if self._closed:
            raise ConfigurationError("server is shut down")
        registered = self.registry.replace(
            name,
            model,
            row_shape=row_shape,
            buckets=buckets,
            fixedpoint_dtype=fixedpoint_dtype,
            input_name=input_name,
        )
        queue = self._queues.get(name)
        if queue is not None:
            queue.model = registered
        return registered

    def unregister_model(self, name: str) -> None:
        """Retire a model (the control plane unloading a rolled-back
        generation): close its queue — queued-but-undispatched requests
        fail with a retryable ``ReplicaDrainingError`` so the router
        resubmits them elsewhere — and drop the registration."""
        queue = self._queues.pop(name, None)
        if queue is None:
            raise ConfigurationError(
                f"unknown model {name!r}; registered: "
                f"{sorted(self._queues)}"
            )
        queue.close()
        self.registry.unregister(name)

    def load_snapshot(self, directory, source_digests=None,
                      rewarm: bool = True) -> dict:
        """Restore every model from the live warm-state snapshot under
        ``directory`` (see :mod:`.snapshot`) and start a micro-batch
        scheduler per restored model.  Raises
        :class:`~moose_tpu.errors.SnapshotError` on any validation
        failure, leaving the server empty (callers fall back to fresh
        ``register_model`` calls)."""
        from . import snapshot as snapshot_mod

        if self._closed:
            raise ConfigurationError("server is shut down")
        report = snapshot_mod.restore_registry(
            self.registry, directory,
            source_digests=source_digests, rewarm=rewarm,
        )
        for name in report["models"]:
            self._queues[name] = ModelQueue(
                model=self.registry.get(name),
                registry=self.registry,
                config=self.config,
                metrics=self.metrics,
            )
        return report

    def save_snapshot(self, directory, source_digests=None, only=None):
        """Persist the warm registry (see :mod:`.snapshot`); returns the
        new snapshot path.  ``only`` restricts the snapshot to the named
        models (drain-time snapshots exclude ephemeral control-plane
        generations)."""
        from . import snapshot as snapshot_mod

        return snapshot_mod.save_snapshot(
            self, directory, source_digests=source_digests, only=only
        )

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Graceful shutdown, phase one: stop admission on every model
        queue (submissions raise retryable ``ReplicaDrainingError``) and
        wait for all in-flight requests to finish, bounded by
        ``timeout_s`` total.  Returns True when every queue emptied in
        time.  The server stays alive for metrics scrapes; call
        :meth:`close` to stop the scheduler threads afterwards."""
        import time

        self._draining = True
        deadline = time.perf_counter() + timeout_s
        drained = True
        for queue in self._queues.values():
            remaining = max(0.0, deadline - time.perf_counter())
            drained = queue.drain(timeout_s=remaining) and drained
        return drained

    @property
    def draining(self) -> bool:
        return self._draining

    def close(self) -> None:
        self._closed = True
        for queue in self._queues.values():
            queue.close()

    def __enter__(self) -> "InferenceServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- request path ------------------------------------------------------

    def submit(self, model_name: str, x,
               deadline_ms: Optional[float] = None) -> Future:
        """Enqueue one request; returns a Future resolving to the
        per-row results (shape ``(k, ...)`` for a ``(k, *row_shape)``
        request, ``(1, ...)`` for a bare row).  Raises
        ``ServerOverloadedError`` when the model's queue is full and
        the Future raises ``DeadlineExceededError`` on expiry."""
        queue = self._queues.get(model_name)
        if queue is None:
            raise ConfigurationError(
                f"unknown model {model_name!r}; registered: "
                f"{sorted(self._queues)}"
            )
        return queue.submit(x, deadline_ms=deadline_ms)

    def predict(self, model_name: str, x,
                deadline_ms: Optional[float] = None,
                timeout_s: Optional[float] = 120.0) -> np.ndarray:
        """Synchronous submit + await.  A wait timeout cancels the
        queued request so a caller that gave up never occupies batch
        rows (the batcher drops cancelled futures at gather time)."""
        future = self.submit(model_name, x, deadline_ms=deadline_ms)
        try:
            return future.result(timeout=timeout_s)
        except FutureTimeoutError:
            future.cancel()
            raise

    # -- observability -----------------------------------------------------

    def queue_depth(self, model_name: str) -> int:
        return self._queues[model_name].depth()

    def metrics_snapshot(self) -> dict:
        """Aggregate serving metrics plus per-model queue depths and
        warmup reports."""
        snap = self.metrics.snapshot()
        snap["queue_depths"] = {
            name: q.depth() for name, q in self._queues.items()
        }
        snap["models"] = {
            name: {
                "buckets": list(q.model.buckets),
                "warmup": {
                    str(b): dict(r)
                    for b, r in q.model.warmup_report.items()
                },
            }
            for name, q in self._queues.items()
        }
        return snap
