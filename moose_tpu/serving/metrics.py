"""Serving telemetry: aggregate counters + per-request latency quantiles.

Every dispatched batch emits a ``serve_batch`` span through the existing
``telemetry`` module (queue depth, batch size, bucket, fill ratio, plan
state as span attrs — so OTLP export and ``MOOSE_TPU_TRACE=1`` work
unchanged); this module keeps the cheap always-on aggregates a serving
loop needs without retaining span trees: batch-size histogram, batch
fill ratio, p50/p99 request latency, deadline misses, and admission
rejections.  The two ``*_after_warm`` counters are the acceptance hook
for the warm registry: a registered model must never re-trace or re-run
the validated-jit ladder once registration finished, so both stay 0 in
a healthy server (bench.py and scripts/serve_smoke.py assert this).
"""

from __future__ import annotations

import math
import threading
from collections import deque
from typing import Dict, Optional


def _quantile(sorted_values, q: float) -> Optional[float]:
    if not sorted_values:
        return None
    # nearest-rank with rounding UP: a flooring index would report the
    # MINIMUM as "p99" for small samples (int(0.99 * 1) == 0)
    idx = min(len(sorted_values) - 1, math.ceil(q * len(sorted_values)) - 1)
    return sorted_values[max(0, idx)]


def _registry_metrics():
    """Bridge counters on the unified registry (metrics.py): the
    aggregates below stay the windowed JSON surface, while these are
    the monotone whole-process series Prometheus scrapes."""
    from .. import metrics

    return {
        "batches": metrics.counter(
            "moose_tpu_serving_batches_total",
            "micro-batches dispatched",
        ),
        "rows": metrics.counter(
            "moose_tpu_serving_rows_total", "rows served",
        ),
        "overloads": metrics.counter(
            "moose_tpu_serving_overloads_total",
            "submissions rejected by admission control (HTTP 429)",
        ),
        "deadline_misses": metrics.counter(
            "moose_tpu_serving_deadline_misses_total",
            "results delivered after their deadline",
        ),
        "deadline_drops": metrics.counter(
            "moose_tpu_serving_deadline_drops_total",
            "requests expired in queue, never batched (HTTP 504)",
        ),
        "eval_failures": metrics.counter(
            "moose_tpu_serving_eval_failures_total",
            "batches that failed evaluation",
        ),
        "latency": metrics.histogram(
            "moose_tpu_serving_request_latency_seconds",
            "request latency from submit to scatter",
        ),
        # the serve_batch latency, DECOMPOSED (ISSUE 12): queue-wait is
        # submit -> dispatch claim per request; compute is one batch's
        # evaluation.  The profiler's serve_queue_wait / serve_compute
        # phases record the identical instants, so the Perfetto
        # timeline and a Prometheus scrape agree on where serving time
        # goes.
        "queue_wait": metrics.histogram(
            "moose_tpu_serving_queue_wait_seconds",
            "per-request wait from submit to batch dispatch claim",
        ),
        "compute": metrics.histogram(
            "moose_tpu_serving_compute_seconds",
            "per-batch evaluation time (registry.evaluate)",
        ),
        # the warm-registry acceptance counters, scrapeable: the fleet
        # smoke asserts a snapshot-restored replica holds both at 0
        # from its /metrics endpoint alone (no in-process access)
        "retraces_after_warm": metrics.counter(
            "moose_tpu_serving_retraces_after_warm_total",
            "serving batches that re-entered the tracer after warmup",
        ),
        "validating_after_warm": metrics.counter(
            "moose_tpu_serving_validating_after_warm_total",
            "serving batches that landed on a validating (ladder) "
            "evaluation after warmup",
        ),
        "drained": metrics.counter(
            "moose_tpu_serving_drained_requests_total",
            "queued requests completed with retryable "
            "ReplicaDrainingError during shutdown",
        ),
    }


class ServingMetrics:
    """Thread-safe aggregate serving counters (one instance per
    :class:`~moose_tpu.serving.server.InferenceServer`).  Every record
    also increments the unified registry's monotone serving counters,
    so ``GET /metrics`` (Prometheus) and ``/v1/metrics`` (this
    windowed JSON snapshot) describe the same traffic."""

    def __init__(self, latency_window: int = 4096):
        self._lock = threading.Lock()
        self._registry = _registry_metrics()
        self.batches = 0
        self.rows_served = 0
        self.fill_sum = 0.0  # sum of rows/bucket over batches
        self.batch_size_hist: Dict[int, int] = {}
        self.deadline_misses = 0  # results delivered after their deadline
        self.deadline_drops = 0  # expired before dispatch, never batched
        self.overloads = 0  # submissions rejected by admission control
        self.eval_failures = 0
        self.drained_requests = 0  # completed with ReplicaDrainingError
        # acceptance counters: both must stay 0 after registration
        self.retraces_after_warm = 0
        self.validating_after_warm = 0
        # most recent request latencies (seconds), bounded — plus the
        # two components the batcher decomposes them into
        self._latencies = deque(maxlen=latency_window)
        self._queue_waits = deque(maxlen=latency_window)
        self._computes = deque(maxlen=latency_window)

    def record_batch(self, rows: int, bucket: int, retraced: bool,
                     validating: bool) -> None:
        with self._lock:
            self.batches += 1
            self.rows_served += rows
            self.fill_sum += rows / float(bucket)
            self.batch_size_hist[bucket] = (
                self.batch_size_hist.get(bucket, 0) + 1
            )
            if retraced:
                self.retraces_after_warm += 1
            if validating:
                self.validating_after_warm += 1
        self._registry["batches"].inc()
        self._registry["rows"].inc(rows)
        if retraced:
            self._registry["retraces_after_warm"].inc()
        if validating:
            self._registry["validating_after_warm"].inc()

    def record_latency(self, seconds: float, missed_deadline: bool) -> None:
        with self._lock:
            self._latencies.append(seconds)
            if missed_deadline:
                self.deadline_misses += 1
        self._registry["latency"].observe(seconds)
        if missed_deadline:
            self._registry["deadline_misses"].inc()

    def record_queue_wait(self, seconds: float) -> None:
        """One request's submit -> dispatch-claim wait."""
        with self._lock:
            self._queue_waits.append(seconds)
        self._registry["queue_wait"].observe(seconds)

    def record_compute(self, seconds: float) -> None:
        """One batch's evaluation time."""
        with self._lock:
            self._computes.append(seconds)
        self._registry["compute"].observe(seconds)

    def record_deadline_drop(self) -> None:
        with self._lock:
            self.deadline_drops += 1
        self._registry["deadline_drops"].inc()

    def record_overload(self) -> None:
        with self._lock:
            self.overloads += 1
        self._registry["overloads"].inc()

    def record_eval_failure(self) -> None:
        with self._lock:
            self.eval_failures += 1
        self._registry["eval_failures"].inc()

    def record_drained(self, count: int = 1) -> None:
        with self._lock:
            self.drained_requests += count
        self._registry["drained"].inc(count)

    def reset_window(self) -> None:
        """Zero the traffic aggregates (batches, fill, histogram,
        latencies, misses/drops/overloads) so a measurement window
        starts clean — e.g. bench snapshots after a warm-up loop.  The
        ``*_after_warm`` acceptance counters are NOT reset: they must
        hold over the server's whole post-registration lifetime."""
        with self._lock:
            self.batches = 0
            self.rows_served = 0
            self.fill_sum = 0.0
            self.batch_size_hist = {}
            self.deadline_misses = 0
            self.deadline_drops = 0
            self.overloads = 0
            self.eval_failures = 0
            self._latencies.clear()
            self._queue_waits.clear()
            self._computes.clear()

    def snapshot(self) -> dict:
        """One JSON-able dict of every aggregate (the ``blitzen``
        ``/v1/metrics`` payload and the bench/smoke assertion surface)."""
        with self._lock:
            lat = sorted(self._latencies)
            waits = sorted(self._queue_waits)
            computes = sorted(self._computes)
            batches = self.batches
            return {
                "batches": batches,
                "rows_served": self.rows_served,
                "batch_fill_ratio": (
                    self.fill_sum / batches if batches else None
                ),
                "batch_size_hist": dict(self.batch_size_hist),
                "request_latency_p50_s": _quantile(lat, 0.50),
                "request_latency_p99_s": _quantile(lat, 0.99),
                "queue_wait_p50_s": _quantile(waits, 0.50),
                "queue_wait_p99_s": _quantile(waits, 0.99),
                "compute_p50_s": _quantile(computes, 0.50),
                "compute_p99_s": _quantile(computes, 0.99),
                "deadline_misses": self.deadline_misses,
                "deadline_drops": self.deadline_drops,
                "overloads": self.overloads,
                "eval_failures": self.eval_failures,
                "drained_requests": self.drained_requests,
                "retraces_after_warm": self.retraces_after_warm,
                "validating_after_warm": self.validating_after_warm,
            }
