"""Warm model registry: trace/compile/validate once, serve forever.

A registered model pays the full per-plan cost exactly once, at
registration time, per (model, batch-bucket) pair:

- the predictor is traced ONCE (``Predictor.traced_predictor`` memoizes
  the traced Computation per (instance, fixedpoint dtype) — the same
  cache ``predictor_factory`` users hit outside the server);
- each batch bucket's plan compiles through the existing pipeline (the
  runtime's weak-keyed plan caches, keyed on the stable computation
  object + argument shapes);
- the PR-2 validated-jit self-check ladder is DRIVEN TO STEADY STATE
  with warmup evaluations, so no serving request ever lands on a
  validating (eager-reference-paying) evaluation;

after which requests only ever pay the resolved plan's execution cost.
Bucket policy: powers of two up to ``max_batch`` (padding a ragged
batch to the next bucket re-uses a warm plan instead of recompiling for
every distinct batch size).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, Optional, Tuple

import numpy as np

from .. import telemetry
from ..errors import ConfigurationError


def power_of_two_buckets(max_batch: int) -> Tuple[int, ...]:
    """(1, 2, 4, ..., max_batch) — a non-power-of-two max_batch rounds
    UP so a full ``max_batch``-row batch is always servable, at the cost
    of one extra-large warm plan and up to 2x padding on batches above
    the previous power of two.  Pass an explicit ``buckets=`` ladder to
    ``register_model`` to opt out."""
    if max_batch < 1:
        raise ConfigurationError(f"max_batch must be >= 1, got {max_batch}")
    buckets = []
    b = 1
    while b < max_batch:
        buckets.append(b)
        b <<= 1
    buckets.append(b)
    return tuple(buckets)


def bucket_for(rows: int, buckets: Tuple[int, ...]) -> int:
    """Smallest registered bucket holding ``rows`` rows."""
    for b in buckets:
        if rows <= b:
            return b
    raise ConfigurationError(
        f"batch of {rows} rows exceeds the largest bucket {buckets[-1]}"
    )


@dataclasses.dataclass
class RegisteredModel:
    """One warm model: the traced computation plus everything needed to
    evaluate a padded bucket without re-tracing or re-validating."""

    name: str
    comp: object  # traced Computation (held strongly: keys weak caches)
    input_name: str
    row_shape: Tuple[int, ...]  # per-row trailing shape
    buckets: Tuple[int, ...]
    warmup_report: Dict[int, dict]  # bucket -> {evals, plan_state, ...}

    def pad(self, rows: np.ndarray) -> Tuple[np.ndarray, int]:
        """Zero-pad a (n, *row_shape) batch up to its bucket."""
        n = rows.shape[0]
        bucket = bucket_for(n, self.buckets)
        if n == bucket:
            return rows, bucket
        padded = np.zeros((bucket, *rows.shape[1:]), dtype=rows.dtype)
        padded[:n] = rows
        return padded, bucket


class ModelRegistry:
    """Registry of warm models over one shared runtime.

    The runtime is single-flight by design (one XLA program executes at
    a time; plan caches are plain dicts): every evaluation — warmup and
    serving alike — runs under ``eval_lock``, which the micro-batch
    schedulers share."""

    def __init__(self, runtime=None, config=None):
        if runtime is None:
            from ..runtime import LocalMooseRuntime

            runtime = LocalMooseRuntime(["alice", "bob", "carole"])
        if config is None:
            from .config import ServingConfig

            config = ServingConfig.from_env()
        self.runtime = runtime
        self.config = config
        self.eval_lock = threading.Lock()
        self._models: Dict[str, RegisteredModel] = {}

    def __contains__(self, name: str) -> bool:
        return name in self._models

    def get(self, name: str) -> RegisteredModel:
        try:
            return self._models[name]
        except KeyError:
            raise ConfigurationError(
                f"unknown model {name!r}; registered: "
                f"{sorted(self._models)}"
            ) from None

    def names(self):
        return sorted(self._models)

    def register(
        self,
        name: str,
        model,
        row_shape: Tuple[int, ...],
        buckets: Tuple[int, ...] = (),
        fixedpoint_dtype=None,
        input_name: Optional[str] = None,
        max_warmup_evals: int = 12,
        arg_ranges: Optional[Dict[str, Tuple[float, float]]] = None,
    ) -> RegisteredModel:
        """Trace, compile, and ladder-validate ``model`` for every batch
        bucket; returns the warm :class:`RegisteredModel`.

        ``model`` is a ``predictors.Predictor`` (traced via its memoized
        ``traced_predictor``), an ``AbstractComputation``, or an
        already-traced ``Computation``.  ``row_shape`` is the per-row
        input shape (e.g. ``(n_features,)``).  Each bucket is warmed
        until the runtime reports a non-``validating`` plan state, so
        serving traffic never executes a ladder step.

        ``arg_ranges`` optionally declares real-space input bounds
        ({input name: (lo, hi)}); when given, the MSA7xx range analysis
        runs strictly at the door against the LARGEST batch bucket
        (worst-case dot accumulation), so a model whose fixed-point
        encoding cannot hold the declared input dynamics is rejected at
        registration instead of wrapping in the ring at serve time."""
        if name in self._models:
            raise ConfigurationError(f"model {name!r} already registered")
        with telemetry.span("register_model", model=name) as root:
            comp = self._resolve(model, fixedpoint_dtype)
            self._check_single_output(comp)
            # strict lint at the door: a model whose graph fails the
            # static analyzer is a typed CompilationError HERE (blitzen
            # answers 4xx at registration) — never a worker hang or a
            # share leak discovered at serve time
            from ..compilation.analysis import lint_check

            with telemetry.span("lint", model=name):
                lint_check(comp)
            input_name = input_name or self._input_name(comp)
            if not buckets:
                # autotuned default ladder: the full power-of-two set,
                # minus buckets whose measured warmup latency (recorded
                # by earlier registrations) is flat against the next
                # bucket — padding is free there and each pruned bucket
                # saves its warmup compiles.  An explicit buckets= stays
                # the override.
                from ..compilation import autotune as _autotune

                bucket_dec = _autotune.serving_bucket_plan(
                    self.config.max_batch
                )
                buckets = tuple(bucket_dec.choice)
                root.attrs["buckets_source"] = bucket_dec.source
                from .. import flight

                flight.record(
                    "serving_buckets_autotuned", model=name,
                    buckets=[int(b) for b in buckets],
                    source=bucket_dec.source, why=bucket_dec.why,
                )
            buckets = tuple(sorted(set(int(b) for b in buckets)))
            if buckets[0] < 1:
                # an explicit 0/negative bucket would warm a degenerate
                # shape and then reject every request at admission
                raise ConfigurationError(
                    f"buckets must all be >= 1, got {buckets}"
                )
            if arg_ranges:
                # before any warmup spend: overflow against the largest
                # bucket is a registration-time rejection
                with telemetry.span("lint_ranges", model=name):
                    lint_check(
                        comp, analyses=["ranges"],
                        context={
                            "arg_specs": {
                                input_name: (
                                    buckets[-1], *tuple(row_shape)
                                )
                            },
                            "arg_ranges": dict(arg_ranges),
                        },
                    )
            warmup_report: Dict[int, dict] = {}
            for bucket in buckets:
                warmup_report[bucket] = self._warm_bucket(
                    comp, input_name, bucket, row_shape, max_warmup_evals
                )
            # the CHOSEN plan: if warmup routed through the lowering
            # pipeline, the lowered/networked graph now sits in the
            # runtime's compiled cache — run the full strict lint
            # (including the MSA5xx schedule rules, which only bite on
            # networked graphs) over it before committing the model
            with telemetry.span("lint_plan", model=name):
                self._lint_resolved_plans(comp)
            root.attrs["buckets"] = list(buckets)
            root.attrs["warmup_evals"] = sum(
                r["evals"] for r in warmup_report.values()
            )
        registered = RegisteredModel(
            name=name,
            comp=comp,
            input_name=input_name,
            row_shape=tuple(row_shape),
            buckets=buckets,
            warmup_report=warmup_report,
        )
        self._models[name] = registered
        return registered

    def replace(
        self,
        name: str,
        model,
        row_shape: Tuple[int, ...],
        **kwargs,
    ) -> RegisteredModel:
        """Warm hot-swap of a live model (the training export path): the
        replacement traces/compiles/ladder-validates under a staging
        name while the OLD version keeps serving every request, then
        one dict assignment flips the name — in-flight batches against
        the old ``RegisteredModel`` finish on its still-cached plans,
        so nothing is dropped and nothing ever serves cold."""
        import dataclasses as _dc

        if name not in self._models:
            raise ConfigurationError(
                f"model {name!r} is not registered (use register)"
            )
        old = self._models[name]
        # inherit the live registration's bucket ladder when the caller
        # doesn't override it: requests already ADMITTED against the
        # old buckets must still fit the replacement's largest bucket,
        # or a queued batch would fail at pad() after the swap.
        # (fixedpoint_dtype is not recoverable from the old model —
        # callers serving a non-default dtype must re-pass it.)
        if not kwargs.get("buckets"):
            kwargs["buckets"] = old.buckets
        elif max(kwargs["buckets"]) < old.buckets[-1]:
            # a SHRINKING largest bucket would strand any queued
            # request admitted against the old ladder: _gather could
            # never pop it and it would head-of-line-block the queue
            # forever
            raise ConfigurationError(
                f"replace({name!r}): largest bucket "
                f"{max(kwargs['buckets'])} < live {old.buckets[-1]}; "
                "hot-swap buckets must cover every admissible request"
            )
        if tuple(row_shape) != old.row_shape:
            # the batcher admits requests against one row_shape and
            # evaluates them (one model snapshot per batch) possibly
            # after the swap: a shape-changing replacement would fail
            # already-queued rows.  A different shape is a NEW model —
            # register it under a new name and cut traffic over
            raise ConfigurationError(
                f"replace({name!r}): row_shape {tuple(row_shape)} != "
                f"live {old.row_shape}; hot-swap requires an "
                "identical input shape"
            )
        staging = f"__staging__/{name}"
        while staging in self._models:
            staging += "+"
        registered = self.register(
            staging, model, row_shape=row_shape, **kwargs
        )
        del self._models[staging]
        registered = _dc.replace(registered, name=name)
        self._models[name] = registered
        return registered

    def unregister(self, name: str) -> RegisteredModel:
        """Drop a registration (the control plane retiring a rolled-back
        generation).  In-flight evaluations against the popped
        ``RegisteredModel`` finish on its still-cached plans; only the
        NAME disappears."""
        if name not in self._models:
            raise ConfigurationError(
                f"unknown model {name!r}; registered: "
                f"{sorted(self._models)}"
            )
        return self._models.pop(name)

    def evaluate(self, model: RegisteredModel, batch: np.ndarray):
        """One warm evaluation of a full (already padded) bucket.
        Returns (per-row outputs, eval_report) where the report carries
        the re-trace / ladder-state acceptance bits."""
        with self.eval_lock:
            outputs = self.runtime.evaluate_computation(
                model.comp, arguments={model.input_name: batch}
            )
            if isinstance(outputs, tuple):  # GrpcMooseRuntime returns
                outputs = outputs[0]  # (outputs, per-role timings)
            timings = getattr(self.runtime, "last_timings", {})
            plan_state = getattr(self.runtime, "last_plan", {}).get(
                "plan_state"
            )
        (result,) = outputs.values()
        return np.asarray(result), {
            # a warm evaluation re-entering the tracer means the
            # registry's central promise broke — surfaced per batch
            "retraced": "trace" in timings,
            "plan_state": plan_state,
            "validating": plan_state == "validating",
        }

    # -- internals ---------------------------------------------------------

    def _lint_resolved_plans(self, comp) -> None:
        """Strict-lint every lowered graph the runtime compiled for
        ``comp`` during warmup (the plans serving traffic will actually
        execute).  The MSA5xx schedule analyzer proves the worker plan
        deadlock-free; errors raise the same typed
        ``MalformedComputationError`` the logical-graph lint does."""
        from ..compilation.analysis import lint_check
        from ..computation import Computation

        # LocalMooseRuntime caches lowered graphs as `_compiled_cache`;
        # the grpc client runtime as `_compile_cache` with
        # (Computation, bytes) values — cover both so the plan gate
        # never silently skips a runtime flavor
        compiled_cache = getattr(
            self.runtime, "_compiled_cache", None
        ) or getattr(self.runtime, "_compile_cache", None)
        if compiled_cache is None:
            return
        per_comp = compiled_cache.get(comp) or {}
        seen = set()
        for entry in per_comp.values():
            lowered = entry[0] if isinstance(entry, tuple) else entry
            if isinstance(lowered, Computation) and lowered not in seen:
                seen.add(lowered)
                lint_check(lowered)

    def _resolve(self, model, fixedpoint_dtype):
        from ..computation import Computation
        from ..edsl import base as edsl_base
        from ..edsl import tracer

        if isinstance(model, Computation):
            return model
        if isinstance(model, edsl_base.AbstractComputation):
            with telemetry.span("trace"):
                return tracer.trace(model)
        if hasattr(model, "traced_predictor"):
            kwargs = (
                {"fixedpoint_dtype": fixedpoint_dtype}
                if fixedpoint_dtype is not None
                else {}
            )
            with telemetry.span("trace"):
                return model.traced_predictor(**kwargs)
        raise ConfigurationError(
            "model must be a Predictor, AbstractComputation, or "
            f"Computation, found {type(model)}"
        )

    @staticmethod
    def _input_name(comp) -> str:
        inputs = [
            n for n, op in comp.operations.items() if op.kind == "Input"
        ]
        if len(inputs) != 1:
            raise ConfigurationError(
                "serving requires a single-Input computation (pass "
                f"input_name= to disambiguate); found {sorted(inputs)}"
            )
        return inputs[0]

    @staticmethod
    def _check_single_output(comp) -> None:
        # the scatter path slices ONE per-row result tensor; reject
        # multi-output graphs at registration (even when input_name= is
        # passed explicitly) instead of failing every request with an
        # unpacking error at serve time
        outputs = [
            n for n, op in comp.operations.items() if op.kind == "Output"
        ]
        if len(outputs) != 1:
            raise ConfigurationError(
                "serving requires a single-Output computation; found "
                f"{sorted(outputs)}"
            )

    def _warm_bucket(self, comp, input_name, bucket, row_shape,
                     max_warmup_evals) -> dict:
        """Compile + drive the self-check ladder to steady state for one
        bucket shape.  Warmup rows are random (not zeros): validating
        evaluations compare jit against eager bit-for-bit, and a
        degenerate all-zero operand would under-exercise the kernels
        being validated."""
        import time as _time

        rng = np.random.default_rng(bucket)
        x = rng.normal(size=(bucket, *row_shape))
        with telemetry.span("warm_bucket", bucket=bucket) as sp:
            evals = 0
            plan_state = None
            eval_s = None
            for _ in range(max(1, max_warmup_evals)):
                with self.eval_lock:
                    t0 = _time.perf_counter()
                    self.runtime.evaluate_computation(
                        comp, arguments={input_name: x}
                    )
                    eval_s = _time.perf_counter() - t0
                    plan_state = getattr(
                        self.runtime, "last_plan", {}
                    ).get("plan_state")
                evals += 1
                if plan_state != "validating":
                    break
            sp.attrs["evals"] = evals
            sp.attrs["plan_state"] = str(plan_state)
        if eval_s is not None and plan_state != "validating":
            # steady-state latency evidence for the bucket autotuner:
            # later registrations prune buckets that measure flat
            # against their next-larger neighbor
            from ..compilation import autotune as _autotune

            _autotune.measurements().record(
                "bucket_latency", 0, str(bucket), eval_s=eval_s,
            )
        if plan_state == "validating":
            from ..logger import get_logger

            get_logger().warning(
                "bucket %d still validating after %d warmup evaluations;"
                " serving traffic will finish driving the ladder",
                bucket, evals,
            )
        return {"evals": evals, "plan_state": plan_state}
