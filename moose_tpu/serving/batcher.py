"""Dynamic micro-batching scheduler.

One :class:`ModelQueue` per registered model: a bounded pending-request
queue plus a scheduler thread that coalesces concurrent requests into
the batch sizes the TPU path is fast at.  The dispatch policy:

- a batch OPENS when the first request arrives and DISPATCHES when it
  holds ``max_batch`` rows or ``max_wait_ms`` has elapsed since it
  opened, whichever comes first (an idle queue costs nothing — the
  scheduler blocks on a condition variable, no polling);
- assembled rows are padded to the smallest registered power-of-two
  bucket, so every evaluation replays a warm compiled plan instead of
  recompiling for each distinct batch size;
- per-row results scatter back to the per-request futures;
- admission control: a full queue rejects ``submit`` with typed
  :class:`~moose_tpu.errors.ServerOverloadedError` immediately (callers
  shed load; nothing ever blocks on a full queue);
- requests whose deadline expired while queued are completed with
  :class:`~moose_tpu.errors.DeadlineExceededError` and are NEVER given
  batch rows — an expired request cannot contaminate (or consume
  capacity in) a batch.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from .. import telemetry
from ..errors import (
    ConfigurationError,
    DeadlineExceededError,
    ReplicaDrainingError,
    ServerOverloadedError,
)
from .config import ServingConfig
from .metrics import ServingMetrics
from .registry import ModelRegistry, RegisteredModel


@dataclass
class _Request:
    rows: np.ndarray  # (k, *row_shape), k >= 1
    future: Future
    enqueued_s: float
    deadline_s: Optional[float]  # absolute perf_counter seconds

    def expired(self, now: float) -> bool:
        return self.deadline_s is not None and now > self.deadline_s


@dataclass
class ModelQueue:
    """Bounded queue + scheduler thread for one registered model."""

    model: RegisteredModel
    registry: ModelRegistry
    config: ServingConfig
    metrics: ServingMetrics
    _pending: deque = field(default_factory=deque)
    _pending_rows: int = 0

    def __post_init__(self):
        self._cv = threading.Condition()
        self._closed = False
        self._draining = False
        # batches popped from _pending but not yet fully dispatched:
        # drain() must wait on BOTH (a request leaves _pending before
        # its evaluation runs)
        self._in_flight = 0
        # the scheduler thread inherits the registration-time trace
        # context (if any): its serve_batch roots stitch under the
        # server's trace instead of starting orphan roots per batch
        self._trace_ctx = telemetry.current_context()
        self._thread = threading.Thread(
            target=self._loop_in_ctx,
            daemon=True,
            name=f"serve-{self.model.name}",
        )
        self._thread.start()

    def _loop_in_ctx(self) -> None:
        with telemetry.use_context(self._trace_ctx):
            self._loop()

    # -- client side -------------------------------------------------------

    def submit(self, rows: np.ndarray,
               deadline_ms: Optional[float] = None) -> Future:
        """Enqueue one request (``rows`` of shape ``(k, *row_shape)`` or
        a single row of ``row_shape``); returns its Future.  Raises
        ``ServerOverloadedError`` synchronously when the queue is full
        and ``ConfigurationError`` on shape mismatch."""
        rows = np.asarray(rows, dtype=np.float64)
        if rows.shape == self.model.row_shape:
            rows = rows[np.newaxis]
        if rows.ndim < 1 or rows.shape[1:] != self.model.row_shape:
            raise ConfigurationError(
                f"model {self.model.name!r} expects rows of shape "
                f"{self.model.row_shape}, got {rows.shape}"
            )
        if rows.shape[0] < 1:
            raise ConfigurationError("a request must carry >= 1 rows")
        # the admission bound MUST match the scheduler's row budget
        # (_gather): a request admitted here but too large to ever pop
        # would head-of-line-block the queue forever
        max_request = min(self.config.max_batch, self.model.buckets[-1])
        if rows.shape[0] > max_request:
            raise ConfigurationError(
                f"request of {rows.shape[0]} rows exceeds the largest "
                f"admissible batch {max_request}; split it client-side"
            )
        if deadline_ms is None:
            deadline_ms = self.config.default_deadline_ms
        now = time.perf_counter()
        request = _Request(
            rows=rows,
            future=Future(),
            enqueued_s=now,
            deadline_s=(
                now + deadline_ms / 1e3 if deadline_ms is not None else None
            ),
        )
        with self._cv:
            if self._closed or self._draining:
                # RETRYABLE: the request was never evaluated, so the
                # router can safely resubmit it to another replica (a
                # non-retryable error here would fail the caller for a
                # purely operational event — a rolling restart)
                raise ReplicaDrainingError(
                    f"model queue {self.model.name!r} is "
                    f"{'shut down' if self._closed else 'draining'}; "
                    "retry on another replica"
                )
            if len(self._pending) >= self.config.queue_bound:
                self.metrics.record_overload()
                raise ServerOverloadedError(
                    f"model {self.model.name!r}: queue full "
                    f"({self.config.queue_bound} pending requests); "
                    "back off and retry"
                )
            self._pending.append(request)
            self._pending_rows += rows.shape[0]
            self._cv.notify()
        return request.future

    def depth(self) -> int:
        with self._cv:
            return len(self._pending)

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Graceful drain: close admission (new submissions raise
        retryable :class:`ReplicaDrainingError`) but keep the scheduler
        dispatching until every already-admitted request completes —
        including the batch the scheduler already popped but has not
        finished evaluating — up to ``timeout_s``.  Returns True when
        everything finished in time.  Call :meth:`close` afterwards to
        stop the scheduler thread (any leftovers then complete with the
        same retryable error)."""
        from .. import flight

        flight.record("serving_drain", model=self.model.name)
        deadline = time.perf_counter() + timeout_s
        with self._cv:
            self._draining = True
            self._cv.notify_all()
            while self._pending or self._in_flight:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    return False
                self._cv.wait(timeout=min(remaining, 0.05))
        return True

    def close(self, timeout_s: float = 10.0) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout=timeout_s)
        # drain anything the scheduler no longer owns
        with self._cv:
            leftovers = list(self._pending)
            self._pending.clear()
            self._pending_rows = 0
        drained = 0
        for request in leftovers:
            # claim first: a caller-cancelled future rejects
            # set_exception with InvalidStateError, which would abort
            # this drain loop and strand the remaining leftovers
            if not request.future.set_running_or_notify_cancel():
                continue
            # retryable by design: these requests were never evaluated,
            # so the fleet router resubmits them to another replica
            # instead of surfacing a failure to the caller
            request.future.set_exception(
                ReplicaDrainingError(
                    f"model queue {self.model.name!r} shut down before "
                    "the request was served; retry on another replica"
                )
            )
            drained += 1
        if drained:
            self.metrics.record_drained(drained)

    # -- scheduler side ----------------------------------------------------

    def _loop(self) -> None:
        while True:
            batch = self._gather()
            if batch is None:
                return  # closed and drained
            if not batch:
                continue
            try:
                self._dispatch(batch)
            except Exception as e:  # noqa: BLE001 — last-ditch guard:
                # the scheduler thread must NEVER die holding futures
                # (callers would hang); fail them and keep serving
                for request in batch:
                    if not request.future.done():
                        try:
                            request.future.set_exception(e)
                        except Exception:  # noqa: BLE001 — already done
                            pass
            finally:
                with self._cv:
                    self._in_flight -= 1
                    self._cv.notify_all()

    def _gather(self):
        """Block for the first pending request, then hold the batch open
        until ``max_batch`` rows are pending or ``max_wait_ms`` has
        elapsed; pop whole requests up to the row budget (never more
        than the largest registered bucket can carry)."""
        max_rows = min(self.config.max_batch, self.model.buckets[-1])
        with self._cv:
            while not self._pending:
                if self._closed:
                    return None
                self._cv.wait()
            opened_s = time.perf_counter()
            deadline_s = opened_s + self.config.max_wait_ms / 1e3
            # draining: no new requests can arrive, so holding the
            # batch open for stragglers only delays the shutdown
            while (
                self._pending_rows < max_rows
                and not self._closed
                and not self._draining
            ):
                remaining = deadline_s - time.perf_counter()
                if remaining <= 0:
                    break
                self._cv.wait(timeout=remaining)
            batch: list[_Request] = []
            rows = 0
            while self._pending:
                nxt = self._pending[0]
                if rows + nxt.rows.shape[0] > max_rows:
                    break
                self._pending.popleft()
                self._pending_rows -= nxt.rows.shape[0]
                rows += nxt.rows.shape[0]
                batch.append(nxt)
            if batch:
                # counted while still under the lock: drain() must see
                # (pending empty AND nothing mid-dispatch) atomically
                self._in_flight += 1
            return batch

    def _dispatch(self, batch) -> None:
        # ONE model snapshot for the whole batch: a hot-swap
        # (server.replace_model) flips model between any two
        # reads, and a batch padded against one RegisteredModel must be
        # evaluated against the SAME one (its buckets, its warm plans)
        model = self.model
        # deadline admission: expired requests complete exceptionally
        # and never occupy batch rows
        now = time.perf_counter()
        live: list[_Request] = []
        for request in batch:
            # claim the future first: a caller-cancelled request drops
            # out here, and a claimed (RUNNING) future can no longer be
            # cancelled out from under the scatter below
            if not request.future.set_running_or_notify_cancel():
                continue
            if request.expired(now):
                self.metrics.record_deadline_drop()
                request.future.set_exception(
                    DeadlineExceededError(
                        f"model {model.name!r}: deadline expired "
                        "after "
                        f"{(now - request.enqueued_s) * 1e3:.1f} ms in "
                        "queue; request was not evaluated"
                    )
                )
                continue
            live.append(request)
        if not live:
            return
        from .. import profiling

        # queue-wait component per request, measured at the two instants
        # the batcher already owns (submit -> dispatch claim): the
        # serving latency finally decomposes into where it actually goes
        # — and the profiler's timeline and Prometheus agree on it
        for request in live:
            self.metrics.record_queue_wait(now - request.enqueued_s)
            profiling.record_complete(
                "serve_queue_wait", request.enqueued_s, now,
                model=model.name,
            )
        with telemetry.span(
            "serve_batch",
            model=model.name,
            queue_depth=self.depth(),
        ) as sp:
            try:
                rows = np.concatenate([r.rows for r in live], axis=0)
                padded, bucket = model.pad(rows)
                sp.attrs["rows"] = int(rows.shape[0])
                sp.attrs["bucket"] = int(bucket)
                t_compute = time.perf_counter()
                with profiling.phase(
                    "serve_compute", model=model.name,
                    bucket=int(bucket),
                ):
                    result, report = self.registry.evaluate(
                        model, padded
                    )
                    profiling.fence(result)
                compute_s = time.perf_counter() - t_compute
                self.metrics.record_compute(compute_s)
                sp.attrs["compute_s"] = compute_s
            except Exception as e:  # noqa: BLE001 — the batch fails as
                # a unit; every caller gets the typed root cause (and
                # the scheduler thread survives to serve later batches)
                self.metrics.record_eval_failure()
                sp.attrs["error"] = type(e).__name__
                for request in live:
                    request.future.set_exception(e)
                return
            sp.attrs["fill"] = rows.shape[0] / float(bucket)
            sp.attrs["plan_state"] = str(report["plan_state"])
            sp.attrs["retraced"] = report["retraced"]
        self.metrics.record_batch(
            rows=int(rows.shape[0]),
            bucket=int(bucket),
            retraced=report["retraced"],
            validating=report["validating"],
        )
        done = time.perf_counter()
        offset = 0
        for request in live:
            k = request.rows.shape[0]
            slice_ = np.asarray(result)[offset:offset + k]
            offset += k
            missed = request.expired(done)
            self.metrics.record_latency(
                done - request.enqueued_s, missed_deadline=missed
            )
            if missed:
                # too late to be useful: surface the typed error (the
                # rows were evaluated — that cost is already sunk and
                # counted as a miss in telemetry)
                request.future.set_exception(
                    DeadlineExceededError(
                        f"model {model.name!r}: result ready "
                        f"{(done - request.deadline_s) * 1e3:.1f} ms "
                        "past the deadline"
                    )
                )
            else:
                request.future.set_result(slice_.copy())
