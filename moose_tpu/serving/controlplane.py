"""Fleet control plane: the supervised train -> serve loop.

The layer that makes the training half (PR 11: checkpointed, resumable
``TrainingSession``) and the serving half (PR 9: donner/blitzen fleet,
PR 10: latency-split observability) load-bearing as ONE system.  A
long-lived training session continuously produces model generations;
for each generation the :class:`ControlPlane`

1. **stages** it onto every replica under the serving name
   ``<model>@<label>`` — full warm-behind-the-curtain registration
   (trace/compile/ladder or snapshot-grade warm paths), the live model
   keeps answering everything;
2. **canaries** it: installs a weighted generation split in donner
   (deterministic tenant hash buckets — one tenant sees ONE
   generation), routing ``canary_fraction`` of traffic to the new
   generation;
3. **watches SLOs** over donner's sliding per-generation window (p99
   latency, typed-error rate) plus the replicas' PR-10 latency split
   (p99 queue-wait / compute) and the fleet-wide
   ``moose_tpu_cost_drift_total`` counter;
4. **promotes** (hot-swaps the base model to the new weights — atomic
   queue flip, zero dropped requests — then retires the staging name)
   or **auto-rolls-back** on breach (atomic weight flip back to the
   last-good generation, staging name retired, base never touched).

Every transition is a ``generation_*`` flight event and a
``moose_tpu_controlplane_*`` metric.  Chaos-hardening contract (see
tests/test_controlplane.py and scripts/loop_smoke.py): a SIGKILLed
replica mid-canary, a trainer killed mid-epoch, and a poisoned
generation each leave the fleet serving the last-good generation with
zero dropped requests.

Knobs (``MOOSE_TPU_CANARY_*``): see :class:`CanaryConfig`.
"""

from __future__ import annotations

import base64
import json
import time
import urllib.request
from typing import Any, Dict, List, Optional, Tuple

from .. import flight as flight_mod
from .. import metrics as metrics_mod
from ..errors import ConfigurationError
from .config import _env_number

_METRICS: Optional[Dict[str, Any]] = None


def _metrics() -> Dict[str, Any]:
    global _METRICS
    if _METRICS is None:
        _METRICS = {
            "generations": metrics_mod.counter(
                "moose_tpu_controlplane_generations_total",
                "model generations by terminal outcome",
                ("outcome",),
            ),
            "breaches": metrics_mod.counter(
                "moose_tpu_controlplane_slo_breaches_total",
                "canary SLO breaches by reason",
                ("reason",),
            ),
            "promote_s": metrics_mod.gauge(
                "moose_tpu_controlplane_promote_seconds",
                "duration of the most recent promotion flip",
            ),
            "rollback_s": metrics_mod.gauge(
                "moose_tpu_controlplane_rollback_seconds",
                "duration of the most recent auto-rollback flip",
            ),
            "phase": metrics_mod.gauge(
                "moose_tpu_controlplane_phase",
                "current lifecycle phase (0 idle, 1 staging, 2 canary, "
                "3 promoting, 4 rolling back)",
            ),
        }
    return _METRICS


_PHASES = {
    "idle": 0, "staging": 1, "canary": 2,
    "promoting": 3, "rolling_back": 4,
}


class CanaryConfig:
    """Control-plane knobs (env-overridable via ``MOOSE_TPU_CANARY_*``).

    - ``fraction``: share of traffic the canary generation receives;
    - ``watch_s``: minimum observation time before promotion;
    - ``min_requests``: minimum canary-window samples before any
      verdict (breach OR promotion) — no decision on noise;
    - ``p99_slo_s`` / ``error_rate_slo``: the canary window SLOs
      (donner's sliding per-generation window);
    - ``queue_wait_p99_slo_s`` / ``compute_p99_slo_s``: PR-10
      latency-split SLOs read from the replicas (0 disables);
    - ``cost_drift_max``: allowed ``moose_tpu_cost_drift_total``
      increments during the canary (any more is a breach);
    - ``poll_s``: SLO poll period;
    - ``epochs_per_generation``: training epochs per produced
      generation (the loop trains to a growing cumulative target, so
      PR-11 mid-epoch resume carries across generations).
    """

    def __init__(self, **overrides):
        env = {
            "fraction": _env_number(
                "MOOSE_TPU_CANARY_FRACTION", 0.25, float
            ),
            "watch_s": _env_number(
                "MOOSE_TPU_CANARY_WATCH_S", 3.0, float
            ),
            "min_requests": _env_number(
                "MOOSE_TPU_CANARY_MIN_REQUESTS", 20, int
            ),
            "p99_slo_s": _env_number(
                "MOOSE_TPU_CANARY_P99_S", 2.0, float
            ),
            "error_rate_slo": _env_number(
                "MOOSE_TPU_CANARY_ERROR_RATE", 0.02, float
            ),
            "queue_wait_p99_slo_s": _env_number(
                "MOOSE_TPU_CANARY_QUEUE_WAIT_P99_S", 0.0, float
            ),
            "compute_p99_slo_s": _env_number(
                "MOOSE_TPU_CANARY_COMPUTE_P99_S", 0.0, float
            ),
            "cost_drift_max": _env_number(
                "MOOSE_TPU_CANARY_COST_DRIFT", 0, int
            ),
            "poll_s": _env_number(
                "MOOSE_TPU_CANARY_POLL_S", 0.25, float
            ),
            "timeout_s": _env_number(
                "MOOSE_TPU_CANARY_TIMEOUT_S", 60.0, float
            ),
            "epochs_per_generation": _env_number(
                "MOOSE_TPU_CANARY_EPOCHS_PER_GEN", 1, int
            ),
        }
        known = set(env)
        env.update({k: v for k, v in overrides.items() if v is not None})
        unknown = set(env) - known
        if unknown:
            raise ConfigurationError(f"unknown canary knobs: {unknown}")
        for key, value in env.items():
            setattr(self, key, value)
        if not 0.0 < self.fraction <= 1.0:
            raise ConfigurationError(
                f"canary fraction must be in (0, 1], got {self.fraction}"
            )
        if self.min_requests < 1:
            raise ConfigurationError("min_requests must be >= 1")


# -- fleet clients ----------------------------------------------------------


class LocalFleetClient:
    """In-process fleet adapter (tests, bench): a donner Router plus the
    ``InferenceServer`` replicas it routes over — the same surface
    :class:`HttpFleetClient` drives over the wire."""

    def __init__(self, router, servers: List[Any]):
        self.router = router
        self.servers = list(servers)

    def set_route(self, model: str, weights: Dict[str, float],
                  canary: Optional[str] = None) -> None:
        self.router.set_route(model, weights, canary=canary)

    def clear_route(self, model: str) -> None:
        self.router.clear_route(model)

    def fleet(self) -> dict:
        return self.router.fleet_snapshot()

    def load_generation(self, name: str, onnx_bytes: bytes,
                        n_features: int,
                        buckets: Tuple[int, ...] = ()) -> None:
        from ..predictors import from_onnx

        for server in self.servers:
            if name in server.registry:
                server.replace_model(
                    name, from_onnx(onnx_bytes),
                    row_shape=(n_features,), buckets=buckets,
                )
            else:
                server.register_model(
                    name, from_onnx(onnx_bytes),
                    row_shape=(n_features,), buckets=buckets,
                )

    def unload_generation(self, name: str) -> None:
        for server in self.servers:
            if name in server.registry:
                server.unregister_model(name)

    def promote_base(self, model: str, onnx_bytes: bytes,
                     n_features: int) -> None:
        from ..predictors import from_onnx

        for server in self.servers:
            server.replace_model(
                model, from_onnx(onnx_bytes), row_shape=(n_features,)
            )

    def replica_metrics(self) -> List[dict]:
        return [s.metrics_snapshot() for s in self.servers]

    def cost_drift_total(self) -> float:
        metric = metrics_mod.REGISTRY.get("moose_tpu_cost_drift_total")
        if metric is None:
            return 0.0
        return float(sum(metric.snapshot_values().values()))


class HttpFleetClient:
    """Wire fleet adapter: donner's ``/admin/routes`` + ``/fleet`` and
    every replica's ``/admin/models/*`` + ``/v1/metrics`` +
    ``/metrics`` (requires ``--admin`` on both daemons)."""

    def __init__(self, router_url: str, replica_urls: List[str],
                 timeout_s: float = 300.0):
        self.router_url = router_url.rstrip("/")
        self.replica_urls = [u.rstrip("/") for u in replica_urls]
        self.timeout_s = timeout_s

    def _post(self, url: str, payload: dict) -> dict:
        request = urllib.request.Request(
            url,
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(
            request, timeout=self.timeout_s
        ) as resp:
            return json.loads(resp.read().decode())

    def _get(self, url: str):
        with urllib.request.urlopen(url, timeout=self.timeout_s) as resp:
            return resp.read().decode()

    def set_route(self, model: str, weights: Dict[str, float],
                  canary: Optional[str] = None) -> None:
        self._post(
            self.router_url + "/admin/routes",
            {"model": model, "weights": weights, "canary": canary},
        )

    def clear_route(self, model: str) -> None:
        self._post(
            self.router_url + "/admin/routes",
            {"model": model, "clear": True},
        )

    def fleet(self) -> dict:
        return json.loads(self._get(self.router_url + "/fleet"))

    def load_generation(self, name: str, onnx_bytes: bytes,
                        n_features: int,
                        buckets: Tuple[int, ...] = ()) -> None:
        payload = {
            "onnx_b64": base64.b64encode(onnx_bytes).decode(),
            "features": int(n_features),
        }
        if buckets:
            payload["buckets"] = [int(b) for b in buckets]
        for url in self.replica_urls:
            self._post(f"{url}/admin/models/{name}:load", payload)

    def unload_generation(self, name: str) -> None:
        import urllib.error

        for url in self.replica_urls:
            try:
                self._post(f"{url}/admin/models/{name}:unload", {})
            except urllib.error.HTTPError as e:
                if e.code != 404:  # already gone (replica restarted)
                    raise

    def promote_base(self, model: str, onnx_bytes: bytes,
                     n_features: int) -> None:
        self.load_generation(model, onnx_bytes, n_features)

    def replica_metrics(self) -> List[dict]:
        return [
            json.loads(self._get(url + "/v1/metrics"))
            for url in self.replica_urls
        ]

    def cost_drift_total(self) -> float:
        total = 0.0
        for url in self.replica_urls:
            for line in self._get(url + "/metrics").splitlines():
                if line.startswith("moose_tpu_cost_drift_total"):
                    try:
                        total += float(line.rsplit(" ", 1)[1])
                    except (IndexError, ValueError):
                        pass
        return total


# -- generation producers ---------------------------------------------------


class SessionGenerationProducer:
    """Drives ONE long-lived :class:`TrainingSession` to a growing
    cumulative epoch target: generation N covers epochs
    ``(N-1)*epochs_per_generation + 1 .. N*epochs_per_generation``,
    resuming from whatever is durably committed — a trainer killed
    mid-epoch resumes into the SAME generation (PR-11) and the loop
    never notices beyond the retry counters."""

    def __init__(self, session, x, y, epochs_per_generation: int = 1):
        self.session = session
        self.x = x
        self.y = y
        self.epochs_per_generation = max(1, int(epochs_per_generation))
        self.generations = 0

    def next_generation(self) -> Tuple[str, bytes, int]:
        """(label, onnx_bytes, n_features) for the next generation."""
        from ..training.export import logreg_onnx_bytes

        self.generations += 1
        target = self.generations * self.epochs_per_generation
        report = self.session.run(self.x, self.y, epochs=target)
        weights = report["weights"]["w"]
        label = f"g{report['final_epoch']:04d}"
        return label, logreg_onnx_bytes(weights), int(
            weights.reshape(-1).shape[0]
        )


# -- the control plane ------------------------------------------------------


class ControlPlane:
    """Canary/promote/rollback supervisor for one fleet model."""

    def __init__(self, client, model: str,
                 config: Optional[CanaryConfig] = None):
        self.client = client
        self.model = model
        self.config = config or CanaryConfig()
        self.history: List[dict] = []
        self._phase("idle")

    def _phase(self, phase: str) -> None:
        self.phase = phase
        _metrics()["phase"].set(_PHASES[phase])

    def _event(self, kind: str, **fields) -> None:
        flight_mod.record(kind, party="controlplane", **fields)

    @staticmethod
    def serving_name(model: str, label: str) -> str:
        return model if label == "base" else f"{model}@{label}"

    # -- SLO evaluation ----------------------------------------------------

    def _slo_verdict(self, label: str,
                     cost_drift_base: float) -> Tuple[str, dict]:
        """("ok"|"wait"|<breach reason>, observed) for one poll."""
        cfg = self.config
        routes = self.client.fleet().get("routes") or {}
        window = (
            (routes.get(self.model) or {}).get("window") or {}
        ).get(label) or {}
        observed = {
            "count": int(window.get("count") or 0),
            "p99_s": float(window.get("p99_s") or 0.0),
            "error_rate": float(window.get("error_rate") or 0.0),
            "cost_drift": (
                self.client.cost_drift_total() - cost_drift_base
            ),
        }
        # the PR-10 latency split: worst replica wins (one overloaded
        # replica is an SLO problem even if the mean looks fine)
        queue_wait = compute = 0.0
        for snap in self.client.replica_metrics():
            queue_wait = max(
                queue_wait, float(snap.get("queue_wait_p99_s") or 0.0)
            )
            compute = max(
                compute, float(snap.get("compute_p99_s") or 0.0)
            )
        observed["queue_wait_p99_s"] = queue_wait
        observed["compute_p99_s"] = compute
        if observed["cost_drift"] > cfg.cost_drift_max:
            return "cost_drift", observed
        if observed["count"] < cfg.min_requests:
            return "wait", observed
        if observed["p99_s"] > cfg.p99_slo_s:
            return "latency", observed
        if observed["error_rate"] > cfg.error_rate_slo:
            return "errors", observed
        if (
            cfg.queue_wait_p99_slo_s
            and queue_wait > cfg.queue_wait_p99_slo_s
        ):
            return "queue_wait", observed
        if cfg.compute_p99_slo_s and compute > cfg.compute_p99_slo_s:
            return "compute", observed
        return "ok", observed

    # -- the generation lifecycle ------------------------------------------

    def run_generation(self, label: str, onnx_bytes: bytes,
                       n_features: int) -> dict:
        """Stage -> canary -> watch -> promote | rollback, one
        generation.  Returns the generation report (also appended to
        ``history``)."""
        cfg = self.config
        model = self.model
        staging = self.serving_name(model, label)
        report = {
            "model": model, "generation": label, "staging": staging,
            "promoted": False, "reason": "", "observed": {},
        }
        t_start = time.perf_counter()

        self._phase("staging")
        self._event("generation_staged", model=model, generation=label)
        self.client.load_generation(
            staging, onnx_bytes, n_features
        )

        self._phase("canary")
        cost_drift_base = self.client.cost_drift_total()
        self.client.set_route(
            model,
            {"base": 1.0 - cfg.fraction, label: cfg.fraction}
            if cfg.fraction < 1.0 else {label: 1.0},
            canary=label,
        )
        self._event(
            "generation_canary", model=model, generation=label,
            fraction=cfg.fraction,
        )

        verdict = "wait"
        observed: dict = {}
        watch_start = time.monotonic()
        while True:
            time.sleep(cfg.poll_s)
            verdict, observed = self._slo_verdict(label, cost_drift_base)
            if verdict not in ("ok", "wait"):
                break  # breach: roll back NOW, not at watch_s
            if (
                verdict == "ok"
                and time.monotonic() - watch_start >= cfg.watch_s
            ):
                break
            if (
                verdict == "wait"
                and time.monotonic() - watch_start >= cfg.timeout_s
            ):
                # a canary that never collects min_requests is
                # undecidable — treat like a breach and keep last-good
                verdict = "no_traffic"
                break
        report["observed"] = observed

        if verdict == "ok":
            self._phase("promoting")
            t0 = time.perf_counter()
            # warm the new weights under the base name behind the
            # curtain, then the atomic queue flip — zero requests
            # dropped; only THEN move traffic off the staging label and
            # retire it
            self.client.promote_base(model, onnx_bytes, n_features)
            self.client.clear_route(model)
            self.client.unload_generation(staging)
            promote_s = time.perf_counter() - t0
            _metrics()["promote_s"].set(promote_s)
            _metrics()["generations"].inc(outcome="promoted")
            self._event(
                "generation_promoted", model=model, generation=label,
                promote_s=promote_s, **observed,
            )
            report.update(promoted=True, reason="slo_ok",
                          promote_s=promote_s)
        else:
            self._phase("rolling_back")
            t0 = time.perf_counter()
            _metrics()["breaches"].inc(reason=verdict)
            # the flip back IS the rollback: clearing the route is
            # atomic in donner, so every subsequent request routes to
            # the last-good base generation; the poisoned staging name
            # is retired after traffic has moved
            self.client.clear_route(model)
            self.client.unload_generation(staging)
            rollback_s = time.perf_counter() - t0
            _metrics()["rollback_s"].set(rollback_s)
            _metrics()["generations"].inc(outcome="rolled_back")
            self._event(
                "generation_rolled_back", model=model, generation=label,
                reason=verdict, rollback_s=rollback_s, **observed,
            )
            report.update(reason=verdict, rollback_s=rollback_s)

        self._phase("idle")
        report["total_s"] = time.perf_counter() - t_start
        self.history.append(report)
        return report

    def run_loop(self, producer, generations: int = 1) -> List[dict]:
        """The continuous loop: produce (train) -> run one generation
        lifecycle, ``generations`` times.  A produced generation that
        fails to train raises; a generation that breaches its SLO rolls
        back and the loop CONTINUES to the next one (a bad generation
        is an expected outcome, not a loop failure)."""
        reports = []
        for _ in range(generations):
            label, onnx_bytes, n_features = producer.next_generation()
            reports.append(
                self.run_generation(label, onnx_bytes, n_features)
            )
        return reports
