"""Per-party secret-shared checkpoint store.

One :class:`CheckpointStore` wraps one party's storage backend (a
:class:`~moose_tpu.storage.FilesystemStorage` for durability, any
dict-like for tests) and gives the training protocol its commit
discipline:

- **Staged writes**: ``Save`` ops whose key carries the checkpoint
  prefix (the lowered form of ``SaveShares``) land in an in-memory
  staging buffer, NOT on disk — a session that dies mid-epoch leaves
  the durable state untouched.
- **Atomic generation commit**: :meth:`commit` writes every staged
  array to a fresh ``_ckpt/gen-%08d/`` namespace through the backend's
  atomic save (tempfile + ``os.replace``), writes a checksum manifest
  LAST, then flips the ``CURRENT`` pointer — the same
  staged-directory-then-pointer discipline as the PR-9 serving
  snapshots.  A crash at any point leaves either the old or the new
  generation current, never a torn one.
- **Validated reads**: ``Load`` ops under the prefix resolve against
  the pinned (or current) generation; the manifest is verified on
  first open (format version, per-array blake2b digests, fixed-keys
  discipline tag) and a torn/tampered/stale generation is rejected
  with a typed :class:`~moose_tpu.errors.CheckpointError` — reads fall
  back to the newest previous VALID generation where the protocol
  allows it.
- **Durable pin**: the training driver pins the epoch every party must
  read from (two-phase resume: parties may have committed different
  epochs when a failure interleaved with the commit fanout); the pin
  survives a worker restart.
- **Bounded retention**: old generations beyond ``retain`` are deleted
  through the backend's ``list_keys``/``delete`` — never by walking the
  filesystem behind the abstraction's back.

Everything non-checkpoint passes through to the backend unchanged, so a
worker configured with a CheckpointStore still serves ordinary
``Load``/``Save`` traffic.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from .. import flight as flight_mod
from .. import metrics as metrics_mod
from ..errors import CheckpointError, StorageError

CKPT_FORMAT = 1

# backend-side namespace for checkpoint machinery (distinct from the
# graph-level key prefix so a graph key can never collide with it)
_META = "_ckpt"

_METRICS: Optional[Dict[str, Any]] = None


def _metrics() -> Dict[str, Any]:
    global _METRICS
    if _METRICS is None:
        _METRICS = {
            "commits": metrics_mod.counter(
                "moose_tpu_training_checkpoint_commits_total",
                "committed checkpoint generations, by party",
                ("party",),
            ),
            "invalid": metrics_mod.counter(
                "moose_tpu_training_checkpoint_invalid_total",
                "checkpoint generations rejected at validation",
                ("reason",),
            ),
            "commit_s": metrics_mod.histogram(
                "moose_tpu_training_checkpoint_commit_seconds",
                "wall seconds per checkpoint generation commit",
            ),
        }
    return _METRICS


def _fixed_keys_digest() -> Optional[str]:
    """Digest of the PRF-determinism discipline in effect: under
    ``MOOSE_TPU_FIXED_KEYS`` every party's PrfKeyGen is a pure function
    of (tag, identity, op name), so a checkpoint written under one tag
    is only bit-exactly resumable under the SAME tag — the manifest
    records it and validation rejects a mismatch instead of silently
    breaking the resume bit-exactness contract."""
    tag = os.environ.get("MOOSE_TPU_FIXED_KEYS")
    if not tag:
        return None
    return hashlib.blake2b(tag.encode(), digest_size=8).hexdigest()


def _array_digest(arr: np.ndarray) -> str:
    h = hashlib.blake2b(digest_size=16)
    h.update(str(arr.dtype).encode())
    h.update(repr(tuple(arr.shape)).encode())
    h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


# -- backend shims (FilesystemStorage protocol OR plain dict) -----------


def _b_save(backing: Any, key: str, value: Any) -> None:
    if hasattr(backing, "save"):
        backing.save(key, value)
    else:
        backing[key] = np.asarray(value)


def _b_load(backing: Any, key: str) -> Any:
    if hasattr(backing, "load"):
        return backing.load(key)
    return backing[key]


def _b_contains(backing: Any, key: str) -> bool:
    return key in backing


def _b_list(backing: Any, prefix: str) -> List[str]:
    if hasattr(backing, "list_keys"):
        return backing.list_keys(prefix)
    return sorted(k for k in backing if k.startswith(prefix))


def _b_delete(backing: Any, key: str) -> None:
    if hasattr(backing, "delete"):
        backing.delete(key)
    else:
        backing.pop(key, None)


def _json_save(backing: Any, key: str, obj: Any) -> None:
    _b_save(
        backing, key,
        np.frombuffer(json.dumps(obj).encode(), dtype=np.uint8).copy(),
    )


def _json_load(backing: Any, key: str) -> Any:
    return json.loads(bytes(np.asarray(_b_load(backing, key))).decode())


class CheckpointStore:
    """Storage wrapper implementing the secret-shared checkpoint
    protocol for ONE party.  Drop-in for the worker/runtime storage
    interface (``load``/``__getitem__``/``__setitem__``/
    ``__contains__``/``setdefault``)."""

    def __init__(self, backing: Any, party: str = "",
                 prefix: str = "ckpt/", retain: int = 2) -> None:
        if retain < 2:
            # the two-phase commit protocol NEEDS the previous
            # generation to survive one more epoch: a party that
            # committed epoch N may be asked to re-serve epoch N-1 when
            # a peer's commit failed
            raise CheckpointError(
                f"checkpoint retention must be >= 2, got {retain}"
            )
        self.backing = backing
        self.party = party
        self.prefix = prefix
        self.retain = int(retain)
        self._lock = threading.RLock()
        self._staged: Dict[str, np.ndarray] = {}
        # generation -> manifest (validated) / None (known invalid)
        self._verdicts: Dict[int, Optional[dict]] = {}
        # memoized read-generation: every checkpoint load/contains
        # would otherwise re-walk the backend's key space (a recursive
        # directory scan on FilesystemStorage) — the only mutation
        # points are commit() and pin() on THIS instance, which
        # invalidate it
        self._read_gen: Optional[int] = None

    # -- storage protocol (what workers and local runtimes call) --------

    def load(self, key: str, query: str = "") -> Any:
        if not key.startswith(self.prefix):
            return _b_load(self.backing, key)
        with self._lock:
            gen = self._read_generation()
            return _b_load(self.backing, f"{_META}/gen-{gen:08d}/{key}")

    def __getitem__(self, key: str) -> Any:
        return self.load(key)

    def __setitem__(self, key: str, value: Any) -> None:
        if not key.startswith(self.prefix):
            _b_save(self.backing, key, value)
            return
        with self._lock:
            self._staged[key] = np.asarray(value)

    def __contains__(self, key: str) -> bool:
        if not key.startswith(self.prefix):
            return _b_contains(self.backing, key)
        # a checkpoint key with NO valid generation raises the typed
        # CheckpointError instead of answering False: the callers of
        # this probe (worker/interpreter Load binding) would otherwise
        # mask the torn/tampered/stale diagnosis as a generic missing
        # key
        with self._lock:
            gen = self._read_generation()
        return _b_contains(
            self.backing, f"{_META}/gen-{gen:08d}/{key}"
        )

    def setdefault(self, key: str, default: Any) -> Any:
        return self.load(key) if key in self else default

    # -- generation resolution ------------------------------------------

    def _generations(self) -> List[int]:
        gens: set = set()
        head = f"{_META}/gen-"
        for key in _b_list(self.backing, head):
            rest = key[len(head):]
            num = rest.split("/", 1)[0]
            if num.isdigit():
                gens.add(int(num))
        return sorted(gens)

    def _manifest(self, gen: int) -> Optional[Dict[str, Any]]:
        """Validated manifest of ``gen``, or None when the generation is
        torn/tampered/stale (verdicts memoized per store instance)."""
        if gen in self._verdicts:
            return self._verdicts[gen]
        verdict: Optional[Dict[str, Any]] = None
        reason: Optional[str] = None
        try:
            manifest = _json_load(
                self.backing, f"{_META}/gen-{gen:08d}/MANIFEST"
            )
            if manifest.get("format") != CKPT_FORMAT:
                reason = "format"
            else:
                fixed = _fixed_keys_digest()
                recorded = manifest.get("fixed_keys")
                if fixed is not None and recorded is not None \
                        and fixed != recorded:
                    # resuming under a different PRF determinism tag
                    # silently voids bit-exactness — reject loudly
                    reason = "fixed_keys"
            if reason is None:
                for key, spec in manifest["keys"].items():
                    arr = np.asarray(_b_load(
                        self.backing, f"{_META}/gen-{gen:08d}/{key}"
                    ))
                    if _array_digest(arr) != spec["digest"]:
                        reason = "tampered"
                        break
                else:
                    verdict = manifest
        except (StorageError, KeyError, ValueError, json.JSONDecodeError):
            reason = "torn"
        if verdict is None:
            _metrics()["invalid"].inc(reason=reason or "torn")
            flight_mod.record(
                "checkpoint_invalid", party=self.party, generation=gen,
                reason=reason or "torn",
            )
        self._verdicts[gen] = verdict
        return verdict

    def _read_generation(self) -> int:
        """The generation reads resolve to: the newest VALID generation
        of the pinned epoch when a pin is set, else the CURRENT pointer
        (falling back past torn/stale generations to the newest valid
        one).  Memoized until the next commit/pin on this instance."""
        if self._read_gen is not None:
            return self._read_gen
        self._read_gen = self._resolve_read_generation()
        return self._read_gen

    def _resolve_read_generation(self) -> int:
        pin = self._read_pin()
        gens = self._generations()
        if pin is not None:
            for gen in reversed(gens):
                manifest = self._manifest(gen)
                if manifest is not None and manifest["epoch"] == pin:
                    return gen
            raise CheckpointError(
                f"{self.party}: no valid checkpoint generation for "
                f"pinned epoch {pin}"
            )
        current: Optional[dict] = None
        if _b_contains(self.backing, f"{_META}/CURRENT"):
            try:
                current = _json_load(self.backing, f"{_META}/CURRENT")
            except (ValueError, json.JSONDecodeError):
                current = None
        if current is not None:
            gen = int(current.get("generation", -1))
            if gen in gens and self._manifest(gen) is not None:
                return gen
            # stale/torn CURRENT: reject it, use the newest valid
            # previous generation instead (typed fallback, recorded)
            _metrics()["invalid"].inc(reason="stale_current")
            flight_mod.record(
                "checkpoint_invalid", party=self.party,
                generation=gen, reason="stale_current",
            )
        for gen in reversed(gens):
            if self._manifest(gen) is not None:
                return gen
        raise CheckpointError(
            f"{self.party}: no valid checkpoint generation exists"
        )

    def _read_pin(self) -> Optional[int]:
        if not _b_contains(self.backing, f"{_META}/PIN"):
            return None
        try:
            return int(_json_load(self.backing, f"{_META}/PIN")["epoch"])
        except (ValueError, KeyError, json.JSONDecodeError):
            return None

    # -- the driver-facing control surface ------------------------------

    def query(self) -> dict:
        """Committed state of this party: valid epochs (ascending, one
        entry per epoch — the newest valid generation wins), the
        current epoch, the durable pin, and what is currently staged."""
        with self._lock:
            by_epoch: Dict[int, int] = {}
            for gen in self._generations():
                manifest = self._manifest(gen)
                if manifest is not None:
                    by_epoch[int(manifest["epoch"])] = gen
            latest = max(by_epoch) if by_epoch else None
            return {
                "epochs": sorted(by_epoch),
                "latest": latest,
                "pin": self._read_pin(),
                "staged": sorted(self._staged),
                "format": CKPT_FORMAT,
            }

    def pin(self, epoch: Optional[int]) -> dict:
        """Durably pin reads to ``epoch`` (None unpins).  Survives a
        worker restart — a party restarted mid-epoch in a mixed-commit
        state must keep reading the generation the driver chose, not
        whatever its own CURRENT happens to be."""
        with self._lock:
            if epoch is None:
                if _b_contains(self.backing, f"{_META}/PIN"):
                    _b_delete(self.backing, f"{_META}/PIN")
            else:
                _json_save(
                    self.backing, f"{_META}/PIN", {"epoch": int(epoch)}
                )
            self._read_gen = None
            return {"pin": epoch}

    def discard_staged(self) -> dict:
        with self._lock:
            n = len(self._staged)
            self._staged.clear()
            return {"discarded": n}

    def commit(self, epoch: int, expected: Optional[list] = None,
               meta: Optional[dict] = None) -> dict:
        """Promote the staged share arrays to a durable generation.

        Write order is the crash-safety argument: arrays first (each an
        atomic tempfile+replace), the checksum MANIFEST second, the
        CURRENT pointer flip last — a crash anywhere leaves the
        previous generation current and the half-written one invisible
        (and detectably invalid).  Retrying a commit whose ack was lost
        is safe: an empty stage against an already-current epoch is
        answered idempotently."""
        t0 = time.monotonic()
        with self._lock:
            epoch = int(epoch)
            if not self._staged:
                cur = self.query()
                if cur["latest"] is not None and epoch in (
                    set(cur["epochs"])
                ):
                    return {"generation": None, "epoch": epoch,
                            "idempotent": True}
                raise CheckpointError(
                    f"{self.party}: commit({epoch}) with nothing staged"
                )
            if expected is not None:
                want = set(expected)
                have = set(self._staged)
                if want != have:
                    raise CheckpointError(
                        f"{self.party}: torn commit({epoch}): staged "
                        f"{sorted(have)} != expected {sorted(want)}"
                    )
            gens = self._generations()
            gen = (gens[-1] + 1) if gens else 0
            head = f"{_META}/gen-{gen:08d}"
            keys: Dict[str, Dict[str, Any]] = {}
            for key, arr in sorted(self._staged.items()):
                _b_save(self.backing, f"{head}/{key}", arr)
                keys[key] = {
                    "digest": _array_digest(arr),
                    "shape": [int(s) for s in arr.shape],
                    "dtype": str(arr.dtype),
                }
            manifest = {
                "format": CKPT_FORMAT,
                "generation": gen,
                "epoch": epoch,
                "keys": keys,
                "fixed_keys": _fixed_keys_digest(),
                "meta": dict(meta or {}),
            }
            _json_save(self.backing, f"{head}/MANIFEST", manifest)
            _json_save(
                self.backing, f"{_META}/CURRENT",
                {"format": CKPT_FORMAT, "generation": gen, "epoch": epoch},
            )
            self._verdicts[gen] = manifest
            self._staged.clear()
            self._read_gen = None
            self._prune(gen)
        _metrics()["commits"].inc(party=self.party or "local")
        _metrics()["commit_s"].observe(time.monotonic() - t0)
        flight_mod.record(
            "checkpoint_committed", party=self.party, epoch=epoch,
            generation=gen, keys=len(keys),
        )
        return {"generation": gen, "epoch": epoch, "idempotent": False}

    def _prune(self, newest: int) -> None:
        """Bounded retention: keep every generation of the newest
        ``retain`` DISTINCT epochs (an epoch re-committed after a
        partial fanout may own two generations — the pinned previous
        epoch must still survive), delete everything else through the
        backend abstraction."""
        gens = self._generations()
        epoch_of = {
            gen: (
                None if (m := self._manifest(gen)) is None
                else int(m["epoch"])
            )
            for gen in gens
        }
        distinct = sorted({e for e in epoch_of.values() if e is not None})
        keep = set(distinct[-self.retain:])
        for gen in gens:
            if gen == newest or epoch_of[gen] in keep:
                continue
            head = f"{_META}/gen-{gen:08d}"
            for key in _b_list(self.backing, head + "/"):
                try:
                    _b_delete(self.backing, key)
                except StorageError:  # pragma: no cover - racing delete
                    pass
            self._verdicts.pop(gen, None)

    # -- rpc dispatch ----------------------------------------------------

    def checkpoint_control(self, cmd: str, args: dict) -> dict:
        """Single dispatch point for the choreography StorageControl
        rpc (and the in-process driver): every command returns a
        msgpack-able dict."""
        args = dict(args or {})
        if cmd == "query":
            return self.query()
        if cmd == "pin":
            return self.pin(args.get("epoch"))
        if cmd == "commit":
            return self.commit(
                args["epoch"], expected=args.get("expected"),
                meta=args.get("meta"),
            )
        if cmd == "discard":
            return self.discard_staged()
        raise CheckpointError(f"unknown checkpoint command {cmd!r}")
