"""Trained-model export: reveal -> ONNX -> serving hot-swap.

The last leg of the training story (ROADMAP item 3): the weights a
:class:`~moose_tpu.training.session.TrainingSession` revealed to the
model receiver become a standard predictor artifact and replace the
live version in the PR-4 serving registry —

- in-process: :func:`hot_swap` drives
  ``InferenceServer.replace_model`` (warm staging registration, atomic
  queue flip, zero dropped requests);
- across processes (a running blitzen): write the ONNX artifact over
  the daemon's model file and roll it through the PR-9 snapshot/drain
  path — SIGTERM drains in-flight batches and re-snapshots, the
  restart invalidates the snapshot on the model-source digest change
  and registers the new weights fresh (``scripts/train_smoke.py``
  exercises exactly this, asserting zero dropped requests).
"""

from __future__ import annotations

from types import SimpleNamespace
from typing import Any, Optional

import numpy as np

from ..predictors import sklearn_export


def logreg_onnx_bytes(weights: np.ndarray,
                      intercept: Optional[np.ndarray] = None) -> bytes:
    """Serialize trained logistic-regression weights as a
    skl2onnx-layout LinearClassifier ONNX model (binary: both class
    rows, LOGISTIC post-transform) — importable by ``from_onnx`` and
    servable by blitzen.  ``weights`` is the trainer's (n_features, 1)
    column; intercept defaults to zero (the SGD trainers are
    bias-free)."""
    w = np.asarray(weights, dtype=np.float64).reshape(-1)
    shim = SimpleNamespace(
        coef_=w[None, :],
        intercept_=np.zeros(1) if intercept is None else (
            np.asarray(intercept, dtype=np.float64).reshape(1)
        ),
        classes_=np.array([0, 1]),
    )
    return sklearn_export.logistic_regression_onnx(
        shim, n_features=w.shape[0]
    ).encode()


def trained_predictor(weights: np.ndarray,
                      intercept: Optional[np.ndarray] = None) -> Any:
    """A ``predictors`` instance for the trained logreg weights (the
    object form of :func:`logreg_onnx_bytes`)."""
    from ..predictors import from_onnx

    return from_onnx(logreg_onnx_bytes(weights, intercept))


def onnx_digest(raw: bytes, n_features: int, max_batch: int) -> str:
    """The fleet's source-digest formula for an ONNX artifact: what
    blitzen stamps into snapshots (and the admin ``:load`` endpoint
    answers for idempotency) — raw bytes plus the registration shape
    knobs that change the warm state."""
    import hashlib

    return hashlib.blake2b(
        bytes(raw) + repr((int(n_features), int(max_batch))).encode(),
        digest_size=16,
    ).hexdigest()


def hot_swap(server: Any, name: str, weights: np.ndarray,
             intercept: Optional[np.ndarray] = None) -> Any:
    """Replace the live model ``name`` on an in-process
    ``InferenceServer`` with freshly trained weights, zero requests
    dropped (see ``InferenceServer.replace_model``)."""
    model = trained_predictor(weights, intercept)
    n_features = np.asarray(weights).reshape(-1).shape[0]
    return server.replace_model(name, model, row_shape=(n_features,))
