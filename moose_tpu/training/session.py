"""The training epoch supervisor.

Runs N epochs as N successive distributed sessions, layered ON TOP of
the PR-3 client session supervisor (which already retries transient
in-session faults under fresh session ids): this layer owns the
checkpoint commit protocol and epoch-granular recovery.

Per epoch:

1. **pin** every party's reads to the last fully-committed epoch
   (durable — a worker restarted mid-epoch keeps reading the generation
   the driver chose even if its own CURRENT has advanced);
2. run the epoch session (``load_shares`` -> SGD steps ->
   ``save_shares``, staged in memory on each party);
3. on success, **commit** on every party (the staged arrays become a
   durable generation, atomically published via the CURRENT pointer).

A retryable failure anywhere — worker SIGKILL, dropped send, peer
unreachable, a commit fanout that only partially landed — backs off
(capped exponential), re-queries every party's committed state, and
resumes from the newest epoch committed by ALL parties.  Committed
epochs are never replayed; an epoch whose commit only reached a subset
of parties is re-run from the common base (the subset re-commits — a
new generation, same epoch — which is why checkpoint retention keeps
the previous epoch alive).  Under ``MOOSE_TPU_FIXED_KEYS`` the whole
recovery dance is bit-exact: a resumed run produces final weights
bit-identical to an uninterrupted one.

Flight events: ``epoch_start`` / ``epoch_committed`` /
``epoch_resumed`` (+ the checkpoint store's ``checkpoint_committed`` /
``checkpoint_invalid``); metrics: ``moose_tpu_training_*``.
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Any, Callable, Dict, Optional

from .. import flight as flight_mod
from .. import metrics as metrics_mod
from ..errors import CheckpointError, MooseError, is_retryable

_METRICS: Optional[Dict[str, Any]] = None


def _metrics() -> Dict[str, Any]:
    global _METRICS
    if _METRICS is None:
        _METRICS = {
            "epochs": metrics_mod.counter(
                "moose_tpu_training_epochs_total",
                "training epochs, by outcome",
                ("outcome",),
            ),
            "resumes": metrics_mod.counter(
                "moose_tpu_training_resumes_total",
                "epoch re-runs after a retryable mid-epoch failure "
                "(resumed from the last committed checkpoint)",
            ),
            "runs": metrics_mod.counter(
                "moose_tpu_training_runs_total",
                "training runs, by outcome",
                ("outcome",),
            ),
            "epoch_s": metrics_mod.histogram(
                "moose_tpu_training_epoch_seconds",
                "wall seconds per committed epoch (session + commit)",
            ),
        }
    return _METRICS


def _retryable(exc: BaseException) -> bool:
    wire_bit = getattr(exc, "retryable", None)
    return bool(wire_bit) if wire_bit is not None else is_retryable(exc)


@dataclasses.dataclass
class TrainingConfig:
    epochs: int = 3
    # epoch-level recovery budget (the inner PR-3 supervisor has its own
    # per-session retry budget underneath)
    max_epoch_attempts: int = 5
    backoff_base_s: float = 0.25
    backoff_cap_s: float = 2.0
    session_timeout_s: float = 120.0
    # export the trained weights (a reveal-to-bob session) at the end
    export: bool = True


class LocalTrainingCluster:
    """In-process adapter: a LocalMooseRuntime whose per-party storages
    are :class:`~moose_tpu.training.checkpoint.CheckpointStore`
    objects."""

    def __init__(self, runtime: Any, parties: Any) -> None:
        self.runtime = runtime
        self.parties = list(parties)
        for party in self.parties:
            store = runtime.storage.get(party)
            if not hasattr(store, "checkpoint_control"):
                raise CheckpointError(
                    f"party {party!r}: LocalMooseRuntime storage must "
                    "be a CheckpointStore (pass storage_mapping="
                    "{party: CheckpointStore(...)})"
                )

    def run(self, comp: Any, arguments: Any, timeout: float) -> Any:
        return self.runtime.evaluate_computation(
            comp, arguments=arguments
        )

    def control(self, party: str, cmd: str, **args: Any) -> Any:
        return self.runtime.storage[party].checkpoint_control(cmd, args)


class GrpcTrainingCluster:
    """Distributed adapter over the PR-3 supervisor: sessions run
    through ``GrpcClientRuntime.run_computation`` (typed wire errors,
    in-session retries, abort fanout), checkpoint control through the
    choreography StorageControl rpc."""

    def __init__(self, client: Any,
                 parties: Optional[list] = None) -> None:
        self.client = client
        self.parties = list(parties or client.identities)

    def run(self, comp: Any, arguments: Any, timeout: float) -> Any:
        outputs, _ = self.client.run_computation(
            comp, arguments, timeout=timeout
        )
        return outputs

    def control(self, party: str, cmd: str, **args: Any) -> Any:
        from ..distributed.client import _classify_rpc_error

        try:
            return self.client._clients[party].storage_control(cmd, args)
        except MooseError:
            raise  # already typed (incl. the wire envelope's class)
        except Exception as e:  # noqa: BLE001 — transport failure
            # a dead/restarting worker must classify RETRYABLE so the
            # epoch supervisor waits it out instead of giving up
            raise _classify_rpc_error(
                e, f"storage_control({cmd}) on {party} failed"
            ) from e


class TrainingSession:
    """Supervised, checkpointed, resumable multi-epoch secure training
    of one ``predictors.trainers.SecureTrainer`` model."""

    def __init__(self, trainer: Any, cluster: Any,
                 config: Optional[TrainingConfig] = None) -> None:
        self.trainer = trainer
        self.cluster = cluster
        self.config = config or TrainingConfig()
        # outcome of the most recent run(): epochs run/skipped/resumed,
        # per-epoch attempts, final committed epoch — the training
        # mirror of the client's last_session_report
        self.last_report: dict = {}

    # -- party control fanout -------------------------------------------

    def _control_all(self, cmd: str, **args: Any) -> dict:
        return {
            party: self.cluster.control(party, cmd, **args)
            for party in self.cluster.parties
        }

    def _common_committed(self) -> Optional[int]:
        """The newest epoch committed (and still valid) on EVERY party
        — the only state the protocol may resume from."""
        queries = self._control_all("query")
        common: Optional[int] = None
        sets = [set(q["epochs"]) for q in queries.values()]
        inter = set.intersection(*sets) if sets else set()
        if inter:
            common = max(inter)
        return common

    def _with_retries(self, fn: Callable[[], Any], what: str) -> Any:
        """Retryable-failure envelope for control-plane steps OUTSIDE
        the epoch loop (queries, the final unpin, the export session):
        a worker mid-restart answers UNAVAILABLE for a second or two,
        and that must not abort a training run whose state is already
        durably committed."""
        cfg = self.config
        for attempt in range(1, cfg.max_epoch_attempts + 1):
            try:
                return fn()
            except Exception as exc:  # noqa: BLE001 — classified
                if not _retryable(exc) or attempt >= (
                    cfg.max_epoch_attempts
                ):
                    raise
                flight_mod.record(
                    "training_control_retry", party="trainer",
                    what=what, attempt=attempt,
                    error=f"{type(exc).__name__}: {exc}",
                )
                delay = min(
                    cfg.backoff_cap_s,
                    cfg.backoff_base_s * 2 ** (attempt - 1),
                )
                time.sleep(delay + random.uniform(0, delay / 2))

    def _commit_all(self, epoch: int) -> None:
        expected = self.trainer.expected_staged()
        self._control_all(
            "commit", epoch=epoch, expected=expected,
            meta={"model": self.trainer.checkpoint_key},
        )

    # -- the supervisor loop --------------------------------------------

    def run(self, x: Any, y: Any,
            epochs: Optional[int] = None) -> dict:
        """Train to ``epochs`` (default ``config.epochs``) committed
        epochs, resuming from whatever is already durably committed.
        The override is the continuous-training lever: the control
        plane calls ``run(x, y, epochs=N * epochs_per_generation)``
        with a growing cumulative target, so each generation inherits
        the committed state (and the mid-epoch resume machinery) of the
        last.  Returns the report dict (also kept as ``last_report``);
        trained weights under ``"weights"`` when ``config.export``."""
        cfg = self.config
        target_epochs = cfg.epochs if epochs is None else int(epochs)
        trainer = self.trainer
        import numpy as np

        x = np.asarray(x, dtype=np.float64)
        y = np.asarray(y, dtype=np.float64)
        n_rows = x.shape[0]
        report: dict = {
            "ok": False,
            "target_epochs": target_epochs,
            "epochs_committed": [],
            "epochs_skipped": [],
            "resumes": 0,
            "attempts": {},
        }
        self.last_report = report

        base = self._with_retries(self._common_committed, "query")
        if base is None:
            # bootstrap: share + persist the initial weights as the
            # epoch-0 checkpoint (one session, committed like an epoch)
            init_args = {
                name: self._initial_value(name, shape)
                for name, shape in trainer.state_shapes.items()
            }
            self._run_epoch(
                report, epoch=0,
                comp=trainer.init_computation(),
                arguments=init_args,
            )
            base = 0
        elif base > target_epochs:
            raise CheckpointError(
                f"checkpoint is already at epoch {base}, beyond the "
                f"requested {target_epochs}"
            )
        else:
            report["epochs_skipped"] = list(range(1, base + 1))

        epoch_comp = trainer.epoch_computation(n_rows)
        while base < target_epochs:
            target = base + 1
            self._run_epoch(
                report, epoch=target, comp=epoch_comp,
                arguments={"x": x, "y": y},
            )
            new_base = self._with_retries(
                self._common_committed, "post_epoch_query"
            )
            if new_base is None or new_base < target:
                raise CheckpointError(
                    f"epoch {target} commit did not land on all "
                    f"parties (common committed: {new_base})"
                )
            base = new_base

        # training is durable; drop the pin so later readers see the
        # newest committed state
        self._with_retries(
            lambda: self._control_all("pin", epoch=None), "unpin"
        )
        report["final_epoch"] = base
        report["ok"] = True
        if cfg.export:
            outputs = self._with_retries(
                lambda: self.cluster.run(
                    trainer.export_computation(), {},
                    timeout=cfg.session_timeout_s,
                ),
                "export",
            )
            report["weights"] = trainer.unpack_export(outputs)
        _metrics()["runs"].inc(outcome="ok")
        return report

    def _initial_value(self, name: str, shape: Any) -> Any:
        """Deterministic small init (the model owner would supply real
        initial weights; trainers may override via ``initial_weights``
        attribute)."""
        import numpy as np

        override = getattr(self.trainer, "initial_weights", None)
        if override is not None and name in override:
            return np.asarray(override[name], dtype=np.float64)
        # hashlib, NOT hash(): Python string hashing is salted per
        # process, and a driver relaunched after a pre-commit crash
        # must regenerate the IDENTICAL bootstrap weights or the
        # bit-exact-resume contract silently breaks across processes
        import hashlib

        digest = hashlib.blake2b(
            f"{self.trainer.checkpoint_key}|{name}".encode(),
            digest_size=4,
        ).digest()
        rng = np.random.default_rng(int.from_bytes(digest, "big"))
        return rng.normal(size=shape) * 0.1

    def _run_epoch(self, report: dict, epoch: int, comp: Any,
                   arguments: Any) -> None:
        """One epoch (or the init bootstrap) with epoch-level recovery:
        pin -> session -> commit, retrying retryable failures from the
        re-queried common committed state."""
        cfg = self.config
        attempts = 0
        resumed = False
        while True:
            attempts += 1
            report["attempts"][epoch] = attempts
            t0 = time.monotonic()
            try:
                self._control_all("discard")
                if epoch > 0:
                    # parties may hold newer (partially-committed)
                    # generations after a failed commit fanout: every
                    # read of this session MUST come from the common
                    # base, durably, even across a worker restart
                    self._control_all("pin", epoch=epoch - 1)
                if resumed:
                    _metrics()["resumes"].inc()
                    report["resumes"] += 1
                    flight_mod.record(
                        "epoch_resumed", party="trainer", epoch=epoch,
                        attempt=attempts,
                        from_epoch=epoch - 1 if epoch > 0 else None,
                    )
                flight_mod.record(
                    "epoch_start", party="trainer", epoch=epoch,
                    attempt=attempts,
                )
                self.cluster.run(
                    comp, arguments, timeout=cfg.session_timeout_s
                )
                self._commit_all(epoch)
            except Exception as exc:  # noqa: BLE001 — classified below
                _metrics()["epochs"].inc(outcome="failed")
                flight_mod.record(
                    "epoch_failed", party="trainer", epoch=epoch,
                    attempt=attempts,
                    error=f"{type(exc).__name__}: {exc}",
                    retryable=_retryable(exc),
                )
                if not _retryable(exc) or attempts >= (
                    cfg.max_epoch_attempts
                ):
                    _metrics()["runs"].inc(outcome="failed")
                    raise
                resumed = True
                delay = min(
                    cfg.backoff_cap_s,
                    cfg.backoff_base_s * 2 ** (attempts - 1),
                )
                time.sleep(delay + random.uniform(0, delay / 2))
                # a party may have committed this epoch before the
                # failure hit the others: never replay a FULLY
                # committed epoch.  The query itself may hit a
                # still-dead worker — treat that as "unknown" and let
                # the next attempt's control calls retry it
                try:
                    committed = self._common_committed()
                except Exception as query_exc:  # noqa: BLE001
                    if not _retryable(query_exc):
                        raise
                    committed = None
                if committed is not None and committed >= epoch:
                    report["epochs_committed"].append(epoch)
                    return
                continue
            _metrics()["epochs"].inc(outcome="committed")
            _metrics()["epoch_s"].observe(time.monotonic() - t0)
            flight_mod.record(
                "epoch_committed", party="trainer", epoch=epoch,
                attempt=attempts,
            )
            report["epochs_committed"].append(epoch)
            return
