"""Secure training as a first-class workload (ROADMAP item 3).

A multi-epoch MPC training run is a LONG-LIVED distributed session
sequence — long enough that a worker *will* die mid-epoch — so this
package turns the PR-3 fault-tolerance stack (retrying supervisor,
typed wire errors, chaos layer) into load-bearing infrastructure:

- :mod:`.checkpoint` — each party durably persists ITS OWN replicated
  share pair of the model state (atomic tempfile + ``os.replace``
  writes, checksum-validated manifests, CURRENT-pointer generations
  reusing the PR-9 snapshot discipline, bounded retention).  The model
  never exists in the clear on any host, on the wire, or at the client.
- :mod:`.session` — the epoch supervisor: runs N epochs as successive
  distributed sessions layered on the PR-3 client supervisor, commits a
  checkpoint generation per epoch (two-phase: stage in-graph via
  ``SaveShares``, commit via the StorageControl rpc after the session
  succeeds), and on a retryable mid-epoch failure resumes from the last
  committed generation under a fresh session id — never replaying a
  committed epoch, never serving a torn checkpoint, bit-exact under
  ``MOOSE_TPU_FIXED_KEYS``.
- :mod:`.export` — reveal + register: a finished model exports to ONNX
  and hot-swaps into the PR-4 serving registry with zero dropped
  requests (in-process via ``ModelRegistry.replace``; across processes
  via the PR-9 snapshot/drain path).

The SGD-step graphs themselves live with the model zoo:
:mod:`moose_tpu.predictors.trainers`.
"""

from .checkpoint import CKPT_FORMAT, CheckpointStore  # noqa: F401
from .session import TrainingConfig, TrainingSession  # noqa: F401
