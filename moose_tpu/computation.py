"""The moose_tpu intermediate representation (IR).

TPU-native re-design of the reference IR (``moose/src/computation.rs``): a
named dataflow graph whose operations are pinned to *placements*.  The dtype
and shape math of each kernel is delegated to JAX/XLA at execution time; the
IR's job is to carry the placement structure, the operator vocabulary, the
value type system, and (de)serialization.

Key differences from the reference (by design, for TPU):
- Operations are plain dataclasses; the operator vocabulary is an open
  registry of names + attribute schemas instead of a closed Rust enum
  (reference ``Operator`` enum, computation.rs:828-914).
- The graph is kept in insertion order; ``toposort`` is a compiler pass.
"""

from __future__ import annotations

import dataclasses
import hashlib
import secrets
from typing import Any, Iterable, Optional

from . import dtypes as dt

# ---------------------------------------------------------------------------
# Placements (reference: Placement enum, computation.rs:1626)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class HostPlacement:
    name: str

    @property
    def kind(self) -> str:
        return "Host"

    def to_textual(self) -> str:
        return f"@Host({self.name})"


@dataclasses.dataclass(frozen=True)
class ReplicatedPlacement:
    """3-party replicated secret-sharing placement (virtual unit of 3 hosts)."""

    name: str
    owners: tuple[str, str, str]

    def __post_init__(self):
        object.__setattr__(self, "owners", tuple(self.owners))
        assert len(self.owners) == 3

    @property
    def kind(self) -> str:
        return "Replicated"

    def host_placements(self) -> tuple[HostPlacement, HostPlacement, HostPlacement]:
        return tuple(HostPlacement(o) for o in self.owners)

    def to_textual(self) -> str:
        return f"@Replicated({', '.join(self.owners)})"


@dataclasses.dataclass(frozen=True)
class AdditivePlacement:
    """2-party additive secret-sharing placement (helper sub-protocols)."""

    name: str
    owners: tuple[str, str]

    def __post_init__(self):
        object.__setattr__(self, "owners", tuple(self.owners))
        assert len(self.owners) == 2

    @property
    def kind(self) -> str:
        return "Additive"

    def host_placements(self) -> tuple[HostPlacement, HostPlacement]:
        return tuple(HostPlacement(o) for o in self.owners)

    def to_textual(self) -> str:
        return f"@Additive({', '.join(self.owners)})"


@dataclasses.dataclass(frozen=True)
class Mirrored3Placement:
    """Public values kept in lockstep on 3 hosts (no secret sharing)."""

    name: str
    owners: tuple[str, str, str]

    def __post_init__(self):
        object.__setattr__(self, "owners", tuple(self.owners))
        assert len(self.owners) == 3

    @property
    def kind(self) -> str:
        return "Mirrored3"

    def host_placements(self) -> tuple[HostPlacement, HostPlacement, HostPlacement]:
        return tuple(HostPlacement(o) for o in self.owners)

    def to_textual(self) -> str:
        return f"@Mirrored3({', '.join(self.owners)})"


Placement = HostPlacement | ReplicatedPlacement | AdditivePlacement | Mirrored3Placement


# ---------------------------------------------------------------------------
# Value types (reference: Ty, computation.rs:330-591 + types.rs)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Ty:
    """A value type.  ``name`` identifies the concrete type (e.g.
    ``HostRing128Tensor``); logical tensors carry a ``dtype``; fixed types
    carry precision inside their dtype."""

    name: str
    dtype: Optional[dt.DType] = None

    def to_textual(self) -> str:
        if self.name == "Tensor":
            return f"Tensor<{self.dtype.short_textual()}>"
        if self.name in ("HostFixed64Tensor", "HostFixed128Tensor",
                         "ReplicatedFixed64Tensor", "ReplicatedFixed128Tensor",
                         "Mirrored3Fixed64Tensor", "Mirrored3Fixed128Tensor"):
            i = self.dtype.integral_precision
            f = self.dtype.fractional_precision
            return f"{self.name}<{i}, {f}>"
        return self.name

    def __str__(self) -> str:
        return self.to_textual()


def tensor_ty(dtype: dt.DType) -> Ty:
    return Ty("Tensor", dtype)


# Frequently used concrete types.
UnitTy = Ty("Unit")
ShapeTy = Ty("HostShape")
SeedTy = Ty("HostSeed")
PrfKeyTy = Ty("HostPrfKey")
StringTy = Ty("HostString")
HostFloat32TensorTy = Ty("HostFloat32Tensor", dt.float32)
HostFloat64TensorTy = Ty("HostFloat64Tensor", dt.float64)
HostInt64TensorTy = Ty("HostInt64Tensor", dt.int64)
HostUint64TensorTy = Ty("HostUint64Tensor", dt.uint64)
HostBitTensorTy = Ty("HostBitTensor", dt.bool_)
HostRing64TensorTy = Ty("HostRing64Tensor")
HostRing128TensorTy = Ty("HostRing128Tensor")
ReplicatedRing64TensorTy = Ty("ReplicatedRing64Tensor")
ReplicatedRing128TensorTy = Ty("ReplicatedRing128Tensor")
ReplicatedBitTensorTy = Ty("ReplicatedBitTensor")
AdditiveRing64TensorTy = Ty("AdditiveRing64Tensor")
AdditiveRing128TensorTy = Ty("AdditiveRing128Tensor")
Mirrored3Ring64TensorTy = Ty("Mirrored3Ring64Tensor")
Mirrored3Ring128TensorTy = Ty("Mirrored3Ring128Tensor")
AesTensorTy = Ty("AesTensor")
AesKeyTy = Ty("AesKey")
ReplicatedAesKeyTy = Ty("ReplicatedAesKey")
HostAesKeyTy = Ty("HostAesKey")

# every AES-typed value name, for boundary dispatch/guards
AES_TY_NAMES = frozenset(
    {"AesTensor", "AesKey", "HostAesKey", "ReplicatedAesKey"}
)


def host_fixed_ty(dtype: dt.DType) -> Ty:
    total = 64 if dtype.name == "fixed64" else 128
    return Ty(f"HostFixed{total}Tensor", dtype)


def rep_fixed_ty(dtype: dt.DType) -> Ty:
    total = 64 if dtype.name == "fixed64" else 128
    return Ty(f"ReplicatedFixed{total}Tensor", dtype)


# ---------------------------------------------------------------------------
# Signatures (reference: Signature, computation.rs:620-767)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Signature:
    input_types: tuple[Ty, ...]
    return_type: Ty
    # variadic signatures (reference Signature::variadic,
    # computation.rs:620-767) carry ONE element type that every input
    # shares; textual form is ``[T] -> R`` and arity is unchecked
    variadic: bool = False

    def __post_init__(self):
        object.__setattr__(self, "input_types", tuple(self.input_types))

    @property
    def arity(self) -> int:
        return len(self.input_types)

    def to_textual(self) -> str:
        if self.variadic:
            return (
                f"[{self.input_types[0].to_textual()}] -> "
                f"{self.return_type.to_textual()}"
            )
        ins = ", ".join(t.to_textual() for t in self.input_types)
        return f"({ins}) -> {self.return_type.to_textual()}"


def signature(input_types: Iterable[Ty], return_type: Ty) -> Signature:
    return Signature(tuple(input_types), return_type)


# ---------------------------------------------------------------------------
# Operator vocabulary (reference: operators! macro, computation.rs:828-914)
# ---------------------------------------------------------------------------

OPERATORS = [
    "Abs", "Add", "And", "AtLeast2D", "BitExtract", "Broadcast", "Cast",
    "Concat", "Constant", "Decrypt", "DeriveSeed", "Div", "Diag", "Dot",
    "ExpandDims", "Identity", "IndexAxis", "Inverse", "Input", "Load", "Mul",
    "Mean", "Output", "Ones", "Or", "PrfKeyGen", "Reshape", "Receive",
    "Relu", "RingFixedpointArgmax", "RingFixedpointDecode",
    "RingFixedpointEncode", "RingInject", "RingFixedpointMean", "Sample",
    "SampleSeeded", "Select", "Send", "Save", "Shape", "Shl", "Shr", "Sign",
    "Slice", "Sqrt", "Squeeze", "Sub", "Sum", "Transpose", "Xor", "Zeros",
    # Fixed-point operators
    "Equal", "EqualZero", "Exp", "FixedpointEncode", "FixedpointDecode",
    "Greater", "Less", "Neg", "Pow2", "Sigmoid",
    # Additive operators
    "AdtToRep",
    # Replicated operators
    "AddN", "Argmax", "BitDecompose", "BitCompose", "Fill", "Index", "Log2",
    "Log", "Maximum", "Msb", "Mux", "RepToAdt", "Reveal", "Share", "Softmax",
    "ShlDim", "TruncPr",
    # Mirrored operators
    "Demirror", "Mirror",
    # Secret-shared checkpoint boundary (training): each party durably
    # persists / reloads ITS OWN replicated share pair through its local
    # storage — lowering expands these into per-owner ring-typed
    # Load/Save ops, so the model state never exists in the clear
    "LoadShares", "SaveShares",
    # Convolution / pooling (north-star extension — BASELINE.json configs
    # list encrypted ResNet-style inference; no reference counterpart)
    "Conv2D", "AvgPool2D", "MaxPool2D", "Im2Col",
]

OPERATOR_SET = frozenset(OPERATORS)


# ---------------------------------------------------------------------------
# Operations & computations
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Operation:
    """One node of the dataflow graph (reference: computation.rs:1656)."""

    name: str
    kind: str
    inputs: list[str]
    placement_name: str
    signature: Signature
    attributes: dict[str, Any] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        if self.kind not in OPERATOR_SET:
            raise ValueError(f"unknown operator kind: {self.kind}")


@dataclasses.dataclass(eq=False)
class Computation:
    """A named dataflow graph (reference: NamedComputation,
    computation.rs:1663-1666).

    Identity-based equality/hash so computations can key weak caches
    (compiled-plan reuse) without structural comparison cost."""

    operations: dict[str, Operation] = dataclasses.field(default_factory=dict)
    placements: dict[str, Placement] = dataclasses.field(default_factory=dict)

    def add_operation(self, op: Operation) -> Operation:
        if op.name in self.operations:
            raise ValueError(f"duplicate operation name: {op.name}")
        self.operations[op.name] = op
        return op

    def add_placement(self, plc: Placement) -> Placement:
        existing = self.placements.get(plc.name)
        if existing is not None and existing != plc:
            raise ValueError(f"conflicting placement for name {plc.name}")
        self.placements[plc.name] = plc
        return plc

    def placement(self, name: str) -> Placement:
        return self.placements[name]

    def placement_of(self, op: Operation) -> Placement:
        return self.placements[op.placement_name]

    def find_outputs(self) -> list[Operation]:
        return [op for op in self.operations.values() if op.kind == "Output"]

    def find_inputs(self) -> list[Operation]:
        return [op for op in self.operations.values() if op.kind == "Input"]

    def consumers(self) -> dict[str, list[str]]:
        out: dict[str, list[str]] = {name: [] for name in self.operations}
        for op in self.operations.values():
            for inp in op.inputs:
                out[inp].append(op.name)
        return out

    def toposort_names(self) -> list[str]:
        """Kahn topological order over dataflow edges, plus the Send/Receive
        rendezvous edges (reference: as_graph(), computation.rs:1879-1942)."""
        indeg: dict[str, int] = {name: 0 for name in self.operations}
        adj: dict[str, list[str]] = {name: [] for name in self.operations}
        # Stitch Send -> Receive edges by rendezvous key within the graph.
        sends: dict[str, str] = {}
        for op in self.operations.values():
            if op.kind == "Send":
                sends[op.attributes["rendezvous_key"]] = op.name
        for op in self.operations.values():
            deps = list(op.inputs)
            if op.kind == "Receive":
                rdv = op.attributes["rendezvous_key"]
                if rdv in sends:
                    deps.append(sends[rdv])
            for dep in deps:
                if dep not in self.operations:
                    raise ValueError(
                        f"operation {op.name} depends on unknown {dep}"
                    )
                adj[dep].append(op.name)
                indeg[op.name] += 1
        ready = [n for n, d in indeg.items() if d == 0]
        order: list[str] = []
        while ready:
            n = ready.pop()
            order.append(n)
            for m in adj[n]:
                indeg[m] -= 1
                if indeg[m] == 0:
                    ready.append(m)
        if len(order) != len(self.operations):
            raise ValueError("cycle detected in computation graph")
        return order

    def clone_empty(self) -> "Computation":
        c = Computation()
        c.placements = dict(self.placements)
        return c


# ---------------------------------------------------------------------------
# Session ids & rendezvous keys
# ---------------------------------------------------------------------------


class SessionId:
    """128-bit session identifier derived by hashing an arbitrary string
    (reference: computation.rs:95-144, blake3-based; we use blake2b which is
    in the Python standard library — documented deviation)."""

    __slots__ = ("_bytes", "_text")

    def __init__(self, text: str):
        self._text = text
        self._bytes = hashlib.blake2b(text.encode(), digest_size=16).digest()

    @classmethod
    def random(cls) -> "SessionId":
        return cls(secrets.token_hex(16))

    @property
    def text(self) -> str:
        return self._text

    def to_bytes(self) -> bytes:
        return self._bytes

    def __eq__(self, other):
        return isinstance(other, SessionId) and self._bytes == other._bytes

    def __hash__(self):
        return hash(self._bytes)

    def __repr__(self):
        return f"SessionId({self._text!r})"


class RendezvousKey:
    """128-bit tag addressing one value transfer inside a session
    (reference: computation.rs:30-93)."""

    __slots__ = ("_bytes",)

    def __init__(self, raw: bytes | str | int):
        if isinstance(raw, int):
            raw = raw.to_bytes(16, "little")
        elif isinstance(raw, str):
            raw = hashlib.blake2b(raw.encode(), digest_size=16).digest()
        assert isinstance(raw, bytes) and len(raw) == 16
        self._bytes = raw

    @classmethod
    def from_index(cls, index: int) -> "RendezvousKey":
        return cls(index)

    def to_bytes(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __eq__(self, other):
        return isinstance(other, RendezvousKey) and self._bytes == other._bytes

    def __hash__(self):
        return hash(self._bytes)

    def __repr__(self):
        return f"RendezvousKey({self.hex()})"
