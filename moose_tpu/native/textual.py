"""ctypes loader for the C++ parallel textual parser
(textual_parser.cpp).  Compiles the shared library on first use with the
system g++ and caches it next to the source; returns the per-line record
list decoded from msgpack (C-speed on both sides)."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path

_HERE = Path(__file__).resolve().parent
_SRC = _HERE / "textual_parser.cpp"
_BUILD = _HERE / "build"
_SO = _BUILD / "libmoose_textual.so"

_lock = threading.Lock()
_lib = None
_build_failed = False


def build(force: bool = False) -> Path:
    with _lock:
        if _SO.exists() and not force:
            if _SO.stat().st_mtime >= _SRC.stat().st_mtime:
                return _SO
        _BUILD.mkdir(exist_ok=True)
        tmp = _SO.with_suffix(f".so.tmp{os.getpid()}")
        cmd = [
            "g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-pthread",
            str(_SRC), "-o", str(tmp),
        ]
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"failed to build native textual parser:\n{proc.stderr}"
            )
        os.replace(tmp, _SO)
        return _SO


def load():
    """The loaded library, or None when the toolchain is unavailable
    (callers fall back to the pure-Python parser)."""
    global _lib, _build_failed
    if _lib is not None:
        return _lib
    if _build_failed:
        return None
    try:
        path = build()
        lib = ctypes.CDLL(str(path))
    except (RuntimeError, OSError):
        _build_failed = True
        return None
    lib.mt_parse_textual.restype = ctypes.POINTER(ctypes.c_char)
    lib.mt_parse_textual.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.mt_parse_free.argtypes = [ctypes.POINTER(ctypes.c_char)]
    _lib = lib
    return lib


def parse_lines(text: str, threads: int = 0):
    """Parse the textual format into per-line records (see
    textual_parser.cpp for the record schema); None if unavailable."""
    import msgpack

    lib = load()
    if lib is None:
        return None
    raw = text.encode()
    out_len = ctypes.c_uint64()
    buf = lib.mt_parse_textual(raw, len(raw), threads,
                               ctypes.byref(out_len))
    if not buf:
        return None
    try:
        data = ctypes.string_at(buf, out_len.value)
    finally:
        lib.mt_parse_free(buf)
    return msgpack.unpackb(data, raw=False, strict_map_key=False)
