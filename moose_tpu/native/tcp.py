"""ctypes loader/wrapper for the C++ TCP transport
(tcp_transport.cpp).  Compiles the shared library on first use with the
system g++ and caches it next to the source."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path

from ..errors import NetworkingError, ReceiveTimeoutError

_HERE = Path(__file__).resolve().parent
_SRC = _HERE / "tcp_transport.cpp"
_BUILD = _HERE / "build"
_SO = _BUILD / "libmoose_tcp.so"

_lock = threading.Lock()
_lib = None


def build(force: bool = False) -> Path:
    with _lock:
        if _SO.exists() and not force:
            if _SO.stat().st_mtime >= _SRC.stat().st_mtime:
                return _SO
        _BUILD.mkdir(exist_ok=True)
        tmp = _SO.with_suffix(f".so.tmp{os.getpid()}")
        cmd = [
            "g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-pthread",
            str(_SRC), "-o", str(tmp),
        ]
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise NetworkingError(
                f"failed to build native TCP transport:\n{proc.stderr}"
            )
        os.replace(tmp, _SO)
        return _SO


def load():
    global _lib
    if _lib is not None:
        return _lib
    path = build()
    lib = ctypes.CDLL(str(path))
    lib.mt_server_new.restype = ctypes.c_void_p
    lib.mt_server_new.argtypes = [ctypes.c_int]
    lib.mt_server_free.argtypes = [ctypes.c_void_p]
    lib.mt_send.restype = ctypes.c_int
    lib.mt_send.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64,
    ]
    lib.mt_receive.restype = ctypes.c_int
    lib.mt_receive.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_int,
    ]
    lib.mt_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
    _lib = lib
    return lib


class ServerHandle:
    def __init__(self, lib, port: int):
        self._lib = lib
        self._handle = lib.mt_server_new(port)
        if not self._handle:
            raise NetworkingError(f"cannot bind TCP server on port {port}")

    def receive(self, key: str, timeout_ms: int) -> bytes:
        out = ctypes.POINTER(ctypes.c_uint8)()
        out_len = ctypes.c_uint64()
        rc = self._lib.mt_receive(
            self._handle, key.encode(), ctypes.byref(out),
            ctypes.byref(out_len), timeout_ms,
        )
        if rc != 0:
            raise ReceiveTimeoutError(
                f"TCP receive timed out ({timeout_ms} ms) for {key!r}"
            )
        try:
            return ctypes.string_at(out, out_len.value)
        finally:
            self._lib.mt_free(out)

    def close(self):
        if self._handle:
            self._lib.mt_server_free(self._handle)
            self._handle = None


def send(lib, host: str, port: int, key: str, payload: bytes):
    buf = (ctypes.c_uint8 * len(payload)).from_buffer_copy(payload)
    rc = lib.mt_send(host.encode(), port, key.encode(), buf, len(payload))
    if rc != 0:
        raise NetworkingError(
            f"TCP send to {host}:{port} failed (rc={rc})"
        )
