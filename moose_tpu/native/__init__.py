"""Native (C++) runtime components, loaded via ctypes.

The reference's native layer is Rust; this framework's is C++ (compiled
on demand with the system toolchain — the numeric path is JAX/XLA, the
native layer carries transport/runtime plumbing)."""
