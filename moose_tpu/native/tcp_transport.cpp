// Native TCP value transport (reference: moose/src/networking/tcpstream.rs,
// which is Rust; this framework's native layer is C++).
//
// Length-prefixed frames over persistent TCP connections:
//
//   frame := u64_le total_len | u32_le key_len | key bytes | value bytes
//
// Each server handle owns an accept loop plus per-connection reader
// threads feeding a rendezvous-keyed store (mutex + condition variable);
// receives may be posted before the matching frame arrives, matching the
// reference's AsyncCell discipline.  Exposed as a C ABI for ctypes.

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Store {
  std::mutex mu;
  std::condition_variable cv;
  std::map<std::string, std::vector<uint8_t>> values;

  void put(std::string key, std::vector<uint8_t> value) {
    {
      std::lock_guard<std::mutex> lock(mu);
      values[std::move(key)] = std::move(value);
    }
    cv.notify_all();
  }

  // returns false on timeout
  bool take(const std::string& key, std::vector<uint8_t>* out,
            int timeout_ms) {
    std::unique_lock<std::mutex> lock(mu);
    bool ok = cv.wait_for(lock, std::chrono::milliseconds(timeout_ms),
                          [&] { return values.count(key) > 0; });
    if (!ok) return false;
    auto it = values.find(key);
    *out = std::move(it->second);
    values.erase(it);
    return true;
  }
};

bool read_exact(int fd, uint8_t* buf, size_t len) {
  size_t got = 0;
  while (got < len) {
    ssize_t n = ::read(fd, buf + got, len - got);
    if (n <= 0) return false;
    got += static_cast<size_t>(n);
  }
  return true;
}

bool write_all(int fd, const uint8_t* buf, size_t len) {
  size_t sent = 0;
  while (sent < len) {
    ssize_t n = ::write(fd, buf + sent, len - sent);
    if (n <= 0) return false;
    sent += static_cast<size_t>(n);
  }
  return true;
}

struct Server {
  int listen_fd = -1;
  Store store;
  std::thread accept_thread;
  std::vector<std::thread> readers;
  std::vector<int> reader_fds;
  std::mutex readers_mu;
  bool stopping = false;

  void reader_loop(int fd) {
    for (;;) {
      uint8_t hdr[12];
      if (!read_exact(fd, hdr, sizeof(hdr))) break;
      uint64_t total;
      uint32_t key_len;
      std::memcpy(&total, hdr, 8);
      std::memcpy(&key_len, hdr + 8, 4);
      if (key_len + 4 > total || total > (1ull << 33)) break;  // 8 GiB cap
      std::vector<uint8_t> key(key_len);
      if (!read_exact(fd, key.data(), key_len)) break;
      size_t value_len = static_cast<size_t>(total) - 4 - key_len;
      std::vector<uint8_t> value(value_len);
      if (!read_exact(fd, value.data(), value_len)) break;
      store.put(std::string(key.begin(), key.end()), std::move(value));
    }
    ::close(fd);
  }

  void accept_loop() {
    for (;;) {
      int fd = ::accept(listen_fd, nullptr, nullptr);
      if (fd < 0) break;  // listener closed -> shutdown
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      std::lock_guard<std::mutex> lock(readers_mu);
      reader_fds.push_back(fd);
      readers.emplace_back([this, fd] { reader_loop(fd); });
    }
  }
};

// Persistent outbound connections, keyed "host:port" (process-global,
// like the reference's lazily-created channels, networking/grpc.rs:62-78).
std::mutex g_conn_mu;
std::map<std::string, int> g_conns;

int connect_to(const std::string& host, int port) {
  struct addrinfo hints;
  std::memset(&hints, 0, sizeof(hints));
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  std::string port_s = std::to_string(port);
  if (::getaddrinfo(host.c_str(), port_s.c_str(), &hints, &res) != 0)
    return -1;
  int fd = -1;
  for (auto* p = res; p != nullptr; p = p->ai_next) {
    fd = ::socket(p->ai_family, p->ai_socktype, p->ai_protocol);
    if (fd < 0) continue;
    if (::connect(fd, p->ai_addr, p->ai_addrlen) == 0) break;
    ::close(fd);
    fd = -1;
  }
  ::freeaddrinfo(res);
  if (fd >= 0) {
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  }
  return fd;
}

}  // namespace

extern "C" {

void* mt_server_new(int port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  struct sockaddr_in addr;
  std::memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(fd, reinterpret_cast<struct sockaddr*>(&addr), sizeof(addr)) <
          0 ||
      ::listen(fd, 64) < 0) {
    ::close(fd);
    return nullptr;
  }
  auto* srv = new Server();
  srv->listen_fd = fd;
  srv->accept_thread = std::thread([srv] { srv->accept_loop(); });
  return srv;
}

void mt_server_free(void* handle) {
  auto* srv = static_cast<Server*>(handle);
  if (srv == nullptr) return;
  ::shutdown(srv->listen_fd, SHUT_RDWR);
  ::close(srv->listen_fd);
  if (srv->accept_thread.joinable()) srv->accept_thread.join();
  // force every reader's blocking read() to fail, then JOIN them before
  // deleting: a detached reader could touch srv->store after the free
  {
    std::lock_guard<std::mutex> lock(srv->readers_mu);
    for (int fd : srv->reader_fds) ::shutdown(fd, SHUT_RDWR);
  }
  for (auto& t : srv->readers) {
    if (t.joinable()) t.join();
  }
  srv->readers.clear();
  delete srv;
}

int mt_send(const char* host, int port, const char* key,
            const uint8_t* data, uint64_t len) {
  std::string conn_key = std::string(host) + ":" + std::to_string(port);
  std::lock_guard<std::mutex> lock(g_conn_mu);
  auto it = g_conns.find(conn_key);
  int fd = (it != g_conns.end()) ? it->second : -1;
  if (fd < 0) {
    fd = connect_to(host, port);
    if (fd < 0) return -1;
    g_conns[conn_key] = fd;
  }
  uint32_t key_len = static_cast<uint32_t>(std::strlen(key));
  uint64_t total = 4ull + key_len + len;
  std::vector<uint8_t> frame(12 + key_len);
  std::memcpy(frame.data(), &total, 8);
  std::memcpy(frame.data() + 8, &key_len, 4);
  std::memcpy(frame.data() + 12, key, key_len);
  if (!write_all(fd, frame.data(), frame.size()) ||
      !write_all(fd, data, len)) {
    ::close(fd);
    g_conns.erase(conn_key);
    return -2;
  }
  return 0;
}

// returns 0 on success, -1 on timeout; caller must mt_free(*out)
int mt_receive(void* handle, const char* key, uint8_t** out,
               uint64_t* out_len, int timeout_ms) {
  auto* srv = static_cast<Server*>(handle);
  std::vector<uint8_t> value;
  if (!srv->store.take(key, &value, timeout_ms)) return -1;
  *out = static_cast<uint8_t*>(std::malloc(value.size()));
  std::memcpy(*out, value.data(), value.size());
  *out_len = value.size();
  return 0;
}

void mt_free(uint8_t* buf) { std::free(buf); }

}  // extern "C"
