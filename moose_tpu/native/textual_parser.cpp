// Parallel chunked parser for the textual computation format.
//
// The reference parses .moose files with a nom grammar sped up by
// rayon-parallel chunking (textual/parsing.rs:83); this is the TPU-native
// build's C++ equivalent: worker threads each parse a contiguous range of
// lines into a msgpack document which Python decodes at C speed and
// assembles into Operation objects (moose_tpu/textual.py owns the
// grammar's long tail — any attribute value this parser does not fully
// understand is forwarded verbatim as a {"__raw__": "..."} map for the
// Python fallback, so the two parsers always agree).
//
// Per line:  name = Kind{attrs}: (T, ...) -> T (inputs) @Placement[...](owners)
//
// msgpack output: array of {"l": source-line-no, "r": record} where
// record is
//   {"n": name, "k": kind, "a": {key: value|{"__raw__": src}},
//    "it": [type-src, ...], "rt": type-src, "in": [input, ...],
//    "p": placement-src}
// or, for lines that fail structural parsing, {"__line__": src}
// (Python reparses those), keeping this layer purely an accelerator.

#include <atomic>
#include <cctype>
#include <cstdint>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

// ---- minimal msgpack writer ----------------------------------------------

struct Pack {
  std::string buf;
  void u8(uint8_t v) { buf.push_back(static_cast<char>(v)); }
  void big32(uint32_t v) {
    u8(v >> 24); u8(v >> 16); u8(v >> 8); u8(v);
  }
  void array_header(uint32_t n) {
    if (n < 16) u8(0x90 | n);
    else { u8(0xdd); big32(n); }
  }
  void map_header(uint32_t n) {
    if (n < 16) u8(0x80 | n);
    else { u8(0xdf); big32(n); }
  }
  void str(const char* s, size_t len) {
    if (len < 32) u8(0xa0 | static_cast<uint8_t>(len));
    else { u8(0xdb); big32(static_cast<uint32_t>(len)); }
    buf.append(s, len);
  }
  void str(const std::string& s) { str(s.data(), s.size()); }
  void boolean(bool v) { u8(v ? 0xc3 : 0xc2); }
  void nil() { u8(0xc0); }
  void int64(long long v) {
    u8(0xd3);
    for (int i = 7; i >= 0; --i) u8(static_cast<uint8_t>(v >> (8 * i)));
  }
  void f64(double v) {
    u8(0xcb);
    uint64_t bits;
    std::memcpy(&bits, &v, 8);
    for (int i = 7; i >= 0; --i) u8(static_cast<uint8_t>(bits >> (8 * i)));
  }
};

// ---- cursor over one line -------------------------------------------------

struct Cur {
  const char* s;
  size_t n;
  size_t i = 0;
  bool ok = true;

  void ws() { while (i < n && (s[i] == ' ' || s[i] == '\t')) ++i; }
  char peek() { ws(); return i < n ? s[i] : '\0'; }
  bool lit(const char* tok) {
    ws();
    size_t len = std::strlen(tok);
    if (i + len <= n && std::memcmp(s + i, tok, len) == 0) {
      i += len;
      return true;
    }
    ok = false;
    return false;
  }
  static bool ident_start(char c) {
    return std::isalpha(static_cast<unsigned char>(c)) || c == '_';
  }
  static bool ident_char(char c) {
    return std::isalnum(static_cast<unsigned char>(c)) || c == '_' ||
           c == '-' || c == '.';
  }
  std::string ident() {
    ws();
    if (i >= n || !ident_start(s[i])) { ok = false; return ""; }
    size_t start = i;
    while (i < n && ident_char(s[i])) ++i;
    return std::string(s + start, i - start);
  }
  // consume a balanced group assuming the opener is next; returns inner
  std::string balanced(char open, char close) {
    if (!lit(std::string(1, open).c_str())) return "";
    int depth = 1;
    size_t start = i;
    while (i < n) {
      char c = s[i];
      if (c == '"') {
        ++i;
        while (i < n) {
          if (s[i] == '\\') { i += 2; continue; }
          if (s[i] == '"') break;
          ++i;
        }
      } else if (c == open) {
        ++depth;
      } else if (c == close) {
        if (--depth == 0) {
          std::string inner(s + start, i - start);
          ++i;
          return inner;
        }
      }
      ++i;
    }
    ok = false;
    return "";
  }
};

void split_top_level(const std::string& src, char sep,
                     std::vector<std::string>* out) {
  int depth = 0;
  size_t start = 0;
  for (size_t i = 0; i < src.size(); ++i) {
    char c = src[i];
    if (c == '"') {
      ++i;
      while (i < src.size()) {
        if (src[i] == '\\') { i += 2; continue; }
        if (src[i] == '"') break;
        ++i;
      }
    } else if (c == '(' || c == '[' || c == '{') {
      ++depth;
    } else if (c == ')' || c == ']' || c == '}') {
      --depth;
    } else if (c == sep && depth == 0) {
      out->push_back(src.substr(start, i - start));
      start = i + 1;
    }
  }
  if (start < src.size() || !src.empty()) {
    out->push_back(src.substr(start));
  }
}

std::string trim(const std::string& v) {
  size_t a = 0, b = v.size();
  while (a < b && std::isspace(static_cast<unsigned char>(v[a]))) ++a;
  while (b > a && std::isspace(static_cast<unsigned char>(v[b - 1]))) --b;
  return v.substr(a, b - a);
}

// scalar attr values this parser understands natively; anything else is
// forwarded as {"__raw__": src} for the Python grammar
void pack_attr_value(Pack* p, const std::string& raw) {
  std::string v = trim(raw);
  if (v == "true") { p->boolean(true); return; }
  if (v == "false") { p->boolean(false); return; }
  if (v == "null") { p->nil(); return; }
  if (!v.empty() && v.front() == '"' && v.back() == '"' && v.size() >= 2 &&
      v.find('\\') == std::string::npos) {
    p->str(v.data() + 1, v.size() - 2);
    return;
  }
  if (!v.empty() && v.front() == '[' && v.back() == ']') {
    // list of scalars -> recurse; bail to raw on nested complexity
    std::string inner = v.substr(1, v.size() - 2);
    std::vector<std::string> parts;
    if (!trim(inner).empty()) split_top_level(inner, ',', &parts);
    p->array_header(static_cast<uint32_t>(parts.size()));
    for (const auto& part : parts) pack_attr_value(p, part);
    return;
  }
  // 128-bit sync/rendezvous keys print as bare 32-char hex
  // (computation.rs RendezvousKey/SyncKey Display); forward raw so the
  // Python grammar decodes them key-aware as bytes — a digit-only key
  // would otherwise parse as a decimal integer below
  if (v.size() == 32) {
    bool all_hex = true;
    for (char ch : v) {
      if (!std::isxdigit(static_cast<unsigned char>(ch))) {
        all_hex = false;
        break;
      }
    }
    if (all_hex) {
      p->map_header(1);
      p->str("__raw__", 7);
      p->str(v);
      return;
    }
  }
  // integer / float (decimal only: 0x... payloads are bytes in the
  // grammar, and strtod would otherwise read them as hex floats)
  bool numeric_lead =
      !v.empty() &&
      (std::isdigit(static_cast<unsigned char>(v[0])) || v[0] == '-' ||
       v[0] == '+' || v[0] == '.') &&
      !(v.size() >= 2 && v[0] == '0' && (v[1] == 'x' || v[1] == 'X'));
  if (numeric_lead) {
    char* end = nullptr;
    errno = 0;
    long long iv = std::strtoll(v.c_str(), &end, 10);
    bool int_syntax = end && *end == '\0' && end != v.c_str();
    if (int_syntax) {
      if (errno == 0) {
        p->int64(iv);
        return;
      }
      // integer too wide for int64 (ring scalars): forward raw so
      // Python keeps arbitrary precision — never degrade to float
    } else {
      errno = 0;
      double dv = std::strtod(v.c_str(), &end);
      if (errno == 0 && end && *end == '\0' && end != v.c_str()) {
        p->f64(dv);
        return;
      }
    }
  }
  p->map_header(1);
  p->str("__raw__", 7);
  p->str(v);
}

bool parse_line(const std::string& line, Pack* p) {
  Cur c{line.data(), line.size()};
  std::string name = c.ident();
  if (!c.ok || !c.lit("=")) return false;
  std::string kind = c.ident();
  if (!c.ok) return false;

  std::vector<std::pair<std::string, std::string>> attrs;
  if (c.peek() == '{') {
    std::string inner = c.balanced('{', '}');
    if (!c.ok) return false;
    std::vector<std::string> parts;
    if (!trim(inner).empty()) split_top_level(inner, ',', &parts);
    for (const auto& part : parts) {
      size_t eq = std::string::npos;
      int depth = 0;
      for (size_t j = 0; j < part.size(); ++j) {
        char ch = part[j];
        if (ch == '(' || ch == '[' || ch == '{') ++depth;
        else if (ch == ')' || ch == ']' || ch == '}') --depth;
        else if (ch == '=' && depth == 0) { eq = j; break; }
      }
      if (eq == std::string::npos) return false;
      attrs.emplace_back(trim(part.substr(0, eq)),
                         trim(part.substr(eq + 1)));
    }
  }
  if (!c.lit(":")) return false;
  std::string sig_in = c.balanced('(', ')');
  if (!c.ok || !c.lit("->")) return false;
  // return type: everything up to the inputs '(' at depth 0
  c.ws();
  size_t rt_start = c.i;
  int depth = 0;
  while (c.i < c.n) {
    char ch = c.s[c.i];
    if (ch == '<' || ch == '(') {
      if (ch == '(' && depth == 0) break;
      ++depth;
    } else if (ch == '>' || ch == ')') {
      --depth;
    } else if (ch == ' ' && depth == 0) {
      break;
    }
    ++c.i;
  }
  std::string ret_ty = trim(std::string(c.s + rt_start, c.i - rt_start));
  if (ret_ty.empty()) return false;
  std::string inputs_src = c.balanced('(', ')');
  if (!c.ok) return false;
  c.ws();
  std::string placement = trim(line.substr(c.i));
  if (placement.empty() || placement[0] != '@') return false;

  std::vector<std::string> in_tys;
  if (!trim(sig_in).empty()) split_top_level(sig_in, ',', &in_tys);
  std::vector<std::string> inputs;
  if (!trim(inputs_src).empty()) split_top_level(inputs_src, ',', &inputs);

  p->map_header(7);
  p->str("n", 1); p->str(name);
  p->str("k", 1); p->str(kind);
  p->str("a", 1);
  p->map_header(static_cast<uint32_t>(attrs.size()));
  for (const auto& kv : attrs) {
    p->str(kv.first);
    pack_attr_value(p, kv.second);
  }
  p->str("it", 2);
  p->array_header(static_cast<uint32_t>(in_tys.size()));
  for (const auto& t : in_tys) p->str(trim(t));
  p->str("rt", 2); p->str(ret_ty);
  p->str("in", 2);
  p->array_header(static_cast<uint32_t>(inputs.size()));
  for (const auto& v : inputs) p->str(trim(v));
  p->str("p", 1); p->str(placement);
  return true;
}

}  // namespace

extern "C" {

// Parses `text` (len bytes) into a msgpack array of per-line maps using
// `threads` workers (0 = hardware concurrency).  Returns a malloc'd
// buffer (caller frees with mt_parse_free) and writes its size to
// out_len.  Never fails: unparseable lines become {"__line__": src}.
char* mt_parse_textual(const char* text, uint64_t len, int threads,
                       uint64_t* out_len) {
  // split into lines (skip blanks and comments, like the Python parser),
  // keeping 1-based source line numbers for error messages
  struct Line { const char* p; size_t n; uint32_t no; };
  std::vector<Line> lines;
  size_t start = 0;
  uint32_t lineno = 1;
  for (size_t i = 0; i <= len; ++i) {
    if (i == len || text[i] == '\n') {
      size_t a = start, b = i;
      while (a < b && (text[a] == ' ' || text[a] == '\t' ||
                       text[a] == '\r'))
        ++a;
      while (b > a && (text[b - 1] == ' ' || text[b - 1] == '\t' ||
                       text[b - 1] == '\r'))
        --b;
      if (b > a && text[a] != '#' &&
          !(b - a >= 2 && text[a] == '/' && text[a + 1] == '/')) {
        lines.push_back({text + a, b - a, lineno});
      }
      start = i + 1;
      ++lineno;
    }
  }

  int n_threads = threads > 0
      ? threads
      : static_cast<int>(std::thread::hardware_concurrency());
  if (n_threads < 1) n_threads = 1;
  if (static_cast<size_t>(n_threads) > lines.size() && !lines.empty()) {
    n_threads = static_cast<int>(lines.size());
  }

  std::vector<Pack> packs(std::max(n_threads, 1));
  std::vector<std::thread> workers;
  size_t per = lines.empty() ? 0 : (lines.size() + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    workers.emplace_back([&, t]() {
      Pack& p = packs[t];
      size_t lo = t * per;
      size_t hi = std::min(lines.size(), lo + per);
      for (size_t j = lo; j < hi; ++j) {
        std::string line(lines[j].p, lines[j].n);
        p.map_header(2);
        p.str("l", 1);
        p.int64(lines[j].no);
        p.str("r", 1);
        Pack attempt;
        if (parse_line(line, &attempt)) {
          p.buf += attempt.buf;
        } else {
          p.map_header(1);
          p.str("__line__", 8);
          p.str(line);
        }
      }
    });
  }
  for (auto& w : workers) w.join();

  Pack head;
  head.array_header(static_cast<uint32_t>(lines.size()));
  size_t total = head.buf.size();
  for (auto& p : packs) total += p.buf.size();
  char* out = static_cast<char*>(std::malloc(total));
  if (out == nullptr) { *out_len = 0; return nullptr; }
  size_t off = 0;
  std::memcpy(out + off, head.buf.data(), head.buf.size());
  off += head.buf.size();
  for (auto& p : packs) {
    std::memcpy(out + off, p.buf.data(), p.buf.size());
    off += p.buf.size();
  }
  *out_len = total;
  return out;
}

void mt_parse_free(char* buf) { std::free(buf); }

}  // extern "C"
