"""Pallas TPU kernels for the hot ring64/ring128 stacked primitives.

The known axon-TPU miscompile (DEVELOP.md "Known issue") lives in XLA's
whole-program passes over LARGE fusions of emulated 64/128-bit integer
math — the fixed(24,40) protocol sigmoid's b2a/polynomial region is the
sharpest reproducer, and it forced the PR-2 validated-jit ladder down to
per-op pinning on the single hottest path in the system (BENCH_r05:
7.1 inf/s user path vs a 1,265 inf/s handwritten ceiling).  These
kernels sidestep that class of bug structurally: each hot primitive is
ONE opaque Mosaic program whose internals XLA cannot re-fuse, so the
128-bit stacked world compiles as a whole-graph jit with zero pinned
ops.

Design (same scaffold as ``dialects/pallas_prf.py``):

- Mosaic has no 64-bit vector lanes, so every kernel operates on
  **uint32 word planes** — a ring64 value is 2 planes, ring128 is 4;
  the u64<->u32 split/recombine happens OUTSIDE the kernel as one fused
  XLA elementwise pass.  Inside, values are lists of 16-bit limbs held
  in u32 lanes (products of 16-bit limbs are exact in u32; column sums
  stay far below 2^32), with explicit carry normalization.
- Real Mosaic kernels on TPU; ``interpret=True`` everywhere else, so
  tier-1 CI exercises the IDENTICAL kernel code on CPU.
- Selection rides the ``MOOSE_TPU_PALLAS`` knob (``1`` force on, ``0``
  force off, unset = auto: on iff the backend is TPU) with
  **per-primitive XLA fallback**: each (kernel, width) is self-checked
  bit-exactly against its lax twin on first use — the same bit-exact
  discipline as the PR-2 self-check ladder, applied at kernel
  granularity — and a divergence or error falls that primitive back to
  the XLA path for the rest of the process
  (``moose_tpu_pallas_fallback_total{kernel=...,reason=...}``).
- Kernel inventory: ``ring_mul`` (elementwise two-limb multiply),
  ``cross_terms_mul`` (the fused v_i = x_i*(y_i+y_{i+1}) + x_{i+1}*y_i
  of secure mul, ``parallel/spmd.py:_cross_terms``),
  ``trunc_combine`` (the full elementwise tail of probabilistic
  truncation after its five PRF draws, ``spmd._trunc_pr_adt``),
  ``bit_decompose``/``msb`` (plain-bit extraction + carry-save + the
  Kogge-Stone adder inner loop of ``parallel/spmd_math.py``, consuming
  pre-drawn AND banks), ``horner`` (the fused fixed-point polynomial
  ladder of ``spmd_math.polynomial_eval`` — the fx_sigmoid / exp
  region where the miscompile actually bites), and
  ``dot_cross_terms`` (party-batched 8-bit-limb matmul cross terms).

Honest status: the elementwise/bit/polynomial kernels are the point —
they replace exactly the emulated-integer fusion region XLA miscompiles.
The dot kernel is correctness-proven but OFF by default
(``MOOSE_TPU_PALLAS_DOT=1`` opts in): component ring dots already jit
exactly on TPU (DEVELOP.md localization) through the limb_int8 MXU
path, which beats the kernel's padded-tile layout on the small-n
predictor shapes; it ships as the fabric for future fused dot+truncate
work, like the threefry kernel before it.

PRF-draw discipline: kernels never draw randomness.  Callers pre-draw
the exact sequence the lax path would (same session-counter order), so
a computation is bit-identical with kernels on, off, or mixed — pinned
by ``tests/test_ring128_kernels.py``.
"""

from __future__ import annotations

import functools
import os
import threading
from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

U8 = jnp.uint8
U32 = jnp.uint32
U64 = jnp.uint64
MASK16 = np.uint32(0xFFFF)
MASK32 = np.uint64(0xFFFFFFFF)

# elementwise block: multiples of the int32 VPU tile (8, 128)
_BLOCK_ROWS = 8
_BLOCK_COLS = 128
_BLOCK = _BLOCK_ROWS * _BLOCK_COLS


# ---------------------------------------------------------------------------
# Selection knob + per-primitive fallback state + first-use self-check
# ---------------------------------------------------------------------------

_OVERRIDE: Optional[bool] = None
_STATE: Dict[Tuple[str, int], str] = {}  # (kernel, width) -> "ok"/"fallback:.."
_STATE_LOCK = threading.RLock()
_KEY_LOCKS: Dict[Tuple[str, int], "threading.Lock"] = {}
# set while a first-use self-check runs on this thread: nested
# dispatches return False, so a check's lax twin is PURE lax (and the
# non-reentrant-lock deadlock a twin's dispatch would cause is moot)
_IN_CHECK = threading.local()


def set_enabled(value: Optional[bool]) -> None:
    """Programmatic override of MOOSE_TPU_PALLAS: True/False force,
    None restores the env/auto default (tests, bench A/B)."""
    global _OVERRIDE
    _OVERRIDE = value


def enabled() -> bool:
    """Whether Pallas kernels are selected: programmatic override wins,
    then MOOSE_TPU_PALLAS (1/0), else auto — on iff the backend is TPU
    (interpret-mode kernels are correctness tools, not a CPU speedup)."""
    if _OVERRIDE is not None:
        return _OVERRIDE
    env = os.environ.get("MOOSE_TPU_PALLAS")
    if env is not None and env != "":
        if env not in ("0", "1"):
            from ..errors import ConfigurationError

            raise ConfigurationError(
                f"MOOSE_TPU_PALLAS must be '0' or '1', got {env!r}"
            )
        return env == "1"
    return jax.default_backend() == "tpu"


def dot_enabled() -> bool:
    """The env-only view of the dot opt-in (the absolute knob:
    ``MOOSE_TPU_PALLAS_DOT=1`` forces the kernel wherever the family is
    on).  The dispatch gate itself is shape-aware: with the knob unset
    it asks the autotuner's measured per-shape-class policy
    (``compilation.autotune.dot_kernel_wanted``) — predictor-small
    shapes keep limb_int8, measured-faster MXU shapes get the kernel."""
    return enabled() and os.environ.get("MOOSE_TPU_PALLAS_DOT") == "1"


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def reset_state() -> None:
    """Forget self-check verdicts and fallbacks (tests)."""
    with _STATE_LOCK:
        _STATE.clear()


def report() -> dict:
    """Bench/debug surface: the knob verdict plus per-(kernel, width)
    state ("ok" after a clean first-use check, "fallback:<reason>")."""
    return {
        "enabled": enabled(),
        "kernels": {f"{k}/{w}": v for (k, w), v in sorted(_STATE.items())},
    }


def _count_dispatch(kernel: str) -> None:
    from .. import metrics

    metrics.counter(
        "moose_tpu_pallas_dispatch_total",
        "trace-time routings of a primitive into its Pallas kernel",
        labels=("kernel",),
    ).inc(kernel=kernel)


def _count_fallback(kernel: str, reason: str) -> None:
    from .. import metrics

    metrics.counter(
        "moose_tpu_pallas_fallback_total",
        "Pallas primitives demoted to the XLA path",
        labels=("kernel", "reason"),
    ).inc(kernel=kernel, reason=reason)


def record_fallback(kernel: str, width: int, reason: str,
                    exc: Optional[BaseException] = None) -> None:
    """Pin a (kernel, width) to the XLA path for the process (divergence
    or runtime error), with the metric + one log line."""
    from ..logger import get_logger

    with _STATE_LOCK:
        _STATE[(kernel, width)] = f"fallback:{reason}"
    _count_fallback(kernel, reason)
    get_logger().warning(
        "pallas kernel %s/ring%d fell back to XLA (%s)%s",
        kernel, width, reason, f": {exc}" if exc is not None else "",
    )


def dispatch(kernel: str, width: int, shape=None) -> bool:
    """True when ``kernel`` should run at ``width``: knob on, width
    supported, and the first-use bit-exactness self-check against the
    lax twin passed.  A failed check records a permanent per-process
    fallback; a pass is cached.  The check runs EAGERLY on canned
    shapes (it needs concrete values to compare), so calling this from
    inside a jit trace is safe — the verdict is a Python bool.

    ``shape`` (``(m, k, n)``, dot only) routes the decision through the
    autotuner's measured per-shape-class policy when the absolute knob
    ``MOOSE_TPU_PALLAS_DOT`` is unset: classes where the A/B micro
    measured the MXU kernel faster than limb_int8 XLA turn it on; the
    rest — and every call without a shape — keep the XLA path."""
    if width not in (64, 128):
        return False
    if getattr(_IN_CHECK, "active", False):
        return False  # a self-check's lax twin must stay pure lax
    if kernel == "dot_cross_terms":
        if not enabled():
            return False
        env = os.environ.get("MOOSE_TPU_PALLAS_DOT")
        if env == "0":
            return False
        if env != "1":
            from ..compilation import autotune

            if not autotune.dot_kernel_wanted(width, shape):
                return False
    elif not enabled():
        return False
    key = (kernel, width)
    state = _STATE.get(key)
    if state is None:
        # per-key lock: first uses of DIFFERENT (kernel, width) pairs
        # check concurrently; only the verdict publishes under the
        # global lock (a module-wide lock would serialize every
        # thread's first session behind seconds of sequential checks)
        with _STATE_LOCK:
            state = _STATE.get(key)
            key_lock = _KEY_LOCKS.setdefault(key, threading.Lock())
        if state is None:
            with key_lock:
                state = _STATE.get(key)
                if state is None:
                    state = _run_first_use_check(kernel, width)
                    with _STATE_LOCK:
                        _STATE[key] = state
    if state == "ok":
        _count_dispatch(kernel)
        from .. import profiling

        # trace-time marker on the profile timeline: which primitives
        # actually routed into their Pallas kernels during this capture
        profiling.record_instant(
            "pallas_dispatch", kernel=kernel, width=width,
        )
        return True
    return False


def _run_first_use_check(kernel: str, width: int) -> str:
    # Dispatch legitimately happens at TRACE time (protocol code under
    # jax.jit — e.g. a plan the registry restored straight to "jit"
    # mode).  The check needs CONCRETE values to compare, so it runs on
    # a fresh thread: trace contexts are thread-local, so the worker
    # executes eagerly no matter what the calling thread is tracing —
    # without this, the check's jitted comparisons would stage into the
    # outer trace and mis-pin the kernel to fallback:error.
    box: Dict[str, BaseException] = {}

    def worker():
        _IN_CHECK.active = True  # thread-local: set on THIS thread
        try:
            _CHECKS[kernel](width)
        except BaseException as e:  # noqa: BLE001 — classified below
            box["exc"] = e
        finally:
            _IN_CHECK.active = False

    from .. import profiling

    with profiling.phase("pallas_selfcheck", kernel=kernel, width=width):
        t = threading.Thread(
            target=worker, name=f"pallas-check-{kernel}-{width}"
        )
        t.start()
        t.join()
    try:
        exc = box.get("exc")
        if exc is not None:
            raise exc
        return "ok"
    except AssertionError as e:
        _count_fallback(kernel, "diverged")
        from ..logger import get_logger

        get_logger().warning(
            "pallas kernel %s/ring%d DIVERGED from its lax twin on the "
            "first-use self-check; using the XLA path (%s)",
            kernel, width, e,
        )
        return "fallback:diverged"
    except Exception as e:  # noqa: BLE001 — the kernel is an
        # optimization; any failure keeps the XLA path
        _count_fallback(kernel, "error")
        from ..logger import get_logger

        get_logger().warning(
            "pallas kernel %s/ring%d failed its first-use self-check "
            "run (%s: %s); using the XLA path",
            kernel, width, type(e).__name__, e,
        )
        return "fallback:error"


# ---------------------------------------------------------------------------
# u64 <-> u32-plane <-> 16-bit-limb plumbing
# ---------------------------------------------------------------------------


def _n_planes(width: int) -> int:
    return width // 32


def _to_planes(lo, hi) -> jax.Array:
    """(lo, hi) u64 arrays -> (L, n) u32 word planes, little-endian
    (one split implementation: :func:`_planes_keep` with no kept
    leading dims)."""
    return _planes_keep(lo, hi, 0)


def _from_planes(planes, shape, width: int):
    """(L, n) u32 planes -> (lo, hi) u64 arrays of ``shape``."""
    lo = planes[0].astype(U64) | (planes[1].astype(U64) << np.uint64(32))
    lo = lo.reshape(shape)
    if width == 64:
        return lo, None
    hi = planes[2].astype(U64) | (planes[3].astype(U64) << np.uint64(32))
    return lo, hi.reshape(shape)


def _tile(planes, rows: int = _BLOCK_ROWS) -> jax.Array:
    """(..., n) -> (..., R, 128) with R a multiple of ``rows``
    (zero-padded).  u32 kernels block 8 rows (the int32 VPU tile); the
    uint8 bit kernels block 32 (the int8 tile)."""
    n = planes.shape[-1]
    block = rows * _BLOCK_COLS
    pad = (-n) % block
    if pad:
        planes = jnp.pad(
            planes, [(0, 0)] * (planes.ndim - 1) + [(0, pad)]
        )
    return planes.reshape(
        planes.shape[:-1] + ((n + pad) // _BLOCK_COLS, _BLOCK_COLS)
    )


def _untile(tiles, n: int) -> jax.Array:
    return tiles.reshape(tiles.shape[:-2] + (-1,))[..., :n]


# -- in-kernel 16-bit-limb arithmetic (u32 lanes, explicit carries) ---------
# A ring value inside a kernel is a list of width//16 u32 arrays, each
# normalized to < 2^16.  All helpers are plain traced jnp, so they work
# identically compiled by Mosaic and in interpret mode.


def _ksplit(planes):
    """u32 word planes -> 16-bit limb list (little-endian)."""
    out = []
    for p in planes:
        out.append(p & MASK16)
        out.append(p >> np.uint32(16))
    return out


def _kjoin(limbs):
    """Normalized 16-bit limb list -> u32 word planes."""
    return [
        limbs[2 * i] | (limbs[2 * i + 1] << np.uint32(16))
        for i in range(len(limbs) // 2)
    ]


def _knorm(limbs):
    out = []
    carry = None
    for limb in limbs:
        t = limb if carry is None else limb + carry
        out.append(t & MASK16)
        carry = t >> np.uint32(16)
    return out


def _kadd(a, b):
    return _knorm([x + y for x, y in zip(a, b)])


def _kneg(a):
    comp = [MASK16 - x for x in a]
    comp[0] = comp[0] + np.uint32(1)
    return _knorm(comp)


def _ksub(a, b):
    return _kadd(a, _kneg(b))


def _kmul(a, b):
    """Schoolbook product mod 2^(16*len(a)): 16-bit limb products are
    exact in u32; columns accumulate split lo/hi halves (each column
    sums <= 2*len 16-bit terms, far below 2^32) then normalize."""
    nl = len(a)
    zero = jnp.zeros_like(a[0])
    cols = [zero] * (nl + 1)
    for i in range(nl):
        for j in range(nl - i):
            p = a[i] * b[j]
            cols[i + j] = cols[i + j] + (p & MASK16)
            cols[i + j + 1] = cols[i + j + 1] + (p >> np.uint32(16))
    return _knorm(cols[:nl])


def _kshl(a, amount: int):
    nl = len(a)
    ls, bs = amount // 16, amount % 16
    zero = jnp.zeros_like(a[0])
    out = []
    for i in range(nl):
        if i - ls < 0:
            out.append(zero)
            continue
        v = a[i - ls] << np.uint32(bs)
        if i - ls - 1 >= 0 and bs:
            v = v | (a[i - ls - 1] >> np.uint32(16 - bs))
        out.append(v & MASK16)
    return out


def _kshr(a, amount: int):
    nl = len(a)
    ls, bs = amount // 16, amount % 16
    zero = jnp.zeros_like(a[0])
    out = []
    for i in range(nl):
        if i + ls >= nl:
            out.append(zero)
            continue
        v = a[i + ls] >> np.uint32(bs)
        if i + ls + 1 < nl and bs:
            v = v | (a[i + ls + 1] << np.uint32(16 - bs))
        out.append(v & MASK16)
    return out


def _kconst(value: int, nl: int):
    """Static ring constant as broadcastable u32 scalars."""
    return [
        np.uint32((int(value) >> (16 * i)) & 0xFFFF) for i in range(nl)
    ]


def _ktrunc(a0, a1, r, mr, mrt, mrm, z0, width: int, amount: int):
    """The elementwise tail of probabilistic truncation given its five
    PRF draws — limb-for-limb the math of ``spmd._trunc_pr_adt`` after
    the draws.  Returns the (z0, z1, y1) replicated stack."""
    nl = width // 16
    k = width - 1
    r_msb = _kshr(r, width - 1)
    r_top = _kshr(_kshl(r, 1), amount + 1)
    r1 = _ksub(r, mr)
    rt1 = _ksub(r_top, mrt)
    rm1 = _ksub(r_msb, mrm)

    a0p = _kadd(a0, _kconst(1 << (k - 1), nl))
    m0 = _kadd(a0p, mr)
    m1 = _kadd(a1, r1)
    c = _kadd(m0, m1)
    ctop = _kshr(_kshl(c, 1), amount + 1)
    cmsb_bit = c[nl - 1] >> np.uint32(15)  # public 0/1 lane
    zero = jnp.zeros_like(cmsb_bit)
    cmsb = [cmsb_bit] + [zero] * (nl - 1)

    def overflow(rm, first: bool):
        p = [limb * cmsb_bit for limb in rm]
        o = _ksub(rm, _kshl(p, 1))
        if first:
            o = _kadd(o, cmsb)
        return _kshl(o, k - amount)

    of0 = overflow(mrm, True)
    of1 = overflow(rm1, False)
    y0 = _ksub(
        _kadd(_ksub(ctop, mrt), of0),
        _kconst(1 << (k - amount - 1), nl),
    )
    y1 = _kadd(_kneg(rt1), of1)
    z1 = _ksub(y0, z0)
    return z0, z1, y1


# ---------------------------------------------------------------------------
# Elementwise kernel family: flat (L, R, 128) u32 plane stacks
# ---------------------------------------------------------------------------


def _flat_spec(a):
    lead = a.shape[:-2]
    nlead = len(lead)
    return pl.BlockSpec(
        lead + (_BLOCK_ROWS, _BLOCK_COLS),
        functools.partial(
            lambda i, nlead: (0,) * nlead + (i, 0), nlead=nlead
        ),
        memory_space=pltpu.VMEM,
    )


def _flat_call(body, ins, out_lead, n_grid_rows: int):
    out_shape = jax.ShapeDtypeStruct(
        out_lead + (n_grid_rows * _BLOCK_ROWS, _BLOCK_COLS), U32
    )
    return pl.pallas_call(
        body,
        grid=(n_grid_rows,),
        in_specs=[_flat_spec(a) for a in ins],
        out_specs=_flat_spec(out_shape),
        out_shape=out_shape,
        interpret=_interpret(),
    )(*ins)


def _read_limbs(ref, L: int):
    return _ksplit([ref[i] for i in range(L)])


def _write_limbs(ref, limbs, offset: int = 0):
    for i, plane in enumerate(_kjoin(limbs)):
        ref[offset + i] = plane


def _mul_body(x_ref, y_ref, o_ref, *, L):
    _write_limbs(
        o_ref, _kmul(_read_limbs(x_ref, L), _read_limbs(y_ref, L))
    )


def ring_mul(lo1, hi1, lo2, hi2, width: int):
    """Elementwise ring multiply mod 2^width (the two-limb u64 multiply
    of ``ring.mul``), one fused Mosaic program."""
    shape = lo1.shape
    n = int(np.prod(shape)) if shape else 1
    L = _n_planes(width)
    a = _tile(_to_planes(lo1, hi1))
    b = _tile(_to_planes(lo2, hi2))
    out = _flat_call(
        functools.partial(_mul_body, L=L), [a, b], (L,),
        a.shape[-2] // _BLOCK_ROWS,
    )
    return _from_planes(_untile(out, n), shape, width)


def _cross_mul_body(x0_ref, x1_ref, y0_ref, y1_ref, o_ref, *, L):
    x0 = _read_limbs(x0_ref, L)
    x1 = _read_limbs(x1_ref, L)
    y0 = _read_limbs(y0_ref, L)
    y1 = _read_limbs(y1_ref, L)
    v = _kadd(_kmul(x0, _kadd(y0, y1)), _kmul(x1, y0))
    _write_limbs(o_ref, v)


def cross_terms_mul(x0, x1, y0, y1, width: int):
    """Fused v = x0*(y0+y1) + x1*y0 (the regrouped cross terms of
    secure mul, ``spmd._cross_terms`` with an elementwise contraction):
    one HBM round trip instead of four elementwise XLA passes.  Each
    argument is a (lo, hi) pair; the party axis rides flattened."""
    shape = x0[0].shape
    n = int(np.prod(shape)) if shape else 1
    L = _n_planes(width)
    tiles = [
        _tile(_to_planes(*v)) for v in (x0, x1, y0, y1)
    ]
    out = _flat_call(
        functools.partial(_cross_mul_body, L=L), tiles, (L,),
        tiles[0].shape[-2] // _BLOCK_ROWS,
    )
    return _from_planes(_untile(out, n), shape, width)


def _trunc_body(a0_ref, a1_ref, r_ref, mr_ref, mrt_ref, mrm_ref, z0_ref,
                o_ref, *, L, width, amount):
    z0, z1, y1 = _ktrunc(
        _read_limbs(a0_ref, L), _read_limbs(a1_ref, L),
        _read_limbs(r_ref, L), _read_limbs(mr_ref, L),
        _read_limbs(mrt_ref, L), _read_limbs(mrm_ref, L),
        _read_limbs(z0_ref, L), width, amount,
    )
    for party, limbs in enumerate((z0, z1, y1)):
        for i, plane in enumerate(_kjoin(limbs)):
            o_ref[party, i] = plane


def trunc_combine(a0, a1, draws, width: int, amount: int, shape):
    """The full elementwise tail of ``spmd._trunc_pr_adt`` — masks,
    reveal, overflow correction, downshift, additive-to-replicated —
    fused into one Mosaic program.  ``draws`` is the (r, m_r, m_rt,
    m_rm, z0) tuple pre-drawn by the caller in the lax path's exact
    session order.  Returns the stacked (3, *shape) (z_lo, z_hi)."""
    n = int(np.prod(shape)) if shape else 1
    L = _n_planes(width)
    ins = [_tile(_to_planes(*v)) for v in (a0, a1, *draws)]
    R = ins[0].shape[-2]
    out_shape = jax.ShapeDtypeStruct((3, L, R, _BLOCK_COLS), U32)
    out = pl.pallas_call(
        functools.partial(
            _trunc_body, L=L, width=width, amount=amount
        ),
        grid=(R // _BLOCK_ROWS,),
        in_specs=[_flat_spec(a) for a in ins],
        out_specs=_flat_spec(out_shape),
        out_shape=out_shape,
        interpret=_interpret(),
    )(*ins)
    flat = _untile(out, n)  # (3, L, n)
    z_lo = (
        flat[:, 0].astype(U64) | (flat[:, 1].astype(U64) << np.uint64(32))
    ).reshape((3,) + tuple(shape))
    if width == 64:
        return z_lo, None
    z_hi = (
        flat[:, 2].astype(U64) | (flat[:, 3].astype(U64) << np.uint64(32))
    ).reshape((3,) + tuple(shape))
    return z_lo, z_hi


class ShapeUnsupported(Exception):
    """A shape guard rejected this invocation (too big for VMEM, k out
    of the exactness bound, ...): the caller falls back to the XLA path
    for THIS call only — the (kernel, width) verdict is untouched."""


# ---------------------------------------------------------------------------
# Bit kernels: plain-bit extraction + carry-save + Kogge-Stone adder
# (the inner loop of spmd_math.bit_decompose / msb), uint8 XOR shares
# ---------------------------------------------------------------------------


def _planes_keep(lo, hi, n_lead: int) -> jax.Array:
    """Like :func:`_to_planes` but flattening only the dims AFTER the
    first ``n_lead`` (the party/slot stacking prefix)."""
    lo = jnp.asarray(lo, U64)
    flat = lo.reshape(lo.shape[:n_lead] + (-1,))
    planes = [
        (flat & MASK32).astype(U32), (flat >> np.uint64(32)).astype(U32)
    ]
    if hi is not None:
        hi = jnp.asarray(hi, U64).reshape(flat.shape)
        planes += [
            (hi & MASK32).astype(U32), (hi >> np.uint64(32)).astype(U32)
        ]
    return jnp.stack(planes)


def _roll_party(a):
    """roll(-1) over a static size-3 leading party axis (Mosaic-safe:
    concatenation of static slices, no gather)."""
    return jnp.concatenate([a[1:], a[:1]], axis=0)


def _bits_body(x_ref, banks_ref, o_ref, *, L, width, msb_only):
    k = width
    planes = [x_ref[i] for i in range(L)]  # each (3, 2, 8, 128) u32
    bits = []
    for j in range(k):
        p = planes[j // 32]
        bits.append(((p >> np.uint32(j % 32)) & np.uint32(1)).astype(U8))
    B = jnp.stack(bits, axis=2)  # (3, 2, k, 8, 128) u8
    # the three summand selections of spmd_math._summand_mask — party j
    # holds x_j at pair slots (j, 0) and (j-1, 1) — assembled by static
    # stacking (Pallas kernels cannot capture ndarray mask constants)
    zero = jnp.zeros_like(B[0, 0])

    def summand(j: int):
        rows = []
        for p in range(3):
            s0 = B[p, 0] if p == j else zero
            s1 = B[p, 1] if p == (j - 1) % 3 else zero
            rows.append(jnp.stack([s0, s1]))
        return jnp.stack(rows)

    b0, b1, b2 = summand(0), summand(1), summand(2)

    bank_idx = [0]

    def b_and(x, y):
        # stacked replicated AND over Z_2 consuming one pre-drawn bank
        # (spmd_math.bits_and with the PRF draw hoisted out)
        x0, x1 = x[:, 0], x[:, 1]
        y0, y1 = y[:, 0], y[:, 1]
        v = (x0 & (y0 ^ y1)) ^ (x1 & y0)
        s = banks_ref[bank_idx[0]]  # (3, k, 8, 128) u8
        bank_idx[0] += 1
        z = v ^ (s ^ _roll_party(s))
        return jnp.stack([z, _roll_party(z)], axis=1)

    def b_shl(x, d):
        if d == 0:
            return x
        if d >= k:
            return jnp.zeros_like(x)
        zero = jnp.zeros_like(x[:, :, :d])
        return jnp.concatenate([zero, x[:, :, : k - d]], axis=2)

    # carry-save: s = b0^b1^b2 ; c = (b0&b1) ^ ((b0^b1)&b2)
    s = b0 ^ b1 ^ b2
    c = b_and(b0, b1) ^ b_and(b0 ^ b1, b2)
    x_, y_ = s, b_shl(c, 1)
    # Kogge-Stone: log2(k) rounds of two ANDs over the whole tensor
    p = x_ ^ y_
    g = b_and(x_, y_)
    p_run = p
    d = 1
    while d < k:
        g = g ^ b_and(p_run, b_shl(g, d))
        if d * 2 < k:
            p_run = b_and(p_run, b_shl(p_run, d))
        d *= 2
    out = p ^ b_shl(g, 1)
    if msb_only:
        o_ref[...] = out[:, :, k - 1]
    else:
        o_ref[...] = out


def _full_lead_spec(lead, rows: int = _BLOCK_ROWS):
    nlead = len(lead)
    return pl.BlockSpec(
        lead + (rows, _BLOCK_COLS),
        functools.partial(
            lambda i, nlead: (0,) * nlead + (i, 0), nlead=nlead
        ),
        memory_space=pltpu.VMEM,
    )


# 8 data rows per block: the AND-bank stack is the VMEM hog — at
# ring128 it is n_ands(16) * 3 * k(128) * rows * 128 bytes, i.e.
# ~6 MiB at 8 rows but ~25 MiB at the uint8-native 32-row tile, which
# would not fit VMEM at all.  Sub-native u8 tiles cost Mosaic a
# relayout, but a kernel that fits beats one that cannot compile; the
# first-use self-check demotes cleanly if a target still rejects it.
_BITS_ROWS = 8


def _bits_call(lo, hi, width: int, banks, msb_only: bool):
    k = width
    shape = lo.shape[2:]
    n = int(np.prod(shape)) if shape else 1
    L = _n_planes(width)
    xt = _tile(_planes_keep(lo, hi, 2), _BITS_ROWS)  # (L, 3, 2, R, 128)
    bt = _tile(
        banks.reshape(banks.shape[:3] + (-1,)), _BITS_ROWS
    )  # (nA, 3, k, R, 128)
    R = xt.shape[-2]
    out_lead = (3, 2) if msb_only else (3, 2, k)
    out_shape = jax.ShapeDtypeStruct(out_lead + (R, _BLOCK_COLS), U8)
    out = pl.pallas_call(
        functools.partial(
            _bits_body, L=L, width=width, msb_only=msb_only
        ),
        grid=(R // _BITS_ROWS,),
        in_specs=[
            _full_lead_spec((L, 3, 2), _BITS_ROWS),
            _full_lead_spec((banks.shape[0], 3, k), _BITS_ROWS),
        ],
        out_specs=_full_lead_spec(out_lead, _BITS_ROWS),
        out_shape=out_shape,
        interpret=_interpret(),
    )(xt, bt)
    return _untile(out, n).reshape(out_lead + tuple(shape))


def bit_decompose(lo, hi, width: int, banks):
    """Arithmetic -> binary sharing (``spmd_math.bit_decompose``) as ONE
    Mosaic program: plain-bit planes of the held shares, static summand
    masks, carry-save, and the full Kogge-Stone adder — consuming the
    pre-drawn AND banks (``banks`` is the (n_ands, 3, k, *shape) uint8
    stack, drawn by the caller in the lax path's exact session order).
    Returns the (3, 2, k, *shape) uint8 bit sharing."""
    return _bits_call(lo, hi, width, banks, msb_only=False)


def msb(lo, hi, width: int, banks):
    """:func:`bit_decompose` writing only the top bit plane
    (3, 2, *shape) — same compute, 1/k-th the HBM output traffic (the
    comparison path msb/less/greater needs nothing else)."""
    return _bits_call(lo, hi, width, banks, msb_only=True)


def adder_bank_count(width: int) -> int:
    """How many AND banks the fused decompose/adder kernel consumes, by
    replaying its structure (callers size the pre-draw with this; the
    order is: 2 carry-save ANDs, the adder's initial g = x AND y, then
    per round the g update and — while d*2 < k — the p_run update)."""
    n = 2  # carry-save
    n += 1  # g = x AND y
    d = 1
    while d < width:
        n += 1  # g ^= p_run AND shl(g, d)
        if d * 2 < width:
            n += 1  # p_run AND shl(p_run, d)
        d *= 2
    return n


# ---------------------------------------------------------------------------
# Fused Horner polynomial (spmd_math.polynomial_eval): the fx_sigmoid /
# exp region the TPU miscompile actually bites
# ---------------------------------------------------------------------------


def _horner_body(x0_ref, x1_ref, zb_ref, td_ref, o_ref, *, L, width, f,
                 raws, steps):
    nl = width // 16
    x0 = _ksplit([x0_ref[i] for i in range(L)])  # limbs (3, 8, 128)
    x1 = _ksplit([x1_ref[i] for i in range(L)])
    xsum = _kadd(x0, x1)
    # party masks built in-kernel (no captured ndarray constants)
    pid = jax.lax.broadcasted_iota(U32, (3, 1, 1), 0)
    mask_p0 = (pid == np.uint32(0)).astype(U32)
    mask_p2 = (pid == np.uint32(2)).astype(U32)

    def const_at(raw: int, mask):
        # trivial public sharing: x_0 = raw held at pair slots
        # (party0, slot0) / (party2, slot1) — mask selects the party
        return [
            np.uint32((int(raw) >> (16 * i)) & 0xFFFF) * mask
            for i in range(nl)
        ]

    acc0 = const_at(raws[0], mask_p0)
    acc1 = const_at(raws[0], mask_p2)
    for st in range(steps):
        # cross terms of fx_mul(acc, x): v_i = acc0_i*(x0_i + x1_i)
        #                                      + acc1_i*x0_i
        v = _kadd(_kmul(acc0, xsum), _kmul(acc1, x0))
        # zero-share: alpha_i = s_i - s_{i+1}
        s = _ksplit([zb_ref[st, i] for i in range(L)])
        z = _kadd(v, _ksub(s, [_roll_party(limb) for limb in s]))
        # fused truncate from the 2-party additive form
        a0 = _kadd([limb[0] for limb in z], [limb[1] for limb in z])
        a1 = [limb[2] for limb in z]
        dr = [
            _ksplit([td_ref[st, d, i] for i in range(L)])
            for d in range(5)
        ]
        z0, z1, y1 = _ktrunc(a0, a1, *dr, width, f)
        zst = [
            jnp.stack([z0[i], z1[i], y1[i]]) for i in range(nl)
        ]
        acc0, acc1 = zst, [_roll_party(limb) for limb in zst]
        # + public coefficient (only share x_0 adjusted)
        acc0 = _kadd(acc0, const_at(raws[st + 1], mask_p0))
        acc1 = _kadd(acc1, const_at(raws[st + 1], mask_p2))
    for i, plane in enumerate(_kjoin(acc0)):
        o_ref[0, i] = plane
    for i, plane in enumerate(_kjoin(acc1)):
        o_ref[1, i] = plane


def horner(x0, x1, width: int, raws, f: int, zbanks, tdraws, shape):
    """Fused fixed-point Horner ladder (``polynomial_eval``): every
    step's cross terms, zero-share add, probabilistic truncation, and
    public-coefficient add run inside ONE Mosaic program — no XLA
    fusion decisions anywhere in the polynomial region.

    ``x0``/``x1`` are the (lo, hi) pair-slot arrays (3, *shape);
    ``raws`` the encoded coefficients highest-first (raws[0] seeds the
    accumulator); ``zbanks`` the per-step zero-share banks stacked
    (steps, 3, *shape) as (lo, hi); ``tdraws`` the per-step truncation
    draws stacked (steps, 5, *shape) as (lo, hi) — both pre-drawn in
    the lax path's exact session order.  Returns the (slot0, slot1)
    pair arrays of the resulting sharing as ((lo, hi), (lo, hi))."""
    steps = len(raws) - 1
    n = int(np.prod(shape)) if shape else 1
    L = _n_planes(width)
    x0t = _tile(_planes_keep(x0[0], x0[1], 1))  # (L, 3, R, 128)
    x1t = _tile(_planes_keep(x1[0], x1[1], 1))
    zbt = jnp.moveaxis(
        _tile(_planes_keep(zbanks[0], zbanks[1], 2)), 0, 1
    )  # (steps, L, 3, R, 128)
    tdt = jnp.moveaxis(
        _tile(_planes_keep(tdraws[0], tdraws[1], 2)), 0, 1
    )  # (steps, 5, L, R, 128) after the second moveaxis below
    tdt = jnp.moveaxis(tdt, 2, 1)
    R = x0t.shape[-2]
    out_shape = jax.ShapeDtypeStruct((2, L, 3, R, _BLOCK_COLS), U32)
    out = pl.pallas_call(
        functools.partial(
            _horner_body, L=L, width=width, f=f,
            raws=tuple(int(r) for r in raws), steps=steps,
        ),
        grid=(R // _BLOCK_ROWS,),
        in_specs=[
            _full_lead_spec((L, 3)),
            _full_lead_spec((L, 3)),
            _full_lead_spec((steps, L, 3)),
            _full_lead_spec((steps, 5, L)),
        ],
        out_specs=_full_lead_spec((2, L, 3)),
        out_shape=out_shape,
        interpret=_interpret(),
    )(x0t, x1t, zbt, tdt)
    flat = _untile(out, n)  # (2, L, 3, n)

    def slot(si):
        lo = (
            flat[si, 0].astype(U64)
            | (flat[si, 1].astype(U64) << np.uint64(32))
        ).reshape((3,) + tuple(shape))
        if width == 64:
            return lo, None
        hi = (
            flat[si, 2].astype(U64)
            | (flat[si, 3].astype(U64) << np.uint64(32))
        ).reshape((3,) + tuple(shape))
        return lo, hi

    return slot(0), slot(1)


# ---------------------------------------------------------------------------
# Party-batched dot cross terms (opt-in; see module docstring)
# ---------------------------------------------------------------------------

_DOT_CHUNK = 256  # 8-bit limb products < 2^16; 256-term f32 dots < 2^24
_DOT_VMEM_BUDGET = 6 << 20


def _dot_body(x0_ref, x1_ref, y0_ref, ys_ref, o_ref, *, width):
    L = width // 32
    in8 = width // 8
    nl = width // 16

    def limbs8(ref):
        planes = [ref[i, 0] for i in range(L)]  # (m, k) / (k, n)
        out = []
        for l8 in range(in8):
            p = planes[l8 // 4]
            out.append(
                ((p >> np.uint32(8 * (l8 % 4))) & np.uint32(0xFF))
                .astype(jnp.float32)
            )
        return out

    a0 = limbs8(x0_ref)
    a1 = limbs8(x1_ref)
    b0 = limbs8(y0_ref)
    bs = limbs8(ys_ref)
    k = a0[0].shape[-1]
    chunks = [
        (c, min(c + _DOT_CHUNK, k)) for c in range(0, k, _DOT_CHUNK)
    ]
    m, n = a0[0].shape[0], b0[0].shape[-1]
    zero = jnp.zeros((m, n), U32)

    def diags(a, b):
        ds = []
        for s in range(in8):
            acc = None
            for i in range(min(s + 1, in8)):
                j = s - i
                if j >= in8:
                    continue
                for (c0, c1) in chunks:
                    p = jax.lax.dot_general(
                        a[i][:, c0:c1], b[j][c0:c1, :],
                        (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    ).astype(U32)
                    acc = p if acc is None else acc + p
            ds.append(acc if acc is not None else zero)
        return ds

    cols = [zero] * (nl + 2)

    def accumulate(ds):
        # byte-aligned diagonals folded into 16-bit columns; values stay
        # far below 2^32 (each term < 2^16, < 100 terms per column)
        for s, d in enumerate(ds):
            half = s // 2
            if s % 2 == 0:
                cols[half] = cols[half] + (d & MASK16)
                cols[half + 1] = cols[half + 1] + (d >> np.uint32(16))
            else:
                cols[half] = cols[half] + (
                    (d & np.uint32(0xFF)) << np.uint32(8)
                )
                cols[half + 1] = cols[half + 1] + (
                    (d >> np.uint32(8)) & MASK16
                )
                cols[half + 2] = cols[half + 2] + (d >> np.uint32(24))

    accumulate(diags(a0, bs))
    accumulate(diags(a1, b0))
    out_limbs = _knorm(cols[:nl])
    for i, plane in enumerate(_kjoin(out_limbs)):
        o_ref[i, 0] = plane


def _dot_tile_plan(m: int, k: int, n: int, width: int):
    """Deterministic tile/segment search for the dot kernel: returns
    ``(bm, bn, kseg)`` — m/n block sizes and the host-side contraction
    segment length.  Preference order: fewest k segments (each segment
    is a separate pallas call accumulated with a ring add), then the
    largest ``bm``, then ``bn`` that fit the VMEM budget.  The per-call
    contraction is bounded by the u32 diagonal exactness limit
    ``(255 // in8) * _DOT_CHUNK``.  Raises :class:`ShapeUnsupported`
    only when nothing fits (degenerate dims)."""
    if m <= 0 or k <= 0 or n <= 0:
        raise ShapeUnsupported(f"degenerate dot shape ({m},{k},{n})")
    L = _n_planes(width)
    in8 = width // 8
    max_k = (255 // in8) * _DOT_CHUNK
    mp, np_ = -(-m // 8) * 8, -(-n // 128) * 128

    def ladder(top, steps):
        out = [top]
        out.extend(s for s in steps if s < top)
        return out

    bms = ladder(mp, (512, 256, 128, 64, 32, 16, 8))
    bns = ladder(np_, (512, 256, 128))
    for segs in range(1, -(-k // 128) + 1):
        kseg = -(-k // segs)
        if kseg > max_k:
            continue
        kp = -(-kseg // 128) * 128
        for bm in bms:
            for bn in bns:
                if (
                    4 * L * (2 * bm * kp + 2 * kp * bn + bm * bn)
                    <= _DOT_VMEM_BUDGET
                ):
                    return bm, bn, kseg
    raise ShapeUnsupported(
        f"no dot tiling fits VMEM for ({m},{k},{n}) ring{width}"
    )


def dot_cross_terms(x0, x1, y0, ysum, width: int, *, tile_plan=None):
    """Fused party-batched matmul cross terms
    v_p = x0_p @ (y0+y1)_p + x1_p @ y0_p over 8-bit limbs on f32 MXU
    dots (exact: products < 2^16, 256-term chunks < 2^24, u32 diagonal
    accumulation).  ``ysum`` is precomputed by the caller (one cheap
    ring add).  Arguments are (lo, hi) pairs shaped (3, m, k) /
    (3, k, n).

    MXU-shaped work is tiled: the grid runs (party, m-tiles, n-tiles)
    with per-tile operands in VMEM, and contractions past the u32
    exactness / VMEM bound are split into k segments on the host — dot
    distributes over ring addition mod 2^w, so per-segment partials
    accumulate exactly with a ring add.  ``tile_plan`` overrides the
    deterministic search (tests force multi-tile grids on small
    shapes).  Raises :class:`ShapeUnsupported` only for degenerate
    shapes."""
    from ..dialects import ring

    a_lo = x0[0]
    if a_lo.ndim != 3 or y0[0].ndim != 3:
        raise ShapeUnsupported("dot kernel needs (3, m, k) @ (3, k, n)")
    _, m, k = a_lo.shape
    n = y0[0].shape[-1]
    L = _n_planes(width)
    bm, bn, kseg = (
        tile_plan if tile_plan is not None
        else _dot_tile_plan(m, k, n, width)
    )
    kp = -(-kseg // 128) * 128
    mt, nt = -(-m // bm), -(-n // bn)
    mp, np_ = mt * bm, nt * bn

    def prep(lo, hi, rows, cols_, r_pad, c_pad):
        planes = _planes_keep(lo, hi, 3).reshape(-1, 3, rows, cols_)
        return jnp.pad(
            planes,
            ((0, 0), (0, 0), (0, r_pad - rows), (0, c_pad - cols_)),
        )

    def slice_x(v, c0, c1):
        hi = None if v[1] is None else v[1][:, :, c0:c1]
        return prep(v[0][:, :, c0:c1], hi, m, c1 - c0, mp, kp)

    def slice_y(v, c0, c1):
        hi = None if v[1] is None else v[1][:, c0:c1, :]
        return prep(v[0][:, c0:c1, :], hi, c1 - c0, n, kp, np_)

    def spec(rows, cols_, index):
        return pl.BlockSpec(
            (L, 1, rows, cols_), index, memory_space=pltpu.VMEM,
        )

    call = pl.pallas_call(
        functools.partial(_dot_body, width=width),
        grid=(3, mt, nt),
        in_specs=[
            spec(bm, kp, lambda p, i, j: (0, p, i, 0)),
            spec(bm, kp, lambda p, i, j: (0, p, i, 0)),
            spec(kp, bn, lambda p, i, j: (0, p, 0, j)),
            spec(kp, bn, lambda p, i, j: (0, p, 0, j)),
        ],
        out_specs=spec(bm, bn, lambda p, i, j: (0, p, i, j)),
        out_shape=jax.ShapeDtypeStruct((L, 3, mp, np_), U32),
        interpret=_interpret(),
    )

    acc = None
    for c0 in range(0, k, kseg):
        c1 = min(c0 + kseg, k)
        out = call(
            slice_x(x0, c0, c1), slice_x(x1, c0, c1),
            slice_y(y0, c0, c1), slice_y(ysum, c0, c1),
        )[:, :, :m, :n]
        lo = out[0].astype(U64) | (out[1].astype(U64) << np.uint64(32))
        hi = (
            None if width == 64
            else out[2].astype(U64) | (out[3].astype(U64) << np.uint64(32))
        )
        acc = (lo, hi) if acc is None else ring.add(*acc, lo, hi)
    return acc


# ---------------------------------------------------------------------------
# First-use self-checks: kernel vs lax twin, bit-exact, canned shapes
# (incl. a non-aligned trailing dim) — the per-kernel analogue of the
# PR-2 ladder's jit-vs-eager bit-exactness discipline.
# ---------------------------------------------------------------------------


def _check_rng():
    return np.random.default_rng(0xC0FFEE)


def _jit_eval(fn):
    """Run a zero-arg closure under jit: interpret-mode pallas calls
    cost ~0.4s per EAGER invocation (the interpreter machinery, not the
    math), so the first-use checks trace once and execute compiled —
    they run at dispatch time inside user processes."""
    return jax.jit(fn)()


def _rand_ring(rng, shape, width: int):
    lo = jnp.asarray(rng.integers(0, 1 << 64, size=shape, dtype=np.uint64))
    if width == 64:
        return lo, None
    hi = jnp.asarray(rng.integers(0, 1 << 64, size=shape, dtype=np.uint64))
    return lo, hi


def _assert_bitwise(got, want, label: str):
    g_lo, g_hi = got
    w_lo, w_hi = want
    assert np.array_equal(np.asarray(g_lo), np.asarray(w_lo)), (
        f"{label}: lo limb diverged"
    )
    if w_hi is not None:
        assert np.array_equal(np.asarray(g_hi), np.asarray(w_hi)), (
            f"{label}: hi limb diverged"
        )


# one shape, deliberately NOT tile-aligned; the test suite sweeps more
_CHECK_SHAPES = ((3, 5),)


def _check_mul(width: int) -> None:
    from ..dialects import ring

    rng = _check_rng()
    for shape in _CHECK_SHAPES + ((9,),):
        x = _rand_ring(rng, shape, width)
        y = _rand_ring(rng, shape, width)
        _assert_bitwise(
            _jit_eval(lambda: ring_mul(*x, *y, width)),
            _jit_eval(lambda: ring.mul(*x, *y)),
            f"ring_mul{shape}",
        )


def _check_cross(width: int) -> None:
    from ..dialects import ring

    rng = _check_rng()
    for shape in ((3, 4, 5),):
        vals = [_rand_ring(rng, shape, width) for _ in range(4)]
        x0, x1, y0, y1 = vals

        def want_fn():
            ys = ring.add(*y0, *y1)
            return ring.add(*ring.mul(*x0, *ys), *ring.mul(*x1, *y0))

        _assert_bitwise(
            _jit_eval(lambda: cross_terms_mul(x0, x1, y0, y1, width)),
            _jit_eval(want_fn),
            f"cross_terms_mul{shape}",
        )


def _check_trunc(width: int) -> None:
    from ..parallel import spmd

    rng = _check_rng()
    for shape in _CHECK_SHAPES:
        a0 = _rand_ring(rng, shape, width)
        a1 = _rand_ring(rng, shape, width)
        draws = tuple(_rand_ring(rng, shape, width) for _ in range(5))
        for amount in (7,):
            want = _jit_eval(
                lambda: spmd._trunc_combine_lax(
                    a0, a1, draws, width, amount
                )
            )
            got = _jit_eval(
                lambda: trunc_combine(a0, a1, draws, width, amount, shape)
            )
            _assert_bitwise(got, want, f"trunc_combine{shape}/{amount}")


def _check_bits_common(width: int, msb_only: bool) -> None:
    from ..parallel import spmd_math as sm

    rng = _check_rng()
    k = width
    n_ands = adder_bank_count(width)
    for shape in ((3, 5), (6,)):
        lo = jnp.asarray(
            rng.integers(0, 1 << 64, size=(3, 2) + shape, dtype=np.uint64)
        )
        hi = (
            jnp.asarray(rng.integers(
                0, 1 << 64, size=(3, 2) + shape, dtype=np.uint64
            ))
            if width == 128 else None
        )
        banks = jnp.asarray(rng.integers(
            0, 2, size=(n_ands, 3, k) + shape, dtype=np.uint8
        ))
        want = _jit_eval(
            lambda: sm._bit_decompose_with_banks(lo, hi, width, banks)
        )
        if msb_only:
            got = _jit_eval(lambda: msb(lo, hi, width, banks))
            want = want[:, :, k - 1]
        else:
            got = _jit_eval(lambda: bit_decompose(lo, hi, width, banks))
        assert np.array_equal(np.asarray(got), np.asarray(want)), (
            f"{'msb' if msb_only else 'bit_decompose'}{shape} diverged"
        )


def _check_bits(width: int) -> None:
    _check_bits_common(width, msb_only=False)


def _check_msb(width: int) -> None:
    _check_bits_common(width, msb_only=True)


def _check_horner(width: int) -> None:
    from ..parallel import spmd, spmd_math as sm

    rng = _check_rng()
    f = 12 if width == 64 else 23
    coeffs = [1.0, 0.7, -0.21, 0.043]
    raws = [
        int(round(c * (1 << f))) % (1 << width) for c in reversed(coeffs)
    ]
    steps = len(raws) - 1
    for shape in ((4, 5),):
        x_lo = jnp.asarray(rng.integers(
            0, 1 << 64, size=(3, 2) + shape, dtype=np.uint64
        ))
        x_hi = (
            jnp.asarray(rng.integers(
                0, 1 << 64, size=(3, 2) + shape, dtype=np.uint64
            ))
            if width == 128 else None
        )
        zb = _rand_ring(rng, (steps, 3) + shape, width)
        td = _rand_ring(rng, (steps, 5) + shape, width)
        # lax twin: the unfused polynomial ladder fed the same draws
        # through a replay session
        queue = []
        for st in range(steps):
            queue.append((
                zb[0][st], None if zb[1] is None else zb[1][st]
            ))
            for d in range(5):
                queue.append((
                    td[0][st, d], None if td[1] is None else td[1][st, d]
                ))
        x_rep = spmd.SpmdRep(x_lo, x_hi, width)
        want = _jit_eval(
            lambda: sm._horner_lax(
                sm._ReplaySession(queue), x_rep, raws, f
            )
        )
        (s0_lo, s0_hi), (s1_lo, s1_hi) = _jit_eval(lambda: horner(
            (x_lo[:, 0], None if x_hi is None else x_hi[:, 0]),
            (x_lo[:, 1], None if x_hi is None else x_hi[:, 1]),
            width, raws, f, zb, td, shape,
        ))
        got_lo = jnp.stack([s0_lo, s1_lo], axis=1)
        assert np.array_equal(np.asarray(got_lo), np.asarray(want.lo)), (
            f"horner{shape}: lo diverged"
        )
        if width == 128:
            got_hi = jnp.stack([s0_hi, s1_hi], axis=1)
            assert np.array_equal(
                np.asarray(got_hi), np.asarray(want.hi)
            ), f"horner{shape}: hi diverged"


def _check_dot(width: int) -> None:
    from ..dialects import ring
    from ..parallel import spmd

    rng = _check_rng()
    # the last row forces a multi-tile grid (2 m-tiles x 2 n-tiles) AND
    # host-side k segmentation (2 segments) on a small shape — the
    # tiled/segmented code paths the MXU shapes exercise, checked at
    # first-use cost
    for (m, k, n, plan) in (
        (4, 37, 3, None),
        (2, 300, 5, None),
        (10, 300, 130, (8, 128, 256)),
    ):
        x0 = _rand_ring(rng, (3, m, k), width)
        x1 = _rand_ring(rng, (3, m, k), width)
        y0 = _rand_ring(rng, (3, k, n), width)
        y1 = _rand_ring(rng, (3, k, n), width)
        ys = ring.add(*y0, *y1)
        def want_fn():
            va = spmd._dot_contract(*x0, *ys)
            vb = spmd._dot_contract(*x1, *y0)
            return ring.add(*va, *vb)

        want = _jit_eval(want_fn)
        got = _jit_eval(
            lambda: dot_cross_terms(x0, x1, y0, ys, width, tile_plan=plan)
        )
        _assert_bitwise(got, want, f"dot_cross_terms({m},{k},{n})")


_CHECKS: Dict[str, Callable[[int], None]] = {
    "ring_mul": _check_mul,
    "cross_terms_mul": _check_cross,
    "trunc_combine": _check_trunc,
    "bit_decompose": _check_bits,
    "msb": _check_msb,
    "horner": _check_horner,
    "dot_cross_terms": _check_dot,
}
