"""Unified metrics registry: labeled, thread-safe counters / gauges /
histograms with Prometheus text and JSON exposition.

The reference ships per-role elapsed-time maps and Jaeger spans but no
metrics endpoint; this reproduction grew counters ad hoc instead —
``worker_plan.PLAN_STATS``, ``serving.metrics.ServingMetrics``,
``last_session_report`` — each with its own exposition (or none).  This
module is the one registry they all bridge onto, so every process
(blitzen, comet, a bench run, a test cluster) exposes the same
catalogue the same two ways:

- ``render_prometheus()`` — the ``GET /metrics`` text format scraped by
  Prometheus / Grafana Alloy / any OpenMetrics collector;
- ``snapshot()`` — a JSON-able dict (the ``/v1/metrics`` payload and the
  bench / smoke assertion surface).

Design rules:

- metrics are **created on first use** (``counter(name, help)`` is
  get-or-create) so instrumented modules never need registration order;
- label sets are fixed per metric at creation; values key on the label
  *values* tuple;
- everything is guarded by one lock per registry — these are cold-path
  increments (one per rpc / batch / plan decision, not per tensor
  element), so a contended lock is not a concern;
- the registry is **process-global** (``REGISTRY``) because its job is
  whole-process exposition; tests assert on *deltas* via
  :func:`snapshot`, never on absolute values.

``serve_http(port)`` starts the stdlib exposition server used by
``comet --metrics-port`` (and by ``scripts/dist_smoke.py``): ``GET
/metrics`` serves the Prometheus text, ``GET /healthz`` a JSON health
document, ``GET /v1/metrics`` the JSON snapshot.

Kernel-path attestation (ISSUE 9): ``native/ring128_kernels.py``
registers ``moose_tpu_pallas_dispatch_total{kernel=...}`` (trace-time
routings of a primitive into its Pallas kernel) and
``moose_tpu_pallas_fallback_total{kernel=..., reason=...}`` (first-use
self-check divergence/error or per-call shape rejection demoting a
primitive to the XLA path), so BENCH/MULTICHIP rounds can attest which
path actually ran instead of inferring it from timings.
"""

from __future__ import annotations

import json
import re
import threading
from typing import Dict, Optional, Sequence, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# latency-shaped default buckets (seconds), doubling from 1ms to ~65s
DEFAULT_BUCKETS = tuple(0.001 * 2 ** i for i in range(17))


def _escape_label_value(value: str) -> str:
    return (
        value.replace("\\", "\\\\").replace("\n", "\\n").replace('"', '\\"')
    )


class _Metric:
    """Shared shape for one named metric family."""

    kind = "untyped"

    def __init__(self, name: str, help: str, label_names: Tuple[str, ...],
                 lock: threading.Lock):
        self.name = name
        self.help = help
        self.label_names = label_names
        self._lock = lock
        self._values: Dict[Tuple[str, ...], float] = {}

    def _label_key(self, labels: dict) -> Tuple[str, ...]:
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"metric {self.name!r} takes labels "
                f"{sorted(self.label_names)}, got {sorted(labels)}"
            )
        return tuple(str(labels[n]) for n in self.label_names)

    # -- exposition ----------------------------------------------------

    def _render_series(self, key: Tuple[str, ...], value) -> str:
        if self.label_names:
            labels = ",".join(
                f'{n}="{_escape_label_value(v)}"'
                for n, v in zip(self.label_names, key)
            )
            return f"{self.name}{{{labels}}} {_fmt(value)}"
        return f"{self.name} {_fmt(value)}"

    def render(self) -> list:
        lines = []
        if self.help:
            lines.append(f"# HELP {self.name} {self.help}")
        lines.append(f"# TYPE {self.name} {self.kind}")
        for key in sorted(self._values):
            lines.append(self._render_series(key, self._values[key]))
        return lines

    def snapshot_values(self):
        return {
            ",".join(
                f"{n}={v}" for n, v in zip(self.label_names, key)
            ): value
            for key, value in self._values.items()
        }


def _fmt(value: float) -> str:
    if isinstance(value, float) and value.is_integer():
        return str(int(value))
    return repr(value) if isinstance(value, float) else str(value)


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1, **labels) -> None:
        if amount < 0:
            raise ValueError(
                f"counter {self.name!r} cannot decrease (inc {amount})"
            )
        key = self._label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0) + amount

    def value(self, **labels) -> float:
        key = self._label_key(labels)
        with self._lock:
            return self._values.get(key, 0)


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        key = self._label_key(labels)
        with self._lock:
            self._values[key] = value

    def inc(self, amount: float = 1, **labels) -> None:
        key = self._label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0) + amount

    def dec(self, amount: float = 1, **labels) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels) -> float:
        key = self._label_key(labels)
        with self._lock:
            return self._values.get(key, 0)


class Histogram(_Metric):
    """Cumulative-bucket histogram (the Prometheus model: ``_bucket``
    series carry counts of observations ``<= le``, plus ``_sum`` and
    ``_count``)."""

    kind = "histogram"

    def __init__(self, name, help, label_names, lock,
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help, label_names, lock)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        # per label key: [counts per bucket] + [sum, count]
        self._hist: Dict[Tuple[str, ...], list] = {}

    def observe(self, value: float, **labels) -> None:
        key = self._label_key(labels)
        with self._lock:
            state = self._hist.get(key)
            if state is None:
                state = self._hist[key] = [
                    [0] * len(self.buckets), 0.0, 0,
                ]
            counts, total, n = state
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    counts[i] += 1
            state[1] = total + value
            state[2] = n + 1

    def render(self) -> list:
        lines = []
        if self.help:
            lines.append(f"# HELP {self.name} {self.help}")
        lines.append(f"# TYPE {self.name} {self.kind}")
        for key in sorted(self._hist):
            counts, total, n = self._hist[key]
            base = list(zip(self.label_names, key))
            for bound, count in zip(self.buckets, counts):
                labels = ",".join(
                    f'{ln}="{_escape_label_value(lv)}"'
                    for ln, lv in base + [("le", _fmt(bound))]
                )
                lines.append(f"{self.name}_bucket{{{labels}}} {count}")
            inf_labels = ",".join(
                f'{ln}="{_escape_label_value(lv)}"'
                for ln, lv in base + [("le", "+Inf")]
            )
            lines.append(f"{self.name}_bucket{{{inf_labels}}} {n}")
            suffix = (
                "{" + ",".join(
                    f'{ln}="{_escape_label_value(lv)}"' for ln, lv in base
                ) + "}"
                if base
                else ""
            )
            lines.append(f"{self.name}_sum{suffix} {_fmt(total)}")
            lines.append(f"{self.name}_count{suffix} {n}")
        return lines

    def snapshot_values(self):
        out = {}
        for key, (counts, total, n) in self._hist.items():
            label = ",".join(
                f"{ln}={lv}" for ln, lv in zip(self.label_names, key)
            )
            out[label] = {"sum": total, "count": n}
        return out


class MetricsRegistry:
    """One process-wide catalogue of metric families."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name: str, help: str, labels, **kwargs):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        label_names = tuple(labels)
        for ln in label_names:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r}")
        with self._lock:
            metric = self._metrics.get(name)
            if metric is None:
                metric = cls(name, help, label_names, self._lock, **kwargs)
                self._metrics[name] = metric
                return metric
        if not isinstance(metric, cls):
            raise ValueError(
                f"metric {name!r} already registered as {metric.kind}"
            )
        if metric.label_names != label_names:
            raise ValueError(
                f"metric {name!r} already registered with labels "
                f"{metric.label_names}, requested {label_names}"
            )
        return metric

    def counter(self, name: str, help: str = "",
                labels: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(
            Histogram, name, help, labels, buckets=buckets
        )

    def render_prometheus(self) -> str:
        with self._lock:
            metrics = [self._metrics[n] for n in sorted(self._metrics)]
            lines = []
            for metric in metrics:
                lines.extend(metric.render())
        return "\n".join(lines) + ("\n" if lines else "")

    def snapshot(self) -> dict:
        with self._lock:
            return {
                name: {
                    "type": metric.kind,
                    "values": metric.snapshot_values(),
                }
                for name, metric in sorted(self._metrics.items())
            }

    def get(self, name: str) -> Optional[_Metric]:
        """The registered family, or None (assertion / snapshot-delta
        surface: ``REGISTRY.get(n).value(**labels)``)."""
        with self._lock:
            return self._metrics.get(name)

    def value(self, name: str, default: float = 0, **labels) -> float:
        """Current value of a counter/gauge series, or ``default`` when
        the family or series doesn't exist yet (bench/smoke delta
        helper)."""
        metric = self.get(name)
        if metric is None or not hasattr(metric, "value"):
            return default
        try:
            return metric.value(**labels)
        except ValueError:
            return default

    def reset(self) -> None:
        """Drop every registered family (tests only — production code
        relies on create-on-first-use, so a reset mid-flight only loses
        history, never breaks instrumentation)."""
        with self._lock:
            self._metrics.clear()


REGISTRY = MetricsRegistry()


def counter(name: str, help: str = "",
            labels: Sequence[str] = ()) -> Counter:
    return REGISTRY.counter(name, help, labels)


def gauge(name: str, help: str = "", labels: Sequence[str] = ()) -> Gauge:
    return REGISTRY.gauge(name, help, labels)


def histogram(name: str, help: str = "", labels: Sequence[str] = (),
              buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
    return REGISTRY.histogram(name, help, labels, buckets)


def render_prometheus() -> str:
    return REGISTRY.render_prometheus()


def snapshot() -> dict:
    return REGISTRY.snapshot()


# ---------------------------------------------------------------------------
# HTTP exposition (comet --metrics-port; dist_smoke scrape target)
# ---------------------------------------------------------------------------


class MetricsServer:
    """Stdlib HTTP exposition server on a daemon thread.

    ``GET /metrics`` — Prometheus text (the scrape target);
    ``GET /v1/metrics`` — the JSON snapshot;
    ``GET /healthz`` — ``{"status": "ok", **health_extra}``.
    """

    def __init__(self, port: int, host: str = "127.0.0.1",
                 registry: Optional[MetricsRegistry] = None,
                 health_extra: Optional[dict] = None):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        registry = registry if registry is not None else REGISTRY
        extra = dict(health_extra or {})

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _reply(self, code: int, body: bytes,
                       content_type: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # scrapes are periodic noise
                pass

            def do_GET(self):
                if self.path == "/metrics":
                    self._reply(
                        200,
                        registry.render_prometheus().encode(),
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                elif self.path.split("?", 1)[0] == "/debug/profile":
                    # bounded on-demand profile capture: the worker-side
                    # per-request opt-in (moose_tpu/profiling.py)
                    from . import profiling

                    query = (
                        self.path.split("?", 1)[1]
                        if "?" in self.path else ""
                    )
                    status, payload = profiling.handle_profile_request(
                        query
                    )
                    self._reply(
                        status,
                        json.dumps(payload).encode(),
                        "application/json",
                    )
                elif self.path == "/v1/metrics":
                    self._reply(
                        200,
                        json.dumps(registry.snapshot()).encode(),
                        "application/json",
                    )
                elif self.path == "/healthz":
                    self._reply(
                        200,
                        json.dumps({"status": "ok", **extra}).encode(),
                        "application/json",
                    )
                else:
                    self._reply(
                        404,
                        json.dumps(
                            {"error": "NotFound", "path": self.path}
                        ).encode(),
                        "application/json",
                    )

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_port
        self.host = host
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"moose-metrics-{self.port}",
        )
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


def serve_http(port: int, host: str = "127.0.0.1",
               health_extra: Optional[dict] = None) -> MetricsServer:
    """Start the metrics exposition server; returns it (``.port`` is
    resolved when ``port`` was 0)."""
    return MetricsServer(port, host=host, health_extra=health_extra)
