"""Sessions: the abstract host-primitive interface protocol kernels are
written against.

This reproduces the reference's architecturally load-bearing trick
(``moose/src/execution/{synchronous,symbolic}.rs``): protocol kernels are
written ONCE against an abstract session and serve both as the executable
implementation (EagerSession -> jnp on device) and as the compiler's lowering
rules (SymbolicSession -> append host-level ops to a new graph).  Under JAX
the eager path is itself traceable, so a whole computation jit-compiles to a
single fused XLA program.

The session's method surface is the host dialect: every method takes the
*host placement name* the op is pinned to.  Protocol dialects (replicated/
additive/mirrored) are pure-Python compositions of these methods and never
touch arrays directly.
"""

from __future__ import annotations

import secrets
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import dtypes as dt
from ..dialects import host
from . import drawledger
from ..values import (
    HostBitTensor,
    HostFixedTensor,
    HostPrfKey,
    HostRingTensor,
    HostSeed,
    HostShape,
    HostTensor,
)


class EagerSession:
    """Direct on-device execution of host kernels (reference SyncSession,
    execution/synchronous.rs:20-27).

    ``master_key`` seeds PRF-key generation: fresh keys are derived on device
    from it per key-gen counter, so a jitted program can take the master key
    as a runtime argument and reuse the compiled program across sessions with
    fresh randomness (the reference's LocalRuntime likewise generates all
    party keys inside one process).
    """

    def __init__(self, session_id: Optional[str] = None, master_key=None,
                 key_domain: int = 0):
        self.session_id = session_id or secrets.token_hex(8)
        # lazy: physical/worker plans feed every PRF key as a runtime
        # input and never touch the master key, yet they construct one
        # session per segment (or per op, on the per-op rung) — drawing
        # entropy and device-putting it on every construction would tax
        # exactly those hot paths
        self._master_arr = (
            None
            if master_key is None
            else jnp.asarray(master_key, dtype=jnp.uint32)
        )
        self._key_counter = 0
        # distinct domains partition the key-derivation nonce space, so
        # several sessions sharing one master key (the segmented-jit
        # executor runs one session per graph segment) never collide
        self._key_domain = int(key_domain)
        self._setup_cache: dict[str, object] = {}

    @property
    def _master(self):
        if self._master_arr is None:
            self._master_arr = jnp.asarray(
                np.frombuffer(secrets.token_bytes(16), dtype=np.uint32),
                dtype=jnp.uint32,
            )
        return self._master_arr

    # -- setup cache (reference execution/synchronous.rs:297-307) ----------

    def replicated_setup(self, rep_plc):
        from ..dialects import replicated

        cache_key = (rep_plc.name, rep_plc.owners)
        cached = self._setup_cache.get(cache_key)
        if cached is None:
            cached = replicated.gen_setup(self, rep_plc)
            self._setup_cache[cache_key] = cached
        return cached

    # -- PRF keys & seeds --------------------------------------------------

    def key_gen(self, plc: str) -> HostPrfKey:
        from ..dialects import ring

        idx = self._key_counter
        self._key_counter += 1
        nonce = np.array(
            [idx, 0x6B657921 ^ self._key_domain, idx ^ 0xDEADBEEF, 1],
            np.uint32,
        )
        # origin = session key index: the i-th eager key_gen corresponds
        # to the i-th PrfKeyGen the symbolic lowering emits (same dialect
        # code, same walk order), which is what lets the draw oracle match
        # runtime draws to the static per-(party, key) report.
        return HostPrfKey(
            ring.mix_seed(self._master, nonce), plc, origin=("key", idx)
        )

    def derive_seed(self, plc: str, key: HostPrfKey, sync_key: bytes) -> HostSeed:
        seed = host.derive_seed(
            key, sync_key, plc, session_id=self.session_id
        )
        seed.origin = (getattr(key, "origin", None), sync_key)
        return seed

    def sample_uniform_seeded(self, plc, shp, seed, width: int):
        drawledger.record_host_draw(plc, seed, "ring", shp.value, width)
        return host.sample_uniform_seeded(shp, seed, width, plc)

    def sample_bits_seeded(self, plc, shp, seed, width: int):
        drawledger.record_host_draw(plc, seed, "bits", shp.value, width)
        return host.sample_bits_seeded(shp, seed, width, plc)

    def sample_bit_tensor_seeded(self, plc, shp, seed):
        drawledger.record_host_draw(plc, seed, "bit_tensor", shp.value, None)
        return host.sample_bit_tensor_seeded(shp, seed, plc)

    # -- value movement ----------------------------------------------------

    def place(self, plc: str, x):
        """Claim/move a value onto a host placement.  Eagerly a relabel; in
        distributed execution the compiler's networking pass turns
        cross-host dataflow edges into Send/Recv pairs."""
        return host.place(x, plc)

    # -- structural / metadata --------------------------------------------

    def shape(self, plc, x) -> HostShape:
        return host.shape(x, plc)

    def constant(self, plc, value, dtype=None):
        return host.constant(value, plc, dtype)

    def fill(self, plc, shp, value, ty_name: str):
        return host.fill(shp, value, plc, ty_name)

    def zeros(self, plc, shp, dtype=dt.float64):
        return host.zeros(shp, dtype, plc)

    def ones(self, plc, shp, dtype=dt.float64):
        return host.ones(shp, dtype, plc)

    def ring_zeros(self, plc, shp, width: int):
        return host.ring_zeros(shp, width, plc)

    def ring_constant(self, plc, ints, width: int):
        return host.ring_constant(ints, width, plc)

    def reshape(self, plc, x, shp):
        return host.reshape(x, shp, plc)

    def transpose(self, plc, x, axes=None):
        return host.transpose(x, plc, axes)

    def expand_dims(self, plc, x, axis):
        return host.expand_dims(x, plc, axis=axis)

    def squeeze(self, plc, x, axis=None):
        return host.squeeze(x, plc, axis=axis)

    def concat(self, plc, xs, axis=0):
        return host.concat(xs, axis, plc)

    def index_axis(self, plc, x, axis, index):
        return host.index_axis(x, axis, index, plc)

    def slice(self, plc, x, begin, end):
        return host.slice_(x, begin, end, plc)

    def strided_slice(self, plc, x, slices):
        return host.strided_slice(x, slices, plc)

    def broadcast(self, plc, x, shp):
        return host.broadcast(x, shp, plc)

    def diag(self, plc, x):
        return host.diag(x, plc)

    def shl_dim(self, plc, x, amount, bit_length):
        return host.shl_dim(x, amount, bit_length, plc)

    def at_least_2d(self, plc, x, to_column_vector=False):
        return host.at_least_2d(x, to_column_vector, plc)

    # -- arithmetic (dispatch on value kind) -------------------------------

    @staticmethod
    def _is_ring(x):
        return isinstance(x, HostRingTensor)

    def add(self, plc, x, y):
        if self._is_ring(x):
            return host.ring_add(x, y, plc)
        return host.add(x, y, plc)

    def sub(self, plc, x, y):
        if self._is_ring(x):
            return host.ring_sub(x, y, plc)
        return host.sub(x, y, plc)

    def mul(self, plc, x, y):
        if self._is_ring(x):
            return host.ring_mul(x, y, plc)
        if isinstance(x, HostBitTensor):
            return host.bit_and(x, y, plc)
        return host.mul(x, y, plc)

    def div(self, plc, x, y):
        return host.div(x, y, plc)

    def dot(self, plc, x, y):
        if self._is_ring(x):
            return host.ring_dot(x, y, plc)
        return host.dot(x, y, plc)

    def conv2d(self, plc, x, k, strides=(1, 1), padding="VALID"):
        if self._is_ring(x):
            return host.ring_conv2d(x, k, strides, padding, plc)
        return host.conv2d(x, k, strides, padding, plc)

    def im2col(self, plc, x, kh, kw, strides=(1, 1), padding="VALID"):
        return host.ring_im2col(x, kh, kw, strides, padding, plc)

    def avg_pool2d(self, plc, x, pool, strides=None, padding="VALID"):
        return host.avg_pool2d(x, pool, strides, padding, plc)

    def max_pool2d(self, plc, x, pool, strides=None, padding="VALID"):
        return host.max_pool2d(x, pool, strides, padding, plc)

    def neg(self, plc, x):
        if self._is_ring(x):
            return host.ring_neg(x, plc)
        return host.neg_(x, plc)

    def sum(self, plc, x, axis=None):
        if self._is_ring(x):
            return host.ring_sum(x, axis, plc)
        return host.sum_(x, axis, plc)

    def mean(self, plc, x, axis=None):
        return host.mean(x, axis, plc)

    def shl(self, plc, x, amount: int):
        return host.ring_shl(x, amount, plc)

    def shr(self, plc, x, amount: int):
        return host.ring_shr(x, amount, plc)

    def shr_arith(self, plc, x, amount: int):
        return host.ring_shr_arith(x, amount, plc)

    # -- bits --------------------------------------------------------------

    def xor(self, plc, x, y):
        return host.bit_xor(x, y, plc)

    def and_(self, plc, x, y):
        return host.bit_and(x, y, plc)

    def or_(self, plc, x, y):
        return host.bit_or(x, y, plc)

    def bit_neg(self, plc, x):
        return host.bit_neg(x, plc)

    def bit_extract(self, plc, x, bit_idx: int):
        return host.ring_bit_extract(x, bit_idx, plc)

    def ring_inject(self, plc, b, bit_idx: int, width: int):
        return host.ring_inject(b, bit_idx, width, plc)

    def decompose_bits(self, plc, x):
        return host.ring_decompose_bits(x, plc)

    def compose_bits(self, plc, b, width: int):
        return host.ring_compose_bits(b, width, plc)

    # -- fixed-point -------------------------------------------------------

    def ring_fixedpoint_encode(self, plc, x, frac: int, width: int):
        return host.ring_fixedpoint_encode(x, frac, width, plc)

    def ring_fixedpoint_decode(self, plc, x, frac: int, dtype=dt.float64):
        return host.ring_fixedpoint_decode(x, frac, plc, dtype)

    def ring_fixedpoint_mean(self, plc, x, axis, frac: int):
        return host.ring_fixedpoint_mean(x, axis, frac, plc)

    # -- plaintext math ----------------------------------------------------

    def exp(self, plc, x):
        return host.exp(x, plc)

    def log(self, plc, x):
        return host.log(x, plc)

    def log2(self, plc, x):
        return host.log2(x, plc)

    def sqrt(self, plc, x):
        return host.sqrt(x, plc)

    def sigmoid(self, plc, x):
        return host.sigmoid(x, plc)

    def relu(self, plc, x):
        return host.relu(x, plc)

    def abs(self, plc, x):
        return host.abs_(x, plc)

    def sign(self, plc, x):
        return host.sign(x, plc)

    def pow2(self, plc, x):
        return host.pow2(x, plc)

    def softmax(self, plc, x, axis):
        return host.softmax(x, axis, plc)

    def argmax(self, plc, x, axis):
        return host.argmax(x, axis, plc)

    def maximum(self, plc, xs):
        return host.maximum(xs, plc)

    def inverse(self, plc, x):
        return host.inverse(x, plc)

    def less(self, plc, x, y):
        return host.less(x, y, plc)

    def greater(self, plc, x, y):
        return host.greater(x, y, plc)

    def equal(self, plc, x, y):
        return host.equal(x, y, plc)

    def mux(self, plc, s, x, y):
        return host.mux(s, x, y, plc)

    def cast(self, plc, x, target: dt.DType):
        return host.cast(x, target, plc)

    def select(self, plc, x, axis, index):
        return host.select(x, axis, index, plc)

    def lift_ring_lo(self, plc, x, dtype=dt.uint64):
        """Reinterpret the low 64-bit limb of a ring tensor as a plaintext
        integer tensor (used for small non-negative values, e.g. revealed
        argmax indices)."""
        return HostTensor(x.lo, plc, dtype)

    # -- host fixed-point wrappers (compositions of the ring methods, kept
    #    on the session so every dialect path is session-routed and thus
    #    symbolically traceable) ------------------------------------------

    def fixedpoint_encode(self, plc, x, integ: int, frac: int, width: int):
        return HostFixedTensor(
            self.ring_fixedpoint_encode(plc, x, frac, width), integ, frac
        )

    def fixedpoint_decode(self, plc, x, dtype=dt.float64):
        return self.ring_fixedpoint_decode(
            plc, x.tensor, x.fractional_precision, dtype
        )
