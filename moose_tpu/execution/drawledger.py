"""Runtime PRF-draw accounting: the dynamic half of the MSA8xx oracle.

The keystream analysis (:mod:`moose_tpu.compilation.analysis.keystream`)
derives per-(party, key) draw sequences *statically* from the graph; this
module counts what the runtime *actually* draws so the two can be asserted
equal (the draw oracle, ``tests/test_keystream_oracle.py``).  Every
bit-exactness guarantee in the system — kernels-on/off identity, snapshot
probe digests, chaos-replay determinism — rests on the invariant that each
execution path consumes each party's PRF streams in the same order from the
correct keys; the oracle is what turns that from convention into a checked
property.

Instrumented choke points:

- :class:`~moose_tpu.execution.session.EagerSession` ``key_gen`` /
  ``derive_seed`` / ``sample_*`` — the per-host layout (logical dialect,
  physical lowered plans, distributed workers all funnel through it).
- :class:`~moose_tpu.parallel.spmd.SpmdSession` ``sample_bank`` /
  ``sample`` / ``sample_bit_bank`` — the party-stacked layout.  The
  kernels' ``_ReplaySession`` (pre-drawn randomness fed back to fallback
  paths) is a *different* class and is deliberately NOT instrumented:
  replays re-consume draws already counted, so counting them would
  double-book exactly the discipline the oracle certifies.

Recording is opt-in and nestable; with no active ledger the hooks are a
single ``if not _LEDGERS`` test, so hot paths pay nothing.  Events carry
Python-level metadata only (placement, key origin, element count) — no
array values — so recording works unchanged under ``jax.jit`` /
``jax.eval_shape`` tracing, where draws happen at trace time.  That is
load-bearing twice over: the static side of the stacked model IS an
abstract (shape-domain) trace, and jitted plans consume their streams when
traced, not when called.
"""

from __future__ import annotations

import dataclasses
import math
from contextlib import contextmanager
from typing import Any, Iterator, Optional


@dataclasses.dataclass(frozen=True)
class DrawEvent:
    """One PRF stream consumption.

    ``layout`` is ``"host"`` (per-host seeded draws) or ``"stacked"``
    (party-stacked session banks).  ``key`` identifies the key lineage:
    the producing op name / session key index for the host layout, the
    ``("master", domain)`` pair for stacked sessions.  ``sync`` is the
    derivation nonce (hex) when one exists.  ``elems`` counts drawn
    elements (for stacked banks: per party slice, excluding the leading
    party axis).  ``op`` is the graph op under execution when the
    interpreter tagged one.
    """

    layout: str
    kind: str  # "ring" | "bits" | "bit_tensor" | "bank" | "sample" | "bit_bank"
    placement: Optional[str]
    key: Any
    sync: Optional[str]
    elems: int
    width: Optional[int]
    op: Optional[str] = None


class DrawLedger:
    """Accumulates :class:`DrawEvent` records for one recording scope."""

    def __init__(self) -> None:
        self.events: list[DrawEvent] = []
        self.current_op: Optional[str] = None

    def record(self, event: DrawEvent) -> None:
        if event.op is None and self.current_op is not None:
            event = dataclasses.replace(event, op=self.current_op)
        self.events.append(event)

    # -- aggregation views used by the oracle ------------------------------

    def host_report(self) -> dict:
        """Per-(placement, key) counts, same shape as the static MSA805
        report's ``per_party_key`` section."""
        out: dict = {}
        for e in self.events:
            if e.layout != "host":
                continue
            slot = out.setdefault(
                (e.placement, _key_label(e.key)),
                {"draws": 0, "elems": 0, "ring_draws": 0, "bit_draws": 0},
            )
            slot["draws"] += 1
            slot["elems"] += e.elems
            if e.kind == "ring":
                slot["ring_draws"] += 1
            else:
                slot["bit_draws"] += 1
        return out

    def stacked_trace(self) -> list[tuple]:
        """The ordered (kind, width, elems) draw sequence of the stacked
        session — the stream-position ledger the oracle compares against
        the static shape-domain trace."""
        return [
            (e.kind, e.width, e.elems)
            for e in self.events
            if e.layout == "stacked"
        ]

    def stacked_counts(self) -> dict:
        out: dict = {"bank": 0, "sample": 0, "bit_bank": 0}
        for e in self.events:
            if e.layout == "stacked":
                out[e.kind] = out.get(e.kind, 0) + 1
        return out


def _key_label(key: Any) -> str:
    """Normalize key origins to a stable string label."""
    if isinstance(key, tuple):
        return ":".join(str(p) for p in key)
    return str(key)


# ---------------------------------------------------------------------------
# Recording scopes
# ---------------------------------------------------------------------------

_LEDGERS: list[DrawLedger] = []


def active() -> Optional[DrawLedger]:
    """The innermost active ledger, or None (the fast-path probe)."""
    return _LEDGERS[-1] if _LEDGERS else None


@contextmanager
def recording() -> Iterator[DrawLedger]:
    ledger = DrawLedger()
    _LEDGERS.append(ledger)
    try:
        yield ledger
    finally:
        _LEDGERS.remove(ledger)


# ---------------------------------------------------------------------------
# Hooks (called from the instrumented sessions; no-ops unless recording)
# ---------------------------------------------------------------------------


def _elems(shape: Any) -> int:
    try:
        return int(math.prod(int(d) for d in tuple(shape)))
    except (TypeError, ValueError):
        return 0


def record_host_draw(placement: str, seed: Any, kind: str, shape: Any,
                     width: Optional[int]) -> None:
    if not _LEDGERS:
        return
    origin = getattr(seed, "origin", None)
    key, sync = (origin if isinstance(origin, tuple) and len(origin) == 2
                 else (origin, None))
    event = DrawEvent(
        layout="host", kind=kind, placement=placement,
        key=key if key is not None else "<untracked>",
        sync=sync.hex() if isinstance(sync, bytes) else sync,
        elems=_elems(shape), width=width,
    )
    for ledger in _LEDGERS:
        ledger.record(event)


def record_stacked_draw(kind: str, shape: Any, width: Optional[int]) -> None:
    if not _LEDGERS:
        return
    event = DrawEvent(
        layout="stacked", kind=kind, placement=None,
        key="master", sync=None, elems=_elems(shape), width=width,
    )
    for ledger in _LEDGERS:
        ledger.record(event)


def tag_op(name: Optional[str]) -> None:
    """Label subsequent draws with the graph op under execution (set by
    the interpreter op walks when a ledger is active)."""
    for ledger in _LEDGERS:
        ledger.current_op = name
