"""Physical executor: runs a *lowered* (host-level) computation.

Counterpart of the reference's per-worker executor over compiled physical
graphs (``moose/src/execution/asynchronous.rs:456-529``), re-designed for
XLA: in local mode the whole host-op graph is traced through the eager
session under ``jax.jit`` into one fused program (PRF keys enter as runtime
arguments so the compiled program is reusable with fresh randomness); in
distributed mode (``identity=...``) the worker walks the same graph eagerly,
executing only its own ops, and Send/Receive hit the networking backend —
the exact role-filtering discipline of the reference
(execution/context.rs:60-74).
"""

from __future__ import annotations

import secrets
from typing import Any, Optional

import numpy as np

from .. import dtypes as dt
from ..computation import Computation
from ..dialects import host
from ..errors import (
    KernelError,
    MissingArgumentError,
    StorageError,
    UnimplementedError,
)
from ..values import (
    HostBitTensor,
    HostPrfKey,
    HostRingTensor,
    HostSeed,
    HostShape,
    HostString,
    HostTensor,
    HostUnit,
)
from .session import EagerSession


def _fresh_key_words(domain: str = "") -> np.ndarray:
    """Fresh 128-bit key words; under MOOSE_TPU_FIXED_KEYS (test-only,
    gated — see interpreter.master_key_words) derived from ``domain``
    (the key op's name) so lowered-plan evaluations are reproducible."""
    import os

    if os.environ.get("MOOSE_TPU_FIXED_KEYS"):
        from .interpreter import master_key_words

        return master_key_words(f"physical|{domain}")
    return np.frombuffer(secrets.token_bytes(16), dtype=np.uint32)


def _ring_width_of(ty_name: str) -> int:
    return 128 if "128" in ty_name else 64


def _sample_from_seed(sess, plc, shp, seed, ret_name: str, attrs):
    """Shared Sample/SampleSeeded dispatch: bit tensor vs bit-valued ring
    (max_value == 1) vs uniform ring draw."""
    if ret_name == "HostBitTensor":
        return sess.sample_bit_tensor_seeded(plc, shp, seed)
    width = _ring_width_of(ret_name)
    if attrs.get("max_value") == 1:
        return sess.sample_bits_seeded(plc, shp, seed, width)
    return sess.sample_uniform_seeded(plc, shp, seed, width)


def execute_kernel(sess: EagerSession, op, plc: str, args: list):
    """Execute one host-level operation with concrete values."""
    kind = op.kind
    A = op.attributes
    ret = op.signature.return_type

    if kind == "Identity":
        return sess.place(plc, args[0])
    if kind == "Constant":
        value = A["value"]
        if ret.name == "HostShape":
            return HostShape(tuple(int(d) for d in value), plc)
        if ret.name == "HostString":
            return HostString(value, plc)
        if ret.name.startswith("HostRing"):
            return sess.ring_constant(plc, value, _ring_width_of(ret.name))
        if ret.name == "HostBitTensor":
            import jax.numpy as jnp

            return HostBitTensor(
                jnp.asarray(np.asarray(value).astype(np.uint8)), plc
            )
        return sess.constant(plc, np.asarray(value), ret.dtype)
    if kind == "Fill":
        return sess.fill(plc, args[0], A["value"], ret.name)
    if kind == "Zeros":
        return sess.zeros(plc, args[0], ret.dtype or dt.float64)
    if kind == "Ones":
        return sess.ones(plc, args[0], ret.dtype or dt.float64)
    if kind == "PrfKeyGen":
        # normally handled by the plan (keys enter as runtime inputs so the
        # jitted program stays reusable); eager fallback for direct calls
        # (domain = op name so fixed-keys mode gives DISTINCT keys per op)
        import jax.numpy as jnp

        return HostPrfKey(
            jnp.asarray(_fresh_key_words(op.name)), plc, origin=op.name
        )
    if kind == "DeriveSeed":
        return sess.derive_seed(plc, args[0], A["sync_key"])
    if kind == "SampleSeeded":
        return _sample_from_seed(sess, plc, args[0], args[1], ret.name, A)
    if kind == "Sample":
        # eager/distributed fallback for unseeded draws; the plan-driven
        # path feeds the fresh seed through `keys` instead
        # (_run_physical_ops)
        import jax.numpy as jnp

        seed = HostSeed(
            jnp.asarray(_fresh_key_words(op.name)), plc,
            origin=(("fresh", op.name), None),
        )
        return _sample_from_seed(sess, plc, args[0], seed, ret.name, A)
    if kind == "Add":
        return sess.add(plc, args[0], args[1])
    if kind == "Sub":
        return sess.sub(plc, args[0], args[1])
    if kind == "Mul":
        return sess.mul(plc, args[0], args[1])
    if kind == "Div":
        return sess.div(plc, args[0], args[1])
    if kind == "Dot":
        return sess.dot(plc, args[0], args[1])
    if kind == "Conv2D":
        return sess.conv2d(
            plc, args[0], args[1],
            tuple(A.get("strides", (1, 1))), A.get("padding", "VALID"),
        )
    if kind == "Im2Col":
        return sess.im2col(
            plc, args[0], A["kh"], A["kw"],
            tuple(A.get("strides", (1, 1))), A.get("padding", "VALID"),
        )
    if kind in ("AvgPool2D", "MaxPool2D"):
        method = (
            sess.avg_pool2d if kind == "AvgPool2D" else sess.max_pool2d
        )
        strides = A.get("strides")
        return method(
            plc, args[0], tuple(A["pool_size"]),
            tuple(strides) if strides is not None else None,
            A.get("padding", "VALID"),
        )
    if kind == "And":
        return sess.and_(plc, args[0], args[1])
    if kind == "Or":
        return sess.or_(plc, args[0], args[1])
    if kind == "Xor":
        return sess.xor(plc, args[0], args[1])
    if kind == "Neg":
        if isinstance(args[0], HostBitTensor):
            return sess.bit_neg(plc, args[0])
        return sess.neg(plc, args[0])
    if kind == "Sum":
        return sess.sum(plc, args[0], A.get("axis"))
    if kind == "Mean":
        return sess.mean(plc, args[0], A.get("axis"))
    if kind == "Shl":
        return sess.shl(plc, args[0], A["amount"])
    if kind == "Shr":
        if A.get("arithmetic"):
            return sess.shr_arith(plc, args[0], A["amount"])
        return sess.shr(plc, args[0], A["amount"])
    if kind == "BitExtract":
        return sess.bit_extract(plc, args[0], A["bit_idx"])
    if kind == "RingInject":
        return sess.ring_inject(
            plc, args[0], A["bit_idx"], _ring_width_of(ret.name)
        )
    if kind == "BitDecompose":
        return sess.decompose_bits(plc, args[0])
    if kind == "BitCompose":
        return sess.compose_bits(plc, args[0], _ring_width_of(ret.name))
    if kind == "RingFixedpointEncode":
        return sess.ring_fixedpoint_encode(
            plc, args[0], A["scaling_exp"], _ring_width_of(ret.name)
        )
    if kind == "RingFixedpointDecode":
        return sess.ring_fixedpoint_decode(
            plc, args[0], A["scaling_exp"], ret.dtype or dt.float64
        )
    if kind == "RingFixedpointMean":
        return sess.ring_fixedpoint_mean(
            plc, args[0], A.get("axis"), A["scaling_exp"]
        )
    if kind == "Cast":
        x = args[0]
        target = A["dtype"]
        if isinstance(x, HostRingTensor):
            x = sess.lift_ring_lo(plc, x, dt.uint64)
            if target.name == "uint64":
                return x
        return sess.cast(plc, x, target)
    if kind == "Exp":
        return sess.exp(plc, args[0])
    if kind == "Log":
        return sess.log(plc, args[0])
    if kind == "Log2":
        return sess.log2(plc, args[0])
    if kind == "Sqrt":
        return sess.sqrt(plc, args[0])
    if kind == "Sigmoid":
        return sess.sigmoid(plc, args[0])
    if kind == "Relu":
        return sess.relu(plc, args[0])
    if kind == "Abs":
        return sess.abs(plc, args[0])
    if kind == "Sign":
        return sess.sign(plc, args[0])
    if kind == "Pow2":
        return sess.pow2(plc, args[0])
    if kind == "Softmax":
        return sess.softmax(plc, args[0], A["axis"])
    if kind == "Argmax":
        return sess.argmax(plc, args[0], A["axis"])
    if kind == "Maximum":
        return sess.maximum(plc, args)
    if kind == "Inverse":
        return sess.inverse(plc, args[0])
    if kind == "Less":
        return sess.less(plc, args[0], args[1])
    if kind == "Greater":
        return sess.greater(plc, args[0], args[1])
    if kind == "Equal":
        return sess.equal(plc, args[0], args[1])
    if kind == "Mux":
        return sess.mux(plc, args[0], args[1], args[2])
    if kind == "Select":
        return sess.select(plc, args[0], A["axis"], args[1])
    if kind == "Reshape":
        return sess.reshape(plc, args[0], args[1])
    if kind == "Broadcast":
        return sess.broadcast(plc, args[0], args[1])
    if kind == "Slice":
        spec = A.get("slices", A.get("slice_spec"))
        if spec is not None:
            slices = tuple(
                Ellipsis
                if s == "..."
                else (slice(*s) if isinstance(s, (tuple, list)) else s)
                for s in spec
            )
            return sess.strided_slice(plc, args[0], slices)
        return sess.slice(plc, args[0], A["begin"], A["end"])
    if kind == "ExpandDims":
        return sess.expand_dims(plc, args[0], A["axis"])
    if kind == "Squeeze":
        return sess.squeeze(plc, args[0], A.get("axis"))
    if kind == "Concat":
        return sess.concat(plc, args, A.get("axis", 0))
    if kind == "IndexAxis":
        return sess.index_axis(plc, args[0], A["axis"], A["index"])
    if kind == "Transpose":
        return sess.transpose(plc, args[0], A.get("axes"))
    if kind == "Diag":
        return sess.diag(plc, args[0])
    if kind == "ShlDim":
        return sess.shl_dim(plc, args[0], A["amount"], A["bit_length"])
    if kind == "AtLeast2D":
        return sess.at_least_2d(plc, args[0], A.get("to_column_vector", False))
    if kind == "Shape":
        return sess.shape(plc, args[0])
    if kind == "AddN":
        # variadic sum (reference AddNOp, computation.rs Signature::variadic)
        out = args[0]
        for a in args[1:]:
            out = sess.add(plc, out, a)
        return out
    raise UnimplementedError(f"physical op {kind} ({op.name})")


_DYNAMIC_SHAPE_KINDS = frozenset({"Select"})


def _recv_sources(comp: Computation, order) -> dict:
    """Map each Receive op to the env name of its Send's input: in-process
    execution needs no rendezvous store — the received value IS the sent
    value (and expressing it as a dataflow edge lets the segmented
    executor carry it across segment boundaries like any other value)."""
    send_of: dict[str, str] = {}
    for n in order:
        op = comp.operations[n]
        if op.kind == "Send":
            send_of[op.attributes["rendezvous_key"]] = op.inputs[0]
    out = {}
    for n in order:
        op = comp.operations[n]
        if op.kind == "Receive":
            out[n] = send_of[op.attributes["rendezvous_key"]]
    return out


def _run_physical_ops(sess, comp, names, static_env, env, outputs, saves,
                      keys, dyn, recv_src, trace_ops=False,
                      fault_kinds=frozenset()):
    """Execute host-level ops in order against ``env`` — shared by the
    whole-graph core and the per-segment cores.  ``fault_kinds``
    (self-check jit candidates only) injects a synthetic divergence into
    ops of the listed kinds — see ``interpreter._fault_kinds``."""
    import jax
    import jax.numpy as jnp

    from .. import telemetry
    from .interpreter import _fault_perturb, _lift_array

    for n in names:
        op = comp.operations[n]
        plc = comp.placement_of(op).name
        if n in env:
            continue
        if op.kind == "Send":
            env[n] = HostUnit(plc)
            continue
        if op.kind == "Receive":
            env[n] = host.place(env[recv_src[n]], plc)
            continue
        if op.kind == "PrfKeyGen":
            env[n] = HostPrfKey(jnp.asarray(keys[n]), plc, origin=n)
            continue
        if op.kind == "Sample":
            # unseeded draw (reference SampleOp): fresh 128-bit seed per
            # evaluation, fed like PrfKeyGen keys so the jitted program
            # stays reusable
            env[n] = _sample_from_seed(
                sess, plc, env[op.inputs[0]],
                HostSeed(jnp.asarray(keys[n]), plc,
                         origin=(("fresh", n), None)),
                op.signature.return_type.name, op.attributes,
            )
            continue
        if op.kind in ("Input", "Load"):
            env[n] = _lift_array(dyn[n], op, plc)
            continue
        if op.kind == "Save":
            key = env[op.inputs[0]]
            if not isinstance(key, HostString):
                raise KernelError(
                    f"Save {n}: key must be a string, found "
                    f"{type(key).__name__}"
                )
            saves[(plc, key.value)] = env[op.inputs[1]]
            env[n] = HostUnit(plc)
            continue
        if op.kind == "Output":
            value = env[op.inputs[0]]
            env[n] = value
            # keyed by Output tag like the reference's executor
            # (execution/asynchronous.rs:623); op name when untagged
            outputs[op.attributes.get("tag", n)] = value
            continue
        args = [env[i] for i in op.inputs]
        if trace_ops:
            # block inside the span: async dispatch would otherwise
            # misattribute device time (see interpreter.build_plan)
            with telemetry.span(f"op:{op.kind}"):
                env[n] = jax.block_until_ready(
                    execute_kernel(sess, op, plc, args)
                )
        else:
            env[n] = execute_kernel(sess, op, plc, args)
        if fault_kinds and op.kind in fault_kinds:
            env[n] = _fault_perturb(env[n])


def _build_plan(comp: Computation, arguments: dict, use_jit: bool,
                segment_limit=None, jit_segments: bool = True,
                fault_kinds=frozenset()):
    """Build (and jit) the execution closure for one (computation,
    binding) pair; cached by PhysicalInterpreter across calls."""
    import jax

    order = comp.toposort_names()
    if any(comp.operations[n].kind in _DYNAMIC_SHAPE_KINDS for n in order):
        use_jit = False

    key_ops = [
        n for n in order
        if comp.operations[n].kind in ("PrfKeyGen", "Sample")
    ]
    dyn_names: list[str] = []
    static_env: dict[str, Any] = {}
    for n in order:
        op = comp.operations[n]
        plc = comp.placement_of(op).name
        if op.kind == "Input":
            val = arguments.get(n)
            if val is None:
                raise MissingArgumentError(f"missing argument {n!r}")
            if isinstance(val, str):
                static_env[n] = HostString(val, plc)
            else:
                dyn_names.append(n)
        elif op.kind == "Load":
            dyn_names.append(n)

    import weakref

    from .. import telemetry

    comp_ref = weakref.ref(comp)
    recv_src = _recv_sources(comp, order)
    # per-op spans in eager mode only (see interpreter.build_plan)
    trace_ops = telemetry.trace_ops_enabled() and not use_jit

    from .interpreter import _segment_limit

    limit = segment_limit if segment_limit is not None else _segment_limit()
    if use_jit and len(order) > limit:
        fn = _build_segmented_physical(
            comp_ref, order, static_env, dyn_names, key_ops, recv_src,
            limit, jit_segments, fault_kinds,
        )
        return order, key_ops, dyn_names, static_env, fn

    def core(keys: dict, dyn: dict):
        comp = comp_ref()
        if comp is None:  # pragma: no cover - defensive
            raise KernelError("computation was garbage-collected")
        sess = EagerSession()
        env: dict[str, Any] = dict(static_env)
        outputs: dict[str, Any] = {}
        saves: dict[tuple, Any] = {}
        _run_physical_ops(
            sess, comp, order, static_env, env, outputs, saves, keys,
            dyn, recv_src, trace_ops, fault_kinds,
        )
        return outputs, saves

    fn = jax.jit(core) if (use_jit and jit_segments) else core
    return order, key_ops, dyn_names, static_env, fn


def _build_segmented_physical(comp_ref, order, static_env, dyn_names,
                              key_ops, recv_src, limit=None,
                              jit_segments: bool = True,
                              fault_kinds=frozenset()):
    """Lowered-graph segmentation over the SHARED orchestrator
    (interpreter.build_segmented_runner).  Receive ops read their Send's
    input through ``recv_src``, so cross-segment transfers are ordinary
    boundary values; each segment receives only its own PRF keys."""
    from .interpreter import build_segmented_runner

    comp = comp_ref()

    def effective_inputs(n):
        op = comp.operations[n]
        if op.kind == "Receive":
            return [recv_src[op.name]]
        return op.inputs

    key_set = set(key_ops)

    def seg_exec(si, names, keys, dyn, env, outputs, saves):
        comp = comp_ref()
        if comp is None:  # pragma: no cover - defensive
            raise KernelError("computation was garbage-collected")
        sess = EagerSession()
        _run_physical_ops(
            sess, comp, names, static_env, env, outputs, saves,
            keys, dyn, recv_src, False, fault_kinds,
        )

    # per-segment key narrowing needs the chunking; compute it once and
    # hand the same result to the orchestrator
    from .interpreter import _segment_limit, plan_segments

    segmentation = plan_segments(
        order, static_env, effective_inputs,
        limit if limit is not None else _segment_limit(),
    )
    keys_of = [
        [n for n in names if n in key_set] for names in segmentation[0]
    ]

    return build_segmented_runner(
        order, static_env, dyn_names, effective_inputs, limit,
        jit_segments, seg_exec,
        lambda keys, si: {n: keys[n] for n in keys_of[si]},
        segmentation=segmentation,
    )


def _physical_plan_builder(comp, arguments, use_jit, segment_limit,
                           jit_segments, fault_kinds=frozenset()):
    """builder hook for the shared ``_SelfCheckRunner``: physical plans
    take every PRF key as a runtime input and bake sync keys as graph
    attributes, so eager and jitted execution from the same ``keys``
    dict must be bit-identical (no nonce pinning)."""
    plan = _build_plan(
        comp, arguments, use_jit, segment_limit=segment_limit,
        jit_segments=jit_segments, fault_kinds=fault_kinds,
    )
    return plan, plan[4]


# host-boundary / trivial kinds the per-op rung never jit-wraps: there
# is nothing to fuse and nothing the miscompile class can touch
_PER_OP_EAGER_KINDS = frozenset({
    "Input", "Load", "Save", "Output", "Send", "Receive", "PrfKeyGen",
    "Constant", "Identity",
})


def _physical_per_op_builder(comp, arguments, eager_plan, fault_kinds,
                             nonce_seed, pinned=()):
    """per-op-rung builder hook for lowered plans (the shared
    ``_SelfCheckRunner``'s ``per_op_builder``): ops take their PRF keys
    as runtime inputs — no nonce pinning needed — and each Receive reads
    its Send's input as an ordinary dataflow edge, so per-op programs
    compose exactly like segments do."""
    import weakref

    from .interpreter import _per_op_limit, _PerOpPlan, _SelfCheckBase

    order, key_ops, dyn_names, static_env, _ = eager_plan
    limit = _per_op_limit()
    if limit <= 0:
        return None
    seg_size = 1
    if len(order) > limit:
        # Too many ops for one-program-per-op validation (the cap bounds
        # how many tiny XLA programs the rung may compile).  Physical
        # plans are deterministic given their key dict, so the rung
        # still applies at coarser granularity: validate and pin
        # ``seg_size``-op CHUNKS — at least the ladder's finest segment
        # rung, grown until the chunk count fits the cap.  A bench-scale
        # lowered predictor (~10k host ops) lands here with only its
        # divergent chunks eager instead of the whole plan.
        finest = _SelfCheckBase.LADDER[-2]
        seg_size = max(finest, -(-len(order) // limit))
    comp_ref = weakref.ref(comp)
    recv_src = _recv_sources(comp, order)
    key_set = set(key_ops)

    def effective_inputs(n):
        op = comp.operations[n]
        if op.kind == "Receive":
            return [recv_src[op.name]]
        return op.inputs

    def seg_exec(si, names, keys, dyn, env, outputs, saves,
                 fault=frozenset()):
        comp = comp_ref()
        if comp is None:  # pragma: no cover - defensive
            raise KernelError("computation was garbage-collected")
        sess = EagerSession()
        _run_physical_ops(
            sess, comp, names, static_env, env, outputs, saves,
            keys, dyn, recv_src, False, fault,
        )

    always = {
        n for n in order
        if comp.operations[n].kind in _PER_OP_EAGER_KINDS
    }
    # chunking mirrors _PerOpPlan's own (consecutive seg_size slices of
    # the same order), so per-chunk key narrowing stays aligned
    keys_of = [
        [n for n in order[i:i + seg_size] if n in key_set]
        for i in range(0, len(order), seg_size)
    ]
    return _PerOpPlan(
        order, static_env, dyn_names, effective_inputs, seg_exec,
        fault_kinds,
        lambda keys, si: {n: keys[n] for n in keys_of[si]},
        always_eager=always, pinned=pinned, seg_size=seg_size,
    )


class PhysicalInterpreter:
    """Executes lowered computations with plan/jit caching (same weak-key
    discipline as the logical Interpreter)."""

    def __init__(self):
        import weakref

        self._cache = weakref.WeakKeyDictionary()
        # resolved plan shape of the most recent evaluate() — the
        # runtime lifts this into last_timings/last_plan
        self.last_plan_info: dict = {}

    def _plan_info(self, comp, use_jit, fn) -> dict:
        from .interpreter import _segment_limit, _SelfCheckRunner

        runner = getattr(fn, "__self__", None)
        if isinstance(runner, _SelfCheckRunner):
            return {
                "plan_mode": runner.plan_mode,
                "pinned_ops": runner.pinned_ops,
                "plan_state": runner.mode,
            }
        if not use_jit:
            mode = "eager"
        elif len(comp.operations) > _segment_limit():
            mode = "segmented"
        else:
            mode = "whole-graph"
        return {"plan_mode": mode, "pinned_ops": [], "plan_state": "static"}

    def evaluate(
        self,
        comp: Computation,
        storage: dict,
        arguments: Optional[dict] = None,
        use_jit: bool = True,
    ) -> dict:
        from .interpreter import _selfcheck_runs, heavy_jit_gate

        arguments = arguments or {}
        gated = heavy_jit_gate(len(comp.operations), use_jit)
        selfcheck = use_jit and not gated and _selfcheck_runs() > 0
        use_jit = gated
        per_comp = self._cache.get(comp)
        if per_comp is None:
            per_comp = self._cache[comp] = {}
        from .interpreter import binding_cache_key

        cache_key = binding_cache_key(arguments, (use_jit, selfcheck))
        plan = per_comp.get(cache_key)
        if plan is None:
            if selfcheck:
                from .interpreter import _SelfCheckRunner

                runner = _SelfCheckRunner(
                    comp, arguments, _selfcheck_runs(),
                    builder=_physical_plan_builder, pin_nonces=False,
                    per_op_builder=_physical_per_op_builder,
                    plan_key="physical",
                )
                order, key_ops, dyn_names, static_env, _ = runner.eager_plan
                plan = (order, key_ops, dyn_names, static_env, runner.run)
            else:
                plan = _build_plan(comp, arguments, use_jit)
            per_comp[cache_key] = plan
        order, key_ops, dyn_names, static_env, fn = plan

        from .interpreter import _device_cache

        dyn = {}
        for n in dyn_names:
            op = comp.operations[n]
            plc = comp.placement_of(op).name
            if op.kind == "Input":
                val = arguments[n]
                if not isinstance(val, np.ndarray):
                    val = np.asarray(val)
                dyn[n] = _device_cache.put(val)
            else:  # Load
                key_op = comp.operations[op.inputs[0]]
                key = key_op.attributes.get("value")
                if key is None:
                    key_val = static_env.get(op.inputs[0])
                    if isinstance(key_val, HostString):
                        key = key_val.value
                store = storage.get(plc, {})
                if key not in store:
                    raise StorageError(
                        f"no value for key {key!r} in storage of {plc!r}"
                    )
                val = store[key]
                if not isinstance(val, np.ndarray):
                    val = np.asarray(val)
                dyn[n] = _device_cache.put(val)

        from .. import telemetry

        keys = {n: _fresh_key_words(n) for n in key_ops}
        with telemetry.span("execute", jit=use_jit) as sp:
            outputs, saves = fn(keys, dyn)
            # plan shape AFTER the run: a validating evaluation may have
            # promoted/demoted/pinned during the call
            info = self._plan_info(comp, use_jit, fn)
            self.last_plan_info = info
            sp.attrs["plan_mode"] = info["plan_mode"]
            sp.attrs["pinned_ops"] = len(info["pinned_ops"])

        from .interpreter import (
            _save_user_value,
            _to_user_value,
            ordered_output_names,
            prefetch_to_host,
        )

        # start every device-to-host transfer before any conversion
        # blocks (serialized per-output fetches dominated latency on
        # tunneled setups — BENCH_r05 result_to_host_latency_s)
        prefetch_to_host(outputs, saves)
        for (plc_name, key), value in saves.items():
            storage.setdefault(plc_name, {})[key] = _save_user_value(value)
        return {
            name: _to_user_value(outputs[name])
            for name in ordered_output_names(outputs)
        }


_DEFAULT = PhysicalInterpreter()


def execute_physical(
    comp: Computation,
    storage: dict,
    arguments: Optional[dict] = None,
    use_jit: bool = True,
) -> dict:
    """Execute a lowered computation locally (all hosts in one process,
    one fused XLA program)."""
    return _DEFAULT.evaluate(comp, storage, arguments, use_jit)
