"""SymbolicSession: the compiler half of the session duality.

This is the TPU-native reproduction of the reference's load-bearing trick
(``moose/src/execution/symbolic.rs:139-200``): protocol kernels are written
once against the abstract session surface, and *lowering is just running
them with a session that records host-level operations into a new
``Computation`` instead of executing them*.

Symbolic values reuse the concrete value dataclasses (``HostRingTensor``,
``HostBitTensor``, ...) so all dialect structure/introspection (isinstance
checks, ``.width``, ``.plc``, ``.shape``) works unchanged — only the array
payloads are replaced by :class:`SymArray` handles naming the producing
operation.  This mirrors the reference's ``Symbolic<T>`` hybrid values
(symbolic.rs:21-31): structure concrete, leaves symbolic.

Shapes are tracked concretely through the trace (XLA requires static shapes;
SURVEY §7 hard part (e)): every session method infers its output shape with
numpy shape rules on zero-stride dummies, so ``sess.shape`` can answer at
lowering time and the lowered graph bakes shapes into Constant ops.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import numpy as np

from .. import dtypes as dt
from ..computation import (
    Computation,
    HostPlacement,
    Operation,
    Signature,
    Ty,
)
from ..errors import CompilationError, TypeMismatchError
from ..values import (
    HostBitTensor,
    HostFixedTensor,
    HostPrfKey,
    HostRingTensor,
    HostSeed,
    HostShape,
    HostString,
    HostTensor,
    HostUnit,
)


class SymArray:
    """Array payload of a symbolic value: names the producing op and tracks
    the static shape."""

    __slots__ = ("op", "_shape")

    def __init__(self, op: str, shape: Optional[tuple]):
        self.op = op
        self._shape = None if shape is None else tuple(int(d) for d in shape)

    @property
    def shape(self) -> tuple:
        if self._shape is None:
            raise CompilationError(
                f"shape of symbolic value {self.op!r} is data-dependent "
                "(produced by Select) and cannot be used at lowering time"
            )
        return self._shape

    @property
    def ndim(self) -> int:
        return len(self.shape)

    def __repr__(self):
        return f"SymArray({self.op!r}, {self._shape})"


@dataclasses.dataclass
class SymShape(HostShape):
    """A shape value during lowering: concrete tuple + optional producing
    op (materialized lazily as a Constant when used as an op input)."""

    op: Optional[str] = None


def _dummy(shape: tuple):
    """Zero-stride dummy array for numpy shape-rule inference (no
    allocation)."""
    return np.broadcast_to(np.int8(0), tuple(shape))


def _dot_shape(sa: tuple, sb: tuple) -> tuple:
    la, lb = len(sa), len(sb)
    if la == 2 and lb == 2:
        return (sa[0], sb[1])
    if la == 2 and lb == 1:
        return (sa[0],)
    if la == 1 and lb == 2:
        return (sb[1],)
    if la == 1 and lb == 1:
        return ()
    raise CompilationError(f"dot on ranks {la} x {lb} not supported")


def _reduce_shape(shape: tuple, axis) -> tuple:
    if axis is None:
        return ()
    return tuple(d for i, d in enumerate(shape) if i != axis % len(shape))


def _tensor_ty(dtype: dt.DType) -> Ty:
    if dtype.is_boolean:
        return Ty("HostBitTensor", dt.bool_)
    name = {
        "float32": "HostFloat32Tensor",
        "float64": "HostFloat64Tensor",
        "int32": "HostInt32Tensor",
        "int64": "HostInt64Tensor",
        "uint32": "HostUint32Tensor",
        "uint64": "HostUint64Tensor",
    }[dtype.name]
    return Ty(name, dtype)


def _ring_ty(width: int) -> Ty:
    return Ty(f"HostRing{width}Tensor")


_BIT_TY = Ty("HostBitTensor", dt.bool_)
_SHAPE_TY = Ty("HostShape")
_SEED_TY = Ty("HostSeed")
_KEY_TY = Ty("HostPrfKey")
_STRING_TY = Ty("HostString")
_UNIT_TY = Ty("Unit")


def _ty_of(v) -> Ty:
    if isinstance(v, HostRingTensor):
        return _ring_ty(v.width)
    if isinstance(v, HostBitTensor):
        return _BIT_TY
    if isinstance(v, HostTensor):
        return _tensor_ty(v.dtype)
    if isinstance(v, HostShape):
        return _SHAPE_TY
    if isinstance(v, HostSeed):
        return _SEED_TY
    if isinstance(v, HostPrfKey):
        return _KEY_TY
    if isinstance(v, HostString):
        return _STRING_TY
    if isinstance(v, HostUnit):
        return _UNIT_TY
    raise TypeMismatchError(f"no Ty for {type(v).__name__}")


class SymbolicSession:
    """Records host-level operations into ``self.computation``.

    Implements the full :class:`EagerSession` method surface; dialect code
    (replicated/additive/mirrored/fixedpoint/logical) runs unchanged on top
    and its host-primitive calls become graph nodes.
    """

    def __init__(self, computation: Optional[Computation] = None):
        self.computation = computation or Computation()
        self._counter = 0
        self._setup_cache: dict = {}
        self._const_cache: dict = {}
        self._placements = self.computation.placements

    # ------------------------------------------------------------------
    # Graph construction
    # ------------------------------------------------------------------

    def fresh_name(self, prefix: str = "op") -> str:
        name = f"{prefix}_{self._counter}"
        self._counter += 1
        return name

    def _ensure_host_placement(self, plc: str):
        if plc not in self.computation.placements:
            self.computation.add_placement(HostPlacement(plc))

    def add_operation(
        self,
        kind: str,
        inputs: list,
        plc: str,
        sig: Signature,
        attributes: Optional[dict] = None,
        name: Optional[str] = None,
    ) -> str:
        self._ensure_host_placement(plc)
        name = name or self.fresh_name()
        self.computation.add_operation(
            Operation(
                name=name,
                kind=kind,
                inputs=list(inputs),
                placement_name=plc,
                signature=sig,
                attributes=attributes or {},
            )
        )
        return name

    def _name_of(self, v) -> str:
        """The producing op of a symbolic value, materializing constants
        lazily for shapes/strings."""
        if isinstance(v, HostRingTensor):
            return v.lo.op
        if isinstance(v, (HostTensor, HostBitTensor, HostSeed, HostPrfKey)):
            return v.value.op
        if isinstance(v, SymShape):
            if v.op is not None:
                return v.op
            return self._shape_const(v.value, v.plc)
        if isinstance(v, HostShape):
            return self._shape_const(v.value, v.plc)
        if isinstance(v, HostString):
            known = getattr(v, "op", None)
            return known or self._string_const(v.value, v.plc)
        raise TypeMismatchError(
            f"cannot use {type(v).__name__} as a symbolic op input"
        )

    def _shape_const(self, value: tuple, plc: str) -> str:
        key = ("shape", tuple(value), plc)
        cached = self._const_cache.get(key)
        if cached is None:
            cached = self.add_operation(
                "Constant", [], plc,
                Signature((), _SHAPE_TY),
                {"value": tuple(int(d) for d in value)},
            )
            self._const_cache[key] = cached
        return cached

    def _string_const(self, value: str, plc: str) -> str:
        key = ("string", value, plc)
        cached = self._const_cache.get(key)
        if cached is None:
            cached = self.add_operation(
                "Constant", [], plc,
                Signature((), _STRING_TY),
                {"value": value},
            )
            self._const_cache[key] = cached
        return cached

    def _emit(self, kind, args, plc, ret_ty, attributes=None, name=None):
        inputs = [self._name_of(a) for a in args]
        sig = Signature(tuple(_ty_of(a) for a in args), ret_ty)
        return self.add_operation(kind, inputs, plc, sig, attributes, name)

    # Typed output constructors ----------------------------------------

    def _ring(self, op: str, shape, width: int, plc: str) -> HostRingTensor:
        lo = SymArray(op, shape)
        hi = SymArray(op, shape) if width == 128 else None
        return HostRingTensor(lo, hi, width, plc)

    def _bit(self, op: str, shape, plc: str) -> HostBitTensor:
        return HostBitTensor(SymArray(op, shape), plc)

    def _tensor(self, op: str, shape, plc: str, dtype: dt.DType):
        return HostTensor(SymArray(op, shape), plc, dtype)

    def _like(self, op: str, shape, x, plc: Optional[str] = None):
        """Output value of the same leaf kind as ``x`` with a new shape."""
        plc = plc or x.plc
        if isinstance(x, HostRingTensor):
            return self._ring(op, shape, x.width, plc)
        if isinstance(x, HostBitTensor):
            return self._bit(op, shape, plc)
        if isinstance(x, HostPrfKey):
            return HostPrfKey(SymArray(op, shape), plc)
        if isinstance(x, HostSeed):
            return HostSeed(SymArray(op, shape), plc)
        return self._tensor(op, shape, plc, x.dtype)

    # ------------------------------------------------------------------
    # Setup cache (same protocol as EagerSession)
    # ------------------------------------------------------------------

    def replicated_setup(self, rep_plc):
        from ..dialects import replicated

        cache_key = (rep_plc.name, rep_plc.owners)
        cached = self._setup_cache.get(cache_key)
        if cached is None:
            cached = replicated.gen_setup(self, rep_plc)
            self._setup_cache[cache_key] = cached
        return cached

    # ------------------------------------------------------------------
    # PRF keys & seeds
    # ------------------------------------------------------------------

    def key_gen(self, plc: str) -> HostPrfKey:
        op = self._emit("PrfKeyGen", [], plc, _KEY_TY)
        return HostPrfKey(SymArray(op, (4,)), plc)

    def derive_seed(self, plc, key, sync_key: bytes) -> HostSeed:
        op = self._emit(
            "DeriveSeed", [key], plc, _SEED_TY, {"sync_key": sync_key}
        )
        return HostSeed(SymArray(op, (4,)), plc)

    def sample_uniform_seeded(self, plc, shp, seed, width: int):
        op = self._emit(
            "SampleSeeded", [shp, seed], plc, _ring_ty(width), {}
        )
        return self._ring(op, tuple(shp.value), width, plc)

    def sample_bits_seeded(self, plc, shp, seed, width: int):
        op = self._emit(
            "SampleSeeded", [shp, seed], plc, _ring_ty(width),
            {"max_value": 1},
        )
        return self._ring(op, tuple(shp.value), width, plc)

    def sample_bit_tensor_seeded(self, plc, shp, seed):
        op = self._emit(
            "SampleSeeded", [shp, seed], plc, _BIT_TY, {"max_value": 1}
        )
        return self._bit(op, tuple(shp.value), plc)

    # ------------------------------------------------------------------
    # Value movement
    # ------------------------------------------------------------------

    def place(self, plc: str, x):
        if getattr(x, "plc", plc) == plc:
            return x
        if isinstance(x, HostShape):
            return SymShape(x.value, plc, getattr(x, "op", None))
        if isinstance(x, HostString):
            return HostString(x.value, plc)
        if isinstance(x, HostUnit):
            return HostUnit(plc)
        # A cross-host move: an Identity op pinned to the destination; the
        # networking pass later splits the edge into Send/Receive
        # (reference compilation/networking.rs:77-119).
        ret = _ty_of(x)
        op = self._emit("Identity", [x], plc, ret)
        return self._like(op, self._shape_of_leaf(x), x, plc=plc)

    @staticmethod
    def _shape_of_leaf(x) -> Optional[tuple]:
        arr = x.lo if isinstance(x, HostRingTensor) else x.value
        return arr._shape if isinstance(arr, SymArray) else tuple(arr.shape)

    # ------------------------------------------------------------------
    # Structural / metadata
    # ------------------------------------------------------------------

    def shape(self, plc, x) -> SymShape:
        return SymShape(self._shape_of_leaf(x), plc)

    def constant(self, plc, value, dtype=None):
        if isinstance(value, str):
            return HostString(value, plc)
        if isinstance(value, (tuple, list)) and all(
            isinstance(v, (int, np.integer)) for v in value
        ) and dtype is None:
            return SymShape(tuple(int(v) for v in value), plc)
        arr = np.asarray(value)
        if dtype is not None and not dtype.is_fixedpoint:
            arr = arr.astype(np.dtype(dtype.numpy_name))
        if arr.dtype == np.bool_:
            op = self.add_operation(
                "Constant", [], plc, Signature((), _BIT_TY),
                {"value": arr.astype(np.uint8)},
            )
            return self._bit(op, arr.shape, plc)
        out_dtype = dt.from_numpy(arr.dtype)
        op = self.add_operation(
            "Constant", [], plc, Signature((), _tensor_ty(out_dtype)),
            {"value": arr},
        )
        return self._tensor(op, arr.shape, plc, out_dtype)

    def fill(self, plc, shp, value, ty_name: str):
        shape = tuple(shp.value)
        if ty_name.startswith("HostRing"):
            width = 128 if "128" in ty_name else 64
            op = self._emit(
                "Fill", [shp], plc, _ring_ty(width), {"value": int(value)}
            )
            return self._ring(op, shape, width, plc)
        if ty_name == "HostBitTensor":
            op = self._emit(
                "Fill", [shp], plc, _BIT_TY, {"value": int(value) & 1}
            )
            return self._bit(op, shape, plc)
        raise CompilationError(f"fill for {ty_name}")

    def zeros(self, plc, shp, dtype=dt.float64):
        op = self._emit("Zeros", [shp], plc, _tensor_ty(dtype))
        return self._tensor(op, tuple(shp.value), plc, dtype)

    def ones(self, plc, shp, dtype=dt.float64):
        op = self._emit("Ones", [shp], plc, _tensor_ty(dtype))
        return self._tensor(op, tuple(shp.value), plc, dtype)

    def ring_zeros(self, plc, shp, width: int):
        return self.fill(plc, shp, 0, f"HostRing{width}Tensor")

    def ring_constant(self, plc, ints, width: int):
        arr = np.asarray(ints, dtype=object)
        op = self.add_operation(
            "Constant", [], plc, Signature((), _ring_ty(width)),
            {"value": ints},
        )
        return self._ring(op, arr.shape, width, plc)

    def reshape(self, plc, x, shp):
        op = self._emit("Reshape", [x, shp], plc, _ty_of(x))
        return self._like(op, tuple(shp.value), x)

    def transpose(self, plc, x, axes=None):
        attrs = {"axes": tuple(axes)} if axes is not None else None
        op = self._emit("Transpose", [x], plc, _ty_of(x), attrs)
        shape = self._shape_of_leaf(x)
        if axes is None:
            shape = tuple(reversed(shape))
        else:
            shape = tuple(shape[a] for a in axes)
        return self._like(op, shape, x)

    def expand_dims(self, plc, x, axis):
        op = self._emit("ExpandDims", [x], plc, _ty_of(x), {"axis": axis})
        shape = np.expand_dims(_dummy(self._shape_of_leaf(x)), axis).shape
        return self._like(op, shape, x)

    def squeeze(self, plc, x, axis=None):
        op = self._emit("Squeeze", [x], plc, _ty_of(x), {"axis": axis})
        shape = np.squeeze(_dummy(self._shape_of_leaf(x)), axis=axis).shape
        return self._like(op, shape, x)

    def concat(self, plc, xs, axis=0):
        op = self._emit("Concat", list(xs), plc, _ty_of(xs[0]),
                        {"axis": axis})
        shape = np.concatenate(
            [_dummy(self._shape_of_leaf(x)) for x in xs], axis=axis
        ).shape
        return self._like(op, shape, xs[0])

    def index_axis(self, plc, x, axis, index):
        op = self._emit("IndexAxis", [x], plc, _ty_of(x),
                        {"axis": axis, "index": index})
        shape = np.take(_dummy(self._shape_of_leaf(x)), index, axis=axis).shape
        return self._like(op, shape, x)

    def slice(self, plc, x, begin, end):
        op = self._emit("Slice", [x], plc, _ty_of(x),
                        {"begin": tuple(begin), "end": tuple(end)})
        d = _dummy(self._shape_of_leaf(x))
        shape = d[tuple(slice(b, e) for b, e in zip(begin, end))].shape
        return self._like(op, shape, x)

    def strided_slice(self, plc, x, slices):
        spec = tuple(
            (s.start, s.stop, s.step)
            if isinstance(s, slice)
            else ("..." if s is Ellipsis else s)
            for s in slices
        )
        op = self._emit("Slice", [x], plc, _ty_of(x), {"slices": spec})
        shape = _dummy(self._shape_of_leaf(x))[tuple(slices)].shape
        return self._like(op, shape, x)

    def broadcast(self, plc, x, shp):
        op = self._emit("Broadcast", [x, shp], plc, _ty_of(x))
        return self._like(op, tuple(shp.value), x)

    def diag(self, plc, x):
        op = self._emit("Diag", [x], plc, _ty_of(x))
        shape = np.diag(_dummy(self._shape_of_leaf(x))).shape
        return self._like(op, shape, x)

    def shl_dim(self, plc, x, amount, bit_length):
        op = self._emit("ShlDim", [x], plc, _ty_of(x),
                        {"amount": amount, "bit_length": bit_length})
        return self._like(op, self._shape_of_leaf(x), x)

    def at_least_2d(self, plc, x, to_column_vector=False):
        op = self._emit("AtLeast2D", [x], plc, _ty_of(x),
                        {"to_column_vector": to_column_vector})
        shape = self._shape_of_leaf(x)
        if len(shape) == 0:
            shape = (1, 1)
        elif len(shape) == 1:
            shape = (shape[0], 1) if to_column_vector else (1, shape[0])
        return self._like(op, shape, x)

    # ------------------------------------------------------------------
    # Arithmetic
    # ------------------------------------------------------------------

    def _binop(self, kind, plc, x, y):
        op = self._emit(kind, [x, y], plc, _ty_of(x))
        shape = np.broadcast_shapes(
            self._shape_of_leaf(x), self._shape_of_leaf(y)
        )
        return self._like(op, shape, x)

    def add(self, plc, x, y):
        return self._binop("Add", plc, x, y)

    def sub(self, plc, x, y):
        return self._binop("Sub", plc, x, y)

    def mul(self, plc, x, y):
        if isinstance(x, HostBitTensor):
            return self._binop("And", plc, x, y)
        return self._binop("Mul", plc, x, y)

    def div(self, plc, x, y):
        return self._binop("Div", plc, x, y)

    def dot(self, plc, x, y):
        op = self._emit("Dot", [x, y], plc, _ty_of(x))
        shape = _dot_shape(self._shape_of_leaf(x), self._shape_of_leaf(y))
        return self._like(op, shape, x)

    def _conv_spatial(self, x, kh, kw, strides, padding):
        from ..dialects import ring

        n, h, w, _ = self._shape_of_leaf(x)
        sh, sw = strides
        (p0, p1), (q0, q1) = ring.resolve_padding(
            padding, h, w, kh, kw, sh, sw
        )
        return (
            n,
            ring.conv_out_size(h, kh, sh, p0, p1),
            ring.conv_out_size(w, kw, sw, q0, q1),
        )

    def conv2d(self, plc, x, k, strides=(1, 1), padding="VALID"):
        op = self._emit(
            "Conv2D", [x, k], plc, _ty_of(x),
            {"strides": tuple(strides), "padding": padding},
        )
        kh, kw, _, o = self._shape_of_leaf(k)
        n, oh, ow = self._conv_spatial(x, kh, kw, strides, padding)
        return self._like(op, (n, oh, ow, o), x)

    def im2col(self, plc, x, kh, kw, strides=(1, 1), padding="VALID"):
        op = self._emit(
            "Im2Col", [x], plc, _ty_of(x),
            {"kh": kh, "kw": kw, "strides": tuple(strides),
             "padding": padding},
        )
        c = self._shape_of_leaf(x)[3]
        n, oh, ow = self._conv_spatial(x, kh, kw, strides, padding)
        return self._like(op, (n, oh, ow, kh * kw * c), x)

    def _pool2d(self, kind, plc, x, pool, strides, padding):
        strides = tuple(strides) if strides is not None else tuple(pool)
        attrs = {
            "pool_size": tuple(pool), "strides": strides,
            "padding": padding,
        }
        op = self._emit(kind, [x], plc, _ty_of(x), attrs)
        c = self._shape_of_leaf(x)[3]
        n, oh, ow = self._conv_spatial(
            x, pool[0], pool[1], strides, padding
        )
        return self._like(op, (n, oh, ow, c), x)

    def avg_pool2d(self, plc, x, pool, strides=None, padding="VALID"):
        return self._pool2d("AvgPool2D", plc, x, pool, strides, padding)

    def max_pool2d(self, plc, x, pool, strides=None, padding="VALID"):
        return self._pool2d("MaxPool2D", plc, x, pool, strides, padding)

    def neg(self, plc, x):
        op = self._emit("Neg", [x], plc, _ty_of(x))
        return self._like(op, self._shape_of_leaf(x), x)

    def sum(self, plc, x, axis=None):
        op = self._emit("Sum", [x], plc, _ty_of(x), {"axis": axis})
        return self._like(op, _reduce_shape(self._shape_of_leaf(x), axis), x)

    def mean(self, plc, x, axis=None):
        op = self._emit("Mean", [x], plc, _ty_of(x), {"axis": axis})
        return self._like(op, _reduce_shape(self._shape_of_leaf(x), axis), x)

    def shl(self, plc, x, amount: int):
        op = self._emit("Shl", [x], plc, _ty_of(x), {"amount": amount})
        return self._like(op, self._shape_of_leaf(x), x)

    def shr(self, plc, x, amount: int):
        op = self._emit("Shr", [x], plc, _ty_of(x), {"amount": amount})
        return self._like(op, self._shape_of_leaf(x), x)

    def shr_arith(self, plc, x, amount: int):
        op = self._emit("Shr", [x], plc, _ty_of(x),
                        {"amount": amount, "arithmetic": True})
        return self._like(op, self._shape_of_leaf(x), x)

    # ------------------------------------------------------------------
    # Bits
    # ------------------------------------------------------------------

    def xor(self, plc, x, y):
        return self._binop("Xor", plc, x, y)

    def and_(self, plc, x, y):
        return self._binop("And", plc, x, y)

    def or_(self, plc, x, y):
        return self._binop("Or", plc, x, y)

    def bit_neg(self, plc, x):
        op = self._emit("Neg", [x], plc, _BIT_TY)
        return self._bit(op, self._shape_of_leaf(x), plc)

    def bit_extract(self, plc, x, bit_idx: int):
        op = self._emit("BitExtract", [x], plc, _BIT_TY,
                        {"bit_idx": bit_idx})
        return self._bit(op, self._shape_of_leaf(x), plc)

    def ring_inject(self, plc, b, bit_idx: int, width: int):
        op = self._emit("RingInject", [b], plc, _ring_ty(width),
                        {"bit_idx": bit_idx})
        return self._ring(op, self._shape_of_leaf(b), width, plc)

    def decompose_bits(self, plc, x):
        op = self._emit("BitDecompose", [x], plc, _BIT_TY)
        shape = (x.width,) + tuple(self._shape_of_leaf(x))
        return self._bit(op, shape, plc)

    def compose_bits(self, plc, b, width: int):
        op = self._emit("BitCompose", [b], plc, _ring_ty(width))
        return self._ring(op, tuple(self._shape_of_leaf(b))[1:], width, plc)

    # ------------------------------------------------------------------
    # Fixed-point
    # ------------------------------------------------------------------

    def ring_fixedpoint_encode(self, plc, x, frac: int, width: int):
        op = self._emit(
            "RingFixedpointEncode", [x], plc, _ring_ty(width),
            {"scaling_base": 2, "scaling_exp": frac},
        )
        return self._ring(op, self._shape_of_leaf(x), width, plc)

    def ring_fixedpoint_decode(self, plc, x, frac: int, dtype=dt.float64):
        op = self._emit(
            "RingFixedpointDecode", [x], plc, _tensor_ty(dtype),
            {"scaling_base": 2, "scaling_exp": frac},
        )
        return self._tensor(op, self._shape_of_leaf(x), plc, dtype)

    def ring_fixedpoint_mean(self, plc, x, axis, frac: int):
        op = self._emit(
            "RingFixedpointMean", [x], plc, _ty_of(x),
            {"axis": axis, "scaling_base": 2, "scaling_exp": frac},
        )
        return self._like(op, _reduce_shape(self._shape_of_leaf(x), axis), x)

    def fixedpoint_encode(self, plc, x, integ: int, frac: int, width: int):
        return HostFixedTensor(
            self.ring_fixedpoint_encode(plc, x, frac, width), integ, frac
        )

    def fixedpoint_decode(self, plc, x, dtype=dt.float64):
        return self.ring_fixedpoint_decode(
            plc, x.tensor, x.fractional_precision, dtype
        )

    # ------------------------------------------------------------------
    # Plaintext math
    # ------------------------------------------------------------------

    def _unary(self, kind, plc, x, attributes=None):
        op = self._emit(kind, [x], plc, _ty_of(x), attributes)
        return self._like(op, self._shape_of_leaf(x), x)

    def exp(self, plc, x):
        return self._unary("Exp", plc, x)

    def log(self, plc, x):
        return self._unary("Log", plc, x)

    def log2(self, plc, x):
        return self._unary("Log2", plc, x)

    def sqrt(self, plc, x):
        return self._unary("Sqrt", plc, x)

    def sigmoid(self, plc, x):
        return self._unary("Sigmoid", plc, x)

    def relu(self, plc, x):
        return self._unary("Relu", plc, x)

    def abs(self, plc, x):
        return self._unary("Abs", plc, x)

    def sign(self, plc, x):
        return self._unary("Sign", plc, x)

    def pow2(self, plc, x):
        return self._unary("Pow2", plc, x)

    def softmax(self, plc, x, axis):
        return self._unary("Softmax", plc, x, {"axis": axis})

    def argmax(self, plc, x, axis):
        op = self._emit("Argmax", [x], plc, _tensor_ty(dt.uint64),
                        {"axis": axis})
        return self._tensor(
            op, _reduce_shape(self._shape_of_leaf(x), axis), plc, dt.uint64
        )

    def maximum(self, plc, xs):
        op = self._emit("Maximum", list(xs), plc, _ty_of(xs[0]))
        shape = np.broadcast_shapes(*[self._shape_of_leaf(x) for x in xs])
        return self._like(op, shape, xs[0])

    def inverse(self, plc, x):
        return self._unary("Inverse", plc, x)

    def less(self, plc, x, y):
        op = self._emit("Less", [x, y], plc, _BIT_TY)
        shape = np.broadcast_shapes(
            self._shape_of_leaf(x), self._shape_of_leaf(y)
        )
        return self._bit(op, shape, plc)

    def greater(self, plc, x, y):
        op = self._emit("Greater", [x, y], plc, _BIT_TY)
        shape = np.broadcast_shapes(
            self._shape_of_leaf(x), self._shape_of_leaf(y)
        )
        return self._bit(op, shape, plc)

    def equal(self, plc, x, y):
        op = self._emit("Equal", [x, y], plc, _BIT_TY)
        shape = np.broadcast_shapes(
            self._shape_of_leaf(x), self._shape_of_leaf(y)
        )
        return self._bit(op, shape, plc)

    def mux(self, plc, s, x, y):
        op = self._emit("Mux", [s, x, y], plc, _ty_of(x))
        shape = np.broadcast_shapes(
            self._shape_of_leaf(s),
            self._shape_of_leaf(x),
            self._shape_of_leaf(y),
        )
        return self._like(op, shape, x)

    def cast(self, plc, x, target: dt.DType):
        if target.is_boolean:
            op = self._emit("Cast", [x], plc, _BIT_TY, {"dtype": target})
            return self._bit(op, self._shape_of_leaf(x), plc)
        op = self._emit("Cast", [x], plc, _tensor_ty(target),
                        {"dtype": target})
        return self._tensor(op, self._shape_of_leaf(x), plc, target)

    def lift_ring_lo(self, plc, x, dtype=dt.uint64):
        op = self._emit("Cast", [x], plc, _tensor_ty(dtype),
                        {"dtype": dtype})
        return self._tensor(op, self._shape_of_leaf(x), plc, dtype)

    def select(self, plc, x, axis, index):
        op = self._emit("Select", [x, index], plc, _ty_of(x),
                        {"axis": axis})
        return self._like(op, None, x)
