"""Logical-computation interpreter: walks the IR and executes via the
logical dialect, compiling the whole computation to ONE fused XLA program.

This is the TPU-native replacement for the reference's per-op async executor
(``moose/src/execution/asynchronous.rs``): instead of spawning one task per
operation and letting tokio schedule, the entire dataflow graph is traced
through the dialect kernels under ``jax.jit`` and XLA schedules/fuses it.
Host boundaries (Input/Load/Save/Output) are resolved outside the jitted
core; everything numeric happens on device.

Computations containing dynamic-shape ops (Select) fall back to eager
execution — XLA requires static shapes.
"""

from __future__ import annotations

import dataclasses
import secrets
from typing import Any, Callable, Optional

import jax
import numpy as np

from .. import dtypes as dt
from ..computation import Computation, HostPlacement
from ..dialects import logical
from ..values import (
    HostBitTensor,
    HostFixedTensor,
    HostRingTensor,
    HostShape,
    HostString,
    HostTensor,
    HostUnit,
    host_tensor_from_numpy,
    to_numpy,
)
from .session import EagerSession

_DYNAMIC_SHAPE_KINDS = frozenset({"Select"})

# Kinds resolved at the host boundary rather than by the logical dialect.
_BOUNDARY_KINDS = frozenset({"Input", "Load", "Save", "Output"})


@dataclasses.dataclass
class _Plan:
    """Static execution plan for one (computation, binding) pair.

    Deliberately does NOT hold the Computation: plans are cached in a
    weak-keyed dict keyed by the computation, and a strong back-reference
    from the value would keep every entry alive forever."""

    order: list[str]
    static_env: dict[str, Any]  # op name -> static value (strings, scalars)
    dynamic_names: list[str]  # Input/Load ops fed arrays at call time
    use_jit: bool
    core: Callable  # (master_key, dyn: dict[str, array]) -> (outputs, saves)
    # pre-built executable (segmented plans jit each segment themselves);
    # when set, the evaluator calls it instead of wrapping `core`
    fn: Optional[Callable] = None


def _is_static_scalar(ty_name: str) -> bool:
    return ty_name in ("HostInt", "HostFloat", "HostString")


def master_key_words(domain: str = "") -> np.ndarray:
    """The per-evaluation 128-bit master key as four uint32 words.

    Normally drawn from local entropy (each evaluation gets fresh
    masks).  Under ``MOOSE_TPU_FIXED_KEYS`` (TEST-ONLY, gated exactly
    like the worker's PrfKeyGen knob: replicated fixed-point results
    carry ±1 LSB of share-dependent truncation noise, so bit-exactness
    tests — chaos replay, serving batch-scatter — need reproducible
    keys) the key derives deterministically from the knob value and
    ``domain``.  A real deployment must never run with derivable keys,
    hence the MOOSE_TPU_ALLOW_WEAK_PRF=1 requirement."""
    import os

    fixed = os.environ.get("MOOSE_TPU_FIXED_KEYS")
    if fixed:
        if os.environ.get("MOOSE_TPU_ALLOW_WEAK_PRF") != "1":
            from ..errors import ConfigurationError

            raise ConfigurationError(
                "MOOSE_TPU_FIXED_KEYS is a testing knob and requires "
                "MOOSE_TPU_ALLOW_WEAK_PRF=1 — fixed PRF keys void all "
                "inter-party secrecy"
            )
        import hashlib

        digest = hashlib.blake2b(
            f"{fixed}|{domain}".encode(), digest_size=16
        ).digest()
        return np.frombuffer(digest, dtype=np.uint32)
    return np.frombuffer(secrets.token_bytes(16), dtype=np.uint32)


def _fixed_sync_seed() -> Optional[int]:
    """Philox seed pinning the logical dialect's trace-time sync-key
    nonces under MOOSE_TPU_FIXED_KEYS (physical plans bake sync keys as
    graph attributes and need no pinning).  None when the knob is off —
    nonces then come from OS entropy as usual."""
    import os

    fixed = os.environ.get("MOOSE_TPU_FIXED_KEYS")
    if not fixed:
        return None
    import hashlib

    digest = hashlib.blake2b(
        f"{fixed}|sync".encode(), digest_size=8
    ).digest()
    return int.from_bytes(digest, "little")


def _fault_kinds() -> frozenset:
    """Op kinds listed in MOOSE_TPU_SELFCHECK_FAULT (comma-separated):
    the self-check runners corrupt those ops' results in their JIT
    CANDIDATES only, forcing a synthetic divergence so the demotion
    ladder (including the per-op rung's selective pinning) is testable
    on backends where the real miscompile cannot reproduce.  Read when
    a candidate is built; never applied outside self-check candidates."""
    import os

    raw = os.environ.get("MOOSE_TPU_SELFCHECK_FAULT", "")
    return frozenset(k.strip() for k in raw.split(",") if k.strip())


def _fault_perturb(value):
    """Corrupt every array leaf of one op's result — the synthetic
    stand-in for a value-dependent miscompiled kernel."""
    import jax.numpy as jnp

    def bump(leaf):
        if not hasattr(leaf, "dtype"):
            return leaf
        if leaf.dtype == jnp.bool_:
            return ~leaf
        return leaf + jnp.ones((), leaf.dtype)

    return jax.tree_util.tree_map(bump, value)


def build_plan(comp: Computation, arguments: dict, use_jit: bool,
               segment_limit: Optional[int] = None,
               jit_segments: bool = True, dialect=None,
               fault_kinds=frozenset()) -> _Plan:
    dialect = dialect if dialect is not None else logical
    order = comp.toposort_names()
    static_env: dict[str, Any] = {}
    dynamic_names: list[str] = []

    for name in order:
        op = comp.operations[name]
        plc = comp.placement_of(op)
        if op.kind == "Input":
            val = arguments.get(op.name)
            if val is None:
                raise ValueError(f"missing argument {op.name!r}")
            if isinstance(val, str):
                static_env[name] = HostString(val, plc.name)
            elif isinstance(val, (int, float)) and _is_static_scalar(
                op.signature.return_type.name
            ):
                static_env[name] = val
            else:
                dynamic_names.append(name)
        elif op.kind == "Constant":
            value = op.attributes["value"]
            if isinstance(value, str):
                static_env[name] = HostString(value, plc.name)
            elif op.signature.return_type.name in ("HostInt", "HostFloat"):
                static_env[name] = value
        elif op.kind in ("Load", "LoadShares"):
            dynamic_names.append(name)

    if any(
        comp.operations[n].kind in _DYNAMIC_SHAPE_KINDS for n in order
    ):
        use_jit = False

    import weakref

    from .. import telemetry

    # Per-op spans (reference: one tracing span per async op task) are
    # meaningful only in eager mode — under jit the whole graph is one
    # XLA program and Python-side timers would be traced away.
    trace_ops = telemetry.trace_ops_enabled() and not use_jit

    # The closure must not keep the computation alive: the compiled plan is
    # cached weak-keyed on the computation, so a strong capture here would
    # make eviction impossible.  While any caller can invoke `core` it also
    # holds the computation, so the deref below cannot fail in practice.
    comp_ref = weakref.ref(comp)

    limit = segment_limit if segment_limit is not None else _segment_limit()
    if use_jit and len(order) > limit:
        return _build_segmented_plan(
            comp_ref, order, static_env, dynamic_names, limit, jit_segments,
            dialect, fault_kinds,
        )

    def core(master_key, dyn: dict):
        comp = comp_ref()
        if comp is None:  # pragma: no cover - defensive
            raise RuntimeError("computation was garbage-collected")
        sess = dialect.make_session(master_key)
        dialect.bind_placements(sess, comp)
        env: dict[str, Any] = {}
        outputs: dict[str, Any] = {}
        # dict keyed by (placement, storage key) so the returned structure is
        # a valid jit output pytree (strings live in the keys = aux data)
        saves: dict[tuple[str, str], Any] = {}
        _run_ops(
            sess, comp, order, static_env, env, outputs, saves, dyn,
            trace_ops, dialect, fault_kinds,
        )
        return outputs, saves

    return _Plan(order, static_env, dynamic_names, use_jit, core)


def _run_ops(sess, comp, names, static_env, env, outputs, saves, dyn,
             trace_ops=False, dialect=None, fault_kinds=frozenset()):
    """Execute ``names`` in order against ``env`` — the single op-walk
    shared by the whole-graph core and the per-segment cores.  ``dialect``
    selects the execution layout (per-host ``dialects.logical`` by
    default; ``dialects.stacked`` for the party-stacked SPMD backend).
    ``fault_kinds`` (self-check candidates only) injects a synthetic
    divergence into ops of the listed kinds — see :func:`_fault_kinds`."""
    dialect = dialect if dialect is not None else logical
    for name in names:
        op = comp.operations[name]
        plc = comp.placement_of(op)
        if name in static_env:
            env[name] = static_env[name]
            continue
        if op.kind in ("Input", "Load"):
            arr = dyn[name]
            ret_name = op.signature.return_type.name
            from ..computation import AES_TY_NAMES

            if ret_name in AES_TY_NAMES:
                env[name] = dialect.lift_aes_input(
                    sess, comp, op, arr, plc.name
                )
            else:
                env[name] = _lift_array(arr, op, plc.name)
            continue
        if op.kind == "LoadShares":
            env[name] = _lift_shares(dyn[name], op, plc)
            continue
        if op.kind == "SaveShares":
            key = env[op.inputs[0]]
            assert isinstance(key, HostString), (
                f"SaveShares key must be a string, found "
                f"{type(key).__name__}"
            )
            _stage_shares(
                sess, dialect, plc, key.value, env[op.inputs[1]], saves
            )
            env[name] = HostUnit(plc.owners[-1])
            continue
        if op.kind == "Save":
            key = env[op.inputs[0]]
            assert isinstance(key, HostString), (
                f"Save key must be a string, found {type(key).__name__}"
            )
            value = dialect.to_host(sess, plc.name, env[op.inputs[1]])
            saves[(plc.name, key.value)] = value
            env[name] = HostUnit(plc.name)
            continue
        if op.kind == "Output":
            value = env[op.inputs[0]]
            if not isinstance(value, HostUnit):
                value = dialect.to_host(sess, plc.name, value)
            env[name] = value
            # the reference keys result dicts by the Output tag, not the
            # op name (execution/asynchronous.rs:623); fall back to the
            # name for tag-less graphs
            outputs[op.attributes.get("tag", name)] = value
            continue
        args = [env[i] for i in op.inputs]
        if trace_ops:
            # same-named spans aggregate in phase_timings, giving a
            # per-kind time profile of the eager run.  jax dispatch
            # is async, so the span must force materialization or
            # the device time would be misattributed to whichever
            # later op first blocks (tracing is opt-in; the sync
            # cost is the price of honest per-op numbers)
            from .. import telemetry

            with telemetry.span(f"op:{op.kind}"):
                env[name] = jax.block_until_ready(
                    dialect.execute_op(sess, comp, op, args)
                )
        else:
            env[name] = dialect.execute_op(sess, comp, op, args)
        if fault_kinds and op.kind in fault_kinds:
            env[name] = _fault_perturb(env[name])


def heavy_jit_gate(n_ops: int, use_jit: bool) -> bool:
    """The effective use_jit after the experimental-TPU guard: jitted
    protocol graphs above the segment limit miscompile for some session
    keys on the TPU backend (see DEVELOP.md "Known issue"); every
    executor entry point — not just the auto-lowering route — must make
    the same call, so it lives here.  MOOSE_TPU_TPU_JIT_HEAVY=1
    re-enables (debugging).

    Both LOCAL executors upgrade this blanket gate to a validated-jit
    path (:class:`_SelfCheckRunner` here,
    ``physical._PhysicalSelfCheckRunner`` for lowered graphs): gated
    graphs still run, but each plan's segmented-jit candidate is checked
    bit-for-bit against the eager reference on its first evaluations and
    promoted to pure jit when it validates.  Only the distributed WORKER
    scheduler (``distributed/worker.execute_role``) keeps plain eager
    behavior — its outputs are spread across workers, so no single
    process can compare them.

    The gate threshold is independent of MOOSE_TPU_JIT_SEGMENT:
    disabling segmentation (=0) means "one fused program", not "trust
    the experimental backend" — the miscompile threshold is a hardware
    property (~2000 host-op equivalents), so only the explicit
    MOOSE_TPU_TPU_JIT_HEAVY=1 opt-out bypasses validation."""
    import os

    if os.environ.get("MOOSE_TPU_SELFCHECK_FORCE") == "1":
        # testing knob: treat EVERY jitted plan as gated so the
        # validated-jit ladder (and the MOOSE_TPU_SELFCHECK_FAULT hook)
        # can be exercised on backends without the real miscompile
        return False
    if not use_jit or n_ops <= min(_segment_limit(), 2000):
        return use_jit
    if os.environ.get("MOOSE_TPU_TPU_JIT_HEAVY") == "1":
        return use_jit
    import jax

    return jax.default_backend() != "tpu"


def _selfcheck_runs() -> int:
    """How many clean jit-vs-eager comparisons promote a gated plan to
    pure jit (0 disables the self-check, restoring the unconditional
    eager gate)."""
    import os

    raw = os.environ.get("MOOSE_TPU_JIT_SELFCHECK", "2")
    try:
        n = int(raw)
    except ValueError as e:
        from ..errors import ConfigurationError

        raise ConfigurationError(
            f"MOOSE_TPU_JIT_SELFCHECK must be an integer, got {raw!r}"
        ) from e
    return max(0, n)


def _per_op_limit() -> int:
    """Op-count cap on the per-op ladder rung: above this, per-op
    validation would compile thousands of tiny XLA programs for a plan
    three segment rungs already rejected, so the ladder skips straight
    to eager (and the runtime's cross-layout reroute applies).  The rung
    exists for LOGICAL plans — a stacked predictor is ~40 logical ops
    each expanding to a whole protocol circuit — where per-op jit is the
    difference between one op eager and the whole plan eager."""
    import os

    raw = os.environ.get("MOOSE_TPU_PEROP_MAX", "4000")
    try:
        n = int(raw)
    except ValueError as e:
        from ..errors import ConfigurationError

        raise ConfigurationError(
            f"MOOSE_TPU_PEROP_MAX must be an integer, got {raw!r}"
        ) from e
    return max(0, n)


def _results_equal(a, b) -> bool:
    """Bit-exact pytree comparison of two (outputs, saves) results.  The
    eager and jitted paths execute identical integer protocol math from
    the same master key, so anything short of exact equality is a
    miscompile."""
    la, ta = jax.tree_util.tree_flatten(a)
    lb, tb = jax.tree_util.tree_flatten(b)
    if ta != tb or len(la) != len(lb):
        return False
    def eq(x, y):
        x, y = np.asarray(x), np.asarray(y)
        # identical NaNs on both paths are agreement, not divergence
        # (np.array_equal rejects equal_nan for non-float dtypes)
        equal_nan = x.dtype.kind == "f" and y.dtype.kind == "f"
        return np.array_equal(x, y, equal_nan=equal_nan)

    return all(eq(x, y) for x, y in zip(la, lb))


_PER_OP = "per-op"  # ladder sentinel: per-op-jit rung (not a segment size)


class _PerOpPlan:
    """The per-op rung of the validated-jit ladder: every operation runs
    as its OWN XLA program, validated bit-exactly against its eager
    execution on the same inputs, and only the ops that diverge are
    pinned to eager dispatch — the rest stay jitted.  DEVELOP.md's
    localization shows every component except one region jits exact, so
    the steady state is ~one op eager instead of the whole plan (the
    all-or-nothing terminal demotion this rung replaces).

    Boundary and static ops (Input/Load/Save/Output, baked constants,
    key feeds) always run eagerly — host-boundary work with nothing to
    fuse — and are not counted as "pinned".

    ``seg_size`` generalizes the rung to coarser granularity: plans too
    large for one-program-per-op validation (above MOOSE_TPU_PEROP_MAX)
    validate and pin ``seg_size``-op CHUNKS instead, so exhausting the
    segment rungs lands on mostly-jitted execution with only the
    divergent chunks eager rather than pinning the whole plan (a pinned
    chunk is identified by its first op's name)."""

    def __init__(self, order, static_env, dynamic_names, effective_inputs,
                 seg_exec, fault_kinds, rand_slice, always_eager=(),
                 seg_invoke=None, pinned=(), seg_size: int = 1):
        self.seg_size = max(1, seg_size)
        chunks, in_names, out_names = plan_segments(
            order, static_env, effective_inputs, self.seg_size
        )
        self._chunks = chunks
        self._in_names = in_names
        self._out_names = out_names
        dyn_set = set(dynamic_names)
        self._dyn_of = [
            [n for n in names if n in dyn_set] for names in chunks
        ]
        self._static_env = static_env
        self._seg_exec = seg_exec
        self._fault_kinds = frozenset(fault_kinds)
        self._rand_slice = rand_slice
        self._seg_invoke = seg_invoke
        self._always = set(always_eager) | set(static_env)
        # a chunk is validatable when ANY of its ops does real compute
        # (a seg_size>1 chunk may open with a boundary op yet still
        # carry kernels worth jitting)
        self._validatable = frozenset(
            names[0] for names in chunks
            if any(n not in self._always for n in names)
        )
        # seeding from a previous runner's pins (the plan registry) lets
        # promotion survive across runtimes without re-diverging first
        self.pinned: set = set(pinned) & self._validatable
        # ops whose jit candidate failed to RUN once (transient OOM,
        # tunnel hiccup): retried before pinning, mirroring the segment
        # rungs' retry-once policy
        self._failed_once: set = set()
        self._eager_fns = [
            self._make_seg(si, fault=False) for si in range(len(chunks))
        ]
        self._jit_fns: dict = {}

    def _make_seg(self, si, fault):
        names = self._chunks[si]
        outs = self._out_names[si]
        static_env = self._static_env
        seg_exec = self._seg_exec
        fk = self._fault_kinds if fault else frozenset()

        def seg(rand, dyn, env_in):
            env: dict[str, Any] = dict(static_env)
            env.update(env_in)
            outputs: dict[str, Any] = {}
            saves: dict[tuple[str, str], Any] = {}
            seg_exec(si, names, rand, dyn, env, outputs, saves, fk)
            return {n: env[n] for n in outs}, outputs, saves

        return seg

    def _jit_fn(self, si):
        fn = self._jit_fns.get(si)
        if fn is None:
            fn = self._jit_fns[si] = jax.jit(self._make_seg(si, fault=True))
        return fn

    def _call(self, si, fn, rand, dyn, env):
        args = (
            self._rand_slice(rand, si),
            {n: dyn[n] for n in self._dyn_of[si]},
            {n: env[n] for n in self._in_names[si]},
        )
        if self._seg_invoke is not None:
            return self._seg_invoke(si, fn, *args)
        return fn(*args)

    @staticmethod
    def _merge(env, outputs, saves, result):
        env_out, out_i, sv_i = result
        env.update(env_out)
        outputs.update(out_i)
        saves.update(sv_i)
        if out_i or sv_i:  # overlap host transfer with later chunks
            prefetch_to_host(out_i, sv_i)

    def all_pinned(self) -> bool:
        return self._validatable <= self.pinned

    def run_validate(self, rand, dyn):
        """One validation pass: every op executes eagerly (the exact
        reference the returned result comes from) and, where unpinned,
        also as its own jitted program on the SAME inputs; a divergence
        pins that op, a candidate RUN failure is retried on the next
        pass before pinning (the segment rungs' retry-once policy).
        Returns ((outputs, saves), newly_pinned_names, retried_names)."""
        from ..logger import get_logger

        env: dict[str, Any] = {}
        outputs: dict[str, Any] = {}
        saves: dict[tuple[str, str], Any] = {}
        new_pins: list[str] = []
        retried: list[str] = []
        for si, names in enumerate(self._chunks):
            ref = self._call(si, self._eager_fns[si], rand, dyn, env)
            name = names[0]
            if name in self._validatable and name not in self.pinned:
                pin = False
                try:
                    got = self._call(si, self._jit_fn(si), rand, dyn, env)
                    pin = not _results_equal(ref, got)
                except Exception as e:  # noqa: BLE001 — candidate is
                    # optional; a run failure is not the divergence the
                    # rung exists for
                    if name not in self._failed_once:
                        self._failed_once.add(name)
                        retried.append(name)
                        get_logger().warning(
                            "per-op jit candidate for %s failed to run "
                            "(%s); will retry once", name, e,
                        )
                    else:
                        get_logger().warning(
                            "per-op jit candidate for %s failed twice "
                            "(%s); pinning eager", name, e,
                        )
                        pin = True
                if pin:
                    self.pinned.add(name)
                    new_pins.append(name)
                    self._jit_fns.pop(si, None)
            self._merge(env, outputs, saves, ref)
        return (outputs, saves), new_pins, retried

    def run_mixed(self, rand, dyn):
        """Steady-state execution: pinned/boundary ops eager, everything
        else as its validated per-op XLA program."""
        env: dict[str, Any] = {}
        outputs: dict[str, Any] = {}
        saves: dict[tuple[str, str], Any] = {}
        for si, names in enumerate(self._chunks):
            eager = (
                names[0] not in self._validatable
                or names[0] in self.pinned
            )
            fn = self._eager_fns[si] if eager else self._jit_fn(si)
            self._merge(env, outputs, saves,
                        self._call(si, fn, rand, dyn, env))
        return outputs, saves


class _SelfCheckBase:
    """Validated-jit execution for heavy graphs on the experimental TPU
    backend (VERDICT r3 weak #1: the blanket eager gate was a perf
    cliff exactly where the framework matters most).

    Instead of permanently routing gated graphs to per-op eager
    dispatch, the segmented-jit candidate runs AGAINST an exact eager
    reference on the plan's first K evaluations — identical randomness,
    so the two paths must agree bit-for-bit.  K clean runs (distinct
    random keys) promote the plan to pure jit; a mismatch demotes the
    candidate down the ladder: whole/default segments → 200-op → 50-op
    segments (measured exact where one ~10k-op program miscompiles,
    DEVELOP.md "Known issue") → per-op programs with per-op validation
    (:class:`_PerOpPlan` — only the ops that actually diverge are
    pinned eager) → whole-plan eager.  Full exhaustion is surfaced as
    ``exhausted`` so the runtime can reroute the computation to the
    other layout's validated path instead of keeping the slow plan.

    The underlying backend bug is value-dependent, so K clean runs are
    probabilistic evidence, not proof (the known repro fails on ~2/3 of
    random keys, so K=2 passes a truly bad plan with p ~ 1/9 — and any
    later demotion never happens because validation stops).  K is
    configurable via MOOSE_TPU_JIT_SELFCHECK; deployments that need the
    old absolute guarantee set it to 0.

    Subclasses provide ``_build_candidate`` (set ``_ref_fn``/``_jit_fn``
    — or ``_per_op`` at the per-op rung — for the current ladder
    level), ``_eager_fn`` (final fallback), and may override ``_invoke``
    (e.g. to pin nonce streams) and ``_save_state`` (plan registry)."""

    LADDER = (None, 200, 50, _PER_OP)  # segment overrides; None = default

    def __init__(self, checks: int, level: int = 0,
                 mode: Optional[str] = None):
        self._checks_init = checks
        self._checks_left = checks
        self._level = level
        self._ref_fn = None
        self._jit_fn = None
        self._per_op = None
        self._run_failed_once = False
        # rung names visited, for the single settle-time summary log
        # (per-rung descents log at DEBUG only — BENCH_r05's triple
        # "candidate diverged" WARNING burst was ladder noise, not
        # three independent problems)
        self._descent = [self._rung_label(level)]
        self.mode = "validating"
        if mode == "eager":
            # restored from the plan registry: a previous runner for
            # this computation already exhausted the full ladder
            self.mode = "eager"
            return
        # restoring a promoted plan needs no eager reference (validation
        # never runs again) — let _build_candidate skip constructing it
        self._skip_ref_build = mode == "jit"
        self._build_candidate()
        self._skip_ref_build = False
        if self.LADDER[self._level] is _PER_OP and self._per_op is None:
            self.mode = "eager"  # per-op rung unbuildable (e.g. op cap)
            return
        if mode in ("jit", _PER_OP):
            # restored promotion (the registry weak-keys resolved plans
            # on the computation so promotion survives across runtimes)
            self.mode = mode
            self._on_promoted()

    # -- subclass hooks ----------------------------------------------------

    def _build_candidate(self):  # pragma: no cover - abstract
        raise NotImplementedError

    def _eager_fn(self, *args):  # pragma: no cover - abstract
        raise NotImplementedError

    def _invoke(self, fn, *args):
        return fn(*args)

    def _on_promoted(self):
        """Promotion is terminal (validation stops, so no demotion can
        follow): release everything only validation needed."""
        self._ref_fn = None

    def _save_state(self):
        """Persist ladder level / pins / mode (subclass hook)."""

    def _rung_label(self, level: int) -> str:
        if level >= len(self.LADDER):
            return "eager"
        rung = self.LADDER[level]
        if rung is _PER_OP:
            return "per-op"
        return "default-segments" if rung is None else f"{rung}-op"

    def _announce_resolution(self, verdict: str, warn: bool = False):
        """ONE log line when the ladder settles: the descent path plus
        the final verdict — at INFO for promotions, WARNING only for
        full exhaustion (the one genuinely bad outcome)."""
        from ..logger import get_logger

        log = get_logger().warning if warn else get_logger().info
        log(
            "jit self-check: ladder settled (%s) -> %s",
            " -> ".join(self._descent), verdict,
        )

    # -- state machine -----------------------------------------------------

    @property
    def exhausted(self) -> bool:
        """Every rung (including per-op) failed: the plan would run
        whole-plan eager forever.  The runtime uses this to reroute the
        computation through the other layout's validated path."""
        return self.mode == "eager"

    def run(self, *args):
        if self.mode == "jit":
            # the candidate is fully traced by the time it is promoted;
            # _invoke keeps any nonce context for late retraces (new
            # shapes) so their draws match the validated ones
            return self._invoke(self._jit_fn, *args)
        if self.mode == _PER_OP:
            return self._per_op.run_mixed(*args)
        if self.mode == "eager":
            return self._eager_fn(*args)

        from ..logger import get_logger

        if self._per_op is not None:
            return self._run_per_op_validation(*args)

        from .. import profiling

        run_error = None
        with profiling.phase(
            "ladder_validate", rung=self._rung_label(self._level),
        ):
            ref = self._invoke(self._ref_fn, *args)
            try:
                got = self._invoke(self._jit_fn, *args)
                ok = _results_equal(ref, got)
            except Exception as e:  # noqa: BLE001 — candidate is
                # optional; classified below, outside the timed phase
                run_error = e
        if run_error is not None:
            # a run failure (transient OOM, tunnel hiccup) is NOT the
            # divergence the ladder exists for: retry this rung once
            # before burning it
            if not self._run_failed_once:
                self._run_failed_once = True
                get_logger().warning(
                    "jit self-check candidate failed to run (%s); will "
                    "retry this segment size once", run_error
                )
                return ref
            get_logger().warning(
                "jit self-check candidate failed twice (%s); demoting",
                run_error,
            )
            ok = False
            got = None
        if ok:
            self._run_failed_once = False
            self._checks_left -= 1
            if self._checks_left <= 0:
                self.mode = "jit"
                self._announce_resolution(
                    f"promoted to jit (segment override "
                    f"{self.LADDER[self._level]}) after "
                    f"{self._checks_init} clean runs"
                )
                self._on_promoted()
                self._save_state()
            return got
        self._descend()
        return ref

    def _descend(self):
        """Move to the next usable ladder rung (or pin eager)."""
        from ..logger import get_logger

        self._level += 1
        per_op_skipped = False
        while self._level < len(self.LADDER):
            rung = self.LADDER[self._level]
            self._build_candidate()
            if rung is _PER_OP and self._per_op is None:
                per_op_skipped = True
                self._level += 1
                continue
            self._descent.append(self._rung_label(self._level))
            # rung-by-rung descent is normal ladder operation, not an
            # actionable warning: the settle-time summary carries the
            # verdict (ISSUE 9 satellite — BENCH_r05 warning burst)
            get_logger().debug(
                "jit self-check: candidate diverged from eager; retrying "
                "with %s",
                "per-op programs (divergent ops will be pinned eager)"
                if rung is _PER_OP else f"{rung}-op segments",
            )
            self._checks_left = self._checks_init
            self._run_failed_once = False
            self._save_state()
            return
        self._descent.append("eager")
        self._announce_resolution(
            "every rung diverged%s; plan pinned to whole-plan eager "
            "execution" % (
                " (per-op rung skipped: disabled or above "
                "MOOSE_TPU_PEROP_MAX)" if per_op_skipped else ""
            ),
            warn=True,
        )
        self.mode = "eager"
        self._jit_fn = None
        self._ref_fn = None
        self._per_op = None
        self._save_state()

    def _run_per_op_validation(self, *args):
        from .. import profiling
        from ..logger import get_logger

        try:
            with profiling.phase("ladder_validate", rung="per-op"):
                result, new_pins, retried = self._per_op.run_validate(
                    *args
                )
        except Exception as e:  # noqa: BLE001 — candidate is optional
            self._descent.append("eager")
            self._announce_resolution(
                f"per-op validation failed to run ({e}); plan pinned "
                "to whole-plan eager execution", warn=True,
            )
            self.mode = "eager"
            self._per_op = None
            self._save_state()
            return self._eager_fn(*args)
        if new_pins:
            get_logger().debug(
                "per-op jit self-check: pinned %d divergent op(s) "
                "eager: %s", len(new_pins), ", ".join(sorted(new_pins)),
            )
            self._checks_left = self._checks_init
        elif retried:
            # some candidates failed to run and get one retry: neither
            # a clean pass nor a divergence — hold the counter
            pass
        else:
            self._checks_left -= 1
        if self._per_op.all_pinned():
            self._descent.append("eager")
            self._announce_resolution(
                "every %s diverged; plan pinned to whole-plan eager "
                "execution" % (
                    "op" if self._per_op.seg_size == 1
                    else f"{self._per_op.seg_size}-op chunk"
                ),
                warn=True,
            )
            self.mode = "eager"
            self._per_op = None
        elif self._checks_left <= 0:
            self.mode = _PER_OP
            self._announce_resolution(
                f"promoted to per-op jit with "
                f"{len(self._per_op.pinned)} op(s) pinned eager after "
                f"{self._checks_init} clean runs"
            )
            self._on_promoted()
        self._save_state()
        return result


# Resolved-plan registry, weak-keyed on the computation: which ladder
# level a plan settled at, which ops are pinned eager, and the final
# mode — so promotion (and exhaustion) survives across evaluations,
# bindings and runtimes instead of re-validating from the top.  Entries
# are per plan-key ("logical" / "StackedDialect" / "physical"): the same
# traced computation executes on several backends and their ladders are
# independent.
_plan_registry: "weakref.WeakKeyDictionary" = None  # initialized below


def _registry():
    global _plan_registry
    if _plan_registry is None:
        import weakref

        _plan_registry = weakref.WeakKeyDictionary()
    return _plan_registry


# AOT-artifact preloads, weak-keyed on the computation: serialized
# ``jax.export`` programs a snapshot restore stashes here so the runner
# restored at promoted whole-graph jit EXECUTES the deserialized XLA
# program instead of re-jitting its own candidate (the serving
# snapshot's skip-even-the-cached-compile path — the artifact is
# matched to a binding by input avals at the first call).
_aot_preloads = None  # WeakKeyDictionary, initialized lazily


def _aot_stash():
    global _aot_preloads
    if _aot_preloads is None:
        import weakref

        _aot_preloads = weakref.WeakKeyDictionary()
    return _aot_preloads


def preload_aot_artifact(comp, plan_key: str, blob: bytes) -> None:
    """Register one serialized ``jax.export`` artifact for ``comp``:
    the next :class:`_SelfCheckRunner` constructed for ``plan_key`` at
    restored promoted-jit mode deserializes it and runs the exported
    program directly — jax only abstractly traces the candidate once
    (``eval_shape``, to recover the output treedef) and never lowers or
    compiles it, not even through the persistent compile cache."""
    _aot_stash().setdefault(comp, {}).setdefault(
        plan_key, []
    ).append(bytes(blob))


class _SelfCheckRunner(_SelfCheckBase):
    """THE validated-jit runner, shared by the logical and physical
    executors (VERDICT r4 #6: one self-check engine, not two).

    Parameterized by a ``builder(comp, arguments, use_jit, segment_limit,
    jit_segments) -> (plan_obj, executable)``, a ``per_op_builder`` for
    the per-op rung, and by nonce pinning: the logical dialect's kernels
    draw trace-time sync-key nonces, so its eager reference replays the
    candidate under a shared deterministic nonce stream (nonces are
    public; seed security rests on the per-call master key); physical
    plans take every PRF key as a runtime input with sync keys baked as
    attributes, so no pinning is needed."""

    def __init__(self, comp, arguments, checks: int, dialect=None,
                 builder=None, pin_nonces: bool = True,
                 per_op_builder=None, plan_key: Optional[str] = None,
                 segment_limit: Optional[int] = None):
        import weakref

        # autotuned segment limit: substitutes ONLY the ladder's first
        # (None = env default) rung — demotion rungs (200 / 50 / per-op)
        # and the exactness discipline are untouched
        self._tuned_limit = segment_limit

        # weak: the runner is cached in a weak-keyed dict keyed by the
        # computation — a strong capture would keep the entry alive
        # forever (same discipline as _Plan/comp_ref)
        self._comp_ref = weakref.ref(comp)
        self._arguments = arguments
        self._builder = (
            builder
            if builder is not None
            else _logical_plan_builder(dialect)
        )
        self._pin_nonces = pin_nonces
        self._per_op_builder = (
            per_op_builder
            if per_op_builder is not None or builder is not None
            else _logical_per_op_builder(dialect)
        )
        self._plan_key = plan_key or (
            "logical" if dialect is None else type(dialect).__name__
        )
        # whole-graph eager plan: binding metadata + final fallback
        self.eager_plan, self._eager_exec = self._builder(
            comp, arguments, False, None, True
        )
        self._order = (
            self.eager_plan.order
            if hasattr(self.eager_plan, "order")
            else self.eager_plan[0]
        )
        self._nonce_seed = secrets.randbits(63)
        saved = _registry().get(comp, {}).get(self._plan_key)
        self._restored_pins = (
            frozenset(saved["pinned"]) if saved else frozenset()
        )
        super().__init__(
            checks,
            level=saved["level"] if saved else 0,
            mode=saved["mode"] if saved else None,
        )
        # snapshot restores stash serialized jax.export artifacts per
        # (comp, plan_key); a runner restored at promoted jit adopts one
        # lazily so the first call executes the exported program instead
        # of lowering+compiling its own candidate
        self._aot_state = None
        if self.mode == "jit" and self._jit_fn is not None:
            blobs = _aot_stash().get(comp, {}).get(self._plan_key)
            if blobs:
                self._adopt_preloaded_aot(list(blobs))

    @property
    def aot_state(self):
        """None (no artifact preloaded), ``pending`` (artifact staged,
        not yet bound to this binding's avals), ``adopted`` (the
        exported program is what runs), or ``fallback`` (binding failed;
        the ordinary jit candidate runs)."""
        return self._aot_state

    def _adopt_preloaded_aot(self, blobs):
        """Wrap the promoted candidate so the first call binds a
        preloaded ``jax.export`` artifact to this binding's input avals
        and executes the deserialized program from then on.  The traced
        candidate is only abstractly evaluated (``jax.eval_shape``, to
        recover the output treedef the flat export lost) — never
        lowered, never compiled, not even through the persistent compile
        cache.  Binding is best-effort: any failure falls back to the
        ordinary jit path."""
        traced = self._jit_fn
        bound = {}
        self._aot_state = "pending"

        def aot_run(*args):
            fn = bound.get("fn")
            if fn is None:
                try:
                    fn = self._bind_aot(traced, blobs, args)
                    self._aot_state = "adopted"
                except Exception as e:  # noqa: BLE001 — the artifact is
                    # an optimization; never let it take down serving
                    from ..logger import get_logger

                    get_logger().warning(
                        "AOT artifact adoption failed (%s); falling "
                        "back to cached jit", e,
                    )
                    fn = traced
                    self._aot_state = "fallback"
                bound["fn"] = fn
            return fn(*args)

        self._jit_fn = aot_run

    @staticmethod
    def _bind_aot(traced, blobs, args):
        from jax import export as jax_export

        def aval(leaf):
            return (tuple(int(d) for d in leaf.shape), str(leaf.dtype))

        want = [
            aval(leaf)
            for leaf in jax.tree_util.tree_leaves(
                jax.eval_shape(lambda *a: a, *args)
            )
        ]
        for blob in blobs:
            exported = jax_export.deserialize(bytearray(blob))
            if [aval(a) for a in exported.in_avals] != want:
                continue
            treedef = jax.tree_util.tree_structure(
                jax.eval_shape(traced, *args)
            )
            call = exported.call
            return lambda *a: jax.tree_util.tree_unflatten(
                treedef, call(*a)
            )
        raise ValueError(
            f"no preloaded AOT artifact matches input avals {want!r}"
        )

    def _build_candidate(self):
        comp = self._comp_ref()
        if comp is None:  # pragma: no cover - defensive
            raise RuntimeError("computation was garbage-collected")
        limit = self.LADDER[self._level]
        if limit is None:
            limit = self._tuned_limit  # autotuned first rung (or None)
        if limit is _PER_OP:
            self._jit_fn = None
            self._ref_fn = None
            self._per_op = None
            if self._per_op_builder is not None:
                self._per_op = self._per_op_builder(
                    comp, self._arguments, self.eager_plan,
                    _fault_kinds(), self._nonce_seed,
                    pinned=self._restored_pins,
                )
            return
        self._per_op = None
        _, self._jit_fn = self._builder(
            comp, self._arguments, True, limit, True,
            fault_kinds=_fault_kinds(),
        )
        if getattr(self, "_skip_ref_build", False):
            self._ref_fn = None  # restored promotion: never validated
        else:
            _, self._ref_fn = self._builder(
                comp, self._arguments, True, limit, False
            )

    def _eager_fn(self, *args):
        return self._eager_exec(*args)

    def _on_promoted(self):
        super()._on_promoted()
        # the argument binding (possibly large host arrays) was only
        # needed to rebuild candidates; promotion is terminal
        self._arguments = None

    def _invoke(self, fn, *args):
        if not self._pin_nonces:
            return fn(*args)
        from ..dialects import host

        with host.deterministic_sync_keys(self._nonce_seed):
            return fn(*args)

    def _with_nonces(self, fn, *args):  # kept for tests/direct callers
        return self._invoke(fn, *args)

    def _save_state(self):
        comp = self._comp_ref()
        if comp is None:  # pragma: no cover - defensive
            return
        entry = _registry().setdefault(comp, {})
        entry[self._plan_key] = {
            "level": self._level,
            "mode": self.mode,
            "pinned": (
                frozenset(self._per_op.pinned)
                if self._per_op is not None
                else self._restored_pins
            ),
        }

    # -- plan introspection (telemetry / runtime.last_timings) -------------

    @property
    def pinned_ops(self) -> list:
        """Names of the ops the per-op rung pinned eager (sorted)."""
        if self._per_op is not None:
            return sorted(self._per_op.pinned)
        return sorted(self._restored_pins) if self.mode == _PER_OP else []

    @property
    def plan_mode(self) -> str:
        """The resolved (or currently-validating) plan shape: one of
        ``whole-graph`` / ``segmented`` / ``per-op`` / ``eager``."""
        if self.mode == "eager" or self.mode == _PER_OP:
            return self.mode
        limit = self.LADDER[self._level]
        if limit is _PER_OP:
            return _PER_OP
        if limit is None:
            limit = self._tuned_limit
        seg = limit if limit is not None else _segment_limit()
        return "segmented" if len(self._order) > seg else "whole-graph"


def _logical_plan_builder(dialect):
    """builder hook for :class:`_SelfCheckRunner` over logical plans."""

    def build(comp, arguments, use_jit, segment_limit, jit_segments,
              fault_kinds=frozenset()):
        plan = build_plan(
            comp, arguments, use_jit, segment_limit=segment_limit,
            jit_segments=jit_segments, dialect=dialect,
            fault_kinds=fault_kinds,
        )
        if plan.fn is not None:  # segmented: already assembled
            return plan, plan.fn
        if use_jit and jit_segments:
            return plan, jax.jit(plan.core)
        return plan, plan.core

    return build


def _logical_per_op_builder(dialect):
    """per-op-rung builder hook for logical plans: one session per op
    (``key_domain = op index + 1``, the same discipline as segmented
    plans, so PRF streams never collide across ops) and a per-op
    deterministic nonce stream so each op's eager reference and jit
    candidate draw identical trace-time sync keys."""
    d = dialect if dialect is not None else logical

    def build(comp, arguments, eager_plan, fault_kinds, nonce_seed,
              pinned=()):
        import weakref

        order = eager_plan.order
        if len(order) > _per_op_limit():
            return None
        static_env = eager_plan.static_env
        comp_ref = weakref.ref(comp)

        def seg_exec(si, names, master_key, dyn, env, outputs, saves,
                     fault=frozenset()):
            comp = comp_ref()
            if comp is None:  # pragma: no cover - defensive
                raise RuntimeError("computation was garbage-collected")
            sess = d.make_session(master_key, key_domain=si + 1)
            d.bind_placements(sess, comp)
            _run_ops(
                sess, comp, names, static_env, env, outputs, saves, dyn,
                False, d, fault,
            )

        def seg_invoke(si, fn, *args):
            from ..dialects import host

            with host.deterministic_sync_keys(nonce_seed + si + 1):
                return fn(*args)

        always = {
            n for n in order
            if comp.operations[n].kind in _BOUNDARY_KINDS
        }
        return _PerOpPlan(
            order, static_env, eager_plan.dynamic_names,
            lambda n: comp.operations[n].inputs,
            seg_exec, fault_kinds, lambda mk, si: mk,
            always_eager=always, seg_invoke=seg_invoke, pinned=pinned,
        )

    return build


def _segment_limit() -> int:
    """Above this many ops a jitted plan is split into separately-jitted
    segments: XLA compile time is superlinear in program size (measured
    ~quadratic on the CPU backend — an 11k-op softmax graph costs ~340s
    in one program but tens of seconds as ~2k-op segments), while the
    segment boundary only costs keeping the crossing values materialized
    instead of fusing through.  0 disables segmentation."""
    import os

    raw = os.environ.get("MOOSE_TPU_JIT_SEGMENT", "2000")
    try:
        n = int(raw)
    except ValueError as e:
        from ..errors import ConfigurationError

        raise ConfigurationError(
            f"MOOSE_TPU_JIT_SEGMENT must be an integer, got {raw!r}"
        ) from e
    return n if n > 0 else (1 << 62)


def plan_segments(order, static_env, effective_inputs, limit, chunks=None):
    """Shared boundary-dataflow analysis for segmented execution (used by
    the logical and physical executors AND the distributed worker's role
    plan): split ``order`` into consecutive ``limit``-sized chunks and
    compute, per chunk, which earlier-produced values it consumes
    (``in_names``) and which of its values later chunks need
    (``out_names``).  ``effective_inputs(name)`` yields the dataflow
    inputs of one op (the physical executor maps a Receive to its Send's
    input here).

    ``chunks`` overrides the fixed-size split with an explicit chunk
    list — the distributed worker segments its role subgraph at
    Send/Receive boundaries, so its chunks are irregular.  The analysis
    then also tolerates PARTIAL graphs: an input whose producer sits in
    no chunk (a pending Receive, a host-boundary op the orchestrator
    resolves itself) is treated as an external env value — it crosses
    into its consuming chunk as an ordinary input and is never scheduled
    as a chunk output."""
    if chunks is None:
        chunks = [order[i:i + limit] for i in range(0, len(order), limit)]
    produced_by = {}
    for si, names in enumerate(chunks):
        for n in names:
            produced_by[n] = si

    in_names: list[list[str]] = []
    for si, names in enumerate(chunks):
        ins = set()
        for n in names:
            for i in effective_inputs(n):
                if i in static_env:
                    continue
                if produced_by.get(i, -1) != si:
                    ins.add(i)
        in_names.append(sorted(ins))
    out_names: list[list[str]] = [[] for _ in chunks]
    for si in range(len(chunks)):
        needed = set()
        for sj in range(si + 1, len(chunks)):
            needed.update(
                n for n in in_names[sj] if produced_by.get(n) == si
            )
        out_names[si] = sorted(needed)
    return chunks, in_names, out_names


def prefetch_to_host(*trees) -> None:
    """Start device-to-host transfers for every array leaf of ``trees``
    without blocking.  Called on outputs/saves as soon as a segment (or
    the whole plan) produces them, so the final numpy conversion finds
    the bytes already on host instead of paying one serialized
    device-to-host round trip per output at the end
    (``result_to_host_latency_s`` was ~3x the compute latency on
    tunneled setups, BENCH_r05)."""
    for leaf in jax.tree_util.tree_leaves(trees):
        fn = getattr(leaf, "copy_to_host_async", None)
        if fn is None:
            continue
        try:
            fn()
        except Exception:  # noqa: BLE001 — purely advisory: a tracer or
            # an already-deleted buffer just means nothing to prefetch
            pass


def build_segmented_runner(order, static_env, dynamic_names,
                           effective_inputs, limit, jit_segments,
                           seg_exec, rand_slice, segmentation=None):
    """THE segment orchestrator, shared by the logical and physical
    executors (VERDICT r4 #6: one segment planner, not two): split the
    op order into consecutive segments, jit each as its own XLA program,
    and orchestrate them from the host.  Values crossing a boundary
    travel as jit inputs/outputs (all moose value types are registered
    pytrees).

    ``seg_exec(si, names, rand, dyn, env, outputs, saves)`` runs one
    segment's ops against ``env`` (the executor supplies its session
    discipline there); ``rand_slice(rand, si)`` narrows the per-call
    randomness (whole master key for logical plans, the segment's PRF
    key dict for physical ones).  ``jit_segments=False`` keeps the
    identical structure but dispatches each segment eagerly — the exact
    reference the jit self-check compares against.  ``segmentation``
    accepts a precomputed ``plan_segments`` result so callers that also
    need the chunking (per-segment key narrowing) don't run the
    boundary-dataflow analysis twice."""
    chunks, in_names, out_names = (
        segmentation
        if segmentation is not None
        else plan_segments(
            order, static_env, effective_inputs,
            limit if limit is not None else _segment_limit(),
        )
    )
    dyn_set = set(dynamic_names)
    dyn_of = [[n for n in names if n in dyn_set] for names in chunks]

    def make_seg(si, names):
        outs = out_names[si]

        def seg(rand, dyn, env_in):
            # seed with every static value: a static op executed in an
            # earlier segment is not in env_in (statics never cross as
            # jit values) but may feed any later segment
            env: dict[str, Any] = dict(static_env)
            env.update(env_in)
            outputs: dict[str, Any] = {}
            saves: dict[tuple[str, str], Any] = {}
            seg_exec(si, names, rand, dyn, env, outputs, saves)
            return {n: env[n] for n in outs}, outputs, saves

        return jax.jit(seg) if jit_segments else seg

    seg_fns = [make_seg(si, names) for si, names in enumerate(chunks)]

    def run(rand, dyn: dict):
        from .. import profiling

        env: dict[str, Any] = {}
        outputs: dict[str, Any] = {}
        saves: dict[tuple[str, str], Any] = {}
        for si, fn in enumerate(seg_fns):
            # device-fenced profiling phase: while a capture window is
            # active the segment owns its device time (jax dispatch is
            # async — without the fence it would be misattributed to
            # whichever later phase first blocks); no-op otherwise
            with profiling.phase(
                "segment_execute", segment=si, ops=len(chunks[si]),
            ):
                env_out, out_i, sv_i = fn(
                    rand_slice(rand, si),
                    {n: dyn[n] for n in dyn_of[si]},
                    {n: env[n] for n in in_names[si]},
                )
                profiling.fence(env_out, out_i, sv_i)
            env.update(env_out)
            outputs.update(out_i)
            saves.update(sv_i)
            # results this segment finished transfer to host WHILE the
            # remaining segments compute (the final gather then finds
            # them resident instead of fetching serially at the end)
            if out_i or sv_i:
                prefetch_to_host(out_i, sv_i)
        return outputs, saves

    return run


def _build_segmented_plan(comp_ref, order, static_env, dynamic_names,
                          limit: Optional[int] = None,
                          jit_segments: bool = True, dialect=None,
                          fault_kinds=frozenset()):
    """Logical-plan segmentation: each segment runs its own session over
    the same master key with a distinct key domain, so PRF streams never
    collide across segments."""
    dialect = dialect if dialect is not None else logical
    comp = comp_ref()

    def seg_exec(si, names, master_key, dyn, env, outputs, saves):
        comp = comp_ref()
        if comp is None:  # pragma: no cover - defensive
            raise RuntimeError("computation was garbage-collected")
        sess = dialect.make_session(master_key, key_domain=si + 1)
        dialect.bind_placements(sess, comp)
        _run_ops(
            sess, comp, names, static_env, env, outputs, saves, dyn,
            False, dialect, fault_kinds,
        )

    run = build_segmented_runner(
        order, static_env, dynamic_names,
        lambda n: comp.operations[n].inputs,
        limit, jit_segments, seg_exec,
        lambda master_key, si: master_key,
    )
    return _Plan(order, static_env, dynamic_names, True, run, fn=run)


class _DeviceCache:
    """Device-resident copies of repeated argument arrays.

    Host->device transfer is the dominant per-call cost on tunneled TPU
    setups (and non-trivial everywhere); callers that evaluate the same
    computation repeatedly usually pass the same numpy arrays, so cache
    the upload.  Correctness against in-place mutation: entries are
    validated by an exact content hash on every hit (~10ms for 8MB —
    ~50x cheaper than re-uploading through a tunnel), so ``w[:] = new``
    between evaluations re-uploads instead of serving stale data.
    Bounded LRU (default 512MB) so long-lived processes iterating over
    many large arrays cannot exhaust device memory."""

    def __init__(self, max_bytes: int = 512 << 20):
        import threading
        from collections import OrderedDict

        self._entries: "OrderedDict[int, tuple]" = OrderedDict()
        self._bytes = 0
        self._max_bytes = max_bytes
        # the cache is a process-global shared by both interpreters and
        # by distributed worker threads
        self._lock = threading.Lock()

    @staticmethod
    def _fingerprint(arr) -> int:
        return hash(arr.tobytes())

    def put(self, arr):
        import jax

        if not isinstance(arr, np.ndarray) or arr.nbytes < (1 << 16):
            return arr  # small payloads: transfer cost is noise
        key = id(arr)
        fp = self._fingerprint(arr)
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                _, old_fp, device_arr, size = entry
                if old_fp == fp:
                    self._entries.move_to_end(key)
                    return device_arr
                # stale content: account with the size the entry was
                # stored at (the array may have been resized in place)
                self._bytes -= size
                del self._entries[key]
        import weakref

        def _expire(_, k=key):
            with self._lock:
                e = self._entries.pop(k, None)
                if e is not None:
                    self._bytes -= e[3]

        try:
            ref = weakref.ref(arr, _expire)
        except TypeError:  # non-weakrefable subclass
            return arr
        device_arr = jax.device_put(arr)
        with self._lock:
            self._entries[key] = (ref, fp, device_arr, arr.nbytes)
            self._bytes += arr.nbytes
            while self._bytes > self._max_bytes and self._entries:
                _, evicted = self._entries.popitem(last=False)
                self._bytes -= evicted[3]
        return device_arr


_device_cache = _DeviceCache()


def _save_user_value(value):
    """Storage form of a Save'd runtime value: ring tensors persist as
    uint64 limb planes (lossless through ``.npy``; ``to_numpy``'s
    object-int form is not) — the SaveShares/LoadShares round-trip —
    everything else keeps the user-facing conversion."""
    from ..values import HostRingTensor, ring_to_limbs

    if isinstance(value, HostRingTensor):
        return np.asarray(ring_to_limbs(value))
    return _to_user_value(value)


def _lift_shares(arrs, op, plc):
    """Reassemble a replicated sharing from the six party-held limb
    arrays of a LoadShares binding (party-major, slot-minor)."""
    from ..values import RepFixedTensor, RepTensor, limbs_to_ring

    dtype = op.signature.return_type.dtype
    width = 64 if dtype.name == "fixed64" else 128
    it = iter(arrs)
    shares = tuple(
        tuple(limbs_to_ring(next(it), width, owner) for _ in range(2))
        for owner in plc.owners
    )
    return RepFixedTensor(
        RepTensor(shares, plc.name),
        dtype.integral_precision,
        dtype.fractional_precision,
    )


def _stage_shares(sess, dialect, plc, key: str, value, saves) -> None:
    """Stage a SaveShares op: each party's two held ring tensors land in
    ``saves`` under that party's OWN (owner, key) slots — the plaintext
    is never reconstructed."""
    from ..compilation.lowering import _shares_of, share_key
    from ..dialects import logical as _logical

    if dialect is not _logical:
        from ..errors import TypeMismatchError

        raise TypeMismatchError(
            "SaveShares/LoadShares run on the per-host backends only"
        )
    rep = _logical.to_rep(sess, plc, value)
    rep_tensor, _, _ = _shares_of(rep)
    for i, owner in enumerate(plc.owners):
        for slot in (0, 1):
            saves[(owner, share_key(key, slot))] = (
                rep_tensor.shares[i][slot]
            )


def _lift_array(arr, op, plc_name: str):
    """Bind a host-boundary array (possibly a jit tracer) as a runtime
    value."""
    import jax.numpy as jnp

    ret = op.signature.return_type
    if ret.name in ("HostRing64Tensor", "HostRing128Tensor"):
        # ring-typed boundary (secret-shared checkpoints): storage holds
        # uint64 limb planes — see values.ring_to_limbs
        from ..values import limbs_to_ring

        return limbs_to_ring(
            arr, 64 if ret.name == "HostRing64Tensor" else 128, plc_name
        )
    dtype = ret.dtype
    if dtype is not None and dtype.is_fixedpoint:
        raise ValueError(
            f"op {op.name}: fixed-point host inputs must be loaded as floats "
            "and cast"
        )
    if dtype is not None and dtype.is_boolean:
        return HostBitTensor(jnp.asarray(arr).astype(jnp.uint8), plc_name)
    if dtype is not None:
        return HostTensor(
            jnp.asarray(arr).astype(np.dtype(dtype.numpy_name)),
            plc_name,
            dtype,
        )
    if isinstance(arr, np.ndarray):
        return host_tensor_from_numpy(arr, plc_name)
    return HostTensor(jnp.asarray(arr), plc_name, dt.from_numpy(arr.dtype))


class Interpreter:
    """Caches compiled plans per (computation, binding signature).

    The outer cache is weak-keyed on the Computation object itself — an
    ``id()`` key could be reused by a new computation after the old one is
    garbage-collected and silently serve a stale plan."""

    def __init__(self, dialect=None):
        import weakref

        # execution layout: None -> per-host logical dialect; an object
        # with execute_op/to_host/bind_placements/make_session (e.g.
        # dialects.stacked.StackedDialect) selects another backend
        self._dialect = dialect
        self._plan_key = (
            "logical" if dialect is None else type(dialect).__name__
        )
        self._cache = weakref.WeakKeyDictionary()
        # resolved plan shape of the most recent evaluate() — the
        # runtime lifts this into last_timings/last_plan
        self.last_plan_info: dict = {}

    def plan_exhausted(self, comp: Computation, arguments=None,
                       use_jit: bool = True) -> bool:
        """Would evaluating this computation run whole-plan eager
        because its validated-jit ladder already exhausted?  The
        runtime's cross-layout demotion routing asks this BEFORE
        dispatching, so an exhausted stacked plan is rerouted to the
        per-host auto-lowered path instead of pinning stacked-eager."""
        if not use_jit:
            return False
        saved = _registry().get(comp, {}).get(self._plan_key)
        return bool(saved) and saved.get("mode") == "eager"

    def _plan_info(self, plan, fn) -> dict:
        runner = getattr(fn, "__self__", None)
        if isinstance(runner, _SelfCheckRunner):
            return {
                "plan_mode": runner.plan_mode,
                "pinned_ops": runner.pinned_ops,
                "plan_state": runner.mode,
            }
        if plan.fn is not None:
            mode = "segmented"
        elif plan.use_jit:
            mode = "whole-graph"
        else:
            mode = "eager"
        return {"plan_mode": mode, "pinned_ops": [], "plan_state": "static"}

    def evaluate(
        self,
        comp: Computation,
        storage: dict,
        arguments: Optional[dict] = None,
        use_jit: bool = True,
    ) -> dict:
        from .. import telemetry

        arguments = arguments or {}
        # the gate must see the EXPANDED program size where the dialect
        # can estimate it (stacked graphs are short at the logical level
        # but expand protocol nonlinears into thousands of XLA ops)
        n_ops = (
            self._dialect.effective_ops(comp)
            if hasattr(self._dialect, "effective_ops")
            else len(comp.operations)
        )
        gated = heavy_jit_gate(n_ops, use_jit)
        selfcheck = use_jit and not gated and _selfcheck_runs() > 0
        use_jit = gated
        per_comp = self._cache.get(comp)
        if per_comp is None:
            per_comp = self._cache[comp] = {}
        cache_key = self._cache_key(arguments, (use_jit, selfcheck))
        cached = per_comp.get(cache_key)
        if cached is None:
            from ..compilation import autotune as _autotune

            tuned = _autotune.autotune_plan(comp, est_ops=n_ops)
            seg_dec = tuned["segment_limit"]
            # an env override already flows through _segment_limit();
            # only a measured/predicted choice needs explicit threading
            tuned_limit = (
                seg_dec.choice
                if seg_dec.source in ("predicted", "measured")
                else None
            )
            with telemetry.span("build_plan", n_ops=len(comp.operations)):
                if selfcheck:
                    runner = _SelfCheckRunner(
                        comp, arguments, _selfcheck_runs(),
                        dialect=self._dialect, plan_key=self._plan_key,
                        segment_limit=tuned_limit,
                    )
                    plan, fn = runner.eager_plan, runner.run
                else:
                    plan = build_plan(
                        comp, arguments, use_jit,
                        segment_limit=tuned_limit, dialect=self._dialect,
                    )
                    if plan.fn is not None:  # segmented: already jitted
                        fn = plan.fn
                    else:
                        fn = (
                            jax.jit(plan.core) if plan.use_jit else plan.core
                        )
            per_comp[cache_key] = (plan, fn, tuned)
        else:
            plan, fn, tuned = cached

        dyn = {}
        with telemetry.span("bind_arguments"):
            for name in plan.dynamic_names:
                op = comp.operations[name]
                plc = comp.placement_of(op)
                if op.kind == "Input":
                    val = arguments[name]
                    if not isinstance(val, np.ndarray):
                        val = np.asarray(val)
                    dyn[name] = _device_cache.put(val)
                elif op.kind == "LoadShares":
                    # each party's own persisted share pair, read from
                    # that party's OWN storage (party-major order, the
                    # _lift_shares convention)
                    from ..compilation.lowering import share_key

                    key = self._resolve_load_key(plan, comp, op, arguments)
                    arrs = []
                    for owner in plc.owners:
                        store = storage.get(owner, {})
                        for slot in (0, 1):
                            skey = share_key(key, slot)
                            if skey not in store:
                                raise KeyError(
                                    f"no value for key {skey!r} in "
                                    f"storage of {owner!r}"
                                )
                            val = store[skey]
                            if not isinstance(val, np.ndarray):
                                val = np.asarray(val)
                            arrs.append(_device_cache.put(val))
                    dyn[name] = tuple(arrs)
                else:  # Load
                    key = self._resolve_load_key(plan, comp, op, arguments)
                    store = storage.get(plc.name, {})
                    if key not in store:
                        raise KeyError(
                            f"no value for key {key!r} in storage of "
                            f"{plc.name!r}"
                        )
                    val = store[key]
                    if not isinstance(val, np.ndarray):
                        val = np.asarray(val)
                    dyn[name] = _device_cache.put(val)

        master_key = master_key_words("logical")
        import contextlib

        from ..dialects import host

        sync_seed = _fixed_sync_seed()
        sync_ctx = (
            host.deterministic_sync_keys(sync_seed)
            if sync_seed is not None
            else contextlib.nullcontext()
        )
        # the span covers output materialization as well — jit dispatch is
        # async, so timing the call alone would under-measure
        with telemetry.span("execute", jit=plan.use_jit) as sp, sync_ctx:
            outputs, saves = fn(master_key, dyn)
            # plan shape AFTER the run: a validating evaluation may have
            # promoted/demoted/pinned during the call
            info = self._plan_info(plan, fn)
            if tuned is not None:
                from ..compilation import autotune as _autotune

                info["autotune"] = {
                    "decisions": tuned.as_dict(),
                    # per-(width, class) dot verdicts the trace-time
                    # dispatch actually made (logical signatures carry
                    # no static shapes to predict from)
                    "pallas_dot_classes": _autotune.dot_decision_table(),
                }
            self.last_plan_info = info
            sp.attrs["plan_mode"] = info["plan_mode"]
            sp.attrs["pinned_ops"] = len(info["pinned_ops"])
            # all transfers start before any blocks: the per-output numpy
            # conversions below then overlap instead of serializing
            prefetch_to_host(outputs, saves)
            from .. import profiling

            with profiling.phase(
                "host_transfer", outputs=len(outputs), saves=len(saves),
            ):
                for (plc_name, key), value in saves.items():
                    storage.setdefault(plc_name, {})[key] = (
                        _save_user_value(value)
                    )
                return {
                    name: _to_user_value(outputs[name])
                    for name in ordered_output_names(outputs)
                }

    def _resolve_load_key(self, plan, comp, op, arguments) -> str:
        key_val = plan.static_env.get(op.inputs[0])
        if isinstance(key_val, HostString):
            return key_val.value
        raise ValueError(
            f"Load {op.name}: key must be statically resolvable "
            "(a string constant or string argument)"
        )

    def _cache_key(self, arguments, use_jit):
        return binding_cache_key(arguments, use_jit)


def binding_cache_key(arguments, use_jit):
    """Plan-cache key of one argument binding: shapes/dtypes for arrays,
    values for static scalars/strings (shared by the logical and physical
    interpreters)."""
    parts = [use_jit]
    for name, val in sorted(arguments.items()):
        if isinstance(val, (str, int, float)):
            parts.append((name, val))
        else:
            arr = np.asarray(val)
            parts.append((name, arr.shape, str(arr.dtype)))
    return tuple(parts)


def _to_user_value(value):
    """Convert a runtime value to the user-facing Python/numpy form."""
    if isinstance(value, HostUnit):
        return None
    if isinstance(value, HostFixedTensor):
        # decode plaintext fixed tensors for the user (documented deviation:
        # the reference returns the raw fixed value; floats are friendlier
        # and lossless for the precisions in use)
        from ..dialects import host as host_ops

        return np.asarray(
            to_numpy(host_ops.fixedpoint_decode(value, value.plc))
        )
    return to_numpy(value)


def ordered_output_names(outputs) -> list:
    """Outputs in declaration order: the tracer names them output_{i}
    (tracer.py); execution may reach them in any topological order."""

    import re

    def sort_key(name):
        m = re.match(r"output_(\d+)$", name)
        return (0, int(m.group(1))) if m else (1, name)

    return sorted(outputs, key=sort_key)
