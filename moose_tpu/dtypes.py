"""Logical tensor dtypes for the moose_tpu framework.

TPU-native re-design of the reference's dtype lattice
(``pymoose/pymoose/computation/dtypes.py`` and ``moose/src/logical/mod.rs:18-34``):
the logical ``Tensor`` type abstracts over Float32/Float64/Bool/Uint64 plaintext
dtypes and Fixed64/Fixed128 fixed-point dtypes backed by ring tensors.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class DType:
    """A logical dtype.

    ``name`` is the canonical short name (e.g. ``float64``, ``fixed128``).
    Fixed-point dtypes carry ``integral_precision`` / ``fractional_precision``.
    """

    name: str
    numpy_name: str | None = None
    is_float: bool = False
    is_integer: bool = False
    is_signed: bool = False
    is_boolean: bool = False
    is_fixedpoint: bool = False
    integral_precision: int | None = None
    fractional_precision: int | None = None

    @property
    def is_plaintext(self) -> bool:
        return not self.is_fixedpoint

    @property
    def precision(self) -> tuple[int, int] | None:
        if not self.is_fixedpoint:
            return None
        return (self.integral_precision, self.fractional_precision)

    def __str__(self) -> str:
        if self.is_fixedpoint:
            return (
                f"{self.name}({self.integral_precision}, "
                f"{self.fractional_precision})"
            )
        return self.name

    def __repr__(self) -> str:
        return str(self)

    def short_textual(self) -> str:
        """Textual-format spelling, e.g. ``Fixed128(24, 40)`` or ``Float64``."""
        mapping = {
            "float32": "Float32",
            "float64": "Float64",
            "int32": "Int32",
            "int64": "Int64",
            "uint32": "Uint32",
            "uint64": "Uint64",
            "bool": "Bool",
        }
        if self.is_fixedpoint:
            total = 64 if self.name == "fixed64" else 128
            return (
                f"Fixed{total}({self.integral_precision}, "
                f"{self.fractional_precision})"
            )
        return mapping[self.name]


float32 = DType("float32", "float32", is_float=True, is_signed=True)
float64 = DType("float64", "float64", is_float=True, is_signed=True)
int32 = DType("int32", "int32", is_integer=True, is_signed=True)
int64 = DType("int64", "int64", is_integer=True, is_signed=True)
uint32 = DType("uint32", "uint32", is_integer=True)
uint64 = DType("uint64", "uint64", is_integer=True)
bool_ = DType("bool", "bool", is_boolean=True)


# Accumulation headroom bits reserved when auto-selecting ring64 (covers
# reductions over up to 2^10 elements; see ``fixed``).
_RING64_HEADROOM = 10


def fixed(integral_precision: int, fractional_precision: int) -> DType:
    """Fixed-point dtype backed by a ring chosen by total precision.

    Mirrors the reference's ``pm.fixed(i, f)``.  The reference maps every
    fixed dtype to the 128-bit ring (pymoose/src/computation.rs:682); we
    instead select the 64-bit ring whenever all protocols still fit, which
    halves limb count on TPU.  The binding constraint: a raw product has
    magnitude < 2^{2(i+f)} and must satisfy trunc_pr's input bound
    |x| < 2^{width-3} (additive trunc with sign bit and overflow-correction
    slack), so a single product needs ``2*(i+f) <= 61``.  Reductions (Dot,
    Sum, AddN, Mean) accumulate up to log2(k) extra bits on top of that, so
    we keep ``_RING64_HEADROOM`` bits of slack — ring64 is only chosen when
    ``2*(i+f) + 10 <= 61``, safe for contractions over up to 2^10 = 1024
    elements.  Use ``fixed64(i, f)`` / ``fixed128(i, f)`` to force a ring.
    """
    if 2 * (integral_precision + fractional_precision) + _RING64_HEADROOM <= 61:
        name = "fixed64"
    else:
        name = "fixed128"
    return DType(
        name,
        is_fixedpoint=True,
        is_signed=True,
        integral_precision=integral_precision,
        fractional_precision=fractional_precision,
    )


def fixed64(integral_precision: int, fractional_precision: int) -> DType:
    return DType(
        "fixed64",
        is_fixedpoint=True,
        is_signed=True,
        integral_precision=integral_precision,
        fractional_precision=fractional_precision,
    )


def fixed128(integral_precision: int, fractional_precision: int) -> DType:
    return DType(
        "fixed128",
        is_fixedpoint=True,
        is_signed=True,
        integral_precision=integral_precision,
        fractional_precision=fractional_precision,
    )


_BY_NAME = {
    "float32": float32,
    "float64": float64,
    "int32": int32,
    "int64": int64,
    "uint32": uint32,
    "uint64": uint64,
    "bool": bool_,
}


def from_name(name: str, precision: tuple[int, int] | None = None) -> DType:
    if name == "fixed64":
        return fixed64(*precision)
    if name == "fixed128":
        return fixed128(*precision)
    return _BY_NAME[name]


def from_numpy(np_dtype) -> DType:
    import numpy as np

    return _BY_NAME[np.dtype(np_dtype).name]
