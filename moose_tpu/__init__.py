"""moose_tpu: a TPU-native secure multi-party computation framework.

A from-scratch re-design of the capabilities of the reference Moose framework
(compiler + runtime + Python eDSL for placement-pinned dataflow computations
with 3-party replicated secret sharing over Z_{2^64}/Z_{2^128}) built on
JAX/XLA: host kernels are jnp programs, the 3 parties ride a named mesh axis
with ICI collectives, and whole computations compile to single fused XLA
programs instead of per-op task graphs.
"""

import jax

# Ring arithmetic needs 64-bit lanes; must be set before any jnp usage.
jax.config.update("jax_enable_x64", True)

from . import dtypes  # noqa: E402
from .dtypes import (  # noqa: E402
    bool_,
    fixed,
    fixed64,
    fixed128,
    float32,
    float64,
    int32,
    int64,
    uint32,
    uint64,
)
from .computation import (  # noqa: E402
    AdditivePlacement,
    Computation,
    HostPlacement,
    Mirrored3Placement,
    Operation,
    ReplicatedPlacement,
)

__version__ = "0.1.0"

__all__ = [
    "dtypes",
    "bool_",
    "fixed",
    "fixed64",
    "fixed128",
    "float32",
    "float64",
    "int32",
    "int64",
    "uint32",
    "uint64",
    "AdditivePlacement",
    "Computation",
    "HostPlacement",
    "Mirrored3Placement",
    "Operation",
    "ReplicatedPlacement",
]


def __getattr__(name):
    # Lazy imports to keep `import moose_tpu` light and avoid cycles.
    if name in ("computation", "host_placement", "replicated_placement",
                "mirrored_placement", "Argument", "edsl"):
        from . import edsl

        if name == "edsl":
            return edsl
        return getattr(edsl.base, name)
    if name in ("LocalMooseRuntime", "GrpcMooseRuntime"):
        from . import runtime

        return getattr(runtime, name)
    if name == "predictors":
        from . import predictors

        return predictors
    raise AttributeError(f"module 'moose_tpu' has no attribute {name!r}")
