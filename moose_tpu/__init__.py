"""moose_tpu: a TPU-native secure multi-party computation framework.

A from-scratch re-design of the capabilities of the reference Moose framework
(compiler + runtime + Python eDSL for placement-pinned dataflow computations
with 3-party replicated secret sharing over Z_{2^64}/Z_{2^128}) built on
JAX/XLA: host kernels are jnp programs, the 3 parties ride a named mesh axis
with ICI collectives, and whole computations compile to single fused XLA
programs instead of per-op task graphs.

The public surface mirrors ``pymoose`` (reference pymoose/pymoose/__init__.py)
so existing ``@pm.computation`` graphs run unchanged.
"""

import jax

# Ring arithmetic needs 64-bit lanes; must be set before any jnp usage.
jax.config.update("jax_enable_x64", True)

from . import dtypes  # noqa: E402
from .dtypes import (  # noqa: E402
    bool_,
    fixed,
    fixed64,
    fixed128,
    float32,
    float64,
    int32,
    int64,
    uint32,
    uint64,
)
from .computation import (  # noqa: E402
    AdditivePlacement,
    Computation,
    HostPlacement,
    Mirrored3Placement,
    Operation,
    ReplicatedPlacement,
)
from .vtypes import (  # noqa: E402
    AesKeyType,
    AesTensorType,
    BytesType,
    FloatType,
    IntType,
    ShapeType,
    StringType,
    TensorType,
    UnitType,
)
from .edsl.base import (  # noqa: E402
    Argument,
    abs,
    add,
    add_n,
    argmax,
    atleast_2d,
    avg_pool2d,
    cast,
    computation,
    concatenate,
    constant,
    conv2d,
    decrypt,
    div,
    dot,
    equal,
    exp,
    expand_dims,
    get_current_placement,
    get_current_runtime,
    greater,
    host_placement,
    identity,
    index_axis,
    inverse,
    less,
    load,
    load_shares,
    log,
    log2,
    logical_and,
    logical_or,
    logical_xor,
    max_pool2d,
    maximum,
    mean,
    mirrored_placement,
    mul,
    mux,
    neg,
    ones,
    output,
    relu,
    replicated_placement,
    reshape,
    save,
    save_shares,
    select,
    set_current_runtime,
    shape,
    sigmoid,
    sliced,
    softmax,
    sqrt,
    square,
    squeeze,
    strided_slice,
    sub,
    sum,
    transpose,
    zeros,
)

__version__ = "0.1.0"


def __getattr__(name):
    # Lazy imports of heavier subsystems to keep `import moose_tpu` light.
    lazy = {
        "LocalMooseRuntime": ("runtime", "LocalMooseRuntime"),
        "GrpcMooseRuntime": ("runtime", "GrpcMooseRuntime"),
        "runtime": ("runtime", None),
        "predictors": ("predictors", None),
        "elk_compiler": ("elk_compiler", None),
        "parallel": ("parallel", None),
        "telemetry": ("telemetry", None),
        "metrics": ("metrics", None),
        "flight": ("flight", None),
    }
    if name in lazy:
        import importlib

        mod_name, attr = lazy[name]
        try:
            mod = importlib.import_module(f".{mod_name}", __name__)
        except ModuleNotFoundError as e:
            # keep hasattr()-style feature detection working
            raise AttributeError(
                f"module 'moose_tpu' has no attribute {name!r} ({e})"
            ) from e
        return mod if attr is None else getattr(mod, attr)
    raise AttributeError(f"module 'moose_tpu' has no attribute {name!r}")
