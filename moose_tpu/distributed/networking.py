"""Networking backends: value transfer keyed by (sender, receiver,
rendezvous key, session id).

Re-design of the reference's networking layer (``moose/src/networking/``):
the same trait shape — ``send(value, receiver, rendezvous_key, session_id)``
/ ``receive(sender, rendezvous_key, session_id)`` — with three transports:

- :class:`LocalNetworking` — in-memory store for tests and the dasher
  single-process simulator (networking/local.rs);
- :class:`TcpNetworking` — raw length-prefixed frames over persistent TCP
  with the framing/rendezvous store in native C++ (networking/tcpstream.rs;
  the reference's native layer is Rust, ours is C++ via ctypes);
- :class:`GrpcNetworking` — one ``SendValue`` rpc, out-of-order delivery
  handled by posting receive cells before sends arrive
  (networking/grpc.rs:25-234, protos/networking.proto).

Values cross the wire as msgpack (serde.serialize_value); the reference
uses bincode — same discipline, different codec.
"""

from __future__ import annotations

import threading
from typing import Optional

from ..errors import NetworkingError

DEFAULT_TIMEOUT_S = 120.0

_NET_METRICS = None


def _net_metrics():
    """Lazily-created wire counters on the global registry (every
    transport shares the families, labelled by transport kind)."""
    global _NET_METRICS
    if _NET_METRICS is None:
        from .. import metrics

        _NET_METRICS = {
            "tx_bytes": metrics.counter(
                "moose_tpu_net_tx_bytes_total",
                "serialized bytes handed to the wire",
                ("transport",),
            ),
            "rx_bytes": metrics.counter(
                "moose_tpu_net_rx_bytes_total",
                "serialized bytes received off the wire",
                ("transport",),
            ),
            "sends": metrics.counter(
                "moose_tpu_net_sends_total",
                "single-payload value sends",
                ("transport",),
            ),
            "send_many": metrics.counter(
                "moose_tpu_net_send_many_total",
                "coalesced send_many envelopes",
                ("transport",),
            ),
            "send_many_payloads": metrics.counter(
                "moose_tpu_net_send_many_payloads_total",
                "rendezvous payloads carried inside send_many envelopes",
                ("transport",),
            ),
            "receives": metrics.counter(
                "moose_tpu_net_receives_total",
                "rendezvous payloads consumed by receives",
                ("transport",),
            ),
        }
    return _NET_METRICS

# tensors routinely exceed gRPC's 4 MB default cap (an 800x800 float64 is
# already ~5 MB on the wire); the reference raises the tonic limits the
# same way for its SendValue payloads
GRPC_MESSAGE_OPTIONS = (
    ("grpc.max_send_message_length", -1),
    ("grpc.max_receive_message_length", -1),
)


def transfer_key(session_id: str, rendezvous_key: str) -> str:
    return f"{session_id}/{rendezvous_key}"


def pack_value_frame(sender: str, key: str, payload: bytes) -> bytes:
    """The single-payload SendValue frame.  Module-level (not a method)
    so the static cost model (analysis/cost.py) can price a transfer
    with the exact bytes the transport will emit — the frame layout has
    one definition."""
    import msgpack

    return msgpack.packb(
        {"key": key, "sender": sender, "value": payload},
        use_bin_type=True,
    )


def pack_batch_frame(sender: str, entries) -> bytes:
    """The coalesced send_many envelope: ``entries`` is
    ``[(transfer_key, payload_bytes), ...]`` — one rpc carrying several
    rendezvous payloads of one session.  Shared with the cost model
    like :func:`pack_value_frame`."""
    import msgpack

    return msgpack.packb(
        {
            "sender": sender,
            "batch": [
                {"key": key, "value": payload} for key, payload in entries
            ],
        },
        use_bin_type=True,
    )


class ProgressClock:
    """Monotonic liveness marker shared by a worker's ops: every local op
    completion (and, on gRPC workers, every successful peer ping) bumps
    it, and a blocked receive's deadline extends to ``last + timeout`` —
    so the timeout means "no sign of progress anywhere for timeout
    seconds", not "this one op took long" (the parallel scheduler
    dispatches all receives at launch, so a fixed per-op deadline would
    spuriously kill long pipelines)."""

    __slots__ = ("last", "count")

    def __init__(self):
        import time as _time

        self.last = _time.monotonic()
        # monotone completion counter: pings report it so a peer can
        # distinguish "alive and advancing" from "alive but stuck" —
        # only the former may extend blocked receives (a dropped send
        # would otherwise let mutually-blocked live workers extend each
        # other's deadlines forever)
        self.count = 0

    def bump(self):
        import time as _time

        self.last = _time.monotonic()
        self.count += 1

    def extend(self):
        """Extend the deadline WITHOUT claiming an op completed.  The
        failure detector uses this when peers report real advances:
        counting its own extension as progress would let two mutually
        blocked workers read each other's detector activity as op
        advances and extend forever."""
        import time as _time

        self.last = _time.monotonic()


def sliced_wait(wait_slice, timeout: float, cancel, what: str,
                progress: "ProgressClock" = None) -> None:
    """Wait for ``wait_slice(seconds) -> bool`` to report arrival.

    With no cancel event or progress clock this is one full-length wait;
    otherwise the wait runs in <=200ms slices: a set cancel event
    interrupts a blocked receive promptly (checked both before and after
    each slice so an abort in the final slice is reported as
    cancellation, not a spurious timeout), and a bumped progress clock
    extends the deadline.  Shared by every transport so the semantics
    can't drift."""
    import time as _time

    from ..errors import SessionAbortedError

    from ..errors import ReceiveTimeoutError

    if cancel is None and progress is None:
        if not wait_slice(timeout):
            raise ReceiveTimeoutError(
                f"receive timed out after {timeout}s for {what!r}"
            )
        return
    deadline = _time.monotonic() + timeout
    while True:
        if cancel is not None and cancel.is_set():
            raise SessionAbortedError(
                f"receive for {what!r} cancelled (session aborted)"
            )
        if progress is not None:
            deadline = max(deadline, progress.last + timeout)
        remaining = deadline - _time.monotonic()
        if remaining <= 0:
            raise ReceiveTimeoutError(
                f"receive timed out after {timeout}s (no session "
                f"progress) for {what!r}"
            )
        if wait_slice(min(0.2, remaining)):
            return


class _CellStore:
    """Rendezvous-keyed blocking cells: receive may be posted before the
    send arrives (reference AsyncCell store, networking/grpc.rs:189-207)."""

    # bound on remembered per-session activity events (mirrors the
    # worker server's session-id bookkeeping bound)
    _MAX_ACTIVITY = 4096

    def __init__(self):
        from collections import OrderedDict

        self._lock = threading.Lock()
        self._values: dict = {}
        self._events: dict = {}
        # keys already consumed by a receive: a duplicate delivery
        # (gRPC retry, chaos dup_send) of a consumed key must be
        # DROPPED, not re-posted — sessions never reuse a rendezvous
        # key, so a re-put could only recreate a never-consumed cell
        # (a slow leak) or hand a stale copy to nobody.  Bounded LRU,
        # same discipline as the session-id bookkeeping.
        self._delivered: "OrderedDict[str, None]" = OrderedDict()
        # per-session arrival wakeups: each session's receive poller
        # sleeps on ITS event — a shared one would let one session's
        # poller swallow another's wakeup (clear/wait race), degrading
        # concurrent sessions to the fallback poll interval.  LRU so a
        # busy long-lived session is never evicted by short-session
        # churn (every touch refreshes recency).
        self._activity: "OrderedDict[str, threading.Event]" = OrderedDict()

    def _mark_delivered(self, key: str) -> None:
        # caller holds self._lock
        self._delivered[key] = None
        while len(self._delivered) > self._MAX_ACTIVITY:
            self._delivered.popitem(last=False)

    def activity_for(self, session_id: str):
        with self._lock:
            ev = self._activity.get(session_id)
            if ev is None:
                ev = self._activity[session_id] = threading.Event()
                while len(self._activity) > self._MAX_ACTIVITY:
                    self._activity.popitem(last=False)
            else:
                self._activity.move_to_end(session_id)
            return ev

    def put(self, key: str, value):
        session_id = key.split("/", 1)[0]
        with self._lock:
            if key in self._delivered:
                return  # duplicate delivery of a consumed key: drop
            self._values[key] = value
            ev = self._events.get(key)
            if ev is None:
                ev = self._events[key] = threading.Event()
        ev.set()
        self.activity_for(session_id).set()

    def try_take(self, key: str):
        """Non-blocking probe: (True, value) and consume if present."""
        with self._lock:
            if key in self._values:
                self._events.pop(key, None)
                self._mark_delivered(key)
                return True, self._values.pop(key)
        return False, None

    def get(self, key: str, timeout: float, cancel=None, progress=None):
        with self._lock:
            ev = self._events.get(key)
            if ev is None:
                ev = self._events[key] = threading.Event()
        sliced_wait(ev.wait, timeout, cancel, key, progress)
        with self._lock:
            # single-consumer: drop the cell after use (sessions never
            # reuse a rendezvous key)
            self._events.pop(key, None)
            self._mark_delivered(key)
            return self._values.pop(key)

    def drop_session(self, session_id: str) -> int:
        """Remove every pending cell of one session (abort-path GC —
        payloads that arrived for a cancelled receive would otherwise be
        retained forever in a long-lived worker)."""
        prefix = f"{session_id}/"
        with self._lock:
            stale = [k for k in self._events if k.startswith(prefix)]
            stale += [
                k for k in self._values
                if k.startswith(prefix) and k not in self._events
            ]
            for k in stale:
                self._events.pop(k, None)
                self._values.pop(k, None)
            self._activity.pop(session_id, None)
        return len(stale)


class LocalNetworking:
    """In-memory networking shared by all virtual identities in one
    process.  Serializes values through the real wire codec so local tests
    exercise the same path as TCP/gRPC."""

    def __init__(self, serialize: bool = True):
        self._store = _CellStore()
        self._serialize = serialize

    def send(self, value, receiver: str, rendezvous_key: str,
             session_id: str):
        from .. import profiling
        from ..serde import serialize_value

        if self._serialize:
            with profiling.phase("serde", direction="tx"):
                payload = serialize_value(value)
        else:
            payload = value
        m = _net_metrics()
        m["sends"].inc(transport="local")
        if self._serialize:
            m["tx_bytes"].inc(len(payload), transport="local")
        self._store.put(transfer_key(session_id, rendezvous_key), payload)
        # transmitted bytes (the cost-drift watchdog tallies these per
        # session; None when the payload never hit the wire codec)
        return len(payload) if self._serialize else None

    def send_many(self, items, receiver: str, session_id: str):
        """Coalesced delivery of ``[(rendezvous_key, value), ...]`` to
        one receiver (the worker fast path batches same-destination
        sends at segment boundaries); in-memory this is just the loop,
        kept so local tests exercise the same call shape as gRPC."""
        m = _net_metrics()
        m["send_many"].inc(transport="local")
        m["send_many_payloads"].inc(len(items), transport="local")
        total = 0
        unknown = False
        for rendezvous_key, value in items:
            sent = self.send(value, receiver, rendezvous_key, session_id)
            if sent is None:
                unknown = True
            else:
                total += sent
        return None if unknown else total

    def receive(self, sender: str, rendezvous_key: str, session_id: str,
                plc: str = "", timeout: float = DEFAULT_TIMEOUT_S,
                cancel=None, progress=None):
        from ..serde import deserialize_value

        payload = self._store.get(
            transfer_key(session_id, rendezvous_key), timeout, cancel,
            progress,
        )
        m = _net_metrics()
        m["receives"].inc(transport="local")
        if self._serialize:
            from .. import profiling

            m["rx_bytes"].inc(len(payload), transport="local")
            with profiling.phase("serde", direction="rx"):
                return deserialize_value(payload, plc)
        return payload

    def activity_for(self, session_id: str):
        return self._store.activity_for(session_id)

    def try_receive(self, sender: str, rendezvous_key: str,
                    session_id: str, plc: str = ""):
        """Non-blocking receive probe for the worker's single poller
        thread: (True, value) if the payload has arrived."""
        from ..serde import deserialize_value

        ok, payload = self._store.try_take(
            transfer_key(session_id, rendezvous_key)
        )
        if not ok:
            return False, None
        m = _net_metrics()
        m["receives"].inc(transport="local")
        if self._serialize:
            from .. import profiling

            m["rx_bytes"].inc(len(payload), transport="local")
            with profiling.phase("serde", direction="rx"):
                return True, deserialize_value(payload, plc)
        return True, payload


class TcpNetworking:
    """Raw TCP transport backed by the native C++ library
    (moose_tpu/native/tcp_transport.cpp; reference networking/tcpstream.rs).

    ``endpoints`` maps identity -> "host:port"; the local identity's server
    must be started with :meth:`start`.
    """

    def __init__(self, identity: str, endpoints: dict):
        from ..native import tcp

        self._identity = identity
        self._endpoints = dict(endpoints)
        self._lib = tcp.load()
        self._server = None

    def start(self):
        from ..native import tcp

        _, port = self._endpoints[self._identity].rsplit(":", 1)
        self._server = tcp.ServerHandle(self._lib, int(port))
        return self

    def stop(self):
        if self._server is not None:
            self._server.close()
            self._server = None

    def send(self, value, receiver: str, rendezvous_key: str,
             session_id: str, max_retry_s: float = 30.0):
        import time

        from ..native import tcp
        from ..serde import serialize_value

        endpoint = self._endpoints.get(receiver)
        if endpoint is None:
            raise NetworkingError(f"unknown receiver identity {receiver!r}")
        host, port = endpoint.rsplit(":", 1)
        key = transfer_key(session_id, rendezvous_key)
        payload = serialize_value(value)
        m = _net_metrics()
        m["sends"].inc(transport="tcp")
        m["tx_bytes"].inc(len(payload), transport="tcp")
        # retry with backoff so workers may come up in any order
        # (networking/constants.rs backoff discipline)
        delay = 0.05
        deadline = time.monotonic() + max_retry_s
        while True:
            try:
                tcp.send(self._lib, host, int(port), key, payload)
                return len(payload)
            except NetworkingError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(delay)
                delay = min(delay * 2, 2.0)

    def receive(self, sender: str, rendezvous_key: str, session_id: str,
                plc: str = "", timeout: float = DEFAULT_TIMEOUT_S,
                cancel=None, progress=None):
        from ..serde import deserialize_value

        if self._server is None:
            raise NetworkingError(
                "TcpNetworking.receive before start(): the local server "
                "owns the rendezvous store"
            )
        key = transfer_key(session_id, rendezvous_key)
        box: list = []

        def wait_slice(seconds: float) -> bool:
            # the native wait is uninterruptible, so slices bound how
            # long a cancel can go unnoticed.  If the native call ever
            # returned early without a value, the sleep keeps the loop
            # paced instead of busy-spinning.
            import time as _time

            t0 = _time.monotonic()
            try:
                box.append(
                    self._server.receive(key, max(1, int(seconds * 1000)))
                )
                return True
            except NetworkingError as e:
                from ..errors import ReceiveTimeoutError

                if not isinstance(e, ReceiveTimeoutError):
                    raise
                elapsed = _time.monotonic() - t0
                if elapsed < seconds / 2:
                    _time.sleep(seconds - elapsed)
                return False

        sliced_wait(wait_slice, timeout, cancel, key, progress)
        m = _net_metrics()
        m["receives"].inc(transport="tcp")
        m["rx_bytes"].inc(len(box[0]), transport="tcp")
        return deserialize_value(box[0], plc)


SEND_VALUE_METHOD = "/moose.Networking/SendValue"
ABORT_SESSION_METHOD = "/moose.Networking/AbortSession"
PING_METHOD = "/moose.Networking/Ping"


class GrpcNetworking:
    """gRPC transport: a single SendValue rpc posts into the receiver's
    cell store (reference networking/grpc.rs).  The server half is hosted
    by the worker (see distributed.choreography.WorkerServer), which also
    serves the participant-level AbortSession and Ping methods used by
    the abort fanout and failure detector."""

    def __init__(self, identity: str, endpoints: dict, cells: Optional[
            _CellStore] = None, tls=None):
        self._identity = identity
        self._endpoints = dict(endpoints)
        self.cells = cells or _CellStore()
        self._channels: dict = {}
        self._lock = threading.Lock()
        self._tls = tls  # distributed.tls.TlsConfig or None

    def _stub(self, receiver: str, method: str = SEND_VALUE_METHOD):
        import grpc

        with self._lock:
            ch = self._channels.get(receiver)
            if ch is None:
                endpoint = self._endpoints.get(receiver)
                if endpoint is None:
                    raise NetworkingError(
                        f"unknown receiver identity {receiver!r}"
                    )
                if self._tls is not None:
                    # the server must present a certificate for the
                    # *receiver identity* (CN = party name)
                    ch = self._tls.secure_channel(endpoint, receiver)
                else:
                    ch = grpc.insecure_channel(
                        endpoint, options=GRPC_MESSAGE_OPTIONS
                    )
                self._channels[receiver] = ch
            return ch.unary_unary(method)

    def ping(self, receiver: str, timeout: float = 1.0,
             session_id: str = None) -> dict:
        """Liveness probe against a peer's worker daemon (failure
        detector); raises on any transport error.  With ``session_id``
        the response carries that session's status on the peer
        ("running" / "completed" / "aborted" / "unknown") so a live
        PROCESS whose session already died is distinguishable from real
        liveness — otherwise a missed abort fanout would keep extending
        receive deadlines forever."""
        import msgpack

        payload = msgpack.packb(
            {"from": self._identity, "session_id": session_id},
            use_bin_type=True,
        )
        raw = self._stub(receiver, PING_METHOD)(payload, timeout=timeout)
        return msgpack.unpackb(raw, raw=False) if raw else {}

    def abort_session(self, receiver: str, session_id: str,
                      reason: str, timeout: float = 3.0,
                      envelope: Optional[dict] = None):
        """Participant-level abort on a peer (first-error fanout). No
        retry: a fanout target that is down is already failing the
        session its own way.  ``envelope`` (errors.to_wire) carries the
        typed root cause so the peer's result cell keeps the real error
        class."""
        import msgpack

        payload = msgpack.packb(
            {
                "session_id": session_id,
                "reason": reason,
                "sender": self._identity,
                "envelope": envelope,
            },
            use_bin_type=True,
        )
        self._stub(receiver, ABORT_SESSION_METHOD)(
            payload, timeout=timeout
        )

    def verify_sender(self, frame: dict, context) -> None:
        """Under mTLS the claimed sender must match the peer
        certificate's CN (reference networking/grpc.rs:150-160 rejects
        spoofed senders); no-op without TLS."""
        if self._tls is None:
            return
        from .tls import peer_common_name, reject

        # fail closed: with mTLS configured, a missing context/peer
        # identity is as unacceptable as a mismatched one
        peer = peer_common_name(context) if context is not None else None
        claimed = frame.get("sender")
        if peer is None or peer != claimed:
            reject(
                context,
                f"sender identity mismatch: claimed {claimed!r}, "
                f"peer certificate CN {peer!r}",
            )

    def handle_send_value(self, request: bytes, context=None,
                          frame=None, verified: bool = False) -> bytes:
        """Server-side handler: unpack (key ‖ value) frame and post it
        (``frame`` lets a caller that already unpacked skip the repeat;
        ``verified`` skips the sender check when the caller already ran
        :meth:`verify_sender`).  A ``batch`` frame (send_many envelope)
        posts every entry — one rpc carrying several rendezvous
        payloads of one session."""
        import msgpack

        if frame is None:
            frame = msgpack.unpackb(request, raw=False)
        if not verified:
            self.verify_sender(frame, context)
        _net_metrics()["rx_bytes"].inc(len(request), transport="grpc")
        batch = frame.get("batch")
        if batch is not None:
            for entry in batch:
                self.cells.put(entry["key"], entry["value"])
        else:
            self.cells.put(frame["key"], frame["value"])
        return b""

    def _transmit(self, receiver: str, frame: bytes) -> None:
        # retry with backoff (reference networking/grpc.rs:106-112 retries
        # for up to 5 minutes; workers may come up in any order)
        import time

        delay = 0.05
        deadline = time.monotonic() + 60.0
        while True:
            try:
                self._stub(receiver)(frame, timeout=10.0)
                return
            except Exception as e:  # grpc.RpcError
                # authorization rejections arrive as PERMISSION_DENIED
                # (tls.reject) and are permanent — retrying would hide
                # the real error behind a 60s hang per send
                import grpc

                if (
                    isinstance(e, grpc.RpcError)
                    and e.code() == grpc.StatusCode.PERMISSION_DENIED
                ):
                    from ..errors import AuthorizationError

                    raise AuthorizationError(
                        f"send to {receiver!r} rejected: {e}"
                    ) from e
                if time.monotonic() > deadline:
                    raise NetworkingError(
                        f"send to {receiver!r} failed: {e}"
                    ) from e
                time.sleep(delay)
                delay = min(delay * 2, 2.0)

    def send(self, value, receiver: str, rendezvous_key: str,
             session_id: str):
        from .. import profiling
        from ..serde import serialize_value

        with profiling.phase("serde", direction="tx"):
            frame = pack_value_frame(
                self._identity,
                transfer_key(session_id, rendezvous_key),
                serialize_value(value),
            )
        m = _net_metrics()
        m["sends"].inc(transport="grpc")
        m["tx_bytes"].inc(len(frame), transport="grpc")
        self._transmit(receiver, frame)
        return len(frame)

    def send_many(self, items, receiver: str, session_id: str):
        """One SendValue rpc carrying several rendezvous payloads
        (``[(rendezvous_key, value), ...]``) — the worker fast path
        coalesces same-destination sends at segment boundaries so a
        protocol round costs one envelope per peer instead of one rpc
        per tensor."""
        from .. import profiling
        from ..serde import serialize_value

        with profiling.phase("serde", direction="tx", payloads=len(items)):
            frame = pack_batch_frame(
                self._identity,
                [
                    (transfer_key(session_id, key), serialize_value(value))
                    for key, value in items
                ],
            )
        m = _net_metrics()
        m["send_many"].inc(transport="grpc")
        m["send_many_payloads"].inc(len(items), transport="grpc")
        m["tx_bytes"].inc(len(frame), transport="grpc")
        self._transmit(receiver, frame)
        return len(frame)

    def receive(self, sender: str, rendezvous_key: str, session_id: str,
                plc: str = "", timeout: float = DEFAULT_TIMEOUT_S,
                cancel=None, progress=None):
        from .. import profiling
        from ..serde import deserialize_value

        payload = self.cells.get(
            transfer_key(session_id, rendezvous_key), timeout, cancel,
            progress,
        )
        _net_metrics()["receives"].inc(transport="grpc")
        with profiling.phase("serde", direction="rx"):
            return deserialize_value(payload, plc)

    def activity_for(self, session_id: str):
        return self.cells.activity_for(session_id)

    def try_receive(self, sender: str, rendezvous_key: str,
                    session_id: str, plc: str = ""):
        """Non-blocking receive probe (see LocalNetworking.try_receive)."""
        from ..serde import deserialize_value

        ok, payload = self.cells.try_take(
            transfer_key(session_id, rendezvous_key)
        )
        if not ok:
            return False, None
        _net_metrics()["receives"].inc(transport="grpc")
        from .. import profiling

        with profiling.phase("serde", direction="rx"):
            return True, deserialize_value(payload, plc)
