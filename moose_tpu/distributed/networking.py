"""Networking backends: value transfer keyed by (sender, receiver,
rendezvous key, session id).

Re-design of the reference's networking layer (``moose/src/networking/``):
the same trait shape — ``send(value, receiver, rendezvous_key, session_id)``
/ ``receive(sender, rendezvous_key, session_id)`` — with three transports:

- :class:`LocalNetworking` — in-memory store for tests and the dasher
  single-process simulator (networking/local.rs);
- :class:`TcpNetworking` — raw length-prefixed frames over persistent TCP
  with the framing/rendezvous store in native C++ (networking/tcpstream.rs;
  the reference's native layer is Rust, ours is C++ via ctypes);
- :class:`GrpcNetworking` — one ``SendValue`` rpc, out-of-order delivery
  handled by posting receive cells before sends arrive
  (networking/grpc.rs:25-234, protos/networking.proto).

Values cross the wire as msgpack (serde.serialize_value); the reference
uses bincode — same discipline, different codec.
"""

from __future__ import annotations

import threading
from typing import Optional

from ..errors import NetworkingError

DEFAULT_TIMEOUT_S = 120.0


def transfer_key(session_id: str, rendezvous_key: str) -> str:
    return f"{session_id}/{rendezvous_key}"


def sliced_wait(wait_slice, timeout: float, cancel, what: str) -> None:
    """Wait for ``wait_slice(seconds) -> bool`` to report arrival.

    With no cancel event this is one full-length wait; with one, the wait
    runs in <=200ms slices and a set event interrupts a blocked receive
    promptly — checked both before and after each slice so an abort in
    the final slice is reported as cancellation, not a spurious timeout.
    Shared by every transport so the semantics can't drift."""
    import time as _time

    if cancel is None:
        if not wait_slice(timeout):
            raise NetworkingError(
                f"receive timed out after {timeout}s for {what!r}"
            )
        return
    deadline = _time.monotonic() + timeout
    while True:
        if cancel.is_set():
            raise NetworkingError(
                f"receive for {what!r} cancelled (session aborted)"
            )
        remaining = deadline - _time.monotonic()
        if remaining <= 0:
            raise NetworkingError(
                f"receive timed out after {timeout}s for {what!r}"
            )
        if wait_slice(min(0.2, remaining)):
            return


class _CellStore:
    """Rendezvous-keyed blocking cells: receive may be posted before the
    send arrives (reference AsyncCell store, networking/grpc.rs:189-207)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._values: dict = {}
        self._events: dict = {}

    def put(self, key: str, value):
        with self._lock:
            self._values[key] = value
            ev = self._events.get(key)
            if ev is None:
                ev = self._events[key] = threading.Event()
        ev.set()

    def get(self, key: str, timeout: float, cancel=None):
        with self._lock:
            ev = self._events.get(key)
            if ev is None:
                ev = self._events[key] = threading.Event()
        sliced_wait(ev.wait, timeout, cancel, key)
        with self._lock:
            # single-consumer: drop the cell after use (sessions never
            # reuse a rendezvous key)
            self._events.pop(key, None)
            return self._values.pop(key)

    def drop_session(self, session_id: str) -> int:
        """Remove every pending cell of one session (abort-path GC —
        payloads that arrived for a cancelled receive would otherwise be
        retained forever in a long-lived worker)."""
        prefix = f"{session_id}/"
        with self._lock:
            stale = [k for k in self._events if k.startswith(prefix)]
            stale += [
                k for k in self._values
                if k.startswith(prefix) and k not in self._events
            ]
            for k in stale:
                self._events.pop(k, None)
                self._values.pop(k, None)
        return len(stale)


class LocalNetworking:
    """In-memory networking shared by all virtual identities in one
    process.  Serializes values through the real wire codec so local tests
    exercise the same path as TCP/gRPC."""

    def __init__(self, serialize: bool = True):
        self._store = _CellStore()
        self._serialize = serialize

    def send(self, value, receiver: str, rendezvous_key: str,
             session_id: str):
        from ..serde import serialize_value

        payload = (
            serialize_value(value) if self._serialize else value
        )
        self._store.put(transfer_key(session_id, rendezvous_key), payload)

    def receive(self, sender: str, rendezvous_key: str, session_id: str,
                plc: str = "", timeout: float = DEFAULT_TIMEOUT_S,
                cancel=None):
        from ..serde import deserialize_value

        payload = self._store.get(
            transfer_key(session_id, rendezvous_key), timeout, cancel
        )
        if self._serialize:
            return deserialize_value(payload, plc)
        return payload


class TcpNetworking:
    """Raw TCP transport backed by the native C++ library
    (moose_tpu/native/tcp_transport.cpp; reference networking/tcpstream.rs).

    ``endpoints`` maps identity -> "host:port"; the local identity's server
    must be started with :meth:`start`.
    """

    def __init__(self, identity: str, endpoints: dict):
        from ..native import tcp

        self._identity = identity
        self._endpoints = dict(endpoints)
        self._lib = tcp.load()
        self._server = None

    def start(self):
        from ..native import tcp

        _, port = self._endpoints[self._identity].rsplit(":", 1)
        self._server = tcp.ServerHandle(self._lib, int(port))
        return self

    def stop(self):
        if self._server is not None:
            self._server.close()
            self._server = None

    def send(self, value, receiver: str, rendezvous_key: str,
             session_id: str, max_retry_s: float = 30.0):
        import time

        from ..native import tcp
        from ..serde import serialize_value

        endpoint = self._endpoints.get(receiver)
        if endpoint is None:
            raise NetworkingError(f"unknown receiver identity {receiver!r}")
        host, port = endpoint.rsplit(":", 1)
        key = transfer_key(session_id, rendezvous_key)
        payload = serialize_value(value)
        # retry with backoff so workers may come up in any order
        # (networking/constants.rs backoff discipline)
        delay = 0.05
        deadline = time.monotonic() + max_retry_s
        while True:
            try:
                tcp.send(self._lib, host, int(port), key, payload)
                return
            except NetworkingError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(delay)
                delay = min(delay * 2, 2.0)

    def receive(self, sender: str, rendezvous_key: str, session_id: str,
                plc: str = "", timeout: float = DEFAULT_TIMEOUT_S,
                cancel=None):
        from ..serde import deserialize_value

        if self._server is None:
            raise NetworkingError(
                "TcpNetworking.receive before start(): the local server "
                "owns the rendezvous store"
            )
        key = transfer_key(session_id, rendezvous_key)
        box: list = []

        def wait_slice(seconds: float) -> bool:
            # the native wait is uninterruptible, so slices bound how
            # long a cancel can go unnoticed.  If the native call ever
            # returned early without a value, the sleep keeps the loop
            # paced instead of busy-spinning.
            import time as _time

            t0 = _time.monotonic()
            try:
                box.append(
                    self._server.receive(key, max(1, int(seconds * 1000)))
                )
                return True
            except NetworkingError as e:
                if "timed out" not in str(e):
                    raise
                elapsed = _time.monotonic() - t0
                if elapsed < seconds / 2:
                    _time.sleep(seconds - elapsed)
                return False

        sliced_wait(wait_slice, timeout, cancel, key)
        return deserialize_value(box[0], plc)


class GrpcNetworking:
    """gRPC transport: a single SendValue rpc posts into the receiver's
    cell store (reference networking/grpc.rs).  The server half is hosted
    by the worker (see distributed.worker.WorkerServer)."""

    def __init__(self, identity: str, endpoints: dict, cells: Optional[
            _CellStore] = None, tls=None):
        self._identity = identity
        self._endpoints = dict(endpoints)
        self.cells = cells or _CellStore()
        self._channels: dict = {}
        self._lock = threading.Lock()
        self._tls = tls  # distributed.tls.TlsConfig or None

    def _stub(self, receiver: str):
        import grpc

        with self._lock:
            ch = self._channels.get(receiver)
            if ch is None:
                endpoint = self._endpoints.get(receiver)
                if endpoint is None:
                    raise NetworkingError(
                        f"unknown receiver identity {receiver!r}"
                    )
                if self._tls is not None:
                    # the server must present a certificate for the
                    # *receiver identity* (CN = party name)
                    ch = self._tls.secure_channel(endpoint, receiver)
                else:
                    ch = grpc.insecure_channel(endpoint)
                self._channels[receiver] = ch
            return ch.unary_unary("/moose.Networking/SendValue")

    def handle_send_value(self, request: bytes, context=None,
                          frame=None) -> bytes:
        """Server-side handler: unpack (key ‖ value) frame and post it
        (``frame`` lets a caller that already unpacked skip the repeat).

        Under mTLS the claimed sender must match the peer certificate's CN
        (reference networking/grpc.rs:150-160 rejects spoofed senders)."""
        import msgpack

        if frame is None:
            frame = msgpack.unpackb(request, raw=False)
        if self._tls is not None:
            from .tls import peer_common_name, reject

            # fail closed: with mTLS configured, a missing context/peer
            # identity is as unacceptable as a mismatched one
            peer = (
                peer_common_name(context) if context is not None else None
            )
            claimed = frame.get("sender")
            if peer is None or peer != claimed:
                reject(
                    context,
                    f"sender identity mismatch: claimed {claimed!r}, "
                    f"peer certificate CN {peer!r}",
                )
        self.cells.put(frame["key"], frame["value"])
        return b""

    def send(self, value, receiver: str, rendezvous_key: str,
             session_id: str):
        import msgpack

        from ..serde import serialize_value

        frame = msgpack.packb(
            {
                "key": transfer_key(session_id, rendezvous_key),
                "sender": self._identity,
                "value": serialize_value(value),
            },
            use_bin_type=True,
        )
        # retry with backoff (reference networking/grpc.rs:106-112 retries
        # for up to 5 minutes; workers may come up in any order)
        import time

        delay = 0.05
        deadline = time.monotonic() + 60.0
        while True:
            try:
                self._stub(receiver)(frame, timeout=10.0)
                return
            except Exception as e:  # grpc.RpcError
                # authorization rejections arrive as PERMISSION_DENIED
                # (tls.reject) and are permanent — retrying would hide
                # the real error behind a 60s hang per send
                import grpc

                if (
                    isinstance(e, grpc.RpcError)
                    and e.code() == grpc.StatusCode.PERMISSION_DENIED
                ):
                    raise NetworkingError(
                        f"send to {receiver!r} rejected: {e}"
                    ) from e
                if time.monotonic() > deadline:
                    raise NetworkingError(
                        f"send to {receiver!r} failed: {e}"
                    ) from e
                time.sleep(delay)
                delay = min(delay * 2, 2.0)

    def receive(self, sender: str, rendezvous_key: str, session_id: str,
                plc: str = "", timeout: float = DEFAULT_TIMEOUT_S,
                cancel=None):
        from ..serde import deserialize_value

        payload = self.cells.get(
            transfer_key(session_id, rendezvous_key), timeout, cancel
        )
        return deserialize_value(payload, plc)
