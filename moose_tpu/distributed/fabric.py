"""Fabric transport: parties as device-mesh slices, rendezvous as
``collective_permute``.

The stated goal of this reproduction is the 3-party protocol "executing
on TPU meshes instead of CPU + gRPC" (PAPER.md): when parties opt into a
shared accelerator fabric, an inter-party Send/Receive should be a
device-to-device ``collective_permute`` inside a compiled program — no
host round-trip, no serde — with gRPC kept for party pairs that cross a
real trust boundary.  Design per GSPMD-style compiler-driven collective
lowering applied to the reference Moose rendezvous model: a party is a
mesh slice, a rendezvous key resolves to a permute edge at plan-build
time.

Two pieces:

- :class:`FabricDomain` — the per-deployment declaration ``party ->
  slice of devices`` plus an explicit ``trust_model`` attestation.  A
  domain is a statement that its member parties accept residency on one
  shared device fabric under one controller (the classic TEE /
  colocated-accelerator deployment); parties OUTSIDE the domain keep the
  wire, so mixed sessions (some edges fabric, some gRPC) are
  first-class.
- :class:`FabricNetworking` — the networking-trait implementation that
  lowers intra-fabric sends to ``shard_map`` + ``lax.ppermute`` programs
  over the domain mesh (``send_many`` coalescing becomes ONE batched
  permute program), delivers the moved value straight into the
  receiver's rendezvous cell store (raw value, zero serde), and
  delegates trust-boundary edges to the wrapped wire transport
  unchanged.

Delivery discipline: fabric payloads land in the SAME per-party cell
store the wire transport uses, as raw runtime values (the wire posts
``bytes``).  The payload type IS the transport marker, so receives,
duplicate-drop, abort GC, activity wakeups, and the chaos layer's
drop -> forced-wire replay all compose over one store with no second
rendezvous namespace.

Safety gates:

- the MSA505 rule (analysis/schedule.py) re-runs the deadlock fixed
  point over the fabric-lowered schedule at plan-build time;
  :meth:`FabricNetworking.prepare_fabric` force-wires every edge of a
  rejected computation (flight event ``fabric_rejected``) instead of
  entering an unprovable collective schedule;
- the MSA6xx cost model (analysis/cost.py, ``transport="fabric"``)
  prices each permute as device bytes x ring hops BEFORE anything runs,
  and the cost-drift watchdog compares those predictions against the
  ``moose_tpu_fabric_*`` runtime counters per session.

Env knobs: ``MOOSE_TPU_FABRIC=0`` disables fabric lowering globally (a
declared domain falls back to the wire); ``MOOSE_TPU_FABRIC_TRUST``
names the default trust model for :meth:`FabricDomain.default`.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..errors import ConfigurationError, NetworkingError
from .networking import DEFAULT_TIMEOUT_S, _net_metrics, transfer_key

# trust models a domain may attest to.  The attestation is an explicit,
# auditable deployment statement — "these parties accept shared-fabric
# residency because <model>" — not something the runtime can infer.
TRUST_MODELS = (
    # one controller process drives every party's devices (in-process
    # clusters, single-host multi-chip, TEE-backed single tenants)
    "single_controller",
    # distinct parties whose accelerators share an interconnect inside
    # one attested enclave boundary
    "colocated_tee",
    # test/bench simulation: explicitly NOT a privacy claim
    "simulation",
)

_FABRIC_METRICS = None
_metrics_lock = threading.Lock()


def _fabric_metrics():
    """Fabric-specific counter families on the global registry (the
    wire families in ``networking._net_metrics`` are shared too, under
    ``transport="fabric"``)."""
    global _FABRIC_METRICS
    with _metrics_lock:
        if _FABRIC_METRICS is None:
            from .. import metrics

            _FABRIC_METRICS = {
                "permutes": metrics.counter(
                    "moose_tpu_fabric_permutes_total",
                    "collective-permute program launches",
                    (),
                ),
                "batched": metrics.counter(
                    "moose_tpu_fabric_batched_permutes_total",
                    "permute launches that coalesced >1 rendezvous "
                    "payloads (send_many lowering)",
                    (),
                ),
                "payloads": metrics.counter(
                    "moose_tpu_fabric_permute_payloads_total",
                    "rendezvous payloads moved by collective permutes",
                    (),
                ),
                "tx_bytes": metrics.counter(
                    "moose_tpu_fabric_tx_bytes_total",
                    "device bytes moved by collective permutes "
                    "(array leaf bytes, no serde framing)",
                    (),
                ),
                "fallbacks": metrics.counter(
                    "moose_tpu_fabric_fallbacks_total",
                    "sends that fell back to the wire transport, by "
                    "reason",
                    ("reason",),
                ),
            }
        return _FABRIC_METRICS


def value_leaves(value) -> list:
    """The array leaves a fabric transfer moves — THE single source of
    truth shared with the cost model (``analysis/cost.py`` applies the
    same function to a spec placeholder, which is what makes predicted
    fabric bytes equal measured bytes exactly).  Values with no array
    leaves (HostUnit, HostShape, HostString) pass through the cell
    store directly: there is nothing for a permute to move."""
    import jax

    return jax.tree_util.tree_leaves(value)


def leaf_bytes(leaves: Sequence[Any]) -> int:
    import numpy as np

    return sum(int(np.asarray(leaf).nbytes) for leaf in leaves)


def _restamp_plc(value, plc: str):
    """Re-placement a received value: serde stamps ``plc`` during
    deserialization; fabric delivery skips serde, so the receiver
    rewrites the placement fields of the (host-level) value tree."""
    import dataclasses

    if not plc or not dataclasses.is_dataclass(value):
        return value
    changes = {}
    for field in dataclasses.fields(value):
        v = getattr(value, field.name)
        if field.name == "plc" and isinstance(v, str):
            changes[field.name] = plc
        elif dataclasses.is_dataclass(v) and not isinstance(v, type):
            changes[field.name] = _restamp_plc(v, plc)
        elif isinstance(v, tuple) and any(
            dataclasses.is_dataclass(e) and not isinstance(e, type)
            for e in v
        ):
            changes[field.name] = tuple(
                _restamp_plc(e, plc) if dataclasses.is_dataclass(e)
                else e
                for e in v
            )
    return dataclasses.replace(value, **changes) if changes else value


def fabric_enabled() -> bool:
    """Global kill switch: ``MOOSE_TPU_FABRIC=0`` forces every declared
    domain back onto the wire (bit-identical by construction — the
    fabric moves the same tensors the wire would)."""
    import os

    return os.environ.get("MOOSE_TPU_FABRIC", "1") not in ("0", "off")


class FabricDomain:
    """One shared-fabric trust domain: ``slices`` maps each member
    party to its slice of devices (disjoint across parties), and
    ``trust_model`` is the explicit attestation under which the members
    accept shared-device residency.

    The domain owns the permute mesh (axis ``"parties"``, one lead
    device per party, in declaration order — party index = ring
    position, so the MSA6xx hop count is the ring distance), the
    per-party rendezvous cell registry the permute programs deliver
    into, and the ``force_wire`` latch set (stable rendezvous keys
    whose transfers must ride the wire — the chaos layer's
    drop -> forced-wire-replay contract, and the MSA505 rejection
    path)."""

    def __init__(self, slices: Dict[str, Sequence[Any]],
                 trust_model: str):
        if trust_model not in TRUST_MODELS:
            raise ConfigurationError(
                f"unknown fabric trust_model {trust_model!r}; a domain "
                f"must attest one of {TRUST_MODELS} — the fabric never "
                "infers trust"
            )
        if len(slices) < 2:
            raise ConfigurationError(
                "a FabricDomain needs >= 2 parties (one party has no "
                "inter-party edges to lower)"
            )
        seen: dict = {}
        for party, devs in slices.items():
            if not devs:
                raise ConfigurationError(
                    f"fabric party {party!r} declared an empty device "
                    "slice"
                )
            for d in devs:
                if id(d) in seen:
                    raise ConfigurationError(
                        f"device {d} is claimed by both "
                        f"{seen[id(d)]!r} and {party!r}: fabric slices "
                        "must be disjoint (shared devices would leak "
                        "one party's residency into another's)"
                    )
                seen[id(d)] = party
        self.trust_model = trust_model
        self.slices = {p: tuple(devs) for p, devs in slices.items()}
        self.parties = tuple(self.slices)
        self._index = {p: i for i, p in enumerate(self.parties)}
        self._lock = threading.Lock()
        self._mesh = None  # built lazily (first permute)
        self._programs: dict = {}  # (src, dst) or perm -> jitted program
        self._cells: dict = {}  # party -> its rendezvous _CellStore
        self._force_wire: set = set()  # stable rendezvous keys
        # computations whose fabric schedule MSA505 rejected (weak-keyed
        # like the plan cache) + the sessions currently running them:
        # every edge of a rejected session rides the wire
        import collections
        import weakref

        self._rejected: "weakref.WeakSet" = weakref.WeakSet()
        self._prepared: "weakref.WeakKeyDictionary" = (
            weakref.WeakKeyDictionary()
        )
        self._rejected_sessions: "collections.OrderedDict[str, None]" = (
            collections.OrderedDict()
        )

    @classmethod
    def default(cls, parties: Sequence[str],
                trust_model: Optional[str] = None) -> "FabricDomain":
        """One-device-per-party domain over the first
        ``len(parties)`` local devices (the CPU tier's
        ``xla_force_host_platform_device_count`` virtual devices, or
        real accelerator chips)."""
        import os

        import jax

        if trust_model is None:
            trust_model = os.environ.get(
                "MOOSE_TPU_FABRIC_TRUST", "single_controller"
            )
        devices = jax.devices()
        if len(devices) < len(parties):
            raise ConfigurationError(
                f"fabric needs one device per party: {len(parties)} "
                f"parties, {len(devices)} devices visible"
            )
        return cls(
            {p: (devices[i],) for i, p in enumerate(parties)},
            trust_model=trust_model,
        )

    # -- membership / routing ------------------------------------------

    def party_index(self, party: str) -> int:
        return self._index[party]

    def is_member(self, party: str) -> bool:
        return party in self._index

    def hops(self, sender: str, receiver: str) -> int:
        """MSA6xx distance: ring hops between the parties' mesh
        positions (the permute mesh is a ring; on 3 parties every edge
        is one hop)."""
        n = len(self.parties)
        d = (self._index[receiver] - self._index[sender]) % n
        return min(d, n - d) or n  # self-edges never happen; keep >= 1

    def cost_context(self) -> Tuple[Tuple[str, ...], str]:
        """Hashable descriptor the cost model keys its fabric
        predictions on."""
        return (self.parties, self.trust_model)

    # -- force-wire latches --------------------------------------------

    def force_wire(self, stable_key: str) -> None:
        """Latch one logical rendezvous key onto the wire path.  The
        chaos layer calls this when it drops a fabric send: the
        REPLAY of that key (same stable key, next attempt) must not
        re-enter a collective whose payload was already lost — it rides
        gRPC instead, bit-identically (transport moves, values don't).
        Keys are stable rendezvous keys (no session prefix) so the
        latch survives the supervisor's fresh session id."""
        with self._lock:
            self._force_wire.add(stable_key)

    def is_forced_wire(self, stable_key: str) -> bool:
        with self._lock:
            return stable_key in self._force_wire

    # bound mirrors the cell store's session bookkeeping
    _MAX_REJECTED_SESSIONS = 4096

    def reject_computation(self, comp) -> None:
        self._rejected.add(comp)

    def is_rejected(self, comp) -> bool:
        return comp in self._rejected

    def reject_session(self, session_id: str) -> None:
        with self._lock:
            self._rejected_sessions[session_id] = None
            while len(self._rejected_sessions) > \
                    self._MAX_REJECTED_SESSIONS:
                self._rejected_sessions.popitem(last=False)

    def is_rejected_session(self, session_id: str) -> bool:
        with self._lock:
            return session_id in self._rejected_sessions

    # -- cell registry --------------------------------------------------

    def register_cells(self, party: str, cells) -> None:
        with self._lock:
            self._cells[party] = cells

    def cells_of(self, party: str):
        with self._lock:
            return self._cells.get(party)

    # -- the permute programs ------------------------------------------

    def _mesh_or_build(self):
        with self._lock:
            if self._mesh is None:
                from ..parallel.spmd import fabric_party_mesh

                self._mesh = fabric_party_mesh(
                    [devs[0] for devs in self.slices.values()]
                )
            return self._mesh

    def _program(self, src: int, dst: int):
        """The jitted permute program for one mesh edge.  jax.jit's
        own cache handles per-shape retraces, so one program object per
        (src, dst) serves every leaf signature; a batched ``send_many``
        group simply passes more leaves to the same program."""
        with self._lock:
            prog = self._programs.get((src, dst))
            if prog is not None:
                return prog
        import jax
        import jax.numpy as jnp
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        mesh = self._mesh_or_build()
        n = len(self.parties)

        def _move(*leaves):
            # place each leaf on the sender's mesh row, permute the
            # row to the receiver, read the receiver's row back — all
            # one XLA program: the transfer itself never touches the
            # host or the serde codec
            def shifted(*xs):
                return tuple(
                    jax.lax.ppermute(
                        x, "parties", perm=[(src, dst)]
                    )
                    for x in xs
                )

            stacked = tuple(
                jnp.zeros((n,) + jnp.shape(x), jnp.asarray(x).dtype)
                .at[src].set(x)
                for x in leaves
            )
            moved = shard_map(
                shifted, mesh=mesh,
                in_specs=P("parties"), out_specs=P("parties"),
            )(*stacked)
            return tuple(m[dst] for m in moved)

        prog = jax.jit(_move)
        with self._lock:
            self._programs.setdefault((src, dst), prog)
            return self._programs[(src, dst)]

    def permute(self, sender: str, receiver: str,
                leaves: Sequence[Any]) -> Tuple[list, int]:
        """Run the collective permute moving ``leaves`` from
        ``sender``'s slice to ``receiver``'s; returns (moved leaves,
        device bytes moved).  One call = one compiled collective
        program = one tick of ``moose_tpu_fabric_permutes_total``."""
        from .. import profiling

        src = self._index[sender]
        dst = self._index[receiver]
        bytes_moved = leaf_bytes(leaves)
        program = self._program(src, dst)
        fm = _fabric_metrics()
        with profiling.phase(
            "fabric_permute", src=sender, dst=receiver,
            payload_leaves=len(leaves), bytes=bytes_moved,
        ):
            moved = program(*leaves)
            profiling.fence(moved)
        fm["permutes"].inc()
        fm["tx_bytes"].inc(bytes_moved)
        return list(moved), bytes_moved


class _FabricScheduleRejected(NetworkingError):
    """Internal: MSA505 refused the fabric-lowered schedule; the
    session proceeds on the wire."""


class FabricNetworking:
    """Networking-trait implementation lowering intra-fabric edges to
    collective permutes, with automatic wire fallback on every edge
    that crosses the trust boundary (receiver outside ``domain``),
    every force-wired key, every MSA505-rejected computation, and
    ``MOOSE_TPU_FABRIC=0``.

    ``inner`` is the wire transport (GrpcNetworking or a serializing
    LocalNetworking); everything not intercepted (ping, abort fanout,
    server plumbing) delegates to it unchanged, so the fabric composes
    under the chaos proxy exactly like the plain transports."""

    def __init__(self, domain: FabricDomain, identity: str, inner):
        if not domain.is_member(identity):
            raise ConfigurationError(
                f"{identity!r} is not a member of the fabric domain "
                f"{domain.parties}"
            )
        cells = getattr(inner, "cells", None)
        if cells is None:
            cells = getattr(inner, "_store", None)
        if cells is None or not getattr(inner, "_serialize", True):
            raise ConfigurationError(
                "FabricNetworking needs a wire transport with a "
                "rendezvous cell store and a serializing wire path "
                "(GrpcNetworking or LocalNetworking(serialize=True)): "
                "fabric payloads are raw values, wire payloads are "
                "bytes, and the payload type is the transport marker"
            )
        self.domain = domain
        self.identity = identity
        self.inner = inner
        self.cells = cells
        domain.register_cells(identity, cells)

    def __getattr__(self, name):
        return getattr(self.inner, name)

    # -- routing --------------------------------------------------------

    def _wire_label(self) -> str:
        name = type(self.inner).__name__
        return {"GrpcNetworking": "grpc", "LocalNetworking": "local",
                "TcpNetworking": "tcp"}.get(name, "wire")

    def _wire_reason(self, receiver: str, rendezvous_key: str,
                     session_id: str) -> Optional[str]:
        """Why this edge rides the wire, or None when it is a fabric
        permute.  Checked per logical rendezvous key, BEFORE any
        lowering — the same resolution order the cost model prices."""
        if not fabric_enabled():
            return "disabled"
        if not self.domain.is_member(receiver):
            return "trust_boundary"
        if self.domain.is_rejected_session(session_id):
            return "schedule_rejected"
        if self.domain.is_forced_wire(rendezvous_key):
            return "forced_wire"
        return None

    def _fallback(self, reason: str, count: int = 1) -> None:
        _fabric_metrics()["fallbacks"].inc(count, reason=reason)

    def force_wire(self, rendezvous_key: str) -> None:
        """Latch one stable rendezvous key onto the wire path (the
        chaos layer's drop -> forced-wire-replay hook)."""
        self.domain.force_wire(rendezvous_key)

    # -- plan-build-time gate (MSA505) ---------------------------------

    def prepare_fabric(self, comp, session_id: str) -> None:
        """Resolve this computation's rendezvous keys against the
        fabric at plan-build time and run the MSA505 deadlock rule over
        the fabric-lowered schedule.  A rejected computation is latched
        wire-only (every edge falls back to gRPC) and flight-recorded —
        the fabric never enters a collective schedule the analyzer
        could not prove deadlock-free.  Memoized per computation, like
        the worker plan cache."""
        domain = self.domain
        if domain._prepared.get(comp) is not None:
            if domain.is_rejected(comp):
                domain.reject_session(session_id)
            return
        from ..compilation.analysis.schedule import (
            analyze_fabric_schedules,
            reconstruct_schedules,
        )

        try:
            schedules = reconstruct_schedules(comp)
            errors = [
                d for d in analyze_fabric_schedules(
                    comp, schedules, frozenset(domain.parties)
                )
                if d.rule == "MSA505"
            ]
        except ValueError as e:
            # no linearization exists at all — MSA501 territory; the
            # plan layer rejects it, the fabric just declines too
            errors = [e]
        if errors:
            domain.reject_computation(comp)
            from .. import flight

            flight.record(
                "fabric_rejected", party=self.identity,
                session=session_id, findings=len(errors),
                detail=str(errors[0])[:240],
            )
        domain._prepared[comp] = True
        if domain.is_rejected(comp):
            domain.reject_session(session_id)

    # -- trait: send ----------------------------------------------------

    def send(self, value, receiver: str, rendezvous_key: str,
             session_id: str, **kwargs):
        reason = self._wire_reason(receiver, rendezvous_key, session_id)
        if reason is not None:
            self._fallback(reason)
            return self.inner.send(
                value, receiver, rendezvous_key, session_id, **kwargs
            )
        return self._fabric_send_one(
            value, receiver, rendezvous_key, session_id
        )

    def _fabric_send_one(self, value, receiver: str,
                         rendezvous_key: str, session_id: str):
        m = _net_metrics()
        leaves = value_leaves(value)
        key = transfer_key(session_id, rendezvous_key)
        target = self.domain.cells_of(receiver)
        if target is None:
            # the receiver's worker has not attached to the domain yet
            # (ordering race at cluster start): the wire always works
            self._fallback("unregistered")
            return self.inner.send(
                value, receiver, rendezvous_key, session_id
            )
        if not leaves:
            # nothing for a permute to move (HostUnit/Shape/String):
            # direct cell delivery, zero bytes — the cost model prices
            # these identically (spec placeholder has no leaves)
            m["sends"].inc(transport="fabric")
            target.put(key, value)
            self._flight_send(session_id, receiver, 1, False, 0)
            return 0
        moved, bytes_moved = self.domain.permute(
            self.identity, receiver, leaves
        )
        out = self._rebuild(value, moved)
        m["sends"].inc(transport="fabric")
        m["tx_bytes"].inc(bytes_moved, transport="fabric")
        _fabric_metrics()["payloads"].inc()
        target.put(key, out)
        self._flight_send(session_id, receiver, 1, False, bytes_moved)
        return bytes_moved

    def send_many(self, items, receiver: str, session_id: str):
        """Coalesced delivery: one batched permute program moves every
        array leaf of every payload in the group (``send_many``
        coalescing lowers to batched permutes), then each payload lands
        in its own rendezvous cell."""
        reasons = {
            k: self._wire_reason(receiver, k, session_id)
            for k, _ in items
        }
        wired = [(k, v) for k, v in items if reasons[k] is not None]
        for k, _ in wired:
            self._fallback(reasons[k])
        if len(wired) == len(items):
            return self.inner.send_many(items, receiver, session_id)
        if wired:
            # a chaos force-wire latch split the group: the wired keys
            # keep wire framing, the rest stay collective
            self.inner.send_many(wired, receiver, session_id)
            items = [(k, v) for k, v in items if reasons[k] is None]
        target = self.domain.cells_of(receiver)
        if target is None:
            self._fallback("unregistered")
            return self.inner.send_many(items, receiver, session_id)
        m = _net_metrics()
        m["send_many"].inc(transport="fabric")
        m["send_many_payloads"].inc(len(items), transport="fabric")
        leafy: List[Tuple[str, Any, list]] = []
        passthrough: List[Tuple[str, Any]] = []
        for k, v in items:
            leaves = value_leaves(v)
            if leaves:
                leafy.append((k, v, leaves))
            else:
                passthrough.append((k, v))
        total = 0
        if leafy:
            flat: List[Any] = []
            counts: List[int] = []
            for _, _, leaves in leafy:
                flat.extend(leaves)
                counts.append(len(leaves))
            moved, bytes_moved = self.domain.permute(
                self.identity, receiver, flat
            )
            total = bytes_moved
            fm = _fabric_metrics()
            fm["payloads"].inc(len(leafy))
            if len(leafy) > 1:
                fm["batched"].inc()
            m["tx_bytes"].inc(bytes_moved, transport="fabric")
            pos = 0
            for (k, v, _), n_leaves in zip(leafy, counts):
                out = self._rebuild(v, moved[pos:pos + n_leaves])
                pos += n_leaves
                target.put(transfer_key(session_id, k), out)
        for k, v in passthrough:
            target.put(transfer_key(session_id, k), v)
        self._flight_send(
            session_id, receiver, len(items) + len(wired), True, total
        )
        return total

    @staticmethod
    def _rebuild(value, moved_leaves):
        import jax

        _, treedef = jax.tree_util.tree_flatten(value)
        return jax.tree_util.tree_unflatten(treedef, list(moved_leaves))

    def _flight_send(self, session_id: str, receiver: str,
                     payloads: int, coalesced: bool,
                     bytes_moved: int) -> None:
        from .. import flight

        flight.record(
            "send", party=self.identity, session=session_id,
            receiver=receiver, payloads=payloads, coalesced=coalesced,
            transport="fabric", bytes=bytes_moved,
        )

    # -- trait: receive -------------------------------------------------

    def _consume(self, payload, sender: str, plc: str, session_id: str):
        """Account one arrived payload: raw value = fabric delivery,
        bytes = wire delivery (the payload type is the transport
        marker)."""
        m = _net_metrics()
        if isinstance(payload, (bytes, bytearray)):
            from .. import profiling
            from ..serde import deserialize_value

            m["receives"].inc(transport=self._wire_label())
            m["rx_bytes"].inc(len(payload), transport=self._wire_label())
            with profiling.phase("serde", direction="rx"):
                return deserialize_value(bytes(payload), plc)
        m["receives"].inc(transport="fabric")
        m["rx_bytes"].inc(
            leaf_bytes(value_leaves(payload)), transport="fabric"
        )
        from .. import flight

        flight.record(
            "receive", party=self.identity, session=session_id,
            sender=sender, transport="fabric",
        )
        return _restamp_plc(payload, plc)

    def receive(self, sender: str, rendezvous_key: str, session_id: str,
                plc: str = "", timeout: float = DEFAULT_TIMEOUT_S,
                cancel=None, progress=None):
        payload = self.cells.get(
            transfer_key(session_id, rendezvous_key), timeout, cancel,
            progress,
        )
        return self._consume(payload, sender, plc, session_id)

    def try_receive(self, sender: str, rendezvous_key: str,
                    session_id: str, plc: str = ""):
        ok, payload = self.cells.try_take(
            transfer_key(session_id, rendezvous_key)
        )
        if not ok:
            return False, None
        return True, self._consume(payload, sender, plc, session_id)

    def activity_for(self, session_id: str):
        return self.cells.activity_for(session_id)

    # -- descriptors ----------------------------------------------------

    def fabric_cost_context(self):
        """The cost model's fabric prediction key, or None when no
        exact prediction exists (fabric disabled, or force-wire latches
        make the edge set key-dependent)."""
        if not fabric_enabled():
            return None
        with self.domain._lock:
            if self.domain._force_wire:
                return None
        return self.domain.cost_context()

    def transport_descriptor(self) -> Dict[str, str]:
        """What this party's session transport IS, for session reports
        and bench rows."""
        return {
            "transport": "fabric" if fabric_enabled() else "grpc",
            "trust_model": self.domain.trust_model,
        }
