"""Compiled fast path for the distributed worker: per-role validated jit
with communication overlap.

The reference Moose runtime schedules one async task per op on every
worker (``execution/asynchronous.rs:558-632``); our legacy scheduler in
:mod:`worker` is the Python-thread re-design of that — and, like the
reference, pays per-op eager dispatch for every operation.  On the TPU
backend that dispatch tunnel costs ~4 ms/op, which made the distributed
deployment (the paper's actual trust model) the last permanently-eager
path in the framework.

This module gives ``execute_role`` a compiled plan instead:

- the worker's **role subgraph** (its own ops, in global topological
  order) is split at Send/Receive/host boundaries into **compute
  segments**; each segment jit-compiles as its own XLA program with the
  values crossing segment boundaries (including pending Receives)
  travelling as ordinary jit inputs/outputs — the partial-graph use of
  ``interpreter.plan_segments``;
- every segment is **validated** before it is trusted: a worker's own
  ops are deterministic given their runtime inputs (PrfKeyGen / Sample
  entropy enters at the host boundary), so each segment's jit candidate
  runs against its exact eager twin on the same inputs for the plan's
  first ``MOOSE_TPU_JIT_SELFCHECK`` sessions and must agree
  bit-for-bit; only the segments that actually diverge are **pinned
  eager**, exactly like the in-process executors' per-op rung (no
  single process can compare the *global* outputs — but each worker CAN
  compare its own, which is all the known miscompile class needs);
- resolved plans are cached **weak-keyed on (computation, role)**
  (mirroring the PR-2 plan registry), so repeat sessions — serving
  traffic through comet — never re-validate and never re-jit;
- **communication overlaps compute**: Sends enqueue on a background
  sender thread at segment boundaries — each segment's deferred flush
  group buckets per receiver and every >=2-payload bucket coalesces
  into one ``send_many`` envelope, DETERMINISTICALLY (plan-driven, so
  the static cost model in ``compilation/analysis/cost.py`` predicts
  envelope counts and wire bytes exactly) — while the next segment
  executes, and all Receives are posted up front so the poller
  prefetches arriving payloads into segment input slots before the
  orchestrator needs them;
- plans are **statically vetted before they run**: the schedule
  skeleton comes from ``compilation.analysis.schedule`` (the MSA5xx
  analyzer reconstructs the identical plan), and :func:`get_plan`
  raises the typed :class:`~moose_tpu.errors.PlanRejectedError` on
  would-hang plans — the worker demotes to the legacy eager scheduler
  instead of blocking at runtime.

Chaos compatibility: fault schedules key on the same stable rendezvous
keys — :class:`~.chaos.ChaosNetworking` decomposes ``send_many`` back
into per-key ``send`` decisions — so a chaos seed replays the identical
schedule with worker jit on or off, and ``MOOSE_TPU_FIXED_KEYS`` runs
stay bit-exact (segments are pure functions of their inputs).

``MOOSE_TPU_WORKER_JIT=0`` (or the test suite's ``MOOSE_TPU_JIT=0``
default) disables the fast path, restoring the legacy parallel eager
scheduler.
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from collections import deque
from typing import Any, Callable, Optional

from ..compilation.analysis.schedule import (
    DEFERRABLE_KINDS as _DEFERRABLE_KINDS,
)
from ..compilation.analysis.schedule import (
    DYNAMIC_SHAPE_KINDS as _DYNAMIC_SHAPE_KINDS,
)
from ..compilation.analysis.schedule import (
    HOISTABLE_KINDS as _HOISTABLE_KINDS,
)
from ..compilation.analysis.schedule import (
    HOST_STEP_KINDS as _HOST_STEP_KINDS,
)
from ..compilation.analysis.schedule import MAX_DEFERRED as _MAX_DEFERRED
from ..compilation.analysis.schedule import (
    build_role_schedule,
    worker_min_seg as _min_seg,
)
from ..errors import NetworkingError, PlanRejectedError, SessionAbortedError

# The segmentation rules (host-step / hoistable / deferrable kind sets,
# the deferred-send cap, the sliver threshold) live in
# compilation.analysis.schedule — the worker BUILDS its plan from the
# same ``build_role_schedule`` the static analyzer checks, so what
# prancer proves about a plan is what this worker runs.  The aliases
# keep this module's historical names importable.


def worker_jit_enabled() -> bool:
    """Whether the compiled worker fast path is on.  Explicit
    ``MOOSE_TPU_WORKER_JIT`` wins; the default follows the runtime-wide
    jit default (``MOOSE_TPU_JIT``), so the test suite's eager default
    keeps workers eager while deployments get the fast path."""
    raw = os.environ.get("MOOSE_TPU_WORKER_JIT")
    if raw is not None:
        return raw not in ("0", "")
    return os.environ.get("MOOSE_TPU_JIT", "1") != "0"


def use_fast_path() -> bool:
    """Fast path unless disabled or the PRF implementation is host-side
    eager-only (aes-ctr kernels cannot trace under jit).  Purely
    environmental: the same verdict applies to every computation and
    role."""
    if not worker_jit_enabled():
        return False
    from ..dialects import ring

    if ring.get_prf_impl() == "aes-ctr":
        return False
    from ..execution.interpreter import _selfcheck_runs

    # MOOSE_TPU_JIT_SELFCHECK=0 disables the self-check everywhere; an
    # unvalidated worker jit would reintroduce exactly the miscompile
    # exposure the local ladder exists to close, so fall back to eager
    return _selfcheck_runs() > 0


# ---------------------------------------------------------------------------
# plan statistics (asserted by tests: a warm plan never re-validates)
# ---------------------------------------------------------------------------

PLAN_STATS = {
    "plans_built": 0,
    "cache_hits": 0,
    "validating_evaluations": 0,
    "segments_pinned": 0,
    "plans_rejected": 0,
}
_STATS_LOCK = threading.Lock()

# bridge onto the unified metrics registry (metrics.py): every plan
# decision is visible on /metrics under these names
_STAT_METRIC_NAMES = {
    "plans_built": "moose_tpu_worker_plans_built_total",
    "cache_hits": "moose_tpu_worker_plan_cache_hits_total",
    "validating_evaluations": "moose_tpu_worker_plan_validating_total",
    "segments_pinned": "moose_tpu_worker_segments_pinned_total",
    "plans_rejected": "moose_tpu_worker_plans_rejected_total",
}
_STAT_HELP = {
    "plans_built": "role plans built (compile + boundary analysis)",
    "cache_hits": "role plans served warm from the (computation, role) "
                  "cache",
    "validating_evaluations": "sessions that ran at least one "
                              "jit-vs-eager segment comparison",
    "segments_pinned": "segments pinned eager after divergence",
    "plans_rejected": "plans rejected at build time by the MSA5xx "
                      "schedule analyzer (legacy-scheduler fallback)",
}


_STAT_COUNTERS = None


def _stat(key: str, n: int = 1) -> None:
    global _STAT_COUNTERS
    with _STATS_LOCK:
        PLAN_STATS[key] += n
    if _STAT_COUNTERS is None:
        from .. import metrics

        _STAT_COUNTERS = {
            k: metrics.counter(_STAT_METRIC_NAMES[k], _STAT_HELP[k])
            for k in _STAT_METRIC_NAMES
        }
    _STAT_COUNTERS[key].inc(n)


def plan_stats() -> dict:
    with _STATS_LOCK:
        return dict(PLAN_STATS)


# ---------------------------------------------------------------------------
# cost-model drift watchdog (ISSUE 12): the PR-7 analyzer's predictions
# are compared against what this worker MEASURED, continuously, on every
# planned session — not only in the dist_smoke CI gate.  A mismatch is
# the standing alarm that the planner's cost inputs drifted from the
# runtime wire path (counter + flight event; the session itself is never
# failed by its own observability).
# ---------------------------------------------------------------------------


def _drift_fault_applies(identity: str) -> bool:
    """TEST-ONLY (MOOSE_TPU_DRIFT_FAULT): ``1`` perturbs every party's
    coalescing, a party name perturbs only that party — the watchdog
    coverage hook, mirroring MOOSE_TPU_SELFCHECK_FAULT's role for the
    ladder."""
    raw = os.environ.get("MOOSE_TPU_DRIFT_FAULT", "")
    return raw == "1" or (bool(raw) and raw == identity)


def _watchdog_enabled() -> bool:
    return os.environ.get("MOOSE_TPU_COST_WATCHDOG", "1") != "0"


# (cost report, value specs) per computation, keyed by (transport,
# session-id length) — the only two inputs the wire prediction depends
# on besides the graph itself.  Weak-keyed like the plan cache: serving
# traffic must not re-serialize placeholder payloads per session.
_cost_cache: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()

_DRIFT_COUNTER = None
_WATCHDOG_COUNTER = None


def _drift_counter():
    global _DRIFT_COUNTER
    if _DRIFT_COUNTER is None:
        from .. import metrics

        _DRIFT_COUNTER = metrics.counter(
            "moose_tpu_cost_drift_total",
            "cost-model predictions contradicted by measured session "
            "counters, by kind (the planner's cost inputs drifted)",
            ("kind",),
        )
    return _DRIFT_COUNTER


def _watchdog_counter():
    global _WATCHDOG_COUNTER
    if _WATCHDOG_COUNTER is None:
        from .. import metrics

        _WATCHDOG_COUNTER = metrics.counter(
            "moose_tpu_cost_watchdog_sessions_total",
            "planned sessions screened by the cost-drift watchdog, by "
            "outcome (ok / drift / skipped)",
            ("outcome",),
        )
    return _WATCHDOG_COUNTER


def _watchdog_transport(networking) -> Optional[str]:
    """The cost-model transport semantics matching ``networking``, or
    None when no exact prediction exists: ChaosNetworking decomposes
    coalescing fault-by-fault, TcpNetworking has no ``send_many``, and
    a non-serializing LocalNetworking never touches the wire codec."""
    name = type(networking).__name__
    if name == "GrpcNetworking":
        return "grpc"
    if name == "FabricNetworking":
        return "fabric"
    if name == "LocalNetworking":
        return "local" if getattr(networking, "_serialize", False) else None
    return None


def _cost_prediction(comp, transport: str, session_id: str,
                     fabric_ctx=None):
    key = (transport, len(session_id), fabric_ctx)
    with _cache_lock:
        per_comp = _cost_cache.get(comp)
        if per_comp is None:
            per_comp = _cost_cache[comp] = {}
        entry = per_comp.get(key)
    if entry is not None:
        return entry
    from ..compilation.analysis.cost import cost_report, infer_specs

    entry = (
        cost_report(
            comp, session_id=session_id, transport=transport,
            fabric_parties=fabric_ctx[0] if fabric_ctx else None,
        ),
        infer_specs(comp),
    )
    with _cache_lock:
        per_comp[key] = entry
    return entry


def _live_bytes_overruns(plan, env: dict, specs, cap: int = 4):
    """Boundary values whose REAL in-memory bytes exceed the model's
    ``memory_bytes`` — the observable inputs of the MSA603 live-buffer
    high-water marks.  Undercounting is the drift that matters (the hwm
    stops being an upper bound); a conservative model is fine."""
    import jax

    from ..compilation.analysis.cost import memory_bytes

    over: dict = {}
    names: set = set()
    for seg in plan.segments:
        names.update(seg.in_names)
        names.update(seg.out_names)
    for name in sorted(names):
        value = env.get(name)
        spec = specs.get(name)
        if value is None or spec is None:
            continue
        predicted = memory_bytes(spec)
        if predicted is None:
            continue
        measured = sum(
            int(getattr(leaf, "nbytes", 0))
            for leaf in jax.tree_util.tree_leaves(value)
        )
        if measured > predicted:
            over[name] = {"predicted": predicted, "measured": measured}
            if len(over) >= cap:
                break
    return over


def check_cost_drift(comp, identity: str, session_id: str, networking,
                     sender, receives: int, env: dict,
                     plan) -> Optional[dict]:
    """Compare this party's measured session counters (singles,
    coalesced envelopes/payloads, tx bytes, receives, boundary value
    bytes) against the static cost model's per-party prediction.  On
    mismatch: ONE ``cost_drift`` flight event for the session carrying
    every mismatched kind, plus ``moose_tpu_cost_drift_total{kind}``
    increments.  Returns the mismatch dict (None when clean/skipped) —
    and NEVER raises: the watchdog explains sessions, it must not fail
    them."""
    from ..logger import get_logger

    try:
        if not _watchdog_enabled():
            return None
        transport = _watchdog_transport(networking)
        if transport is None:
            _watchdog_counter().inc(outcome="skipped")
            return None
        fabric_ctx = None
        if transport == "fabric":
            # None when the fabric is disabled or chaos force-wire
            # latches make the edge set key-dependent — no exact
            # prediction exists then, so the watchdog stands down
            fabric_ctx = networking.fabric_cost_context()
            if fabric_ctx is None:
                _watchdog_counter().inc(outcome="skipped")
                return None
        report, specs = _cost_prediction(
            comp, transport, session_id, fabric_ctx
        )
        party = report["per_party"].get(identity)
        if party is None or party["unresolved_sends"]:
            _watchdog_counter().inc(outcome="skipped")
            return None
        stats = sender.stats
        measured = {
            "send_many_envelopes": stats["envelopes"],
            "send_many_payloads": stats["env_payloads"],
            # local transports count coalesced payloads as sends too
            # (send_many delegates to send); grpc sends one rpc frame,
            # and a fabric envelope is one batched permute program
            "sends": stats["singles"] + (
                stats["env_payloads"]
                if transport not in ("grpc", "fabric") else 0
            ),
            "receives": int(receives),
        }
        predicted = {k: int(party[k]) for k in measured}
        tx = sender.measured_tx_bytes
        if tx is not None:
            measured["tx_bytes"] = int(tx)
            predicted["tx_bytes"] = int(party["tx_bytes"])
        mismatches = {
            k: {"predicted": predicted[k], "measured": measured[k]}
            for k in measured
            if measured[k] != predicted[k]
        }
        over = _live_bytes_overruns(plan, env, specs)
        if over:
            mismatches["live_bytes"] = over
        if not mismatches:
            _watchdog_counter().inc(outcome="ok")
            return None
        _watchdog_counter().inc(outcome="drift")
        for kind in mismatches:
            _drift_counter().inc(kind=kind)
        from .. import flight

        flight.record(
            "cost_drift", party=identity, session=session_id,
            transport=transport, mismatches=mismatches,
        )
        get_logger().warning(
            "cost-model drift on %s (session %s): %s — the static "
            "analyzer's prediction no longer matches the runtime wire "
            "path", identity, session_id, sorted(mismatches),
        )
        return mismatches
    except Exception as e:  # noqa: BLE001 — observability must never
        # fail the session it observes
        get_logger().debug("cost-drift watchdog errored: %s", e)
        return None


# ---------------------------------------------------------------------------
# segments
# ---------------------------------------------------------------------------


class _Segment:
    """One compute segment of a role plan: a run of consecutive
    non-boundary ops compiled as its own XLA program, validated
    bit-exactly against its eager twin before being trusted."""

    def __init__(self, index: int, names: list, in_names: list,
                 out_names: list, comp_ref, identity: str,
                 validatable: bool, checks: int):
        self.index = index
        self.names = names
        self.in_names = in_names
        self.out_names = out_names
        self._comp_ref = comp_ref
        self._identity = identity
        self.validatable = validatable
        # "validating" -> "jit" (promoted) | "eager" (pinned/unjittable)
        self.mode = "validating" if validatable else "eager"
        self.pinned = False
        self.checks_left = checks
        self._failed_once = False
        self._eager = None
        self._jit = None
        self._lock = threading.Lock()

    def _make_fn(self, fault_kinds=frozenset()):
        names = self.names
        outs = self.out_names
        comp_ref = self._comp_ref
        identity = self._identity

        def seg(env_in: dict):
            from ..execution.interpreter import _fault_perturb
            from ..execution.physical import execute_kernel
            from ..execution.session import EagerSession

            comp = comp_ref()
            if comp is None:  # pragma: no cover - defensive
                raise RuntimeError("computation was garbage-collected")
            sess = EagerSession(session_id=f"seg-{identity}")
            env = dict(env_in)
            for n in names:
                op = comp.operations[n]
                args = [env[i] for i in op.inputs]
                env[n] = execute_kernel(sess, op, identity, args)
                if fault_kinds and op.kind in fault_kinds:
                    env[n] = _fault_perturb(env[n])
            return {n: env[n] for n in outs}

        return seg

    def _eager_fn(self):
        if self._eager is None:
            self._eager = self._make_fn()
        return self._eager

    def _jit_fn(self):
        if self._jit is None:
            import jax

            from ..execution.interpreter import _fault_kinds

            # fault injection applies to the CANDIDATE only (the test
            # hook forcing divergence/pinning on backends without the
            # real miscompile — see interpreter._fault_kinds)
            self._jit = jax.jit(self._make_fn(_fault_kinds()))
        return self._jit

    def run(self, env_in: dict,
            session_id: Optional[str] = None) -> tuple:
        """Execute the segment; returns ``(out_env, validated)`` where
        ``validated`` reports whether this call ran a jit-vs-eager
        comparison (the plan-level "validating evaluation" counter).
        ``session_id`` stamps a pin's flight event so the decision
        reaches that session's postmortem."""
        from ..execution.interpreter import _results_equal
        from ..logger import get_logger

        mode = self.mode
        if mode == "jit":
            return self._jit_fn()(env_in), False
        if mode == "eager":
            return self._eager_fn()(env_in), False
        # validating: the eager result is the reference AND the value
        # the session continues from — a divergent candidate never
        # contaminates the protocol
        from .. import profiling

        pin = False
        ok = False
        with profiling.phase(
            "ladder_validate", segment=self.index, party=self._identity,
        ):
            ref = self._eager_fn()(env_in)
        try:
            with profiling.phase(
                "ladder_validate", segment=self.index, party=self._identity,
            ):
                got = self._jit_fn()(env_in)
            ok = _results_equal(ref, got)
            pin = not ok
        except Exception as e:  # noqa: BLE001 — candidate is optional
            if not self._failed_once:
                self._failed_once = True
                get_logger().warning(
                    "worker segment %d jit candidate failed to run "
                    "(%s); will retry once", self.index, e,
                )
                return ref, True
            get_logger().warning(
                "worker segment %d jit candidate failed twice (%s); "
                "pinning eager", self.index, e,
            )
            pin = True
        with self._lock:
            if self.mode != "validating":
                return ref, True  # raced a concurrent session's verdict
            if pin:
                self.mode = "eager"
                self.pinned = True
                self._jit = None
                _stat("segments_pinned")
                from .. import flight

                flight.record(
                    "segment_pinned", party=self._identity,
                    session=session_id, segment=self.index,
                    ops=len(self.names),
                )
                get_logger().warning(
                    "worker segment %d (%d ops, %s..%s) diverged from "
                    "its eager reference; pinned eager", self.index,
                    len(self.names), self.names[0], self.names[-1],
                )
            elif ok:
                self.checks_left -= 1
                if self.checks_left <= 0:
                    self.mode = "jit"
                    self._eager = None
        return ref, True


# ---------------------------------------------------------------------------
# the role plan
# ---------------------------------------------------------------------------


class RolePlan:
    """Static execution plan for one (computation, role) pair: the
    ordered step list (host-boundary ops interleaved with compute
    segments) plus per-segment validated-jit state.  Cached weak-keyed
    on the computation, so it must not hold it strongly."""

    # MSA704 summary attached by get_plan (advisory; {} until set)
    ranges_advisory: dict = {}

    def __init__(self, comp, identity: str):
        from ..execution.interpreter import _selfcheck_runs

        self.identity = identity
        self._comp_ref = weakref.ref(comp)
        checks = _selfcheck_runs()

        # the statically-checkable schedule skeleton — segmentation,
        # hoisting, deferral, flush grouping — comes from the SAME
        # reconstruction the MSA5xx analyzer and MSA6xx cost model use
        # (including the autotuned eager floor: the two-pass min_seg
        # resolution lives in reconstruct_schedules, so the plan the
        # analyzer approved and the wire costs the watchdog predicts
        # are byte-for-byte the plan that runs)
        from ..compilation.analysis.schedule import (
            reconstruct_schedules,
            worker_min_seg_decision,
        )

        self.autotune_min_seg = worker_min_seg_decision(comp)
        schedule = reconstruct_schedules(comp).get(identity)
        if schedule is None:  # role with no ops of its own
            schedule = build_role_schedule(
                comp, identity, min_seg=self.autotune_min_seg.choice
            )
        self.schedule = schedule
        self.segments = [
            _Segment(
                seg.index, list(seg.names), list(seg.in_names),
                list(seg.out_names), self._comp_ref, identity,
                validatable=seg.validatable, checks=checks,
            )
            for seg in schedule.segments
        ]
        self.steps = [
            (kind, payload if kind != "sends" else list(payload))
            for kind, payload in schedule.steps
        ]
        self.recv_names = list(schedule.recv_names)

    @property
    def pinned_segments(self) -> list:
        return [s.index for s in self.segments if s.pinned]

    @property
    def plan_mode(self) -> str:
        """Resolved (or currently-validating) plan shape: ``full-jit``
        (the role's whole compute is one jitted program), ``segmented``
        (several jitted segments, possibly with pins), ``validating``,
        or ``eager`` (no jittable compute / everything pinned)."""
        segs = [s for s in self.segments if s.validatable]
        if not segs:
            return "eager"
        if any(s.mode == "validating" for s in segs):
            return "validating"
        jitted = [s for s in segs if s.mode == "jit"]
        if not jitted:
            return "eager"
        if len(self.segments) == 1 and not self.pinned_segments:
            return "full-jit"
        return "segmented"


# Resolved-plan cache, weak-keyed on the computation (the worker server
# memoizes deserialization by computation bytes, so repeat sessions of
# one computation share the object and hit here) — the distributed
# mirror of the PR-2 interpreter._registry.
_plan_cache: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_cache_lock = threading.Lock()

# MSA5xx verdict per computation: the schedule analysis is pure graph
# work (no compiles), but on serving traffic the same computation
# arrives thousands of times — cache the error list weak-keyed like the
# plans themselves.
_verdict_cache: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()

# MSA704 summary per computation (advisory only — the worker has no
# declared arg ranges, so this is the structural representable-interval
# demand; it never rejects a plan).
_ranges_cache: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()

# MSA8xx verdict per computation: key-lineage errors (mis-wired setup,
# missing domain separation, stream-position reuse) are correctness
# *and* secrecy bugs, so like the MSA5xx schedule verdict they reject
# the plan rather than advise.  Weak-keyed: serving traffic replays the
# same computation object thousands of times.
_keystream_cache: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _ranges_advisory(comp) -> dict:
    """The range analysis' per-computation summary (peak raw-bit demand,
    minimal ring width), attached to every resolved plan so operators
    can see ring-width headroom per role without rerunning prancer.
    Advisory by construction: no declared ranges, no errors raised."""
    with _cache_lock:
        cached = _ranges_cache.get(comp)
    if cached is not None:
        return cached
    try:
        from ..compilation.analysis.ranges import range_report

        advisory = dict(range_report(comp)["summary"])
    except Exception:  # noqa: BLE001 — advisory data must never take
        # down plan building
        advisory = {}
    with _cache_lock:
        _ranges_cache[comp] = advisory
    return advisory


def _keystream_errors(comp) -> list:
    """Error-severity MSA8xx findings for ``comp`` (worker graphs are
    already lowered, so the analyzer sees the real Sample/DeriveSeed
    ops directly).  An analysis *crash* must never take down plan
    building — only a clean run that found real errors rejects."""
    with _cache_lock:
        cached = _keystream_cache.get(comp)
    if cached is not None:
        return cached
    try:
        from ..compilation.analysis import Severity
        from ..compilation.analysis.keystream import analyze_keystream

        errors = [d for d in analyze_keystream(comp)
                  if d.severity >= Severity.ERROR]
    except Exception:  # noqa: BLE001 — fail open, like _ranges_advisory
        errors = []
    with _cache_lock:
        _keystream_cache[comp] = errors
    return errors


def _schedule_errors(comp) -> list:
    with _cache_lock:
        cached = _verdict_cache.get(comp)
    if cached is not None:
        return cached
    from ..compilation.analysis.schedule import plan_errors

    errors = plan_errors(comp)
    with _cache_lock:
        _verdict_cache[comp] = errors
    return errors


def get_plan(comp, identity: str,
             session_id: Optional[str] = None) -> RolePlan:
    """Build (or serve warm) the role plan — AFTER the static schedule
    analyzer approved the computation.  A would-hang plan (MSA5xx
    error: wait cycle, oversubscribed rendezvous, use-before-arrival)
    raises :class:`~moose_tpu.errors.PlanRejectedError` at build time;
    the worker then falls back to the legacy eager scheduler, so the
    failure mode is a typed diagnostic instead of a runtime hang."""
    with _cache_lock:
        per_comp = _plan_cache.get(comp)
        if per_comp is None:
            per_comp = _plan_cache[comp] = {}
        plan = per_comp.get(identity)
    if plan is not None:
        _stat("cache_hits")
        return plan
    errors = _schedule_errors(comp)
    if errors:
        from ..compilation.analysis.diagnostics import format_diagnostics

        _stat("plans_rejected")
        from .. import flight

        flight.record(
            "plan_rejected", party=identity, session=session_id,
            rules=sorted({d.rule for d in errors}),
            findings=len(errors),
        )
        raise PlanRejectedError(
            f"worker plan for role {identity!r} rejected by the "
            f"schedule analyzer with {len(errors)} error(s):\n"
            + format_diagnostics(errors),
            diagnostics=errors,
        )
    key_errors = _keystream_errors(comp)
    if key_errors:
        from ..compilation.analysis.diagnostics import format_diagnostics

        _stat("plans_rejected")
        from .. import flight

        flight.record(
            "plan_rejected", party=identity, session=session_id,
            rules=sorted({d.rule for d in key_errors}),
            findings=len(key_errors),
        )
        raise PlanRejectedError(
            f"worker plan for role {identity!r} rejected by the "
            f"keystream analyzer with {len(key_errors)} error(s):\n"
            + format_diagnostics(key_errors),
            diagnostics=key_errors,
        )
    plan = RolePlan(comp, identity)
    plan.ranges_advisory = _ranges_advisory(comp)
    with _cache_lock:
        existing = _plan_cache[comp].get(identity)
        if existing is not None:
            return existing
        _plan_cache[comp][identity] = plan
    _stat("plans_built")
    from .. import flight

    # session-stamped so the plan decision reaches the session-filtered
    # postmortem (last_session_report["flight"])
    flight.record(
        "plan_built", party=identity, session=session_id,
        mode=plan.plan_mode, segments=len(plan.segments),
        steps=len(plan.steps), receives=len(plan.recv_names),
        min_ring_width=plan.ranges_advisory.get("min_ring_width"),
        peak_raw_bits=plan.ranges_advisory.get("peak_raw_bits"),
        min_seg=plan.autotune_min_seg.choice,
        min_seg_source=plan.autotune_min_seg.source,
    )
    return plan


# ---------------------------------------------------------------------------
# communication overlap: async sender + receive prefetcher
# ---------------------------------------------------------------------------


class _AsyncSender:
    """Background send queue: the orchestrator enqueues single sends
    (host-step sends) or whole deferred flush groups (one per segment
    close) and moves on; this thread serializes and transmits off the
    critical path.  Coalescing is DETERMINISTIC and plan-driven: within
    one flush group, payloads bucket per receiver (first-appearance
    order, payload order preserved) and each >=2-payload bucket becomes
    exactly one ``send_many`` envelope — never across groups, never
    timing-dependent — so the static cost model predicts envelope
    counts and wire bytes exactly and chaos fault schedules (keyed on
    stable rendezvous keys) replay identically.  Errors become the
    session's root cause via ``on_error``."""

    def __init__(self, networking, session_id: str, on_error,
                 progress=None, identity: str = ""):
        from .. import telemetry

        self._net = networking
        self._session_id = session_id
        self._on_error = on_error
        self._progress = progress
        self._identity = identity
        # the sender thread inherits the enclosing trace context (the
        # session's launch context) so any span it opens stitches into
        # the session trace instead of starting an orphan root
        self._ctx = telemetry.current_context()
        self._items: deque = deque()
        self._cv = threading.Condition()
        self._pending = 0
        self._closed = False
        self._error = None
        # per-session measured wire stats (the cost-drift watchdog
        # compares these against the static cost model's prediction for
        # this party): singles = payloads transmitted one send() each,
        # envelopes/env_payloads = coalesced send_many units, tx_bytes =
        # sum of the transport's reported transmitted bytes (None once
        # any transmission couldn't report a size)
        self.stats = {
            "singles": 0, "envelopes": 0, "env_payloads": 0,
            "tx_bytes": 0,
        }
        self._bytes_unknown = False
        self._thread = threading.Thread(
            target=self._run_thread, daemon=True, name="moose-sender",
        )
        self._thread.start()

    def _run_thread(self) -> None:
        from .. import telemetry

        with telemetry.use_context(self._ctx):
            self._loop()

    def enqueue(self, value, receiver: str, rendezvous_key: str) -> None:
        """One single-payload transmission unit (a host-step Send with
        nothing to defer behind): never coalesced."""
        with self._cv:
            if self._error is not None:
                return  # session already failing; drop silently
            self._items.append(
                (receiver, [(rendezvous_key, value)])
            )
            self._pending += 1
            self._cv.notify()

    def enqueue_group(self, sends: list) -> None:
        """One deferred flush group: ``[(value, receiver, key), ...]``
        buckets per receiver (first-appearance order; per-receiver
        payload order preserved) and each bucket transmits as ONE unit
        — a ``send_many`` envelope when it carries >=2 payloads.
        Payloads to different receivers commute (rendezvous-keyed), so
        the bucketing never reorders anything a peer can observe."""
        buckets: dict = {}
        order: list = []
        for value, receiver, key in sends:
            if receiver not in buckets:
                buckets[receiver] = []
                order.append(receiver)
            buckets[receiver].append((key, value))
        if _drift_fault_applies(self._identity):
            # TEST-ONLY perturbation (MOOSE_TPU_DRIFT_FAULT): transmit
            # every payload as its own singleton unit, deliberately
            # breaking the deterministic coalescing the static cost
            # model predicts — the watchdog must flag this session as
            # cost_drift (tests/test_profiling.py)
            with self._cv:
                if self._error is not None:
                    return
                for receiver in order:
                    for payload in buckets[receiver]:
                        self._items.append((receiver, [payload]))
                        self._pending += 1
                self._cv.notify()
            return
        with self._cv:
            if self._error is not None:
                return
            for receiver in order:
                self._items.append((receiver, buckets[receiver]))
                self._pending += len(buckets[receiver])
            self._cv.notify()

    def _take_unit(self) -> Optional[tuple]:
        with self._cv:
            while not self._items and not self._closed:
                self._cv.wait(0.2)
            if not self._items:
                return None
            return self._items.popleft()

    def _loop(self) -> None:
        while True:
            unit = self._take_unit()
            if unit is None:
                return
            receiver, payloads = unit
            try:
                if self._error is None:
                    self._transmit(receiver, payloads)
            except BaseException as e:  # noqa: BLE001 — root cause
                with self._cv:
                    if self._error is None:
                        self._error = e
                self._on_error(e)
            finally:
                with self._cv:
                    self._pending -= len(payloads)
                    self._cv.notify_all()

    def _transmit(self, receiver: str, payloads: list) -> None:
        from .. import flight, profiling

        send_many = getattr(self._net, "send_many", None)
        with profiling.phase(
            "net_send", receiver=receiver, payloads=len(payloads),
        ):
            if len(payloads) > 1 and send_many is not None:
                sent = send_many(payloads, receiver, self._session_id)
                self.stats["envelopes"] += 1
                self.stats["env_payloads"] += len(payloads)
                self._tally_bytes(sent)
            else:
                for key, value in payloads:
                    sent = self._net.send(
                        value, receiver, key, self._session_id
                    )
                    self.stats["singles"] += 1
                    self._tally_bytes(sent)
        flight.record(
            "send", party=self._identity or None,
            session=self._session_id, receiver=receiver,
            payloads=len(payloads), coalesced=len(payloads) > 1,
        )
        if self._progress is not None:
            self._progress.bump()

    def _tally_bytes(self, sent) -> None:
        if sent is None:
            self._bytes_unknown = True
        elif not self._bytes_unknown:
            self.stats["tx_bytes"] += int(sent)

    @property
    def measured_tx_bytes(self):
        """Transmitted bytes this session, or None when any transport
        call couldn't report a size (watchdog then skips the bytes
        comparison instead of flagging a phantom drift)."""
        return None if self._bytes_unknown else self.stats["tx_bytes"]

    def flush(self, timeout: float, cancel=None) -> None:
        """Block until every enqueued send has been transmitted (the
        worker must not report success while peers still await its
        payloads); raises the first transmit error, if any."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._pending > 0 and self._error is None:
                if cancel is not None and cancel.is_set():
                    break
                if time.monotonic() > deadline:
                    raise NetworkingError(
                        f"{self._pending} queued send(s) not flushed "
                        f"after {timeout}s"
                    )
                self._cv.wait(0.2)
            if self._error is not None:
                raise self._error

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()


_PREFETCH_COUNTER = None


def _prefetch_counter():
    """Cached family (one registry lookup ever — this sits on the
    per-receive hot path)."""
    global _PREFETCH_COUNTER
    if _PREFETCH_COUNTER is None:
        from .. import metrics

        _PREFETCH_COUNTER = metrics.counter(
            "moose_tpu_worker_prefetch_total",
            "receive waits at the orchestrator, by whether the "
            "prefetcher already held the payload",
            ("outcome",),
        )
    return _PREFETCH_COUNTER


class _ReceivePrefetcher:
    """Posts EVERY Receive of the role up front and fills arriving
    payloads into per-name slots while segments compute, so the
    orchestrator's ``wait`` usually returns immediately.  Pollable
    transports (try_receive) get one poller thread for all keys; others
    get one waiter thread per receive (both mirror the legacy
    scheduler's discipline — receives never occupy compute slots)."""

    def __init__(self, comp, recv_names, networking, session_id: str,
                 identity: str, timeout: float, cancel, progress,
                 on_error):
        from .. import telemetry

        self._net = networking
        self._session_id = session_id
        self._identity = identity
        self._timeout = timeout
        self._cancel = cancel
        self._progress = progress
        self._on_error = on_error
        self._stop = threading.Event()
        self._values: dict = {}
        self._events = {n: threading.Event() for n in recv_names}
        self._ops = {n: comp.operations[n] for n in recv_names}
        # prefetch threads inherit the session trace context (no orphan
        # roots; see _AsyncSender)
        self._ctx = telemetry.current_context()
        self._threads: list = []
        if not recv_names:
            return
        if hasattr(networking, "try_receive"):
            t = threading.Thread(
                target=self._with_ctx, args=(self._poll,), daemon=True,
                name=f"moose-{identity}-prefetch",
            )
            t.start()
            self._threads.append(t)
        else:
            for n in recv_names:
                t = threading.Thread(
                    target=self._with_ctx, args=(self._wait_one, n),
                    daemon=True,
                    name=f"moose-{identity}-recv-{n}",
                )
                t.start()
                self._threads.append(t)

    def _with_ctx(self, fn, *args) -> None:
        from .. import telemetry

        with telemetry.use_context(self._ctx):
            fn(*args)

    def _arrived(self, name: str, value) -> None:
        self._values[name] = value
        self._events[name].set()
        self._progress.bump()

    def _poll(self) -> None:
        get_act = getattr(self._net, "activity_for", None)
        activity = (
            get_act(self._session_id) if get_act is not None else None
        )
        outstanding = dict(self._ops)
        while outstanding and not self._stop.is_set():
            if self._cancel is not None and self._cancel.is_set():
                return
            if activity is not None:
                activity.clear()
            arrived = []
            for name, op in outstanding.items():
                try:
                    ok, val = self._net.try_receive(
                        op.attributes["sender"],
                        op.attributes["rendezvous_key"],
                        self._session_id,
                        plc=self._identity,
                    )
                except BaseException as e:  # noqa: BLE001 — root cause
                    self._on_error(e)
                    return
                if ok:
                    arrived.append(name)
                    self._arrived(name, val)
            for name in arrived:
                outstanding.pop(name, None)
            if activity is not None:
                activity.wait(0.1)
            else:
                time.sleep(0.005)

    def _wait_one(self, name: str) -> None:
        op = self._ops[name]
        try:
            val = self._net.receive(
                op.attributes["sender"],
                op.attributes["rendezvous_key"],
                self._session_id,
                plc=self._identity,
                timeout=self._timeout,
                cancel=self._cancel,
                progress=self._progress,
            )
        except SessionAbortedError:
            return  # the abort is already the session outcome
        except BaseException as e:  # noqa: BLE001 — root cause
            self._on_error(e)
            return
        self._arrived(name, val)

    def wait(self, name: str):
        """Block until ``name``'s payload arrived; progress-clock
        timeout semantics identical to a direct blocking receive."""
        from .. import flight
        from .networking import sliced_wait

        from .. import profiling

        op = self._ops[name]
        hit = self._events[name].is_set()
        _prefetch_counter().inc(outcome="hit" if hit else "wait")
        with profiling.phase(
            "net_receive", key=op.attributes.get("rendezvous_key", ""),
            prefetched=hit,
        ):
            sliced_wait(
                self._events[name].wait, self._timeout, self._cancel,
                op.attributes["rendezvous_key"], self._progress,
            )
        flight.record(
            "receive", party=self._identity, session=self._session_id,
            sender=op.attributes.get("sender"),
            key=op.attributes.get("rendezvous_key"),
            prefetched=hit,
        )
        return self._values.pop(name)

    def stop(self) -> None:
        self._stop.set()


# ---------------------------------------------------------------------------
# the orchestrator
# ---------------------------------------------------------------------------


def execute_role_planned(
    comp,
    identity: str,
    storage: dict,
    arguments: dict,
    networking,
    session_id: str,
    timeout: float,
    cancel,
    progress,
    plan: RolePlan,
) -> dict:
    """Run one role through its compiled plan: host steps and segments
    execute in the global topological order (a linearization every
    worker shares, so the cluster stays deadlock-free: any blocked
    receive's matching send precedes it globally and sends never block),
    with sends async behind and receives prefetched ahead."""
    from .. import telemetry
    from ..execution.interpreter import prefetch_to_host
    from .worker import _AnyEvent, _exec_host_op

    from ..execution.physical import execute_kernel
    from ..execution.session import EagerSession

    t0 = time.perf_counter()
    env: dict = {}
    outputs: dict = {}
    # entropy-drawing host steps (Sample) execute through the same
    # kernel dispatch the legacy scheduler uses; lazy master key makes
    # this cheap even when the role has none
    host_sess = EagerSession(session_id=session_id)
    local_abort = threading.Event()
    abort_any = _AnyEvent(cancel, local_abort)
    failure: list = []
    flock = threading.Lock()

    def fail(exc: BaseException) -> None:
        with flock:
            if not failure:
                failure.append(exc)
        local_abort.set()

    sender = _AsyncSender(
        networking, session_id, fail, progress, identity=identity
    )
    prefetcher = _ReceivePrefetcher(
        comp, plan.recv_names, networking, session_id, identity,
        timeout, abort_any, progress, fail,
    )
    validated = False
    receives_measured = 0
    with telemetry.span(
        "execute_role", party=identity, steps=len(plan.steps),
    ) as root:
        try:
            for kind, payload in plan.steps:
                if abort_any.is_set():
                    raise SessionAbortedError(
                        f"session {session_id} aborted"
                    )
                if kind == "seg":
                    from .. import profiling

                    seg = plan.segments[payload]
                    with telemetry.span(
                        "worker_segment", party=identity,
                        segment=seg.index, ops=len(seg.names),
                        mode=seg.mode,
                    ):
                        out, did_validate = seg.run(
                            {n: env[n] for n in seg.in_names},
                            session_id=session_id,
                        )
                        # device-fenced only while a profiler is active:
                        # the worker_segment phase then owns its device
                        # time instead of the next blocking call
                        profiling.fence(out)
                    env.update(out)
                    validated |= did_validate
                    progress.bump()
                    continue
                if kind == "sends":
                    # one deferred flush group: the sender buckets it
                    # per receiver and coalesces deterministically (the
                    # static cost model walks the identical grouping)
                    from ..values import HostUnit

                    group = []
                    for n in payload:
                        op = comp.operations[n]
                        group.append((
                            env[op.inputs[0]],
                            op.attributes["receiver"],
                            op.attributes["rendezvous_key"],
                        ))
                        env[n] = HostUnit(identity)
                    sender.enqueue_group(group)
                    continue
                op = comp.operations[payload]
                if op.kind == "Send":
                    # not reachable from build_role_schedule (sends ride
                    # in flush groups), kept for hand-built plans
                    sender.enqueue(
                        env[op.inputs[0]],
                        op.attributes["receiver"],
                        op.attributes["rendezvous_key"],
                    )
                    from ..values import HostUnit

                    env[payload] = HostUnit(identity)
                elif op.kind == "Receive":
                    env[payload] = prefetcher.wait(payload)
                    receives_measured += 1
                elif op.kind == "Sample":
                    # unseeded draw: a hard segment boundary (jitting it
                    # would bake one draw into the compiled program) but
                    # NOT an _exec_host_op kind — run the legacy
                    # scheduler's eager kernel
                    env[payload] = execute_kernel(
                        host_sess, op, identity,
                        [env[i] for i in op.inputs],
                    )
                    progress.bump()
                else:
                    env[payload] = _exec_host_op(
                        op, env, identity, arguments, storage, outputs
                    )
                    if op.kind == "Output":
                        # start the device-to-host copy while later
                        # steps (and peers) still compute
                        prefetch_to_host(env[payload])
                    progress.bump()
            sender.flush(timeout, abort_any)
        except BaseException as e:  # noqa: BLE001 — first error wins
            fail(e)
        finally:
            prefetcher.stop()
            sender.close()
        root.attrs["plan_mode"] = plan.plan_mode
        root.attrs["pinned_segments"] = len(plan.pinned_segments)

    if validated:
        _stat("validating_evaluations")
    if failure:
        exc = failure[0]
        if cancel is not None and cancel.is_set() and not isinstance(
            exc, SessionAbortedError
        ):
            raise SessionAbortedError(
                f"session {session_id} aborted"
            ) from exc
        raise exc
    if cancel is not None and cancel.is_set():
        raise SessionAbortedError(f"session {session_id} aborted")

    # the session SUCCEEDED: screen its measured wire/memory counters
    # against the static cost model (continuous drift watchdog)
    check_cost_drift(
        comp, identity, session_id, networking, sender,
        receives_measured, env, plan,
    )

    elapsed = int((time.perf_counter() - t0) * 1e6)
    return {
        "outputs": outputs,
        "elapsed_time_micros": elapsed,
        "plan_mode": plan.plan_mode,
        "pinned_segments": plan.pinned_segments,
    }
