"""Client runtime for a cluster of gRPC workers (reference
GrpcMooseRuntime, execution/grpc.rs:11-146): compile the logical
computation to the host-level graph, fan LaunchComputation out to every
worker, retrieve + merge results and per-role timings."""

from __future__ import annotations

import secrets
from typing import Optional

import numpy as np

from ..computation import Computation
from ..errors import NetworkingError
from .choreography import ChoreographyClient


class GrpcClientRuntime:
    def __init__(self, identities: dict, tls=None):
        """``identities``: {identity/placement name: "host:port"};
        ``tls``: optional :class:`moose_tpu.distributed.tls.TlsConfig` —
        each worker must then present a certificate whose CN is its
        identity name."""
        self.identities = dict(identities)
        self._clients = {
            name: ChoreographyClient(endpoint, tls=tls,
                                     expected_identity=name)
            for name, endpoint in self.identities.items()
        }

    def run_computation(
        self,
        computation: Computation,
        arguments: Optional[dict] = None,
        timeout: float = 120.0,
        arg_specs: Optional[dict] = None,
    ):
        """Compile + fan out + retrieve.  ``arg_specs`` supplies
        shape/dtype specs the client cannot infer from ``arguments`` —
        in particular for Load ops whose values live in worker-side
        storage: ``{load_op_name: ((shape...), np_dtype)}``."""
        from ..compilation import DEFAULT_PASSES, compile_computation
        from ..compilation.lowering import arg_specs_from_arguments
        from ..serde import (
            deserialize_value,
            serialize_computation,
        )

        arguments = dict(arguments or {})
        specs = arg_specs_from_arguments(arguments)
        specs.update(arg_specs or {})
        compiled = compile_computation(
            computation,
            DEFAULT_PASSES,
            arg_specs=specs,
        )
        comp_bytes = serialize_computation(compiled)
        session_id = secrets.token_hex(16)

        # each worker receives ONLY the arguments whose Input op lives on
        # its placement — shipping the full cleartext dict to every party
        # would hand carole alice's private inputs and void the trust
        # model this runtime exists for
        owner_of = {
            op.name: compiled.placement_of(op).name
            for op in compiled.operations.values()
            if op.kind == "Input"
        }
        for name, client in self._clients.items():
            mine = {
                arg: v for arg, v in arguments.items()
                if owner_of.get(arg) == name
            }
            resp = client.launch(session_id, comp_bytes, mine)
            if not resp.get("ok"):
                raise NetworkingError(
                    f"launch on {name} failed: {resp!r}"
                )

        outputs: dict = {}
        timings: dict = {}
        for name, client in self._clients.items():
            result = client.retrieve(session_id, timeout=timeout)
            if "error" in result:
                raise NetworkingError(
                    f"worker {name} failed: {result['error']}"
                )
            timings[name] = result.get("elapsed_time_micros", 0)
            for out_name, blob in (result.get("outputs") or {}).items():
                value = deserialize_value(blob)
                from ..values import HostUnit

                outputs[out_name] = (
                    None if isinstance(value, HostUnit) else value
                )
        from ..execution.interpreter import ordered_output_names

        outputs = {
            name: outputs[name] for name in ordered_output_names(outputs)
        }
        return outputs, timings
