"""Client session supervisor for a cluster of gRPC workers (reference
GrpcMooseRuntime, execution/grpc.rs:11-146): compile the logical
computation to the host-level graph, fan LaunchComputation out to every
worker IN PARALLEL, retrieve in parallel with first-error-wins, and —
because a session is a pure function of (computation, arguments) and
replay protection drops stale traffic for old ids — resubmit the whole
computation under a fresh session id when the failure is *retryable*
(transport fault, receive timeout, detector trip; see
``errors.is_retryable``).  Permanent failures (compile/type errors,
PERMISSION_DENIED) re-raise immediately as their original typed class,
reconstructed from the wire envelope (``errors.from_wire``).

Every run leaves a ``last_session_report`` on the runtime — attempts,
per-party outcomes, injected chaos faults — mirroring
``runtime.last_plan`` for the local executors."""

from __future__ import annotations

import random
import secrets
import threading
import time
from typing import Optional

from .. import flight as flight_mod
from .. import metrics as metrics_mod
from .. import telemetry
from ..computation import Computation
from ..errors import (
    AuthorizationError,
    MooseError,
    NetworkingError,
    is_retryable,
)
from .choreography import ChoreographyClient


_CLIENT_METRICS = None


def _client_metrics():
    """Lazily-created supervisor counters (cached like
    networking._net_metrics — one registry lookup per family, ever)."""
    global _CLIENT_METRICS
    if _CLIENT_METRICS is None:
        _CLIENT_METRICS = {
            "sessions": metrics_mod.counter(
                "moose_tpu_client_sessions_total",
                "distributed sessions run by this client, by outcome",
                ("outcome",),
            ),
            "retries": metrics_mod.counter(
                "moose_tpu_client_retries_total",
                "retryable session failures that were resubmitted",
            ),
            "aborts": metrics_mod.counter(
                "moose_tpu_client_aborts_total",
                "client-initiated abort fanouts (partial launch / first "
                "retrieve error cleanup)",
            ),
        }
    return _CLIENT_METRICS


def _retryable(exc: BaseException) -> bool:
    """The wire bit when the error crossed the wire (the originator's
    taxonomy already classified the live exception), the local taxonomy
    otherwise."""
    wire_bit = getattr(exc, "retryable", None)
    return bool(wire_bit) if wire_bit is not None else is_retryable(exc)


def _error_from_result(party: str, result: dict) -> MooseError:
    """Typed exception for a worker's error cell.  Envelope-carrying
    cells (every current worker) re-raise the REAL class; bare string
    cells (older workers) degrade to a retryable NetworkingError."""
    from ..errors import from_wire

    envelope = result.get("envelope")
    if envelope:
        return from_wire(envelope)
    exc = NetworkingError(f"worker {party} failed: {result['error']}")
    exc.retryable = True
    return exc


def _classify_rpc_error(exc: BaseException, what: str) -> MooseError:
    """Map a raw transport/launch failure into the taxonomy: mTLS /
    choreographer rejections are permanent, everything else about an
    unreachable or failing worker is retryable."""
    if isinstance(exc, MooseError):
        return exc
    detail = str(exc)
    try:
        import grpc

        if isinstance(exc, grpc.RpcError):
            code = exc.code()
            detail = f"{code.name}: {exc.details()}"
            if code == grpc.StatusCode.PERMISSION_DENIED:
                typed = AuthorizationError(f"{what}: {detail}")
                typed.__cause__ = exc
                return typed
    except ModuleNotFoundError:  # pragma: no cover - grpc ships with repo
        pass
    typed = NetworkingError(f"{what}: {detail}")
    typed.__cause__ = exc
    return typed


def _chaos_marks() -> list:
    """Snapshot (config, fault-log length) for every live in-process
    chaos config, so the report can attribute exactly the faults
    injected during this run."""
    from .chaos import active_configs

    return [(cfg, len(cfg.faults)) for cfg in active_configs()]


def _chaos_new_faults(marks: list) -> list:
    faults = []
    for cfg, mark in marks:
        with cfg._lock:
            faults.extend(dict(f) for f in cfg.faults[mark:])
    return faults


class GrpcClientRuntime:
    def __init__(self, identities: dict, tls=None, max_attempts: int = 3,
                 backoff_base_s: float = 0.25, backoff_cap_s: float = 2.0):
        """``identities``: {identity/placement name: "host:port"};
        ``tls``: optional :class:`moose_tpu.distributed.tls.TlsConfig` —
        each worker must then present a certificate whose CN is its
        identity name.  ``max_attempts``: how many times a RETRYABLE
        failure resubmits the computation (fresh session id, capped
        exponential backoff + jitter) before surfacing."""
        self.identities = dict(identities)
        self.max_attempts = int(max_attempts)
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self._clients = {
            name: ChoreographyClient(endpoint, tls=tls,
                                     expected_identity=name)
            for name, endpoint in self.identities.items()
        }
        # supervisor outcome of the most recent run_computation call:
        # attempts, per-party errors, injected chaos faults (the
        # distributed mirror of runtime.last_plan)
        self.last_session_report: dict = {}
        # compiled-computation memo, weak-keyed on the logical
        # computation: lowering bakes fresh DeriveSeed sync-key nonces,
        # so re-compiling per session would ship DIFFERENT bytes each
        # time and the workers' role-plan caches (weak-keyed on the
        # deserialized computation, memoized by bytes) could never hit —
        # every session would re-validate and re-jit
        import weakref

        self._compile_cache: "weakref.WeakKeyDictionary" = (
            weakref.WeakKeyDictionary()
        )

    # -- one attempt ----------------------------------------------------

    def _abort_parties(self, session_id: str, parties) -> None:
        """Best-effort parallel abort — used to clean up launched
        workers after a partial launch failure and to unblock survivors
        after the first retrieve error, so no session outlives the
        abort-fanout window."""
        _client_metrics()["aborts"].inc()
        flight_mod.record(
            "client_abort", party="client", session=session_id,
            parties=sorted(parties),
        )

        def one(name):
            try:
                self._clients[name].abort(session_id)
            except Exception:  # noqa: BLE001 — target may be the dead one
                pass

        threads = [
            threading.Thread(target=one, args=(p,), daemon=True)
            for p in parties
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10.0)

    def _launch_all(self, session_id: str, comp_bytes: bytes,
                    per_party_args: dict, attempt_rec: dict,
                    trace: Optional[dict] = None) -> None:
        """Fan launches out in parallel.  On ANY failure the workers
        that DID launch are aborted before the typed error is raised —
        a partially-launched session must not sit in blocked receives
        until the failure detector notices the missing party."""
        launched: list = []
        failures: dict = {}
        lock = threading.Lock()

        def one(name):
            try:
                resp = self._clients[name].launch(
                    session_id, comp_bytes, per_party_args[name],
                    trace=trace,
                )
                if not resp.get("ok"):
                    raise NetworkingError(
                        f"launch on {name} failed: {resp!r}"
                    )
                with lock:
                    launched.append(name)
            except Exception as e:  # noqa: BLE001 — classified below
                with lock:
                    failures[name] = _classify_rpc_error(
                        e, f"launch on {name} failed"
                    )

        threads = [
            threading.Thread(target=one, args=(n,), daemon=True)
            for n in self._clients
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=150.0)
        with lock:
            for name in self._clients:
                # a launch thread still hanging after the join window is
                # a FAILURE, not a success: treating it as launched
                # would run the session against a party that may never
                # have started (and exclude it from the abort sweep)
                if name not in launched and name not in failures:
                    exc = NetworkingError(
                        f"launch on {name} timed out (no response)"
                    )
                    exc.retryable = True
                    failures[name] = exc
        if failures:
            attempt_rec["errors"].update({
                name: f"{type(e).__name__}: {e}"
                for name, e in failures.items()
            })
            attempt_rec["status"] = "launch_failed"
            if launched:
                self._abort_parties(session_id, launched)
            # surface a PERMANENT failure over a retryable one: if any
            # party rejected the computation outright, retrying the
            # transient co-failures would just replay the rejection
            ranked = sorted(
                failures.values(), key=_retryable
            )
            raise ranked[0]

    def _retrieve_all(self, session_id: str, timeout: float,
                      attempt_rec: dict) -> tuple:
        """Retrieve every party in parallel; the FIRST error wins and
        aborts the survivors (serial retrieval would hide a fast
        failure behind a slow success)."""
        from concurrent.futures import FIRST_EXCEPTION, ThreadPoolExecutor
        from concurrent.futures import wait as futures_wait

        from ..serde import deserialize_value
        from ..values import HostUnit

        def one(name):
            try:
                result = self._clients[name].retrieve(
                    session_id, timeout=timeout
                )
            except Exception as e:  # noqa: BLE001 — classified
                raise _classify_rpc_error(
                    e, f"retrieve from {name} failed"
                ) from e
            if "error" in result:
                raise _error_from_result(name, result)
            return name, result

        pool = ThreadPoolExecutor(
            max_workers=max(1, len(self._clients)),
            thread_name_prefix="moose-retrieve",
        )
        futs = {
            pool.submit(one, name): name for name in self._clients
        }
        outputs: dict = {}
        timings: dict = {}
        plan_modes: dict = {}
        transports: dict = {}
        try:
            done, pending = futures_wait(
                futs, timeout=timeout + 15.0,
                return_when=FIRST_EXCEPTION,
            )
            errors: list = []
            for fut in done:
                name = futs[fut]
                exc = fut.exception()
                if exc is not None:
                    attempt_rec["errors"][name] = (
                        f"{type(exc).__name__}: {exc}"
                    )
                    errors.append(exc)
                    continue
                _, result = fut.result()
                attempt_rec["errors"].setdefault(name, "ok")
                timings[name] = result.get("elapsed_time_micros", 0)
                if result.get("plan_mode") is not None:
                    plan_modes[name] = {
                        "plan_mode": result["plan_mode"],
                        "pinned_segments": result.get(
                            "pinned_segments", []
                        ),
                    }
                if result.get("transport") is not None:
                    transports[name] = {
                        "transport": result["transport"],
                        "trust_model": result.get("trust_model"),
                    }
                for out_name, blob in (
                    result.get("outputs") or {}
                ).items():
                    value = deserialize_value(blob)
                    outputs[out_name] = (
                        None if isinstance(value, HostUnit) else value
                    )
            if not errors and pending:
                exc = NetworkingError(
                    f"retrieve timed out after {timeout}s on "
                    f"{sorted(futs[f] for f in pending)}"
                )
                exc.retryable = True
                errors.append(exc)
            # a PERMANENT error is canonical over any retryable
            # co-failure (same ranking as _launch_all): fanout races
            # can land a peer's adopted SessionAborted in the same
            # FIRST_EXCEPTION wake-up as the real root cause, and
            # replaying a deterministic failure just repeats it
            first_error = (
                sorted(errors, key=_retryable)[0] if errors else None
            )
            if first_error is not None:
                attempt_rec["status"] = "retrieve_failed"
                # unblock everyone still running before surfacing: the
                # fastest failure is canonical, survivors are aborted
                self._abort_parties(session_id, list(self._clients))
                raise first_error
        finally:
            pool.shutdown(wait=False)
        return outputs, timings, plan_modes, transports

    def _collect_flight(self, session_ids) -> list:
        """Gather every party's recent flight-recorder events for the
        given session ids: the in-process recorder first (for local
        clusters it already holds all parties' events — including a
        chaos-killed party whose rpc endpoint is gone), then each
        worker's GetFlight rpc best-effort.  Deduplicated on
        (party, seq) — in-process workers serve the same recorder the
        direct read saw."""
        events = flight_mod.get_recorder().events(sessions=session_ids)
        seen = {(e.get("party"), e.get("seq")) for e in events}
        # parallel fanout (same discipline as _abort_parties): in a
        # full partition every rpc times out, and serial 5 s waits
        # would delay the caller's exception by parties x 5 s
        remote_lists: dict = {}

        def one(name):
            try:
                remote_lists[name] = self._clients[name].flight(
                    session_ids
                )
            except Exception:  # noqa: BLE001 — the dead party can't answer
                pass

        threads = [
            threading.Thread(target=one, args=(n,), daemon=True)
            for n in self._clients
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=6.0)
        # snapshot: a straggler thread past its join window must not
        # mutate the dict mid-iteration
        for remote in list(remote_lists.values()):
            for event in remote:
                key = (event.get("party"), event.get("seq"))
                if key in seen:
                    continue
                seen.add(key)
                events.append(event)
        events.sort(key=lambda e: (e.get("ts", 0.0), e.get("seq", 0)))
        return events

    # -- the supervisor loop --------------------------------------------

    def run_computation(
        self,
        computation: Computation,
        arguments: Optional[dict] = None,
        timeout: float = 120.0,
        arg_specs: Optional[dict] = None,
        max_attempts: Optional[int] = None,
    ):
        """Compile + fan out + retrieve, retrying retryable failures.
        ``arg_specs`` supplies shape/dtype specs the client cannot infer
        from ``arguments`` — in particular for Load ops whose values
        live in worker-side storage: ``{load_op_name: ((shape...),
        np_dtype)}``."""
        from ..compilation import DEFAULT_PASSES, compile_computation
        from ..compilation.lowering import arg_specs_from_arguments
        from ..serde import serialize_computation

        arguments = dict(arguments or {})
        specs = arg_specs_from_arguments(arguments)
        specs.update(arg_specs or {})
        specs_key = tuple(sorted(
            (n, s) if isinstance(s, (str, int, float))
            else (n, tuple(s[0]), str(s[1]))
            for n, s in specs.items()
        ))
        per_comp = self._compile_cache.get(computation)
        if per_comp is None:
            per_comp = self._compile_cache[computation] = {}
        cached = per_comp.get(specs_key)
        if cached is None:
            compiled = compile_computation(
                computation,
                DEFAULT_PASSES,
                arg_specs=specs,
            )
            cached = per_comp[specs_key] = (
                compiled, serialize_computation(compiled)
            )
        compiled, comp_bytes = cached

        # each worker receives ONLY the arguments whose Input op lives on
        # its placement — shipping the full cleartext dict to every party
        # would hand carole alice's private inputs and void the trust
        # model this runtime exists for
        owner_of = {
            op.name: compiled.placement_of(op).name
            for op in compiled.operations.values()
            if op.kind == "Input"
        }
        per_party_args = {
            name: {
                arg: v for arg, v in arguments.items()
                if owner_of.get(arg) == name
            }
            for name in self._clients
        }

        attempts = (
            self.max_attempts if max_attempts is None else int(max_attempts)
        )
        attempts = max(1, attempts)
        marks = _chaos_marks()
        report: dict = {
            "ok": False,
            "n_attempts": 0,
            "max_attempts": attempts,
            "attempts": [],
            "faults_injected": [],
        }
        self.last_session_report = report
        session_ids: list = []

        with telemetry.span(
            "run_computation", parties=len(self._clients),
            max_attempts=attempts,
        ) as root:
            try:
                for attempt in range(1, attempts + 1):
                    session_id = secrets.token_hex(16)
                    session_ids.append(session_id)
                    attempt_rec = {
                        "session_id": session_id,
                        "status": "ok",
                        "errors": {},
                        "elapsed_s": 0.0,
                    }
                    report["attempts"].append(attempt_rec)
                    report["n_attempts"] = attempt
                    t0 = time.monotonic()
                    with telemetry.span(
                        "attempt", attempt=attempt, session_id=session_id,
                    ) as att:
                        # one TraceContext per session attempt: workers
                        # adopt it for their execute_role roots, so the
                        # whole 3-party session exports as ONE stitched
                        # trace under this attempt span
                        trace_ctx = telemetry.TraceContext(
                            att.trace_id, att.span_id
                        )
                        flight_mod.record(
                            "attempt", party="client",
                            session=session_id, attempt=attempt,
                        )
                        try:
                            with telemetry.span("launch"):
                                self._launch_all(
                                    session_id, comp_bytes,
                                    per_party_args, attempt_rec,
                                    trace=trace_ctx.to_dict(),
                                )
                            with telemetry.span("retrieve"):
                                outputs, timings, plan_modes, transports = (
                                    self._retrieve_all(
                                        session_id, timeout, attempt_rec
                                    )
                                )
                        except Exception as exc:
                            attempt_rec["elapsed_s"] = (
                                time.monotonic() - t0
                            )
                            attempt_rec["error"] = (
                                f"{type(exc).__name__}: {exc}"
                            )
                            attempt_rec["retryable"] = _retryable(exc)
                            flight_mod.record(
                                "attempt_failed", party="client",
                                session=session_id,
                                status=attempt_rec["status"],
                                error=attempt_rec["error"],
                                retryable=attempt_rec["retryable"],
                            )
                            if (
                                not attempt_rec["retryable"]
                                or attempt >= attempts
                            ):
                                raise
                            _client_metrics()["retries"].inc()
                            # capped exponential backoff + jitter before
                            # the resubmission (fresh session id; replay
                            # protection drops stragglers of this one)
                            delay = min(
                                self.backoff_cap_s,
                                self.backoff_base_s * 2 ** (attempt - 1),
                            )
                            delay += random.uniform(0, delay / 2)
                            with telemetry.span(
                                "backoff", seconds=round(delay, 3)
                            ):
                                time.sleep(delay)
                            continue
                    attempt_rec["elapsed_s"] = time.monotonic() - t0
                    report["ok"] = True
                    root.attrs["attempts_used"] = attempt
                    _client_metrics()["sessions"].inc(outcome="ok")
                    flight_mod.record(
                        "session_ok", party="client", session=session_id,
                        attempts=attempt,
                    )
                    break
            except Exception:
                # terminal failure: attach every party's recent flight
                # events for the attempted session ids to the report —
                # the postmortem record that makes a chaos failure
                # diagnosable, not merely reproducible.  Exception, not
                # BaseException: a KeyboardInterrupt must propagate
                # immediately, not sit behind a best-effort rpc fanout.
                _client_metrics()["sessions"].inc(outcome="failed")
                flight_mod.record(
                    "session_failed", party="client",
                    session=session_ids[-1] if session_ids else None,
                    attempts=report["n_attempts"],
                )
                report["flight"] = self._collect_flight(session_ids)
                raise
            finally:
                report["faults_injected"] = _chaos_new_faults(marks)
                report["retried"] = report["n_attempts"] > 1

        from ..execution.interpreter import ordered_output_names

        outputs = {
            name: outputs[name] for name in ordered_output_names(outputs)
        }
        report["timings"] = dict(timings)
        # resolved per-role worker plans (worker_plan): the distributed
        # mirror of LocalMooseRuntime.last_plan's plan_mode/pinned_ops
        report["plan_modes"] = dict(plan_modes)
        # resolved transport per party, plus the session-level rollup
        # ("fabric" / "grpc" / "mixed") and trust model — BENCH rows and
        # postmortems must say what the traffic actually rode on
        report["transports"] = dict(transports)
        kinds = {t["transport"] for t in transports.values()}
        report["transport"] = (
            (kinds.pop() if len(kinds) == 1 else "mixed")
            if kinds else None
        )
        models = {
            t.get("trust_model") for t in transports.values()
            if t.get("trust_model")
        }
        report["trust_model"] = (
            models.pop() if len(models) == 1
            else (sorted(models) if models else None)
        )
        return outputs, timings
