"""Choreography: session orchestration across workers over gRPC.

Reference ``moose/src/choreography/grpc.rs:34-234`` +
``protos/choreography.proto``: LaunchComputation / RetrieveResults /
AbortComputation, with per-session result cells and duplicate-session
protection.  gRPC methods carry raw msgpack bytes (no protoc codegen
needed; the reference uses tonic+prost — the method *names* and semantics
match, the payload codec is msgpack like the rest of this framework).
"""

from __future__ import annotations

import threading
from concurrent import futures
from typing import Optional

import msgpack

from ..errors import NetworkingError, SessionAlreadyExistsError
from .networking import GrpcNetworking, _CellStore

LAUNCH = "/moose.Choreography/LaunchComputation"
RETRIEVE = "/moose.Choreography/RetrieveResults"
ABORT = "/moose.Choreography/AbortComputation"
SEND_VALUE = "/moose.Networking/SendValue"


def _pack(obj) -> bytes:
    return msgpack.packb(obj, use_bin_type=True)


def _unpack(data: bytes):
    return msgpack.unpackb(data, raw=False, strict_map_key=False)


class WorkerServer:
    """One worker daemon: hosts the choreography service and the gRPC
    networking endpoint, executes its role of launched sessions in
    background threads (reference comet, bin/comet/comet.rs:12-83)."""

    def __init__(self, identity: str, port: int, endpoints: dict,
                 storage: Optional[dict] = None, tls=None,
                 choreographer: Optional[str] = None):
        self.identity = identity
        self.port = port
        self.endpoints = dict(endpoints)
        self.storage = storage if storage is not None else {}
        self.tls = tls  # distributed.tls.TlsConfig or None
        # when set (requires tls), only a peer whose certificate CN equals
        # this name may launch/abort sessions (reference
        # choreography/grpc.rs:64-94 check_choreographer)
        self.choreographer = choreographer
        if choreographer is not None and tls is None:
            raise NetworkingError(
                "choreographer authorization requires a TlsConfig — "
                "without mTLS there is no verified peer identity"
            )
        import collections

        self.networking = GrpcNetworking(identity, self.endpoints, tls=tls)
        self._sessions: dict = {}  # session id -> cancel Event
        self._aborted: "collections.deque[str]" = collections.deque()
        self._results = _CellStore()
        self._lock = threading.Lock()
        self._server = None

    # -- rpc handlers ---------------------------------------------------

    def _check_choreographer(self, context) -> None:
        if self.choreographer is None:
            return
        from .tls import peer_common_name, reject

        peer = peer_common_name(context) if context is not None else None
        if peer != self.choreographer:
            reject(
                context,
                f"unauthorized choreographer: peer CN {peer!r}, expected "
                f"{self.choreographer!r}",
            )

    def _launch(self, request: bytes, context=None) -> bytes:
        self._check_choreographer(context)
        return self._launch_inner(request)

    def _launch_inner(self, request: bytes) -> bytes:
        from ..serde import deserialize_computation, deserialize_value

        msg = _unpack(request)
        session_id = msg["session_id"]
        cancel = threading.Event()
        with self._lock:
            if session_id in self._aborted:
                # abort raced ahead of launch (gRPC retry/reordering):
                # honor it — never start the session
                raise SessionAlreadyExistsError(
                    f"{session_id} (aborted before launch)"
                )
            if session_id in self._sessions:
                raise SessionAlreadyExistsError(session_id)
            self._sessions[session_id] = cancel
        comp = deserialize_computation(msg["computation"])
        arguments = {
            name: deserialize_value(blob)
            for name, blob in (msg.get("arguments") or {}).items()
        }

        def run():
            from .worker import execute_role

            try:
                result = execute_role(
                    comp, self.identity, self.storage, arguments,
                    self.networking, session_id, cancel=cancel,
                )
                payload = _pack({
                    "outputs": {
                        name: _serialize_output(value)
                        for name, value in result["outputs"].items()
                    },
                    "elapsed_time_micros": result["elapsed_time_micros"],
                })
            except Exception as e:  # surfaced on retrieve
                payload = _pack({"error": f"{type(e).__name__}: {e}"})
            # an aborted session already has its canonical
            # {"error": "aborted"} result; putting again would either
            # clobber it or recreate a never-consumed cell.  The check
            # and put happen under the same lock as _abort's add+put so
            # the two cannot interleave.
            with self._lock:
                if session_id not in self._aborted:
                    self._results.put(session_id, payload)

        threading.Thread(target=run, daemon=True).start()
        return _pack({"ok": True})

    def _retrieve(self, request: bytes, context=None) -> bytes:
        # results carry the computation's outputs — only the configured
        # choreographer may read them, same as launch/abort
        self._check_choreographer(context)
        msg = _unpack(request)
        timeout = float(msg.get("timeout", 120.0))
        return self._results.get(msg["session_id"], timeout)

    # bound on remembered aborted ids (replay/late-send protection); old
    # entries age out FIFO so a long-lived worker's state stays bounded
    _MAX_ABORTED = 4096

    def _abort(self, request: bytes, context=None) -> bytes:
        self._check_choreographer(context)
        msg = _unpack(request)
        session_id = msg["session_id"]
        with self._lock:
            self._aborted.append(session_id)
            while len(self._aborted) > self._MAX_ABORTED:
                self._aborted.popleft()
            known = session_id in self._sessions
            cancel = self._sessions.pop(session_id, None)
            if known:
                # fail-stop semantics: retrievers of a launched session
                # unblock with the canonical error.  Unknown ids get no
                # cell (nobody retrieves a session that never launched;
                # a cell would be retained forever).
                self._results.put(
                    session_id, _pack({"error": "aborted"})
                )
        if cancel is not None:
            # cooperative cancellation: the execute thread checks the
            # event between ops and inside blocked receives
            # (the reference's abort handler is unimplemented!(),
            # choreography/grpc.rs:200-205)
            cancel.set()
        # drop pending rendezvous payloads so aborted sessions don't
        # retain undelivered tensors in a long-lived worker
        self.networking.cells.drop_session(session_id)
        return _pack({"ok": True})

    def _send_value(self, request: bytes, context=None) -> bytes:
        # a peer's send may land after this worker aborted the session:
        # drop it up front so cancelled receives never retain the payload
        # (complements the one-shot GC in _abort)
        frame = _unpack(request)
        session_id = frame.get("key", "").split("/", 1)[0]
        with self._lock:
            aborted = session_id in self._aborted
        if aborted:
            return b""
        return self.networking.handle_send_value(
            request, context, frame=frame
        )

    # -- server lifecycle ----------------------------------------------

    def start(self):
        import grpc

        def unary(fn):
            return grpc.unary_unary_rpc_method_handler(
                lambda req, ctx: fn(req, ctx),
                request_deserializer=None,
                response_serializer=None,
            )

        handlers = {
            "LaunchComputation": unary(self._launch),
            "RetrieveResults": unary(self._retrieve),
            "AbortComputation": unary(self._abort),
        }
        net_handlers = {"SendValue": unary(self._send_value)}
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=16)
        )
        self._server.add_generic_rpc_handlers(
            (
                grpc.method_handlers_generic_handler(
                    "moose.Choreography", handlers
                ),
                grpc.method_handlers_generic_handler(
                    "moose.Networking", net_handlers
                ),
            )
        )
        if self.tls is not None:
            bound = self._server.add_secure_port(
                f"[::]:{self.port}", self.tls.server_credentials()
            )
        else:
            bound = self._server.add_insecure_port(f"[::]:{self.port}")
        if bound == 0:
            raise NetworkingError(f"cannot bind gRPC port {self.port}")
        self.port = bound
        self._server.start()
        return self

    def stop(self, grace: float = 0.5):
        if self._server is not None:
            self._server.stop(grace)
            self._server = None

    def wait(self):
        self._server.wait_for_termination()


def _serialize_output(value) -> bytes:
    from ..serde import serialize_value

    return serialize_value(value)


class ChoreographyClient:
    """Client stub for one worker (reference GrpcMooseRuntime fan-out,
    execution/grpc.rs:57-84)."""

    def __init__(self, endpoint: str, tls=None,
                 expected_identity: Optional[str] = None):
        import grpc

        if tls is not None:
            if expected_identity is None:
                # certificates bind to party names, not addresses — an
                # endpoint can never match a CN, so fail loudly here
                # instead of with an opaque handshake error per-RPC
                raise ValueError(
                    "expected_identity is required with tls: the worker "
                    "certificate's CN is its party name"
                )
            self._channel = tls.secure_channel(endpoint, expected_identity)
        else:
            self._channel = grpc.insecure_channel(endpoint)

    def launch(self, session_id: str, comp_bytes: bytes,
               arguments: dict):
        from ..serde import serialize_value

        payload = _pack({
            "session_id": session_id,
            "computation": comp_bytes,
            "arguments": {
                name: serialize_value(v) for name, v in arguments.items()
            },
        })
        fn = self._channel.unary_unary(LAUNCH)
        return _unpack(fn(payload, timeout=30.0))

    def retrieve(self, session_id: str, timeout: float = 120.0):
        fn = self._channel.unary_unary(RETRIEVE)
        payload = _pack({"session_id": session_id, "timeout": timeout})
        return _unpack(fn(payload, timeout=timeout + 10.0))

    def abort(self, session_id: str):
        fn = self._channel.unary_unary(ABORT)
        return _unpack(fn(_pack({"session_id": session_id}), timeout=10.0))
