"""Choreography: session orchestration across workers over gRPC.

Reference ``moose/src/choreography/grpc.rs:34-234`` +
``protos/choreography.proto``: LaunchComputation / RetrieveResults /
AbortComputation, with per-session result cells and duplicate-session
protection.  gRPC methods carry raw msgpack bytes (no protoc codegen
needed; the reference uses tonic+prost — the method *names* and semantics
match, the payload codec is msgpack like the rest of this framework).

Failure discipline (beyond the reference, whose abort handler is
``unimplemented!()``, choreography/grpc.rs:200-205):

- **abort fanout**: the first worker to hit a root-cause error aborts the
  session on every peer via a participant-level AbortSession rpc, so a
  3-party protocol fails fast everywhere instead of leaving two parties
  blocked in receives until timeout (the reference's
  ``join_on_first_error`` does this within one process,
  execution/asynchronous.rs:27-74; we extend it across workers);
- **failure detector**: while a session runs, each worker pings its peers;
  a peer that stops answering for ``ping_misses`` consecutive rounds
  fails the session locally and fans the abort out to the survivors — a
  killed worker is detected in ~``ping_misses * ping_interval`` seconds.
"""

from __future__ import annotations

import threading
from concurrent import futures
from typing import Optional

import msgpack

from ..errors import (
    NetworkingError,
    PeerUnreachableError,
    SessionAbortedError,
    SessionAlreadyExistsError,
    to_wire,
)
from .networking import GrpcNetworking, _CellStore

LAUNCH = "/moose.Choreography/LaunchComputation"
RETRIEVE = "/moose.Choreography/RetrieveResults"
ABORT = "/moose.Choreography/AbortComputation"
FLIGHT = "/moose.Choreography/GetFlight"
STORAGE_CONTROL = "/moose.Choreography/StorageControl"
SEND_VALUE = "/moose.Networking/SendValue"
ABORT_SESSION = "/moose.Networking/AbortSession"
PING = "/moose.Networking/Ping"


def _pack(obj) -> bytes:
    return msgpack.packb(obj, use_bin_type=True)


def _unpack(data: bytes):
    return msgpack.unpackb(data, raw=False, strict_map_key=False)


class _SessionState:
    """Book-keeping for one running session."""

    __slots__ = (
        "cancel", "peers", "abort_reason", "abort_envelope", "progress",
    )

    def __init__(self, peers):
        from .networking import ProgressClock

        self.cancel = threading.Event()
        self.peers = list(peers)
        # set when the cancel came from outside (choreographer or peer
        # fanout) so the run thread records the root cause, not a bare
        # "aborted"; the envelope carries the TYPED root cause so the
        # client re-raises the real exception class
        self.abort_reason: Optional[str] = None
        self.abort_envelope: Optional[dict] = None
        # receives extend their deadline while this advances; bumped by
        # local op completions AND successful peer pings, so a party
        # idling while live peers crunch a long pipeline never times out
        self.progress = ProgressClock()


class WorkerServer:
    """One worker daemon: hosts the choreography service and the gRPC
    networking endpoint, executes its role of launched sessions in
    background threads (reference comet, bin/comet/comet.rs:12-83)."""

    def __init__(self, identity: str, port: int, endpoints: dict,
                 storage: Optional[dict] = None, tls=None,
                 choreographer: Optional[str] = None,
                 ping_interval: float = 0.5, ping_misses: int = 3,
                 startup_grace: float = 30.0,
                 receive_timeout: Optional[float] = None,
                 stall_grace: Optional[float] = None,
                 chaos=None, metrics_port: Optional[int] = None,
                 fabric_domain=None):
        self.identity = identity
        self.port = port
        self.endpoints = dict(endpoints)
        self.storage = storage if storage is not None else {}
        self.tls = tls  # distributed.tls.TlsConfig or None
        # when set (requires tls), only a peer whose certificate CN equals
        # this name may launch/abort sessions (reference
        # choreography/grpc.rs:64-94 check_choreographer)
        self.choreographer = choreographer
        if choreographer is not None and tls is None:
            raise NetworkingError(
                "choreographer authorization requires a TlsConfig — "
                "without mTLS there is no verified peer identity"
            )
        # failure-detector cadence; interval <= 0 disables the detector.
        # startup_grace: how long an as-yet-never-reachable peer is
        # tolerated (workers may come up in any order); once a peer has
        # answered one ping, ping_misses consecutive failures trip.
        self.ping_interval = ping_interval
        self.ping_misses = ping_misses
        self.startup_grace = startup_grace
        # how long a blocked receive tolerates NO session progress
        # anywhere (local op completions or peer op advances) before it
        # fails retryably; env override for whole deployments
        if receive_timeout is None:
            import os

            receive_timeout = float(
                os.environ.get("MOOSE_TPU_RECEIVE_TIMEOUT", "120")
            )
        self.receive_timeout = receive_timeout
        # how long blocked receives tolerate live-but-NOT-advancing
        # peers beyond the last real op advance: one giant op (a huge
        # jit compile, a 200k-op segment) may legitimately exceed
        # receive_timeout with every count frozen, so extension
        # continues for this bounded budget — unlike the unbounded
        # liveness extension it replaces, a mutually-blocked cluster
        # (lost send) still times out at ~stall_grace + receive_timeout
        self.stall_grace = (
            2.0 * receive_timeout if stall_grace is None else stall_grace
        )
        import collections

        # chaos: explicit config, or MOOSE_TPU_CHAOS from the
        # environment (comet daemons pick the same schedule up without
        # new flags); None disables.  The transport is WRAPPED so every
        # send/ping of this worker flows through the fault schedule.
        from .chaos import ChaosConfig

        self.chaos = chaos if chaos is not None else ChaosConfig.from_env()
        networking = GrpcNetworking(identity, self.endpoints, tls=tls)
        # layering: wire -> fabric -> chaos.  The fabric lowers
        # intra-domain edges to collective permutes over the wire's
        # cell store; chaos stays OUTERMOST so fault decisions happen
        # per logical rendezvous key BEFORE permute lowering (a dropped
        # key latches onto the wire for its replay).
        self.fabric_domain = fabric_domain
        if fabric_domain is not None and fabric_domain.is_member(identity):
            from .fabric import FabricNetworking

            networking = FabricNetworking(
                fabric_domain, identity, networking
            )
        if self.chaos is not None:
            self.chaos.register_kill_hook(identity, self._chaos_kill)
            networking = self.chaos.wrap(networking, identity)
        self.networking = networking
        self._sessions: dict = {}  # session id -> _SessionState (running)
        # serialized-computation memo: repeat sessions of one computation
        # (serving traffic) must share ONE deserialized object, because
        # the worker's resolved role plans are weak-keyed on it — a
        # fresh object per launch would re-validate and re-jit every
        # session (same discipline as runtime._bin_cache)
        self._bin_cache: "collections.OrderedDict" = (
            collections.OrderedDict()
        )
        self._aborted: "collections.deque[str]" = collections.deque()
        # aborted session -> root-cause envelope, served through pings:
        # a peer that missed the abort fanout adopts the abort WITH its
        # typed cause instead of a generic retryable SessionAborted
        self._abort_envelopes: dict = {}
        self._completed: "collections.deque[str]" = collections.deque()
        self._results = _CellStore()
        self._lock = threading.Lock()
        self._server = None
        # HTTP metrics/health exposition (GET /metrics Prometheus text,
        # /healthz, /v1/metrics JSON) — explicit kwarg wins, else
        # MOOSE_TPU_METRICS_PORT (0 = ephemeral), else disabled
        self._metrics_port_from_env = False
        if metrics_port is None:
            import os

            raw = os.environ.get("MOOSE_TPU_METRICS_PORT")
            if raw is not None and raw.strip() != "":
                try:
                    metrics_port = int(raw)
                except ValueError as e:
                    raise NetworkingError(
                        "MOOSE_TPU_METRICS_PORT must be an integer, "
                        f"got {raw!r}"
                    ) from e
                self._metrics_port_from_env = True
        self.metrics_port = metrics_port
        self.metrics_server = None

    # -- rpc handlers ---------------------------------------------------

    def _check_choreographer(self, context) -> None:
        if self.choreographer is None:
            return
        from .tls import peer_common_name, reject

        peer = peer_common_name(context) if context is not None else None
        if peer != self.choreographer:
            reject(
                context,
                f"unauthorized choreographer: peer CN {peer!r}, expected "
                f"{self.choreographer!r}",
            )

    def _launch(self, request: bytes, context=None) -> bytes:
        self._check_choreographer(context)
        return self._launch_inner(request)

    def _launch_inner(self, request: bytes) -> bytes:
        from .. import flight, telemetry
        from ..computation import HostPlacement
        from ..serde import deserialize_computation, deserialize_value

        msg = _unpack(request)
        session_id = msg["session_id"]
        # the client's propagated trace position (Dapper-style): this
        # worker's execute_role root and every span under it — including
        # detector trips and abort fanouts — join the client's trace
        trace_ctx = telemetry.TraceContext.from_dict(msg.get("trace"))
        state = _SessionState([])
        with self._lock:
            if session_id in self._aborted:
                # abort raced ahead of launch (gRPC retry/reordering):
                # honor it — never start the session
                raise SessionAlreadyExistsError(
                    f"{session_id} (aborted before launch)"
                )
            if session_id in self._sessions or session_id in self._completed:
                raise SessionAlreadyExistsError(session_id)
            self._sessions[session_id] = state
        flight.record(
            "launch", party=self.identity, session=session_id,
            args=sorted(msg.get("arguments") or {}),
        )

        def run_in_ctx():
            with telemetry.use_context(trace_ctx):
                run()

        def run():
            from .worker import execute_role

            fanout_reason = None
            fanout_envelope = None
            try:
                # deserialization happens off the rpc thread: a large
                # lowered graph (an AES decrypt circuit is ~200k ops)
                # would otherwise hold the launch rpc past its deadline
                comp = self._computation_for(msg["computation"])
                state.peers.extend(
                    plc.name for plc in comp.placements.values()
                    if isinstance(plc, HostPlacement)
                    and plc.name != self.identity
                    and plc.name in self.endpoints
                )
                if state.peers and self.ping_interval > 0:
                    def detect():
                        # the detector thread inherits the session's
                        # trace context so its detector_trip spans
                        # stitch into the distributed trace
                        with telemetry.use_context(trace_ctx):
                            self._failure_detector(session_id, state)

                    threading.Thread(
                        target=detect,
                        daemon=True,
                        name=f"moose-fd-{session_id[:8]}",
                    ).start()
                arguments = {
                    name: deserialize_value(blob)
                    for name, blob in (msg.get("arguments") or {}).items()
                }
                result = execute_role(
                    comp, self.identity, self.storage, arguments,
                    self.networking, session_id, cancel=state.cancel,
                    progress=state.progress,
                    timeout=self.receive_timeout,
                )
                # resolved transport descriptor rides along so the
                # client's session report (and bench rows) record what
                # this party's traffic actually used
                descriptor = getattr(
                    self.networking, "transport_descriptor", None
                )
                transport = (
                    descriptor() if descriptor is not None
                    else {"transport": "grpc", "trust_model": None}
                )
                payload = _pack({
                    "outputs": {
                        name: _serialize_output(value)
                        for name, value in result["outputs"].items()
                    },
                    "elapsed_time_micros": result["elapsed_time_micros"],
                    # resolved worker-plan shape rides along so the
                    # client (and the distributed smoke/bench) can
                    # assert every role reached its compiled plan
                    "plan_mode": result.get("plan_mode"),
                    "pinned_segments": result.get("pinned_segments", []),
                    "transport": transport.get("transport"),
                    "trust_model": transport.get("trust_model"),
                })
                flight.record(
                    "session_completed", party=self.identity,
                    session=session_id,
                    elapsed_micros=result["elapsed_time_micros"],
                    plan_mode=result.get("plan_mode"),
                )
            except SessionAbortedError as e:
                # someone else's root cause cancelled us; the initiator
                # already fanned out and (if it was this server) already
                # put the canonical error cell
                payload = _pack({
                    "error": state.abort_reason or "aborted",
                    "envelope": state.abort_envelope
                    or to_wire(e, self.identity),
                })
                flight.record(
                    "session_aborted", party=self.identity,
                    session=session_id,
                    reason=state.abort_reason or "aborted",
                )
            except Exception as e:  # surfaced on retrieve + fanned out
                fanout_envelope = to_wire(e, self.identity)
                fanout_reason = f"{type(e).__name__}: {e}"
                payload = _pack({
                    "error": fanout_reason, "envelope": fanout_envelope,
                })
                flight.record(
                    "session_error", party=self.identity,
                    session=session_id, error=fanout_reason,
                )
            # an aborted session already has its canonical error result;
            # putting again would either clobber it or recreate a
            # never-consumed cell.  The check and put happen under the
            # same lock as _abort's add+put so the two cannot interleave.
            with self._lock:
                self._sessions.pop(session_id, None)
                if session_id not in self._aborted:
                    self._results.put(session_id, payload)
                    if fanout_reason is None:
                        self._completed.append(session_id)
                        while len(self._completed) > self._MAX_ABORTED:
                            self._completed.popleft()
                    else:
                        # a root-cause failure is remembered as ABORTED,
                        # not completed: peers' pings then adopt the
                        # abort even if the fanout below never lands
                        # (the result cell above keeps the real error
                        # for the retriever)
                        self._remember_aborted_locked(
                            session_id, fanout_envelope
                        )
            if fanout_reason is not None:
                # peers may be unknown if the failure hit before the
                # graph deserialized — notify every configured endpoint
                targets = state.peers or [
                    p for p in self.endpoints if p != self.identity
                ]
                self._fanout_abort(
                    session_id, fanout_reason, targets,
                    envelope=fanout_envelope,
                )

        threading.Thread(target=run_in_ctx, daemon=True).start()
        return _pack({"ok": True})

    # bound on memoized deserialized computations (a serving deployment
    # cycles through a handful of models; 32 mirrors runtime._bin_cache)
    _MAX_BIN_CACHE = 32

    def _computation_for(self, blob: bytes):
        """Deserialize ``blob``, memoized on the bytes: the worker's
        resolved role plans (worker_plan) are weak-keyed on the
        Computation object, so repeat sessions must share it for the
        plan cache — and its validated jit — to survive across
        launches."""
        from ..serde import deserialize_computation

        with self._lock:
            comp = self._bin_cache.get(blob)
            if comp is not None:
                self._bin_cache.move_to_end(blob)
                return comp
        comp = deserialize_computation(blob)
        with self._lock:
            existing = self._bin_cache.get(blob)
            if existing is not None:
                return existing
            self._bin_cache[blob] = comp
            while len(self._bin_cache) > self._MAX_BIN_CACHE:
                self._bin_cache.popitem(last=False)
        return comp

    def _retrieve(self, request: bytes, context=None) -> bytes:
        # results carry the computation's outputs — only the configured
        # choreographer may read them, same as launch/abort
        self._check_choreographer(context)
        msg = _unpack(request)
        timeout = float(msg.get("timeout", 120.0))
        return self._results.get(msg["session_id"], timeout)

    def _get_flight(self, request: bytes, context=None) -> bytes:
        """Serve this process's recent flight-recorder events for the
        requested session ids (the client's postmortem collection on
        terminal session failure).  Events describe execution structure
        — keys, plan modes, error strings — never payload values; still
        choreographer-gated like retrieve, since error strings may leak
        operational detail."""
        self._check_choreographer(context)
        from .. import flight

        msg = _unpack(request)
        events = flight.get_recorder().events(
            sessions=msg.get("session_ids") or (),
            limit=msg.get("limit"),
        )
        return _pack({"events": events})

    def _storage_control(self, request: bytes, context=None) -> bytes:
        """Checkpoint control plane for the training supervisor
        (query / pin / commit / discard against this party's
        CheckpointStore).  Choreographer-gated like launch/retrieve —
        commit and pin decide which model generation this party serves.
        Errors travel as typed wire envelopes so the driver re-raises
        the real class (CheckpointError is non-retryable; a transport
        failure reaching a dead worker classifies retryable at the
        client)."""
        self._check_choreographer(context)
        msg = _unpack(request)
        cmd = msg.get("cmd")
        try:
            store = self.storage
            if not hasattr(store, "checkpoint_control"):
                from ..errors import ConfigurationError

                raise ConfigurationError(
                    f"{self.identity}: storage has no checkpoint "
                    "support (start the worker with a CheckpointStore "
                    "— comet: --checkpoint)"
                )
            result = store.checkpoint_control(cmd, msg.get("args") or {})
            return _pack({"ok": True, "result": result})
        except Exception as e:  # noqa: BLE001 — typed envelope below
            return _pack({
                "ok": False,
                "error": f"{type(e).__name__}: {e}",
                "envelope": to_wire(e, self.identity),
            })

    # bound on remembered aborted/completed ids (replay/late-send
    # protection); old entries age out FIFO so a long-lived worker's
    # state stays bounded
    _MAX_ABORTED = 4096

    def _remember_aborted_locked(self, session_id: str,
                                 envelope: Optional[dict]) -> None:
        """Record an aborted id (+ typed cause for ping adoption);
        caller holds ``self._lock``."""
        self._aborted.append(session_id)
        if envelope is not None:
            self._abort_envelopes[session_id] = envelope
        while len(self._aborted) > self._MAX_ABORTED:
            old = self._aborted.popleft()
            self._abort_envelopes.pop(old, None)

    def _abort(self, request: bytes, context=None) -> bytes:
        self._check_choreographer(context)
        msg = _unpack(request)
        self._abort_local(msg["session_id"], reason="aborted")
        return _pack({"ok": True})

    def _abort_local(self, session_id: str, reason: str,
                     envelope: Optional[dict] = None) -> None:
        """Shared abort path (choreographer rpc, peer fanout, failure
        detector): cancel a running session, record the canonical error
        cell, remember the id so late launches/sends are dropped.  An
        already-completed session keeps its real result.  ``envelope``
        is the typed root cause (errors.to_wire) when the aborter knows
        it — a peer's fanned-out failure, a detector trip — so every
        party's result cell re-raises the REAL class at the client."""
        from .. import flight

        flight.record(
            "abort", party=self.identity, session=session_id,
            reason=reason,
        )
        if envelope is None:
            envelope = to_wire(SessionAbortedError(reason), self.identity)
        with self._lock:
            completed = session_id in self._completed
            state = self._sessions.pop(session_id, None)
            self._remember_aborted_locked(session_id, envelope)
            if state is not None:
                # fail-stop semantics: retrievers of a launched session
                # unblock with the canonical error.  Unknown ids get no
                # cell (nobody retrieves a session that never launched;
                # a cell would be retained forever), completed ones keep
                # their real result.
                state.abort_reason = reason
                state.abort_envelope = envelope
                self._results.put(session_id, _pack({
                    "error": reason, "envelope": envelope,
                }))
        if state is not None:
            # cooperative cancellation: the execute threads check the
            # event between ops and inside blocked receives
            # (the reference's abort handler is unimplemented!(),
            # choreography/grpc.rs:200-205)
            state.cancel.set()
        if not completed:
            # drop pending rendezvous payloads so aborted sessions don't
            # retain undelivered tensors in a long-lived worker
            self.networking.cells.drop_session(session_id)

    def _fanout_abort(self, session_id: str, reason: str, peers,
                      envelope: Optional[dict] = None) -> None:
        """Propagate a root-cause error: abort the session on every peer
        (best effort, parallel, short timeout — a dead peer is precisely
        the case we're propagating around).  The typed envelope rides
        along so peers' result cells carry the originator's real error
        class, not a generic 'aborted by'."""
        from .. import telemetry

        msg = f"aborted by {self.identity}: {reason}"
        reached = [0]

        def one(peer):
            # two attempts: a transient failure here would otherwise
            # leave the peer relying on its (slower) failure detector
            for attempt in range(2):
                try:
                    self.networking.abort_session(
                        peer, session_id, msg, envelope=envelope
                    )
                    reached[0] += 1
                    return
                except Exception:  # noqa: BLE001 — peer may be the dead one
                    if attempt == 0:
                        import time

                        time.sleep(0.2)

        with telemetry.span(
            "abort_fanout", session_id=session_id, party=self.identity,
            peers=len(list(peers)), reason=reason,
        ) as s:
            threads = [
                threading.Thread(target=one, args=(p,), daemon=True)
                for p in peers
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=5.0)
            s.attrs["reached"] = reached[0]

    def _abort_session(self, request: bytes, context=None) -> bytes:
        """Participant-level abort (peer fanout target).  Under mTLS the
        claimed sender must match the peer certificate's CN and be a
        configured participant — a choreographer credential is NOT
        required: any party that hit a root cause may fail the session."""
        msg = _unpack(request)
        sender = msg.get("sender")
        if self.tls is not None:
            from .tls import peer_common_name, reject

            peer = (
                peer_common_name(context) if context is not None else None
            )
            if peer is None or peer != sender or peer not in self.endpoints:
                reject(
                    context,
                    f"unauthorized session abort: claimed {sender!r}, "
                    f"peer certificate CN {peer!r}",
                )
        self._abort_local(
            msg["session_id"],
            reason=msg.get("reason", "aborted by peer"),
            envelope=msg.get("envelope"),
        )
        return _pack({"ok": True})

    def _ping(self, request: bytes, context=None) -> bytes:
        msg = _unpack(request) if request else {}
        session_id = msg.get("session_id")
        status = None
        ops = None
        abort_envelope = None
        if session_id is not None:
            with self._lock:
                if session_id in self._sessions:
                    status = "running"
                    # op-completion count: progress EVIDENCE, so a
                    # peer's detector can tell "alive and advancing"
                    # (extend blocked receives) from "alive but stuck"
                    # (let the no-progress timeout fire — e.g. after a
                    # lost send leaves everyone mutually blocked)
                    ops = self._sessions[session_id].progress.count
                elif session_id in self._aborted:
                    status = "aborted"
                    # the typed root cause rides along so an adopter
                    # that missed the fanout still re-raises the real
                    # class (and its retryable bit) at the client
                    abort_envelope = self._abort_envelopes.get(
                        session_id
                    )
                elif session_id in self._completed:
                    status = "completed"
                else:
                    status = "unknown"
        return _pack({
            "ok": True, "identity": self.identity, "session": status,
            "ops": ops, "abort_envelope": abort_envelope,
        })

    def _failure_detector(self, session_id: str, state: _SessionState):
        """Ping session peers while the session runs; a consistently
        unreachable peer fails the session everywhere.  Two kinds of
        miss are weighted differently: a connection-level failure
        (UNAVAILABLE — process dead, port closed) scores 2, a slow
        answer (deadline exceeded — peer alive but saturated, common on
        small shared hosts) scores 1, and the session fails at
        ``2 * ping_misses`` points — so a killed worker is detected in
        ~``ping_misses * ping_interval`` seconds while a busy-but-alive
        peer gets twice the patience.  Peers that were never reachable
        get ``startup_grace`` seconds first (workers come up in any
        order)."""
        import time

        import grpc

        start = time.monotonic()
        misses = {p: 0 for p in state.peers}
        seen = {p: False for p in state.peers}
        last_ops: dict = {}  # peer -> last reported op count / status
        last_advance = time.monotonic()
        trip_at = 2 * self.ping_misses
        while True:
            time.sleep(self.ping_interval)
            with self._lock:
                if session_id not in self._sessions:
                    return  # session finished or was aborted
            # progress extends blocked receives only when EVERY peer
            # shows session liveness this round AND at least one peer
            # reports real op advances: a single peer stuck at
            # "unknown" (its launch never arrived — e.g. the client died
            # mid-fanout) must let the hard timeout fire even while the
            # other peers keep answering, and a cluster where every
            # party is mutually blocked (a send was lost on the wire)
            # must time out rather than extend deadlines off bare
            # liveness forever
            all_live = True
            all_completed = bool(state.peers)
            any_advance = False
            for peer in state.peers:
                if state.cancel.is_set():
                    return
                try:
                    resp = self.networking.ping(
                        peer, timeout=3.0, session_id=session_id
                    )
                    seen[peer] = True
                    misses[peer] = 0
                    peer_session = resp.get("session")
                    peer_ops = resp.get("ops")
                    prev = last_ops.get(peer)
                    if peer_session == "completed":
                        # the completion transition is one last advance
                        # (it may deliver this worker's pending value)
                        if prev != "completed":
                            any_advance = True
                        last_ops[peer] = "completed"
                    elif peer_ops is not None:
                        if isinstance(prev, int) and peer_ops > prev:
                            any_advance = True
                        last_ops[peer] = peer_ops
                    if peer_session == "aborted":
                        # the peer killed this session but its fanout
                        # never reached us: adopt the abort instead of
                        # treating the live process as session liveness
                        # (with the peer's typed root cause, when the
                        # ping carried it)
                        reason = (
                            f"session aborted on peer {peer!r} "
                            "(learned via ping)"
                        )
                        self._abort_local(
                            session_id, reason=reason,
                            envelope=resp.get("abort_envelope"),
                        )
                        return
                    if peer_session not in ("running", "completed"):
                        all_live = False
                    if peer_session != "completed":
                        all_completed = False
                except Exception as e:  # noqa: BLE001 — rpc failure
                    all_live = False
                    all_completed = False
                    if (
                        not seen[peer]
                        and time.monotonic() - start < self.startup_grace
                    ):
                        continue
                    hard = (
                        isinstance(e, grpc.RpcError)
                        and e.code() == grpc.StatusCode.UNAVAILABLE
                    )
                    misses[peer] += 2 if hard else 1
                    if misses[peer] >= trip_at:
                        from .. import flight, metrics, telemetry

                        reason = (
                            f"peer {peer!r} unreachable "
                            f"({misses[peer]} ping-miss points)"
                        )
                        envelope = to_wire(
                            PeerUnreachableError(reason), self.identity
                        )
                        metrics.counter(
                            "moose_tpu_detector_trips_total",
                            "failure-detector trips (peer declared "
                            "unreachable)",
                        ).inc()
                        flight.record(
                            "detector_trip", party=self.identity,
                            session=session_id, peer=peer,
                            miss_points=misses[peer],
                        )
                        with telemetry.span(
                            "detector_trip", session_id=session_id,
                            party=self.identity, peer=peer,
                            miss_points=misses[peer],
                        ):
                            self._abort_local(
                                session_id, reason=reason,
                                envelope=envelope,
                            )
                            survivors = [
                                p for p in state.peers if p != peer
                            ]
                            self._fanout_abort(
                                session_id, reason, survivors,
                                envelope=envelope,
                            )
                        return
            # a round where EVERY peer reports 'completed' cannot deliver
            # anything new to this worker's pending receives — bumping
            # progress would extend their deadlines forever when a value
            # this worker still awaits was never sent (role/graph
            # mismatch, dropped send); let the no-progress timeout fire
            # instead (ADVICE r3).  Liveness alone is not progress
            # either, but live peers get a bounded stall_grace beyond
            # the last real advance — one giant op may legitimately
            # freeze every count for longer than the receive timeout.
            if any_advance:
                last_advance = time.monotonic()
            if (
                all_live and state.peers and not all_completed
                and (
                    any_advance
                    or time.monotonic() - last_advance
                    < self.stall_grace
                )
            ):
                # extend, don't bump: a bump would raise OUR op count,
                # which peers' detectors would read as an advance — a
                # mutual-extension loop that never times out
                state.progress.extend()

    def _send_value(self, request: bytes, context=None) -> bytes:
        # a peer's send may land after this worker aborted the session:
        # drop it so cancelled receives never retain the payload — but
        # only after the mTLS sender check, so a spoofed frame is
        # rejected (not silently ACKed) on this path too
        frame = _unpack(request)
        self.networking.verify_sender(frame, context)
        batch = frame.get("batch")
        if batch:  # coalesced send_many envelope: one session per frame
            first_key = batch[0].get("key", "")
        else:
            first_key = frame.get("key", "")
        session_id = first_key.split("/", 1)[0]
        with self._lock:
            aborted = session_id in self._aborted
        if aborted:
            return b""
        return self.networking.handle_send_value(
            request, context, frame=frame, verified=True
        )

    # -- server lifecycle ----------------------------------------------

    def start(self):
        import grpc

        def unary(fn):
            return grpc.unary_unary_rpc_method_handler(
                lambda req, ctx: fn(req, ctx),
                request_deserializer=None,
                response_serializer=None,
            )

        handlers = {
            "LaunchComputation": unary(self._launch),
            "RetrieveResults": unary(self._retrieve),
            "AbortComputation": unary(self._abort),
            "GetFlight": unary(self._get_flight),
            "StorageControl": unary(self._storage_control),
        }
        net_handlers = {
            "SendValue": unary(self._send_value),
            "AbortSession": unary(self._abort_session),
            "Ping": unary(self._ping),
        }
        from .networking import GRPC_MESSAGE_OPTIONS

        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=16),
            options=GRPC_MESSAGE_OPTIONS,
        )
        self._server.add_generic_rpc_handlers(
            (
                grpc.method_handlers_generic_handler(
                    "moose.Choreography", handlers
                ),
                grpc.method_handlers_generic_handler(
                    "moose.Networking", net_handlers
                ),
            )
        )
        if self.tls is not None:
            bound = self._server.add_secure_port(
                f"[::]:{self.port}", self.tls.server_credentials()
            )
        else:
            bound = self._server.add_insecure_port(f"[::]:{self.port}")
        if bound == 0:
            raise NetworkingError(f"cannot bind gRPC port {self.port}")
        self.port = bound
        if self.metrics_port is not None and self.metrics_server is None:
            from .. import metrics

            try:
                self.metrics_server = metrics.serve_http(
                    self.metrics_port,
                    health_extra={"identity": self.identity},
                )
            except OSError as e:
                if not self._metrics_port_from_env:
                    raise NetworkingError(
                        f"cannot bind metrics port {self.metrics_port}: "
                        f"{e}"
                    ) from e
                # env-derived fixed port + several workers in ONE
                # process (an in-process cluster inheriting the comet
                # knob): fall back to an ephemeral port instead of
                # crashing startup — the registry is process-global, so
                # any bound port serves the same series
                from ..logger import get_logger

                get_logger().warning(
                    "metrics port %d (MOOSE_TPU_METRICS_PORT) already "
                    "bound in this process; %s falling back to an "
                    "ephemeral port", self.metrics_port, self.identity,
                )
                self.metrics_server = metrics.serve_http(
                    0, health_extra={"identity": self.identity}
                )
            self.metrics_port = self.metrics_server.port
        self._server.start()
        if self.chaos is not None:
            # an in-process 'restart' constructs a fresh WorkerServer
            # over the SAME chaos config: the restarted identity is
            # alive again (its kill-count persists — max_kills bounds
            # how often the schedule may strike it).  Revive only AFTER
            # the server is actually serving: reviving before a failed
            # bind would clear the _killed latch the restart watchdog
            # iterates, so a transient bind error could never be
            # retried
            self.chaos.revive(self.identity)
        return self

    def stop(self, grace: float = 0.5):
        if self._server is not None:
            self._server.stop(grace)
            self._server = None
        if self.metrics_server is not None:
            self.metrics_server.close()
            self.metrics_server = None

    def _chaos_kill(self):
        """Chaos ``kill_after_ops`` hook: die like a SIGKILL'd process —
        stop answering RPCs abruptly (peers' pings see UNAVAILABLE and
        their detectors trip) without aborting sessions, fanning out, or
        otherwise saying goodbye.  The wrapped transport raises on every
        subsequent op of this identity, so the run thread cannot limp
        along either."""
        server, self._server = self._server, None
        if server is not None:
            server.stop(0)

    def wait(self):
        self._server.wait_for_termination()


def start_local_cluster(identities, storages=None, **server_kwargs):
    """In-process WorkerServer cluster on ephemeral 127.0.0.1 gRPC
    ports, endpoints cross-wired after every port is known (port 0 means
    the endpoint map cannot be built up front) — the single bootstrap
    shared by bench.py, scripts/dist_smoke.py and tests.  Returns
    ``(servers, endpoints)``; caller stops each server."""
    servers, endpoints = {}, {}
    for name in identities:
        srv = WorkerServer(
            name, 0, {}, storage=(storages or {}).get(name),
            **server_kwargs,
        ).start()
        servers[name] = srv
        endpoints[name] = f"127.0.0.1:{srv.port}"
    for srv in servers.values():
        srv.endpoints.update(endpoints)
        srv.networking._endpoints.update(endpoints)
    return servers, endpoints


def start_chaos_restarter(servers, endpoints, storages, chaos,
                          restart_delay_s: float = 1.0,
                          poll_s: float = 0.3, **server_kwargs):
    """Test/bench harness: watch a chaos config and 'process-restart'
    any killed in-process worker — stop the stale WorkerServer, rebind
    a fresh one on the SAME port with the SAME (durable) storage and
    the SAME chaos config (``start`` revives the identity; max_kills
    bounds further strikes).  Returns a zero-arg stop callable.  The
    single restart loop shared by tests/test_training.py and
    bench.py's training bench, so restart semantics cannot drift."""
    import time as _time

    stop_event = threading.Event()

    def loop():
        from ..logger import get_logger

        while not stop_event.is_set():
            _time.sleep(poll_s)
            if chaos is None:
                continue
            for party in list(chaos._killed):
                # a failed restart (port raced by another process,
                # transient bind error) must NOT kill this watcher
                # thread — the identity would stay latched dead and the
                # driver's failure would point at the wrong culprit;
                # log and retry on the next poll
                try:
                    _time.sleep(restart_delay_s)
                    old = servers[party]
                    old.stop(grace=0)
                    srv = WorkerServer(
                        party, old.port, dict(endpoints),
                        storage=(storages or {}).get(party),
                        chaos=chaos, **server_kwargs,
                    )
                    srv.start()
                    servers[party] = srv
                except Exception:  # noqa: BLE001 — retried next poll
                    get_logger().warning(
                        "chaos restarter: restart of %r failed; "
                        "retrying", party, exc_info=True,
                    )

    thread = threading.Thread(target=loop, daemon=True)
    thread.start()

    def stop():
        stop_event.set()
        thread.join(timeout=3.0)

    return stop


def _serialize_output(value) -> bytes:
    from ..serde import serialize_value

    return serialize_value(value)


class ChoreographyClient:
    """Client stub for one worker (reference GrpcMooseRuntime fan-out,
    execution/grpc.rs:57-84)."""

    def __init__(self, endpoint: str, tls=None,
                 expected_identity: Optional[str] = None):
        import grpc

        if tls is not None:
            if expected_identity is None:
                # certificates bind to party names, not addresses — an
                # endpoint can never match a CN, so fail loudly here
                # instead of with an opaque handshake error per-RPC
                raise ValueError(
                    "expected_identity is required with tls: the worker "
                    "certificate's CN is its party name"
                )
            self._channel = tls.secure_channel(endpoint, expected_identity)
        else:
            from .networking import GRPC_MESSAGE_OPTIONS

            self._channel = grpc.insecure_channel(
                endpoint, options=GRPC_MESSAGE_OPTIONS
            )

    def launch(self, session_id: str, comp_bytes: bytes,
               arguments: dict, trace: Optional[dict] = None):
        from ..serde import serialize_value

        payload = _pack({
            "session_id": session_id,
            "computation": comp_bytes,
            "arguments": {
                name: serialize_value(v) for name, v in arguments.items()
            },
            # the client's TraceContext (telemetry.TraceContext.to_dict)
            # — the worker's spans join this trace (Dapper propagation)
            "trace": trace,
        })
        fn = self._channel.unary_unary(LAUNCH)
        # generous: the payload may be a multi-MB serialized graph and
        # the worker may be busy; actual graph deserialization happens
        # off the rpc thread on the worker
        return _unpack(fn(payload, timeout=120.0))

    def retrieve(self, session_id: str, timeout: float = 120.0):
        fn = self._channel.unary_unary(RETRIEVE)
        payload = _pack({"session_id": session_id, "timeout": timeout})
        return _unpack(fn(payload, timeout=timeout + 10.0))

    def abort(self, session_id: str):
        fn = self._channel.unary_unary(ABORT)
        return _unpack(fn(_pack({"session_id": session_id}), timeout=10.0))

    def flight(self, session_ids, limit: Optional[int] = None,
               timeout: float = 5.0) -> list:
        """Fetch the worker's recent flight-recorder events for the
        given session ids (postmortem collection; short timeout — the
        worker may be the dead party)."""
        fn = self._channel.unary_unary(FLIGHT)
        payload = _pack({
            "session_ids": list(session_ids), "limit": limit,
        })
        return _unpack(fn(payload, timeout=timeout)).get("events", [])

    def storage_control(self, cmd: str, args: Optional[dict] = None,
                        timeout: float = 30.0):
        """Drive the worker's CheckpointStore (training control plane).
        Wire-envelope errors re-raise as their real class — a
        CheckpointError on the worker is a CheckpointError here."""
        fn = self._channel.unary_unary(STORAGE_CONTROL)
        resp = _unpack(fn(
            _pack({"cmd": cmd, "args": args or {}}), timeout=timeout,
        ))
        if not resp.get("ok"):
            from ..errors import from_wire

            envelope = resp.get("envelope")
            if envelope:
                raise from_wire(envelope)
            raise NetworkingError(
                f"storage_control({cmd}) failed: {resp.get('error')}"
            )
        return resp.get("result")
