"""Role-filtered parallel execution of a lowered computation on one worker.

The distributed counterpart of the local physical executor: each worker
takes the same global host-level graph, keeps only the operations pinned
to its own identity, and executes them with dependency-counted parallelism
— the re-design of the reference's one-async-task-per-op executor
(execution/asynchronous.rs:453-531) for Python threads:

- compute/send ops run on a bounded thread pool (jax/numpy release the
  GIL for the heavy parts, so independent branches genuinely overlap);
- every Receive gets its own waiter thread, so a blocked receive can
  never occupy a compute slot.

Deadlock freedom: receives don't hold pool slots, compute ops depend only
on locally-available values, and sends are non-blocking w.r.t. the
rendezvous (the receiver's cell store buffers out-of-order arrivals), so
the pool always drains; for any blocked receive the matching send is on
some peer whose own pool drains by the same argument — induction over the
global dataflow order.

Failure discipline: the FIRST exception is the root cause (reference
join_on_first_error, execution/asynchronous.rs:27-74).  It cancels every
in-flight and pending op of the session locally and is re-raised to the
caller; the choreography layer then fans the abort out to peer workers.
A ``SessionAbortedError`` (we were cancelled by someone else's root
cause) is re-raised as-is so the caller knows not to re-fan-out.
"""

from __future__ import annotations

import os
import secrets
import threading
import time
from collections import deque
from typing import Callable, Optional

import numpy as np

from ..computation import Computation, HostPlacement
from ..errors import (
    KernelError,
    MissingArgumentError,
    SessionAbortedError,
    StorageError,
)
from ..execution.physical import execute_kernel
from ..execution.session import EagerSession
from ..values import HostPrfKey, HostString, HostUnit


def _pool_size() -> int:
    raw = os.environ.get("MOOSE_TPU_WORKER_THREADS")
    if raw:
        from ..errors import ConfigurationError

        try:
            n = int(raw)
        except ValueError as e:
            raise ConfigurationError(
                f"MOOSE_TPU_WORKER_THREADS must be an integer >= 1, "
                f"got {raw!r}"
            ) from e
        if n < 1:
            raise ConfigurationError(
                f"MOOSE_TPU_WORKER_THREADS must be >= 1, got {n}"
            )
        return n
    # floor of 2 even on 1-core hosts: jax/numpy/serde release the GIL,
    # so a second thread overlaps wire serialization with compute
    return max(2, min(8, os.cpu_count() or 4))


class _AnyEvent:
    """is_set() over several events — lets a receive slice on both the
    external abort (choreographer/peer) and the local first-error."""

    def __init__(self, *events):
        self._events = [e for e in events if e is not None]

    def is_set(self) -> bool:
        return any(e.is_set() for e in self._events)


def _exec_host_op(op, env: dict, identity: str, arguments: dict,
                  storage, outputs: dict):
    """Execute one host-boundary op (Input/Load/Save/Output/PrfKeyGen)
    eagerly — shared by the legacy parallel scheduler and the compiled
    fast path (worker_plan), so argument lifting, storage discipline and
    the fixed-keys gate cannot drift between them."""
    import jax.numpy as jnp

    from ..execution.interpreter import _lift_array, _to_user_value

    kind = op.kind
    if kind == "PrfKeyGen":
        fixed = os.environ.get("MOOSE_TPU_FIXED_KEYS")
        if fixed:
            # TEST-ONLY determinism: replicated fixed-point results
            # carry +-1 LSB of share-dependent truncation noise, so
            # the chaos layer's bit-exactness checks (chaos run vs
            # clean run, retry vs first attempt) need reproducible
            # keys.  Gated like the weak default PRF: a real
            # deployment must never run with derivable keys.
            if os.environ.get("MOOSE_TPU_ALLOW_WEAK_PRF") != "1":
                from ..errors import ConfigurationError

                raise ConfigurationError(
                    "MOOSE_TPU_FIXED_KEYS is a testing knob and "
                    "requires MOOSE_TPU_ALLOW_WEAK_PRF=1 — fixed "
                    "PRF keys void all inter-party secrecy"
                )
            import hashlib

            digest = hashlib.blake2b(
                f"{fixed}|{identity}|{op.name}".encode(),
                digest_size=16,
            ).digest()
            words = np.frombuffer(digest, dtype=np.uint32)
        else:
            # each party generates its own key from local entropy —
            # this is where the distributed deployment gets real
            # inter-party security, unlike the single-trust-domain
            # local runtime
            words = np.frombuffer(
                secrets.token_bytes(16), dtype=np.uint32
            )
        return HostPrfKey(jnp.asarray(words), identity)
    if kind == "Input":
        val = arguments.get(op.name)
        if val is None:
            raise MissingArgumentError(
                f"missing argument {op.name!r} on {identity}"
            )
        if isinstance(val, str):
            return HostString(val, identity)
        return _lift_array(np.asarray(val), op, identity)
    if kind == "Load":
        key_val = env[op.inputs[0]]
        key = (
            key_val.value
            if isinstance(key_val, HostString)
            else str(key_val)
        )
        query = ""
        if len(op.inputs) > 1:
            q = env[op.inputs[1]]
            query = q.value if isinstance(q, HostString) else str(q)
        if key not in storage:
            raise StorageError(
                f"no value for key {key!r} in storage of {identity!r}"
            )
        if hasattr(storage, "load"):
            raw = storage.load(key, query)
        else:
            raw = storage[key]
        return _lift_array(np.asarray(raw), op, identity)
    if kind == "Save":
        key = env[op.inputs[0]]
        if not isinstance(key, HostString):
            raise KernelError(
                f"Save {op.name}: key must be a string, found "
                f"{type(key).__name__}"
            )
        from ..execution.interpreter import _save_user_value

        storage[key.value] = _save_user_value(env[op.inputs[1]])
        return HostUnit(identity)
    if kind == "Output":
        value = env[op.inputs[0]]
        # keyed by the Output tag like the local executors and the
        # reference (execution/asynchronous.rs:623)
        outputs[op.attributes.get("tag", op.name)] = _to_user_value(value)
        return value
    raise KernelError(f"not a host-boundary op: {kind} ({op.name})")


def validate_deployable(comp: Computation) -> None:
    """Reject graphs that would fail opaquely mid-run: composite
    placements (lowering skipped) and raw cross-host edges (networking
    pass skipped)."""
    composite = [
        plc.name for plc in comp.placements.values()
        if not isinstance(plc, HostPlacement)
    ]
    if composite:
        raise KernelError(
            "worker received an uncompiled computation (composite "
            f"placements {composite}); compile it first — e.g. "
            "`elk compile --passes typing,lowering,prune,networking,"
            "toposort`"
        )
    for op in comp.operations.values():
        plc_name = comp.placement_of(op).name
        for inp in op.inputs:
            src = comp.operations[inp]
            if (
                comp.placement_of(src).name != plc_name
                and op.kind != "Receive"
            ):
                raise KernelError(
                    f"op {op.name} on {plc_name} reads {inp} from "
                    f"{comp.placement_of(src).name} without a "
                    "Send/Receive pair; run the `networking` compiler "
                    "pass before deploying"
                )


def execute_role(
    comp: Computation,
    identity: str,
    storage: dict,
    arguments: Optional[dict],
    networking,
    session_id: str,
    timeout: float = 120.0,
    cancel=None,
    max_workers: Optional[int] = None,
    progress=None,
) -> dict:
    """Execute ``identity``'s share of a lowered computation; returns
    {"outputs": {...}, "elapsed_time_micros": int, "plan_mode": str,
    "pinned_segments": [...]} — ``plan_mode`` is the resolved worker
    plan shape (full-jit / segmented / validating / eager; see
    :mod:`worker_plan`).

    ``cancel``: optional ``threading.Event`` — a set event (choreographer
    abort or peer-failure fanout) stops pending ops and interrupts
    blocked receives promptly; the run raises ``SessionAbortedError``.

    ``progress``: optional :class:`~.networking.ProgressClock`.  Receives
    time out ``timeout`` seconds after the LAST progress (local op
    completion, or whatever else the caller bumps it on — the gRPC
    worker bumps it on successful peer pings), not after dispatch: the
    parallel scheduler starts every receive waiter up front, so a fixed
    deadline would kill any pipeline whose upstream takes longer than
    ``timeout`` to produce.
    """
    # genuinely-distributed parties must not derive share masks from the
    # non-cryptographic default PRF (ADVICE r1; the client runtime guards
    # too, but workers execute whatever arrives)
    from ..dialects.ring import require_strong_prf

    require_strong_prf("distributed worker")

    from .networking import ProgressClock

    t0 = time.perf_counter()
    arguments = arguments or {}
    validate_deployable(comp)
    # fabric transports resolve this computation's rendezvous keys to
    # permute schedules at plan-build time (MSA505 deadlock gate; a
    # rejected computation is latched wire-only for the session) —
    # delegates through proxy transports like ChaosNetworking
    prepare_fabric = getattr(networking, "prepare_fabric", None)
    if prepare_fabric is not None:
        prepare_fabric(comp, session_id)
    if progress is None:
        progress = ProgressClock()

    # compiled fast path (worker_plan): the role subgraph splits at
    # Send/Receive boundaries into validated-jit compute segments, sends
    # go async, receives prefetch — the legacy per-op parallel scheduler
    # below remains the eager fallback (MOOSE_TPU_WORKER_JIT=0, aes-ctr
    # PRF, disabled self-check, or an MSA5xx build-time plan rejection)
    from . import worker_plan

    if worker_plan.use_fast_path():
        from ..errors import PlanRejectedError
        from ..logger import get_logger

        try:
            plan = worker_plan.get_plan(
                comp, identity, session_id=session_id
            )
        except PlanRejectedError as e:
            # the schedule analyzer proved the sequential plan would
            # hang; the dependency-driven legacy scheduler below is not
            # subject to the plan's step ordering, so demote instead of
            # failing the session
            get_logger().warning(
                "worker plan for %s rejected by the schedule analyzer; "
                "falling back to the legacy eager scheduler: %s",
                identity, e,
            )
        else:
            return worker_plan.execute_role_planned(
                comp, identity, storage, arguments, networking,
                session_id, timeout, cancel, progress, plan,
            )

    sess = EagerSession(session_id=session_id)
    env: dict = {}
    outputs: dict = {}

    def exec_one(op):
        """Run one op to a value; called off-thread, must not touch
        scheduler state."""
        kind = op.kind
        if kind == "Send":
            networking.send(
                env[op.inputs[0]],
                op.attributes["receiver"],
                op.attributes["rendezvous_key"],
                session_id,
            )
            from .. import flight

            flight.record(
                "send", party=identity, session=session_id,
                receiver=op.attributes["receiver"], payloads=1,
                coalesced=False,
            )
            return HostUnit(identity)
        if kind == "Receive":
            return networking.receive(
                op.attributes["sender"],
                op.attributes["rendezvous_key"],
                session_id,
                plc=identity,
                timeout=timeout,
                cancel=abort_any,
                progress=progress,
            )
        if kind in ("PrfKeyGen", "Input", "Load", "Save", "Output"):
            return _exec_host_op(
                op, env, identity, arguments, storage, outputs
            )
        args = [env[i] for i in op.inputs]
        return execute_kernel(sess, op, identity, args)

    # ---- dependency-counted scheduler --------------------------------
    mine = [
        comp.operations[name]
        for name in comp.toposort_names()
        if comp.placement_of(comp.operations[name]).name == identity
    ]
    local_abort = threading.Event()
    abort_any = _AnyEvent(cancel, local_abort)

    if not mine:
        return {
            "outputs": {}, "elapsed_time_micros": 0,
            "plan_mode": "eager", "pinned_segments": [],
        }

    pending: dict = {}
    dependents: dict = {name: [] for name in (op.name for op in mine)}
    for op in mine:
        if op.kind == "Receive":
            # a Receive's inputs live on the sender's host; the value
            # arrives through the rendezvous store, not the local env
            local = []
        else:
            local = [i for i in op.inputs if i in dependents]
        pending[op.name] = len(local)
        for i in local:
            dependents[i].append(op.name)
    by_name = {op.name: op for op in mine}

    from concurrent.futures import ThreadPoolExecutor

    lock = threading.Lock()
    done = threading.Event()
    remaining = [len(mine)]
    failure: list = []  # [exception] — first error wins

    def fail(exc: BaseException) -> None:
        with lock:
            if not failure:
                failure.append(exc)
        local_abort.set()
        done.set()

    n_compute = max_workers or _pool_size()
    pool = ThreadPoolExecutor(
        max_workers=n_compute,
        thread_name_prefix=f"moose-{identity}",
    )

    def finish(name: str, ready_sink: Callable[[object], None]) -> None:
        progress.bump()
        newly_ready = []
        with lock:
            remaining[0] -= 1
            if remaining[0] == 0:
                done.set()
            for dep in dependents[name]:
                pending[dep] -= 1
                if pending[dep] == 0:
                    newly_ready.append(by_name[dep])
        for op in newly_ready:
            ready_sink(op)

    # Receives: one POLLER thread probes every outstanding rendezvous via
    # the transport's non-blocking try_receive — thousands of receives
    # cost one thread, not one each (deadlock-free: receives never hold
    # compute slots, and the poller itself never blocks on any single
    # key).  Transports without try_receive (raw TCP) fall back to a
    # waiter thread per receive.
    pollable = hasattr(networking, "try_receive")
    recv_lock = threading.Lock()
    outstanding: dict = {}  # op name -> op, receives awaiting payload

    def poll_receives() -> None:
        get_act = getattr(networking, "activity_for", None)
        activity = get_act(session_id) if get_act is not None else None
        while not abort_any.is_set():
            if activity is not None:
                activity.clear()
            with recv_lock:
                items = list(outstanding.items())
            if not items:
                if done.is_set():
                    return
            arrived = []
            for name, op in items:
                try:
                    ok, val = networking.try_receive(
                        op.attributes["sender"],
                        op.attributes["rendezvous_key"],
                        session_id,
                        plc=identity,
                    )
                except BaseException as e:  # noqa: BLE001 — root cause
                    fail(e)
                    return
                if ok:
                    env[name] = val
                    with recv_lock:
                        outstanding.pop(name, None)
                    arrived.append(name)
            for name in arrived:
                finish(name, dispatch)
            if items and not arrived and (
                time.monotonic() > progress.last + timeout
            ):
                from ..errors import ReceiveTimeoutError

                keys = sorted(
                    op.attributes["rendezvous_key"] for _, op in items
                )[:4]
                fail(ReceiveTimeoutError(
                    f"receive timed out after {timeout}s of no session "
                    f"progress; {len(items)} pending (first keys "
                    f"{keys})"
                ))
                return
            if activity is not None:
                activity.wait(0.1)
            else:
                time.sleep(0.005)

    def dispatch(op) -> None:
        if abort_any.is_set():
            return  # the main wait loop polls the abort, not `done`
        if op.kind == "Receive":
            if pollable:
                with recv_lock:
                    outstanding[op.name] = op
                get_act = getattr(networking, "activity_for", None)
                if get_act is not None:
                    get_act(session_id).set()  # wake poller: new key
            else:
                # dedicated waiter thread: blocked receives must never
                # occupy compute slots (deadlock-freedom invariant)
                threading.Thread(
                    target=run_op, args=(op,), daemon=True,
                    name=f"moose-{identity}-recv-{op.name}",
                ).start()
        else:
            try:
                pool.submit(run_op, op)
            except RuntimeError:
                # raced an abort-triggered pool shutdown; the abort
                # outcome is already decided, just stop feeding it
                if not abort_any.is_set():
                    raise

    def run_op(op) -> None:
        try:
            env[op.name] = exec_one(op)
        except BaseException as e:  # noqa: BLE001 — root cause capture
            fail(e)
            return
        finish(op.name, dispatch)

    initial = [op for op in mine if pending[op.name] == 0]
    has_receives = any(op.kind == "Receive" for op in mine)
    poller = None
    # the eager scheduler's root span: adopts the session's propagated
    # TraceContext (installed by the worker server around this call) so
    # even the legacy path stitches into the client's distributed trace
    from .. import telemetry

    with telemetry.span(
        "execute_role", party=identity, ops=len(mine), plan_mode="eager",
    ):
        try:
            for op in initial:
                dispatch(op)
            if pollable and has_receives:
                poller = threading.Thread(
                    target=poll_receives, daemon=True,
                    name=f"moose-{identity}-recv-poller",
                )
                poller.start()
            # `done` fires on completion or local failure; an external
            # abort (choreographer / peer fanout) only sets its event, so
            # poll it — in-flight receives unwind via their own sliced
            # waits
            while not done.wait(0.1):
                if abort_any.is_set():
                    break
        finally:
            pool.shutdown(wait=False, cancel_futures=True)

    if failure:
        exc = failure[0]
        if cancel is not None and cancel.is_set() and not isinstance(
            exc, SessionAbortedError
        ):
            # the external abort raced our own error path: report it as
            # an abort so the caller doesn't re-fan-out
            raise SessionAbortedError(
                f"session {session_id} aborted"
            ) from exc
        raise exc
    if cancel is not None and cancel.is_set():
        raise SessionAbortedError(f"session {session_id} aborted")

    elapsed = int((time.perf_counter() - t0) * 1e6)
    return {
        "outputs": outputs, "elapsed_time_micros": elapsed,
        "plan_mode": "eager", "pinned_segments": [],
    }
