"""Role-filtered execution of a lowered computation on one worker.

The distributed counterpart of the local physical executor: each worker
walks the same global toposorted host-level graph but executes only the
operations pinned to its own identity, exactly as the reference's
AsyncExecutor role filter (execution/asynchronous.rs:590-605,
execution/context.rs:60-74); Send/Receive ops hit the networking backend.

Deadlock freedom: workers follow the global topological order (which
includes Send->Receive rendezvous edges), sends are non-blocking and
receives block on the cell store — for any blocked receive, the matching
send is strictly earlier in the global order, so by induction over that
order some worker can always make progress.
"""

from __future__ import annotations

import secrets
import time
from typing import Optional

import numpy as np

from ..computation import Computation, HostPlacement
from ..errors import KernelError, MissingArgumentError, StorageError
from ..execution.physical import execute_kernel
from ..execution.session import EagerSession
from ..values import HostPrfKey, HostString, HostUnit


def execute_role(
    comp: Computation,
    identity: str,
    storage: dict,
    arguments: Optional[dict],
    networking,
    session_id: str,
    timeout: float = 120.0,
    cancel=None,
) -> dict:
    """Execute ``identity``'s share of a lowered computation; returns
    {"outputs": {...}, "elapsed_time_micros": int}.

    ``cancel``: optional ``threading.Event`` — checked between ops and
    inside blocked receives (sliced waits) so an AbortComputation can
    actually stop a running session (the reference leaves its abort
    handler unimplemented, choreography/grpc.rs:200-205).
    """
    import jax.numpy as jnp

    from ..execution.interpreter import _lift_array, _to_user_value

    # genuinely-distributed parties must not derive share masks from the
    # non-cryptographic default PRF (ADVICE r1; the client runtime guards
    # too, but workers execute whatever arrives)
    from ..dialects.ring import require_strong_prf

    require_strong_prf("distributed worker")

    t0 = time.perf_counter()
    arguments = arguments or {}
    composite = [
        plc.name for plc in comp.placements.values()
        if not isinstance(plc, HostPlacement)
    ]
    if composite:
        # a logical graph would silently skip every replicated op (no
        # worker owns the composite placement) and fail later with an
        # opaque missing-operand error
        raise KernelError(
            "worker received an uncompiled computation (composite "
            f"placements {composite}); compile it first — e.g. "
            "`elk compile --passes typing,lowering,prune,networking,"
            "toposort`"
        )
    for op in comp.operations.values():
        plc_name = comp.placement_of(op).name
        for inp in op.inputs:
            src = comp.operations[inp]
            if (
                comp.placement_of(src).name != plc_name
                and op.kind != "Receive"
            ):
                # cross-host edge with no Send/Receive stitched in — the
                # networking pass was skipped
                raise KernelError(
                    f"op {op.name} on {plc_name} reads {inp} from "
                    f"{comp.placement_of(src).name} without a "
                    "Send/Receive pair; run the `networking` compiler "
                    "pass before deploying"
                )
    sess = EagerSession(session_id=session_id)
    env: dict = {}
    outputs: dict = {}

    for name in comp.toposort_names():
        if cancel is not None and cancel.is_set():
            raise KernelError(f"session {session_id} aborted")
        op = comp.operations[name]
        plc = comp.placement_of(op)
        if plc.name != identity:
            continue
        kind = op.kind
        if kind == "Send":
            networking.send(
                env[op.inputs[0]],
                op.attributes["receiver"],
                op.attributes["rendezvous_key"],
                session_id,
            )
            env[name] = HostUnit(identity)
            continue
        if kind == "Receive":
            env[name] = networking.receive(
                op.attributes["sender"],
                op.attributes["rendezvous_key"],
                session_id,
                plc=identity,
                timeout=timeout,
                cancel=cancel,
            )
            continue
        if kind == "PrfKeyGen":
            # each party generates its own key from local entropy — this
            # is where the distributed deployment gets real inter-party
            # security, unlike the single-trust-domain local runtime
            words = np.frombuffer(secrets.token_bytes(16), dtype=np.uint32)
            env[name] = HostPrfKey(jnp.asarray(words), identity)
            continue
        if kind == "Input":
            val = arguments.get(name)
            if val is None:
                raise MissingArgumentError(
                    f"missing argument {name!r} on {identity}"
                )
            if isinstance(val, str):
                env[name] = HostString(val, identity)
            else:
                env[name] = _lift_array(np.asarray(val), op, identity)
            continue
        if kind == "Load":
            key_val = env[op.inputs[0]]
            key = (
                key_val.value
                if isinstance(key_val, HostString)
                else str(key_val)
            )
            query = ""
            if len(op.inputs) > 1:
                q = env[op.inputs[1]]
                query = q.value if isinstance(q, HostString) else str(q)
            if key not in storage:
                raise StorageError(
                    f"no value for key {key!r} in storage of {identity!r}"
                )
            if hasattr(storage, "load"):
                raw = storage.load(key, query)
            else:
                raw = storage[key]
            env[name] = _lift_array(np.asarray(raw), op, identity)
            continue
        if kind == "Save":
            key = env[op.inputs[0]]
            if not isinstance(key, HostString):
                raise KernelError(
                    f"Save {name}: key must be a string, found "
                    f"{type(key).__name__}"
                )
            storage[key.value] = _to_user_value(env[op.inputs[1]])
            env[name] = HostUnit(identity)
            continue
        if kind == "Output":
            value = env[op.inputs[0]]
            env[name] = value
            outputs[name] = _to_user_value(value)
            continue
        args = [env[i] for i in op.inputs]
        env[name] = execute_kernel(sess, op, identity, args)

    elapsed = int((time.perf_counter() - t0) * 1e6)
    return {"outputs": outputs, "elapsed_time_micros": elapsed}
