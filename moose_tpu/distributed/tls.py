"""mTLS identity for the distributed runtime.

Reference: gRPC transports optionally run under mutual TLS, with the
*certificate common name as the party identity* — senders are verified
against the peer X.509 CN (``networking/grpc.rs:150-160``,
``grpc.rs:1-30``) and the choreographer is authorized by CN
(``choreography/grpc.rs:64-94``); certificates are loaded from PEM files
(``reindeer.rs:40-78``).

TPU-native build: same discipline on ``grpc``'s Python credentials API.
A :class:`TlsConfig` holds the local identity's cert/key plus the CA that
signs every party; servers require client auth, and channels override the
TLS target name with the receiver's identity so certificates bind to
*party names*, not network addresses.  Party certificates need the
identity both as CN (checked server-side for sender/choreographer authz)
and as a subjectAltName DNS entry (modern gRPC/BoringSSL matches the
target-name override against the SAN, not the CN).
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Optional


@dataclasses.dataclass
class TlsConfig:
    """PEM material for one identity (reference reindeer.rs:40-78)."""

    certificate_chain: bytes
    private_key: bytes
    root_ca: bytes

    @classmethod
    def from_files(cls, cert: str, key: str, ca: str) -> "TlsConfig":
        return cls(
            certificate_chain=Path(cert).read_bytes(),
            private_key=Path(key).read_bytes(),
            root_ca=Path(ca).read_bytes(),
        )

    def server_credentials(self):
        import grpc

        return grpc.ssl_server_credentials(
            [(self.private_key, self.certificate_chain)],
            root_certificates=self.root_ca,
            require_client_auth=True,
        )

    def channel_credentials(self):
        import grpc

        return grpc.ssl_channel_credentials(
            root_certificates=self.root_ca,
            private_key=self.private_key,
            certificate_chain=self.certificate_chain,
        )

    def secure_channel(self, endpoint: str, expected_identity: str):
        """Channel to ``endpoint`` whose server must present a certificate
        for ``expected_identity`` (CN = party name, not hostname)."""
        import grpc

        from .networking import GRPC_MESSAGE_OPTIONS

        return grpc.secure_channel(
            endpoint,
            self.channel_credentials(),
            options=(
                ("grpc.ssl_target_name_override", expected_identity),
            ) + GRPC_MESSAGE_OPTIONS,
        )


def tls_config_from_flags(cert: Optional[str], key: Optional[str],
                          ca: Optional[str]) -> Optional["TlsConfig"]:
    """Build a TlsConfig from CLI flags: all three or none.

    Returns None when no flag is given; raises ValueError on a partial
    triple or an unreadable file (shared by the comet and cometctl
    CLIs, whose handlers turn ValueError into a one-line usage error)."""
    if not (cert or key or ca):
        return None
    if not (cert and key and ca):
        raise ValueError(
            "--tls-cert, --tls-key and --tls-ca must be given together"
        )
    try:
        return TlsConfig.from_files(cert, key, ca)
    except OSError as e:
        raise ValueError(f"cannot read TLS material: {e}") from e


def reject(context, message: str) -> None:
    """Refuse an RPC with PERMISSION_DENIED so clients can distinguish
    permanent authorization failures from transient transport errors
    structurally (by status code, not message text)."""
    if context is not None and hasattr(context, "abort"):
        import grpc

        context.abort(grpc.StatusCode.PERMISSION_DENIED, message)
    from ..errors import NetworkingError

    raise NetworkingError(message)


def peer_common_name(context) -> Optional[str]:
    """The peer certificate's CN, or None on a non-TLS connection
    (reference grpc.rs:1-30 extracts the CN from the peer X.509)."""
    try:
        auth = context.auth_context()
    except Exception:  # pragma: no cover - non-grpc test contexts
        return None
    values = auth.get("x509_common_name") if auth else None
    if not values:
        return None
    name = values[0]
    return name.decode() if isinstance(name, bytes) else str(name)
