"""Distributed execution: networking backends, role-filtered workers,
choreography, and the client runtime (reference ``moose/src/networking``,
``moose/src/choreography``, ``moose/src/execution/grpc.rs``)."""
