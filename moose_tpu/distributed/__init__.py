"""Distributed execution: networking backends, role-filtered workers,
choreography, the client session supervisor, and the deterministic
chaos layer (reference ``moose/src/networking``,
``moose/src/choreography``, ``moose/src/execution/grpc.rs``)."""
