"""Distributed execution: networking backends, role-filtered workers,
choreography, the client session supervisor, the fabric transport
(parties as mesh slices exchanging values via collective permutes), and
the deterministic chaos layer (reference ``moose/src/networking``,
``moose/src/choreography``, ``moose/src/execution/grpc.rs``)."""

from typing import Any

__all__ = ["FabricDomain", "FabricNetworking"]


def __getattr__(name: str) -> Any:
    # lazy re-export: importing the package must not drag jax in before
    # the caller has set XLA_FLAGS / JAX_PLATFORMS for virtual devices
    if name in __all__:
        from . import fabric

        return getattr(fabric, name)
    raise AttributeError(name)
