"""Deterministic chaos layer for the distributed runtime.

Every failure mode the choreography layer defends against — dropped
sends, delayed sends, duplicate delivery, failed pings, a worker dying
mid-session — becomes injectable ON DEMAND and REPRODUCIBLY: each fault
decision is a pure function of ``(seed, fault kind, stable key, attempt
count)`` via blake2b, so the same seed replays the identical fault
schedule in-process (tier-1 tests over LocalNetworking), cross-process
(comet daemons reading ``MOOSE_TPU_CHAOS``), and across reruns (the CI
determinism job).  Nothing here consults wall-clock randomness.

Env format (mirrors ``MOOSE_TPU_SELFCHECK_FAULT`` for the jit ladder)::

    MOOSE_TPU_CHAOS=seed:17,drop_send:0.2,delay_ms:5,dup_send:0.1,\
fail_ping:0.3,kill_after_ops:40,party:carole

- ``seed`` (int): the schedule key; required for any fault to fire.
- ``drop_send`` (probability): a *first-attempt* send of a rendezvous
  key is swallowed — the receiver never sees it and times out.  Client
  resubmissions reuse the same rendezvous keys under a new session id,
  advance the per-key attempt count, and pass — so the supervisor's
  retry path is exercised end to end and still converges.
- ``delay_ms`` (float): every send sleeps this long first (reordering /
  slow-network pressure).
- ``dup_send`` (probability): a send is delivered twice — exercising the
  cell store's duplicate-delivery idempotency.
- ``fail_ping`` (probability): a failure-detector ping raises —
  exercising the miss-point budget without a dead peer.
- ``kill_after_ops`` (int): after this many networking operations the
  party "dies": its gRPC server stops answering (peers see UNAVAILABLE
  and the detector trips) and every further transport op — including
  its own abort fanout, exactly like a SIGKILL — raises.
- ``party`` (name): scope all faults to one identity; unscoped chaos
  applies everywhere (each identity keeps its own op counter).
- ``max_kills`` (int, default 1): lifetime cap on how many times one
  identity dies.  The drop/dup schedules are self-healing across
  sessions (decisions key on STABLE rendezvous keys with per-key
  attempt counts, so an epoch resume under the same seed does not
  re-trip the identical drop), but the kill op-budget is not — a
  revived worker would die again at the same op count forever.  With
  the cap, a restarted WorkerServer (``revive``) runs clean once the
  budget is spent, so multi-session drivers (training epoch resume)
  converge.  ``max_kills:0`` disables kills entirely.

Transports are wrapped, not modified: :meth:`ChaosConfig.wrap` returns
a :class:`ChaosNetworking` proxy composing over Local/Tcp/Grpc
networking, so the same schedule runs over any wire.
"""

from __future__ import annotations

import hashlib
import threading
import time
import weakref
from typing import Optional

from ..errors import ConfigurationError, NetworkingError

# live configs, for fault-report aggregation (client.last_session_report
# collects in-process fault logs); weak so dead clusters don't pile up
_ACTIVE: "weakref.WeakSet" = weakref.WeakSet()


def active_configs() -> list:
    return list(_ACTIVE)


def _observe_fault(kind: str, detail: dict,
                   session: Optional[str] = None) -> None:
    """Mirror an injected fault onto the metrics registry and the
    flight recorder, so chaos activity is visible on /metrics and in
    postmortem session reports — not only in the in-process fault log.
    ``session`` stamps the flight event only: the fault log feeds the
    cross-run determinism digest and session ids are random per run."""
    from .. import flight, metrics

    metrics.counter(
        "moose_tpu_chaos_injections_total",
        "deterministic chaos faults injected, by kind",
        ("kind",),
    ).inc(kind=kind)
    flight.record(
        f"chaos_{kind}", party=detail.get("party"), session=session,
        **{k: v for k, v in detail.items() if k != "party"},
    )


class ChaosConfig:
    """One deterministic fault schedule, shared by every party of an
    in-process cluster (each party wraps its transport via
    :meth:`wrap`; cross-process deployments parse the same env string
    per worker and stay aligned because decisions never depend on
    process-local state)."""

    def __init__(self, seed: int = 0, drop_send: float = 0.0,
                 delay_ms: float = 0.0, dup_send: float = 0.0,
                 fail_ping: float = 0.0,
                 kill_after_ops: Optional[int] = None,
                 party: Optional[str] = None,
                 max_kills: Optional[int] = 1):
        self.seed = int(seed)
        self.drop_send = float(drop_send)
        self.delay_ms = float(delay_ms)
        self.dup_send = float(dup_send)
        self.fail_ping = float(fail_ping)
        self.kill_after_ops = (
            None if kill_after_ops is None else int(kill_after_ops)
        )
        self.party = party
        # cap on how many times ONE identity dies across the config's
        # lifetime (None = unlimited).  Multi-session drivers (training
        # epochs) need this: drop/dup schedules self-heal across
        # sessions because they key on STABLE rendezvous keys with
        # attempt counts, but the kill op-budget would otherwise
        # re-trip on every revived worker forever and the supervisor
        # could never converge.  Default 1 = the classic
        # kill-once-stay-dead schedule until a revive.
        self.max_kills = None if max_kills is None else int(max_kills)
        self._lock = threading.Lock()
        # per-rendezvous-key send attempts: retries under a fresh
        # session id land on count 1, 2, ... (session ids are random,
        # so schedules must key on the STABLE rendezvous key instead)
        self._send_count: dict = {}
        self._ping_count: dict = {}
        self._ops: dict = {}  # identity -> networking op count
        self._killed: set = set()  # identities past their kill budget
        self._kill_counts: dict = {}  # identity -> lifetime kill count
        self._kill_hooks: dict = {}  # identity -> callable
        self.faults: list = []  # injected-fault log, in schedule order
        _ACTIVE.add(self)

    # -- parsing -------------------------------------------------------

    @classmethod
    def from_env(cls, value: Optional[str] = None) -> Optional[
            "ChaosConfig"]:
        """Parse ``MOOSE_TPU_CHAOS`` (or an explicit spec string);
        None/empty disables chaos."""
        import os

        if value is None:
            value = os.environ.get("MOOSE_TPU_CHAOS", "")
        value = (value or "").strip()
        if not value:
            return None
        kwargs: dict = {}
        for part in value.split(","):
            if not part.strip():
                continue
            key, sep, raw = part.partition(":")
            key, raw = key.strip(), raw.strip()
            if not sep or not raw:
                raise ConfigurationError(
                    f"MOOSE_TPU_CHAOS entry {part!r}: expected key:value"
                )
            try:
                if key == "seed":
                    kwargs["seed"] = int(raw)
                elif key in ("drop_send", "dup_send", "fail_ping"):
                    p = float(raw)
                    if not 0.0 <= p <= 1.0:
                        raise ValueError(p)
                    kwargs[key] = p
                elif key == "delay_ms":
                    kwargs["delay_ms"] = float(raw)
                elif key == "kill_after_ops":
                    kwargs["kill_after_ops"] = int(raw)
                elif key == "max_kills":
                    kwargs["max_kills"] = int(raw)
                elif key == "party":
                    kwargs["party"] = raw
                else:
                    raise ConfigurationError(
                        f"MOOSE_TPU_CHAOS: unknown knob {key!r}"
                    )
            except (TypeError, ValueError) as e:
                raise ConfigurationError(
                    f"MOOSE_TPU_CHAOS entry {part!r}: bad value"
                ) from e
        return cls(**kwargs)

    # -- deterministic decisions ---------------------------------------

    def _fraction(self, *key_parts) -> float:
        """Uniform [0, 1) fraction, a pure function of (seed, parts)."""
        material = "|".join(str(p) for p in (self.seed,) + key_parts)
        digest = hashlib.blake2b(
            material.encode(), digest_size=8
        ).digest()
        return int.from_bytes(digest, "big") / 2.0 ** 64

    def _applies(self, identity: str) -> bool:
        return self.party is None or self.party == identity

    def _record(self, kind: str, _session: Optional[str] = None,
                **detail) -> None:
        with self._lock:
            self.faults.append({"kind": kind, **detail})
        _observe_fault(kind, detail, session=_session)

    def schedule_digest(self, kinds=None) -> str:
        """Stable digest of the injected-fault log — two runs of the
        same seed over the same computation must agree (the CI
        determinism check compares these).  ``kinds`` restricts the
        digest to fault kinds whose OCCURRENCE COUNT is itself
        deterministic (drop/dup/kill); fail_ping entries scale with how
        many detector rounds ran before the session died, which is
        timing, not schedule."""
        with self._lock:
            entries = [
                sorted(f.items()) for f in self.faults
                if kinds is None or f.get("kind") in kinds
            ]
        # order across concurrent parties is scheduling noise; the
        # SCHEDULE is the set of (kind, key, ...) decisions
        material = repr(sorted(map(repr, entries)))
        return hashlib.blake2b(
            material.encode(), digest_size=16
        ).hexdigest()

    # -- kill plumbing -------------------------------------------------

    def register_kill_hook(self, identity: str, hook) -> None:
        """``hook()`` runs once, when ``identity`` exceeds its op
        budget (the WorkerServer registers a stop-serving callback so
        peers observe a dead endpoint, not a graceful shutdown)."""
        self._kill_hooks[identity] = hook

    def _count_op(self, identity: str,
                  session: Optional[str] = None) -> None:
        if self.kill_after_ops is None or not self._applies(identity):
            return
        fire = False
        with self._lock:
            if identity in self._killed:
                raise NetworkingError(
                    f"chaos: {identity!r} killed (op budget exhausted)"
                )
            if (
                self.max_kills is not None
                and self._kill_counts.get(identity, 0) >= self.max_kills
            ):
                # kill budget for this identity is spent: a revived
                # worker runs clean from here on, so a multi-session
                # driver (epoch resume) converges instead of dying at
                # the same op count forever
                return
            n = self._ops.get(identity, 0) + 1
            self._ops[identity] = n
            if n > self.kill_after_ops:
                self._killed.add(identity)
                self._kill_counts[identity] = (
                    self._kill_counts.get(identity, 0) + 1
                )
                self.faults.append({
                    "kind": "kill", "party": identity, "after_ops": n - 1,
                })
                fire = True
        if fire:
            _observe_fault(
                "kill", {"party": identity, "after_ops": n - 1},
                session=session,
            )
            hook = self._kill_hooks.get(identity)
            if hook is not None:
                hook()
            raise NetworkingError(
                f"chaos: {identity!r} killed (op budget exhausted)"
            )

    def revive(self, identity: str) -> None:
        """A restarted worker is alive again: clear the killed latch
        and the op counter (the kill-count survives, so ``max_kills``
        bounds how often the schedule can strike).  WorkerServer.start
        calls this — an in-process 'process restart' shares the config
        object, and without the revive every transport op of the
        restarted identity would keep raising forever."""
        with self._lock:
            self._killed.discard(identity)
            self._ops.pop(identity, None)

    def check_alive(self, identity: str) -> None:
        with self._lock:
            if identity in self._killed:
                raise NetworkingError(
                    f"chaos: {identity!r} killed (op budget exhausted)"
                )

    # -- transport wrapper ---------------------------------------------

    def wrap(self, networking, identity: str):
        return ChaosNetworking(networking, identity, self)


class ChaosNetworking:
    """Transport proxy injecting the configured faults for one
    identity.  Everything not intercepted (cells, verify_sender,
    handle_send_value, activity_for, start/stop, ...) delegates to the
    wrapped transport unchanged, so the proxy composes over
    Local/Tcp/Grpc networking alike."""

    def __init__(self, inner, identity: str, config: ChaosConfig):
        self._inner = inner
        self._identity = identity
        self._config = config

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def send(self, value, receiver: str, rendezvous_key: str,
             session_id: str, **kwargs):
        cfg = self._config
        cfg._count_op(self._identity, session=session_id)
        if not cfg._applies(self._identity):
            return self._inner.send(
                value, receiver, rendezvous_key, session_id, **kwargs
            )
        with cfg._lock:
            count = cfg._send_count.get(rendezvous_key, 0)
            cfg._send_count[rendezvous_key] = count + 1
        if cfg.delay_ms > 0:
            cfg._record(
                "delay", _session=session_id, key=rendezvous_key,
                ms=cfg.delay_ms, party=self._identity,
            )
            time.sleep(cfg.delay_ms / 1000.0)
        # only FIRST attempts drop: a supervisor resubmission reuses
        # the rendezvous key at count >= 1 and must go through, so a
        # finite schedule cannot starve the retry path
        if (
            count == 0
            and cfg.drop_send > 0
            and cfg._fraction("drop_send", rendezvous_key) < cfg.drop_send
        ):
            cfg._record(
                "drop_send", _session=session_id, key=rendezvous_key,
                party=self._identity,
            )
            # fabric transports: the dropped key's REPLAY must not
            # re-enter a collective whose payload was already lost —
            # latch it onto the wire path (stable key, so the latch
            # survives the supervisor's fresh session id).  The fault
            # record itself gains no transport field: a chaos seed's
            # schedule digest is identical with the fabric on or off.
            force_wire = getattr(self._inner, "force_wire", None)
            if force_wire is not None:
                force_wire(rendezvous_key)
            return None  # swallowed: the receiver never hears of it
        result = self._inner.send(
            value, receiver, rendezvous_key, session_id, **kwargs
        )
        if (
            cfg.dup_send > 0
            and cfg._fraction("dup_send", rendezvous_key, count)
            < cfg.dup_send
        ):
            cfg._record(
                "dup_send", _session=session_id, key=rendezvous_key,
                party=self._identity,
            )
            self._inner.send(
                value, receiver, rendezvous_key, session_id, **kwargs
            )
        return result

    def send_many(self, items, receiver: str, session_id: str):
        """Decompose a coalesced envelope into per-key sends: every
        fault decision keys on the STABLE rendezvous key and attempt
        count, so a seed's schedule is identical whether the worker
        fast path batched the sends or not (the bit-exact-replay
        contract with worker jit on)."""
        for rendezvous_key, value in items:
            self.send(value, receiver, rendezvous_key, session_id)

    def receive(self, *args, **kwargs):
        self._config.check_alive(self._identity)
        return self._inner.receive(*args, **kwargs)

    def try_receive(self, *args, **kwargs):
        # polled every ~100ms per outstanding key: checked for kill but
        # NOT counted toward the op budget (poll cadence is timing
        # noise; counting it would make the kill point nondeterministic)
        self._config.check_alive(self._identity)
        return self._inner.try_receive(*args, **kwargs)

    def ping(self, receiver: str, **kwargs):
        cfg = self._config
        cfg.check_alive(self._identity)
        if cfg._applies(self._identity) and cfg.fail_ping > 0:
            with cfg._lock:
                count = cfg._ping_count.get(receiver, 0)
                cfg._ping_count[receiver] = count + 1
            if cfg._fraction("fail_ping", receiver, count) < cfg.fail_ping:
                cfg._record(
                    "fail_ping", _session=kwargs.get("session_id"),
                    peer=receiver, party=self._identity, count=count,
                )
                raise NetworkingError(
                    f"chaos: ping to {receiver!r} failed"
                )
        return self._inner.ping(receiver, **kwargs)

    def abort_session(self, *args, **kwargs):
        # a killed worker cannot fan its abort out — that silence is
        # precisely what the peers' failure detectors must cover
        self._config.check_alive(self._identity)
        return self._inner.abort_session(*args, **kwargs)
