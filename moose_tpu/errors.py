"""Typed error hierarchy (reference: ``moose/src/error.rs:7-59``).

The reference carries a closed ``Error`` enum through every kernel and
session; here the same taxonomy is an exception hierarchy so protocol
invariants survive ``python -O`` (a bare ``assert`` would not) and callers
can catch by failure class.
"""

from __future__ import annotations


class MooseError(Exception):
    """Base class for all moose_tpu errors (reference Error, error.rs:7)."""


class KernelError(MooseError):
    """A kernel was invoked with operands violating its contract
    (reference Error::KernelError)."""


class TypeMismatchError(MooseError, TypeError):
    """Unexpected value/dtype/ring width at a kernel or dispatch boundary
    (reference Error::TypeMismatch)."""


class CompilationError(MooseError):
    """A compiler pass failed (reference Error::Compilation)."""


class MalformedComputationError(CompilationError):
    """The computation graph violates well-formedness (reference
    Error::MalformedComputation / MalformedEnvironment).

    When raised by the static analyzer (``compilation.analysis``), the
    ``diagnostics`` attribute carries the individual
    ``Diagnostic`` findings so callers can inspect rule ids
    programmatically instead of parsing the message."""

    def __init__(self, *args, diagnostics=()):
        super().__init__(*args)
        self.diagnostics = tuple(diagnostics)


class PlanRejectedError(MalformedComputationError):
    """The static schedule analyzer (MSA5xx) proved the compiled worker
    plan would hang — raised by ``worker_plan.get_plan`` at BUILD time
    so the worker demotes to the legacy eager scheduler instead of
    blocking at runtime.  Deterministic (a property of the computation),
    hence never retryable.  Carries ``diagnostics`` like its parent."""


class MissingArgumentError(MooseError, KeyError):
    """An Input op had no bound argument at evaluation time."""


class NetworkingError(MooseError):
    """Transport-level send/receive failure (reference Error::Networking)."""


class ReceiveTimeoutError(NetworkingError, TimeoutError):
    """A blocking receive expired without its payload arriving.  A
    DISTINCT class so transports can retry/poll on timeouts without
    string-matching error messages (which silently breaks when wording
    changes)."""


class AuthorizationError(NetworkingError):
    """A peer rejected the request on identity grounds (mTLS CN
    mismatch, unauthorized choreographer — gRPC PERMISSION_DENIED).
    Permanent: resubmitting the same credentials can never succeed, so
    the session supervisor must NOT retry it."""


class PeerUnreachableError(NetworkingError):
    """The failure detector tripped: a session peer stopped answering
    pings for the configured miss budget.  Retryable — the peer may be
    restarting or the partition transient."""


class StorageError(MooseError, KeyError):
    """Load/Save against a storage backend failed (reference
    Error::Storage)."""


class SessionAlreadyExistsError(MooseError):
    """A session id was launched twice on one worker (reference
    Error::SessionAlreadyExists, execution/asynchronous.rs:571-576)."""


class SessionAbortedError(MooseError):
    """A session was cancelled (choreographer abort, peer abort fanout, or
    failure-detector trip) rather than failing on its own work.  Receivers
    of this error must NOT re-fan-out an abort: the initiator already did
    (reference root-cause discipline, execution/asynchronous.rs:27-74)."""


class UnimplementedError(MooseError, NotImplementedError):
    """Operator/placement combination not supported (reference
    Error::UnimplementedOperator)."""


class ConfigurationError(MooseError, ValueError):
    """Invalid runtime/session configuration."""


class ReplicaDrainingError(MooseError):
    """The serving replica is draining (graceful shutdown in progress)
    or shut down before the request was served: admission is closed and
    queued requests are completed with this error instead of being
    evaluated.  RETRYABLE by the taxonomy — the request was never
    executed, so resubmitting it to ANOTHER replica (the ``donner``
    router does this automatically) succeeds without double-evaluation
    risk.  Surfaces over HTTP as ``503`` with a ``Retry-After``
    header."""


class CheckpointError(StorageError):
    """A secret-shared training checkpoint was rejected: torn commit,
    checksum/tamper mismatch, stale or missing generation, format or
    fixed-keys discipline mismatch.  NON-retryable — replaying the same
    session against the same bad checkpoint deterministically fails;
    the training supervisor instead falls back to the previous valid
    generation (or surfaces the error when none exists)."""


class SnapshotError(MooseError):
    """A warm-state snapshot could not be written, or an on-disk
    snapshot failed validation at load time (format-version skew,
    checksum mismatch, model-set mismatch, or a bit-exactness probe
    divergence under ``MOOSE_TPU_FIXED_KEYS``).  Loaders treat this as
    "no snapshot": the replica falls back to a fresh registration
    instead of serving from suspect state."""


class ServerOverloadedError(MooseError):
    """The serving layer's bounded request queue is full (admission
    control, ``moose_tpu/serving``): the request was REJECTED, not
    queued.  Raised synchronously at submit time so callers shed load
    instead of hanging; retryable by the taxonomy — backing off and
    resubmitting can succeed once the queue drains."""


class DeadlineExceededError(MooseError, TimeoutError):
    """A serving request's deadline expired before its result was
    produced.  Requests already expired when their batch is assembled
    are dropped WITHOUT being evaluated (an expired request never
    occupies batch rows); requests that expire mid-evaluation surface
    this error after the fact and count as a deadline miss in serving
    telemetry."""


# ---------------------------------------------------------------------------
# Typed wire errors: structured envelopes for the distributed runtime.
#
# The reference stringifies errors at the session boundary (its abort
# handler is unimplemented!(), choreography/grpc.rs:200); here a failure
# crosses the wire as a small msgpack-able dict so the CLIENT re-raises
# the real typed exception and the session supervisor can tell transient
# faults (resubmit) from permanent ones (surface immediately).
# ---------------------------------------------------------------------------

# Classes whose failures can be healed by resubmitting the computation
# under a fresh session id: transport faults, receive timeouts, detector
# trips, and adopted aborts whose root cause never reached us.  Anything
# authorization-shaped is excluded — same credentials, same rejection.
_PERMANENT_NETWORKING = (AuthorizationError,)


def is_retryable(exc: BaseException) -> bool:
    """True when resubmitting the same (computation, arguments) under a
    fresh session id can plausibly succeed.  Sessions are pure functions
    of their inputs and replay protection drops stale traffic for old
    ids, so the supervisor may replay any *transient* failure; compile
    and type errors (and PERMISSION_DENIED) are deterministic and must
    surface immediately."""
    if isinstance(exc, _PERMANENT_NETWORKING):
        return False
    return isinstance(
        exc,
        (
            NetworkingError,
            SessionAbortedError,
            ServerOverloadedError,
            ReplicaDrainingError,
        ),
    )


def _class_registry() -> dict:
    return {
        cls.__name__: cls
        for cls in list(globals().values())
        if isinstance(cls, type) and issubclass(cls, MooseError)
    }


def _cause_chain(exc: BaseException, limit: int = 8) -> list:
    """[{class, message}] for the __cause__/__context__ chain below
    ``exc`` (nearest first), bounded so a pathological chain cannot
    bloat the wire frame."""
    chain = []
    seen = {id(exc)}
    cur = exc.__cause__ or exc.__context__
    while cur is not None and len(chain) < limit and id(cur) not in seen:
        seen.add(id(cur))
        chain.append({
            "class": type(cur).__name__,
            "message": str(cur),
        })
        cur = cur.__cause__ or cur.__context__
    return chain


def to_wire(exc: BaseException, party: str = "") -> dict:
    """Encode an exception as a wire envelope: error class, originating
    party, root-cause chain, and the retryable bit derived from the
    taxonomy.  msgpack-able (strings/bools only)."""
    return {
        "class": type(exc).__name__,
        "message": str(exc),
        "party": party,
        "retryable": bool(is_retryable(exc)),
        "chain": _cause_chain(exc),
    }


def from_wire(envelope: dict) -> MooseError:
    """Decode an envelope back into a typed exception.  The class is
    resolved by name against this module's taxonomy; a class the local
    build does not know (version skew, non-Moose root cause) degrades to
    :class:`NetworkingError` with the original name preserved in the
    message.  The instance carries ``party`` / ``retryable`` /
    ``wire_chain`` attributes for programmatic inspection."""
    name = envelope.get("class", "NetworkingError")
    cls = _class_registry().get(name)
    message = envelope.get("message", "")
    party = envelope.get("party", "")
    if cls is None:
        message = f"{name}: {message}"
        cls = NetworkingError
    if party:
        message = f"{message} (party {party})"
    exc = cls(message)
    exc.party = party
    # trust the wire bit over local re-derivation: the ORIGINATOR'S
    # taxonomy classified the live exception (a degraded unknown class
    # would otherwise flip permanent -> retryable)
    exc.retryable = bool(envelope.get("retryable", False))
    exc.wire_chain = tuple(
        (c.get("class", ""), c.get("message", ""))
        for c in envelope.get("chain") or ()
    )
    return exc
