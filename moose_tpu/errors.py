"""Typed error hierarchy (reference: ``moose/src/error.rs:7-59``).

The reference carries a closed ``Error`` enum through every kernel and
session; here the same taxonomy is an exception hierarchy so protocol
invariants survive ``python -O`` (a bare ``assert`` would not) and callers
can catch by failure class.
"""

from __future__ import annotations


class MooseError(Exception):
    """Base class for all moose_tpu errors (reference Error, error.rs:7)."""


class KernelError(MooseError):
    """A kernel was invoked with operands violating its contract
    (reference Error::KernelError)."""


class TypeMismatchError(MooseError, TypeError):
    """Unexpected value/dtype/ring width at a kernel or dispatch boundary
    (reference Error::TypeMismatch)."""


class CompilationError(MooseError):
    """A compiler pass failed (reference Error::Compilation)."""


class MalformedComputationError(CompilationError):
    """The computation graph violates well-formedness (reference
    Error::MalformedComputation / MalformedEnvironment).

    When raised by the static analyzer (``compilation.analysis``), the
    ``diagnostics`` attribute carries the individual
    ``Diagnostic`` findings so callers can inspect rule ids
    programmatically instead of parsing the message."""

    def __init__(self, *args, diagnostics=()):
        super().__init__(*args)
        self.diagnostics = tuple(diagnostics)


class MissingArgumentError(MooseError, KeyError):
    """An Input op had no bound argument at evaluation time."""


class NetworkingError(MooseError):
    """Transport-level send/receive failure (reference Error::Networking)."""


class ReceiveTimeoutError(NetworkingError, TimeoutError):
    """A blocking receive expired without its payload arriving.  A
    DISTINCT class so transports can retry/poll on timeouts without
    string-matching error messages (which silently breaks when wording
    changes)."""


class StorageError(MooseError, KeyError):
    """Load/Save against a storage backend failed (reference
    Error::Storage)."""


class SessionAlreadyExistsError(MooseError):
    """A session id was launched twice on one worker (reference
    Error::SessionAlreadyExists, execution/asynchronous.rs:571-576)."""


class SessionAbortedError(MooseError):
    """A session was cancelled (choreographer abort, peer abort fanout, or
    failure-detector trip) rather than failing on its own work.  Receivers
    of this error must NOT re-fan-out an abort: the initiator already did
    (reference root-cause discipline, execution/asynchronous.rs:27-74)."""


class UnimplementedError(MooseError, NotImplementedError):
    """Operator/placement combination not supported (reference
    Error::UnimplementedOperator)."""


class ConfigurationError(MooseError, ValueError):
    """Invalid runtime/session configuration."""
