"""Static schedule analysis over distributed execution plans (``MSA5xx``).

The op-level communication rules (MSA2xx) check the *graph* the compiler
emitted; the distributed workers execute a *plan* derived from it —
:mod:`moose_tpu.distributed.worker_plan` reorders each role's subgraph:
input-free host ops hoist before merged compute segments, value-consuming
host ops (Send/Save/Output) defer after them, consecutive deferred sends
coalesce into one ``send_many`` flush group per receiver, and every
Receive is prefetched but *waited on* at its step position by a strictly
sequential orchestrator.  A malformed plan is a silent runtime hang, so
this module makes the plan itself machine-checkable **without
executing**:

- :func:`build_role_schedule` reconstructs one role's step schedule with
  the exact segmentation rules the worker applies (the worker's
  ``RolePlan`` builds its runtime plan from this same function, so the
  analysis can never drift from execution);
- :func:`analyze_schedule` proves deadlock-freedom of the cross-role
  segment-level wait graph under send-coalescing and receive-prefetch
  semantics — a strict generalization of MSA204, which only sees
  op-granularity dataflow edges and cannot model the sequential
  orchestrator (where a receive blocks every later step of its role,
  related by dataflow or not) or a deferred send moving past its
  original position.

Rules:

- ``MSA501`` (error): unsatisfiable wait — the fixed point of the
  segment-level wait graph leaves a Receive step that can never be
  served under single-delivery rendezvous semantics (a wait cycle
  between sequential role schedules, a key whose every Send is itself
  blocked, a key with no Send at all, or a key oversubscribed by
  several Receives).  The sequential orchestrator would hang.
- ``MSA502`` (warning): deferred-send overflow — more than
  ``MAX_DEFERRED`` value-consuming host ops queued behind one merged
  segment forces an early segment split (previously a silent fallback);
  the flush happens earlier and the segment merge is lost.
- ``MSA503`` (error): receive arrives later than first use — a step
  consumes a value whose producing step (a Receive wait, or any other
  step) comes *after* it in the role's schedule; the orchestrator would
  read an absent environment slot.
- ``MSA504`` (info): segment inputs straddle the jit/eager boundary — a
  jit-candidate segment consumes values produced by always-eager sliver
  segments (below ``MOOSE_TPU_WORKER_MIN_SEG``, or carrying
  dynamic-shape kinds), paying a host/device crossing per input per
  evaluation.
- ``MSA505`` (error): fabric-lowered schedule not provably
  deadlock-free — run only when a FabricDomain claims a session (see
  :func:`analyze_fabric_schedules`); rejection makes the fabric
  transport fall back to the wire on every edge of the computation.

On graphs with composite placements (pre-lowering) or without any
Send/Receive op (single-role / pre-networking) the analysis is a no-op,
so it is safe to run unconditionally.
"""

from __future__ import annotations

import dataclasses
import os
import weakref
from typing import (
    Any,
    Dict,
    FrozenSet,
    List,
    Optional,
    Sequence,
    Set,
    Tuple,
)

from ...computation import Computation, HostPlacement
from .diagnostics import Diagnostic, Severity

__all__ = [
    "DEFERRABLE_KINDS",
    "DYNAMIC_SHAPE_KINDS",
    "HOISTABLE_KINDS",
    "HOST_STEP_KINDS",
    "MAX_DEFERRED",
    "RoleSchedule",
    "SegmentPlan",
    "Step",
    "analyze_fabric_schedules",
    "analyze_schedule",
    "analyze_schedules",
    "build_role_schedule",
    "plan_errors",
    "reconstruct_schedules",
    "worker_min_seg",
    "worker_min_seg_decision",
]

# One plan step: ("op", op_name) — a host-boundary op the orchestrator
# resolves itself; ("seg", segment_index) — a merged compute segment;
# ("sends", (op_name, ...)) — a deferred-send flush group whose
# consecutive same-receiver payloads coalesce into send_many envelopes.
Step = Tuple[str, Any]

# Kinds the orchestrator resolves on the host side, OUTSIDE compute
# segments: I/O boundaries, communication, and entropy draws (PrfKeyGen /
# Sample must stay eager — jitting them would bake one draw into the
# compiled program and replay it forever).
HOST_STEP_KINDS = frozenset({
    "Input", "Load", "Save", "Output", "Send", "Receive", "PrfKeyGen",
    "Sample",
})

# Of those, only some actually FORCE a segment split.  A lowered
# protocol graph interleaves communication with compute every few ops —
# splitting at every host step would shatter a role into hundreds of
# tiny XLA programs (measured ~300 for one logreg role), paying compile
# and dispatch per fragment.  Instead:
#  - HOISTABLE ops have no dataflow inputs (PrfKeyGen, Input): they
#    execute BEFORE the merged segment, their values entering as
#    ordinary segment inputs;
#  - DEFERRABLE ops only consume values (Send, Save, Output): they
#    execute right AFTER the merged segment that produces their
#    operands.  A deferred Send still flushes before the next receive
#    WAIT, so the deadlock argument is untouched — the orchestrator
#    never blocks between a send's original position and its deferred
#    flush;
#  - HARD boundaries end the segment: Receive (the value arrives
#    mid-order), Load (its key is computed locally), Sample (consumes a
#    locally-computed shape, cannot hoist).
HOISTABLE_KINDS = frozenset({"PrfKeyGen", "Input"})
DEFERRABLE_KINDS = frozenset({"Send", "Save", "Output"})

# dynamic-shape kinds XLA cannot compile; segments containing one run
# eagerly and are never validated (there is no candidate to validate)
DYNAMIC_SHAPE_KINDS = frozenset({"Select"})

# bound on sends deferred behind one merged segment: merging trades
# send latency (peers wait for the whole segment) for dispatch cost, so
# cap how much latency one segment may hoard.  Exceeding it splits the
# segment early — surfaced as MSA502.
MAX_DEFERRED = 16


def worker_min_seg() -> int:
    """Segments below this many ops always run eagerly on the worker
    (not validated, not counted as pinned): a 2-op XLA program saves
    ~one dispatch but costs a compile during validation."""
    raw = os.environ.get("MOOSE_TPU_WORKER_MIN_SEG", "4")
    try:
        return max(1, int(raw))
    except ValueError as e:
        from ...errors import ConfigurationError

        raise ConfigurationError(
            f"MOOSE_TPU_WORKER_MIN_SEG must be an integer, got {raw!r}"
        ) from e


def _segment_limit() -> int:
    from ...execution.interpreter import _segment_limit as limit_fn

    return int(limit_fn())


@dataclasses.dataclass(frozen=True)
class SegmentPlan:
    """One merged compute segment of a role schedule: the op run it
    compiles, its boundary dataflow, and whether the worker would
    jit-validate it (``validatable``) or always run it eagerly."""

    index: int
    names: Tuple[str, ...]
    in_names: Tuple[str, ...]
    out_names: Tuple[str, ...]
    validatable: bool


@dataclasses.dataclass(frozen=True)
class RoleSchedule:
    """The statically-reconstructed execution plan of one role: the
    ordered step list the sequential orchestrator walks, its compute
    segments, and the step index at which every op's value
    materializes (``exec_step``)."""

    role: str
    steps: Tuple[Step, ...]
    segments: Tuple[SegmentPlan, ...]
    recv_names: Tuple[str, ...]
    # (segment index closed early, deferred-op count at the cap)
    overflows: Tuple[Tuple[int, int], ...]
    exec_step: Dict[str, int]

    def summary(self) -> Dict[str, object]:
        """Machine-readable schedule shape (prancer ``--schedule``)."""
        return {
            "role": self.role,
            "steps": len(self.steps),
            "segments": [
                {
                    "index": seg.index,
                    "ops": len(seg.names),
                    "inputs": len(seg.in_names),
                    "outputs": len(seg.out_names),
                    "validatable": seg.validatable,
                }
                for seg in self.segments
            ],
            "receives": len(self.recv_names),
            "deferred_flushes": [
                {"segment": si, "deferred": n} for si, n in self.overflows
            ],
            "send_groups": [
                list(payload) for kind, payload in self.steps
                if kind == "sends"
            ],
        }


def build_role_schedule(
    comp: Computation,
    role: str,
    order: Optional[Sequence[str]] = None,
    limit: Optional[int] = None,
    min_seg: Optional[int] = None,
    max_deferred: int = MAX_DEFERRED,
) -> RoleSchedule:
    """Reconstruct ``role``'s worker plan from the segmentation rules —
    the single source of truth shared with ``worker_plan.RolePlan``, so
    what the analyzer proves is what the worker runs.  ``order`` is the
    shared global linearization (defaults to ``comp.toposort_names()``,
    which every worker derives identically from the same bytes)."""
    from ...execution.interpreter import plan_segments

    if order is None:
        order = comp.toposort_names()
    if limit is None:
        limit = _segment_limit()
    if min_seg is None:
        min_seg = worker_min_seg()
    mine = [
        n for n in order
        if comp.placement_of(comp.operations[n]).name == role
    ]

    chunks: List[List[str]] = []
    steps: List[Step] = []
    chunk: List[str] = []
    pre: List[str] = []
    post: List[str] = []
    overflows: List[Tuple[int, int]] = []

    def flush_post() -> None:
        """Emit deferred ops, grouping consecutive Sends into one flush
        group (the async sender coalesces each group per receiver)."""
        group: List[str] = []
        for n in post:
            if comp.operations[n].kind == "Send":
                group.append(n)
                continue
            if group:
                steps.append(("sends", tuple(group)))
                group = []
            steps.append(("op", n))
        if group:
            steps.append(("sends", tuple(group)))

    def close(overflow: bool = False) -> None:
        nonlocal chunk, pre, post
        for n in pre:
            steps.append(("op", n))
        if chunk:
            chunks.append(chunk)
            steps.append(("seg", len(chunks) - 1))
            if overflow:
                overflows.append((len(chunks) - 1, len(post)))
        flush_post()
        chunk, pre, post = [], [], []

    for n in mine:
        kind = comp.operations[n].kind
        if kind in HOISTABLE_KINDS:
            pre.append(n)
        elif kind in DEFERRABLE_KINDS:
            if not chunk:
                close()  # nothing to defer behind: flush hoisted ops
                if kind == "Send":
                    steps.append(("sends", (n,)))
                else:
                    steps.append(("op", n))
            else:
                post.append(n)
                if len(post) >= max_deferred:
                    close(overflow=True)
        elif kind in HOST_STEP_KINDS:  # hard: Receive/Load/Sample
            close()
            steps.append(("op", n))
        else:
            chunk.append(n)
            if len(chunk) >= limit:
                close()
    close()

    # boundary-dataflow analysis over the partial role graph: values
    # produced outside any chunk (Receives, host-boundary steps) are
    # external env inputs
    _, in_names, _ = plan_segments(
        mine, {}, lambda n: comp.operations[n].inputs, limit,
        chunks=chunks,
    )
    # a segment's outputs are the values ANY later consumer needs —
    # later segments (their in_names) or host-boundary steps
    # (Send/Save/Output/... inputs); plan_segments only sees chunk
    # consumers, so fold the boundary consumers in here
    needed = set()
    for ins in in_names:
        needed.update(ins)
    for n in mine:
        op = comp.operations[n]
        if op.kind in HOST_STEP_KINDS:
            needed.update(op.inputs)
    segments = tuple(
        SegmentPlan(
            index=si,
            names=tuple(names),
            in_names=tuple(in_names[si]),
            out_names=tuple(sorted(x for x in names if x in needed)),
            validatable=(
                len(names) >= min_seg
                and not any(
                    comp.operations[n].kind in DYNAMIC_SHAPE_KINDS
                    for n in names
                )
            ),
        )
        for si, names in enumerate(chunks)
    )

    exec_step: Dict[str, int] = {}
    for idx, (kind, payload) in enumerate(steps):
        if kind == "seg":
            for n in segments[int(str(payload))].names:
                exec_step[n] = idx
        elif kind == "sends":
            for n in payload:
                exec_step[str(n)] = idx
        else:
            exec_step[str(payload)] = idx

    return RoleSchedule(
        role=role,
        steps=tuple(steps),
        segments=segments,
        recv_names=tuple(
            n for n in mine if comp.operations[n].kind == "Receive"
        ),
        overflows=tuple(overflows),
        exec_step=exec_step,
    )


_reconstruct_cache: "weakref.WeakKeyDictionary[Computation, Dict[Tuple[int, int, int], Dict[str, RoleSchedule]]]" = (
    weakref.WeakKeyDictionary()
)


def reconstruct_schedules(
    comp: Computation,
    roles: Optional[Sequence[str]] = None,
    limit: Optional[int] = None,
    min_seg: Optional[int] = None,
    max_deferred: int = MAX_DEFERRED,
) -> Dict[str, RoleSchedule]:
    """Every role's reconstructed schedule over ONE shared global
    linearization (the cross-role wait graph is only meaningful when
    all schedules agree on the order, exactly as the workers do).

    All-role reconstructions are memoized weak-keyed on the computation
    (per resolved knob values): one ``analyze()`` run asks for the
    schedules from both the schedule and cost analyses, the worker plan
    gate asks again per session, and the walk is O(ops) pure Python —
    pay it once per graph."""
    resolved_limit = _segment_limit() if limit is None else limit
    if roles is not None:
        resolved = worker_min_seg() if min_seg is None else min_seg
        order = comp.toposort_names()
        return {
            role: build_role_schedule(
                comp, role, order=order, limit=resolved_limit,
                min_seg=resolved, max_deferred=max_deferred,
            )
            for role in roles
        }
    if min_seg is None:
        # default resolution is autotune-aware, TWO-PASS: build at the
        # env floor, decide from the segment histogram, rebuild only if
        # the floor lifts.  Resolving here (not in the worker) keeps the
        # MSA5xx analyzer, the MSA6xx cost model, fabric and prancer on
        # the SAME schedule the worker runs — predictions cannot drift.
        # Both passes hit the explicit-min_seg memo below.
        base = reconstruct_schedules(
            comp, limit=limit, min_seg=worker_min_seg(),
            max_deferred=max_deferred,
        )
        decision = worker_min_seg_decision(comp, base)
        if decision.choice == worker_min_seg():
            return base
        return reconstruct_schedules(
            comp, limit=limit, min_seg=decision.choice,
            max_deferred=max_deferred,
        )
    resolved_min = min_seg
    knobs = (resolved_limit, resolved_min, max_deferred)
    per_comp = _reconstruct_cache.get(comp)
    if per_comp is not None and knobs in per_comp:
        return per_comp[knobs]
    order = comp.toposort_names()
    schedules = {
        role: build_role_schedule(
            comp, role, order=order, limit=resolved_limit,
            min_seg=resolved_min, max_deferred=max_deferred,
        )
        for role in sorted({
            comp.placement_of(op).name
            for op in comp.operations.values()
        })
    }
    if per_comp is None:
        per_comp = _reconstruct_cache[comp] = {}
    per_comp[knobs] = schedules
    return schedules


def worker_min_seg_decision(comp: Computation, base=None):
    """The autotuned worker eager-floor decision for ``comp`` (a
    :class:`~moose_tpu.compilation.autotune.Decision`): env override >
    segment-histogram heuristic > default.  ``base`` may carry the
    env-floor schedules to decide from (avoids a rebuild); without it
    they come from the memoized reconstruction.  Deterministic given
    (computation, env) — every process resolves the same floor, so
    chaos seed replays stay bit-identical."""
    from .. import autotune

    if base is None:
        base = reconstruct_schedules(comp, min_seg=worker_min_seg())
    sizes = [
        len(seg.names)
        for sched in base.values()
        for seg in sched.segments
    ]
    return autotune.worker_min_seg_for(sizes)


def _analyzable(comp: Computation) -> bool:
    """Plans exist only for lowered, networked, host-only graphs; on
    anything else (single-role, pre-networking, composite placements)
    the schedule analysis is a documented no-op."""
    if not all(
        isinstance(plc, HostPlacement) for plc in comp.placements.values()
    ):
        return False
    return any(
        op.kind in ("Send", "Receive")
        for op in comp.operations.values()
    )


def analyze_schedule(comp: Computation) -> List[Diagnostic]:
    """MSA5xx entry point registered with :func:`analysis.analyze`."""
    if not _analyzable(comp):
        return []
    try:
        schedules = reconstruct_schedules(comp)
    except ValueError as e:
        # toposort rejected the graph (dataflow/rendezvous cycle):
        # there is no linearization to schedule, which IS the deadlock
        return [Diagnostic(
            "MSA501", Severity.ERROR,
            f"no consistent linearization exists to schedule: {e}",
        )]
    return analyze_schedules(comp, schedules)


def plan_errors(comp: Computation) -> List[Diagnostic]:
    """Error-severity schedule findings only — the worker-side
    build-time gate (``worker_plan.get_plan`` rejects plans on these
    and falls back to the legacy eager scheduler)."""
    return [
        d for d in analyze_schedule(comp)
        if d.severity >= Severity.ERROR
    ]


# ---------------------------------------------------------------------------
# the rules
# ---------------------------------------------------------------------------


def analyze_schedules(
    comp: Computation,
    schedules: Dict[str, RoleSchedule],
) -> List[Diagnostic]:
    """Run every MSA5xx rule over explicit schedules.  Public so tests
    (and future planners) can check hand-built plans that the
    by-construction-safe reconstruction could never produce."""
    diagnostics: List[Diagnostic] = []
    diagnostics.extend(_check_wait_graph(comp, schedules))
    diagnostics.extend(_check_overflows(comp, schedules))
    diagnostics.extend(_check_use_before_arrival(comp, schedules))
    diagnostics.extend(_check_boundary_straddle(comp, schedules))
    return diagnostics


def _check_wait_graph(
    comp: Computation,
    schedules: Dict[str, RoleSchedule],
) -> List[Diagnostic]:
    """MSA501: fixed point of the cross-role wait graph.

    Model: each role executes its step list strictly sequentially; only
    a Receive step blocks, and it completes when ONE payload of its
    rendezvous key has been flushed by a completed Send step and not
    already consumed by another Receive (single-delivery cell-store
    semantics: a session never refills a consumed key).  Send flushes —
    deferred, coalesced, or immediate — never block, so a step
    completes as soon as its role predecessor and (for receives) its
    payload are available.  Any step the fixed point cannot complete is
    a would-hang, reported with the blocking chain."""
    ops = comp.operations
    # rendezvous key -> send op names / receive (role, step, op name)
    sends_of: Dict[str, List[str]] = {}
    recvs_of: Dict[str, List[Tuple[str, int, str]]] = {}
    send_role_step: Dict[str, Tuple[str, int]] = {}
    for role, sched in schedules.items():
        for name in sched.exec_step:
            op = ops[name]
            key = op.attributes.get("rendezvous_key")
            if not isinstance(key, str):
                continue  # malformed attributes are MSA203's domain
            if op.kind == "Send":
                sends_of.setdefault(key, []).append(name)
                send_role_step[name] = (role, sched.exec_step[name])
            elif op.kind == "Receive":
                recvs_of.setdefault(key, []).append(
                    (role, sched.exec_step[name], name)
                )

    # single delivery: the first-scheduled receive of a key is the one
    # the payload can serve; later receives of the same key are
    # unsatisfiable by construction (the cell store drops duplicate
    # deliveries of consumed keys)
    serviceable: Dict[str, Tuple[str, int, str]] = {}
    oversubscribed: List[Tuple[str, int, str, str]] = []
    for key, recvs in recvs_of.items():
        ranked = sorted(recvs, key=lambda r: (r[1], r[0]))
        serviceable[key] = ranked[0]
        for role, step, name in ranked[1:]:
            oversubscribed.append((role, step, name, key))

    pointer = {role: 0 for role in schedules}
    done_sends: Set[str] = set()

    def _recv_ready(role: str, name: str) -> bool:
        key = ops[name].attributes.get("rendezvous_key")
        if not isinstance(key, str):
            return True  # not modellable here; MSA203 reports it
        if serviceable.get(key, (None,))[0] != role or \
                serviceable[key][2] != name:
            return False  # oversubscribed: payload serves another wait
        return any(s in done_sends for s in sends_of.get(key, ()))

    progressed = True
    while progressed:
        progressed = False
        for role, sched in schedules.items():
            while pointer[role] < len(sched.steps):
                kind, payload = sched.steps[pointer[role]]
                if (
                    kind == "op"
                    and ops[str(payload)].kind == "Receive"
                    and not _recv_ready(role, str(payload))
                ):
                    break
                if kind == "sends":
                    done_sends.update(str(n) for n in payload)
                elif kind == "op" and ops[str(payload)].kind == "Send":
                    done_sends.add(str(payload))
                pointer[role] += 1
                progressed = True

    stuck = {
        role: sched.steps[pointer[role]]
        for role, sched in schedules.items()
        if pointer[role] < len(sched.steps)
    }
    if not stuck and not oversubscribed:
        return []

    diagnostics: List[Diagnostic] = []
    for role, step, name, key in sorted(oversubscribed):
        winner = serviceable[key]
        diagnostics.append(Diagnostic(
            "MSA501", Severity.ERROR,
            f"rendezvous key {key!r} is oversubscribed: its single "
            f"payload serves {winner[2]!r} on {winner[0]!r}, so this "
            f"wait can never be satisfied (the cell store drops "
            f"duplicate deliveries of consumed keys)",
            op=name, placement=role,
        ))

    already = {name for _, _, name, _ in oversubscribed}
    seen_chains: Set[Any] = set()
    for role in sorted(stuck):
        kind, payload = stuck[role]
        if kind != "op" or ops[str(payload)].kind != "Receive":
            continue  # blocked transitively behind this role's receive
        if str(payload) in already:
            continue  # the oversubscription diagnostic already says why
        chain = _blocking_chain(
            comp, schedules, pointer, sends_of, role, str(payload)
        )
        signature = frozenset(chain)
        if signature in seen_chains:
            continue
        seen_chains.add(signature)
        key = ops[str(payload)].attributes.get("rendezvous_key")
        if not sends_of.get(key or ""):
            detail = f"no Send in any role's schedule flushes key {key!r}"
        else:
            detail = "blocking chain " + " <- ".join(
                f"{r}:{n}" for r, n in chain
            )
        diagnostics.append(Diagnostic(
            "MSA501", Severity.ERROR,
            f"the sequential orchestrator would hang: receive "
            f"{payload!r} (key {key!r}) can never be served; {detail}",
            op=str(payload), placement=role,
        ))
    return diagnostics


def _blocking_chain(
    comp: Computation,
    schedules: Dict[str, RoleSchedule],
    pointer: Dict[str, int],
    sends_of: Dict[str, List[str]],
    role: str,
    recv_name: str,
) -> List[Tuple[str, str]]:
    """Readable who-waits-on-whom path from one stuck receive: follow
    its key to a blocked sender role, then to THAT role's stuck
    receive, until a node repeats."""
    chain: List[Tuple[str, str]] = []
    seen: Set[Tuple[str, str]] = set()
    current: Optional[Tuple[str, str]] = (role, recv_name)
    while current is not None and current not in seen:
        seen.add(current)
        chain.append(current)
        r, name = current
        key = comp.operations[name].attributes.get("rendezvous_key")
        current = None
        for send in sends_of.get(key or "", ()):
            for peer, sched in schedules.items():
                step = sched.exec_step.get(send)
                if step is None or pointer[peer] >= len(sched.steps):
                    continue
                if step >= pointer[peer]:
                    stuck_kind, stuck_payload = sched.steps[pointer[peer]]
                    if stuck_kind == "op" and comp.operations[
                        str(stuck_payload)
                    ].kind == "Receive":
                        current = (peer, str(stuck_payload))
                    break
            if current is not None:
                break
    return chain


def _check_overflows(
    comp: Computation,
    schedules: Dict[str, RoleSchedule],
) -> List[Diagnostic]:
    """MSA502: the deferred-send cap forced an early segment split."""
    diagnostics: List[Diagnostic] = []
    for role in sorted(schedules):
        for seg_index, count in schedules[role].overflows:
            seg = schedules[role].segments[seg_index]
            diagnostics.append(Diagnostic(
                "MSA502", Severity.WARNING,
                f"deferred-send overflow: {count} value-consuming host "
                f"ops queued behind segment {seg_index} "
                f"({len(seg.names)} ops) hit the cap of {MAX_DEFERRED} "
                f"and forced an early segment split",
                op=seg.names[-1] if seg.names else None,
                placement=role,
            ))
    return diagnostics


def _check_use_before_arrival(
    comp: Computation,
    schedules: Dict[str, RoleSchedule],
) -> List[Diagnostic]:
    """MSA503: a step consumes a value whose producing step comes later
    in the same role's schedule (for Receives: the payload arrives in a
    later step than its first use)."""
    diagnostics: List[Diagnostic] = []
    for role in sorted(schedules):
        sched = schedules[role]
        for idx, (kind, payload) in enumerate(sched.steps):
            if kind == "seg":
                consumer = f"segment {payload}"
                inputs = sched.segments[int(str(payload))].in_names
                anchor = sched.segments[int(str(payload))].names[0]
            elif kind == "sends":
                consumer = f"send group {list(payload)}"
                inputs = tuple(
                    i for n in payload
                    for i in comp.operations[str(n)].inputs
                )
                anchor = str(payload[0])
            else:
                consumer = f"op {payload!r}"
                inputs = tuple(comp.operations[str(payload)].inputs)
                anchor = str(payload)
            for i in inputs:
                produced_at = sched.exec_step.get(i)
                if produced_at is None or produced_at <= idx:
                    continue
                producer_kind = comp.operations[i].kind
                what = (
                    "its Receive wait"
                    if producer_kind == "Receive"
                    else f"its producing {producer_kind} step"
                )
                diagnostics.append(Diagnostic(
                    "MSA503", Severity.ERROR,
                    f"{consumer} at step {idx} consumes {i!r} but "
                    f"{what} is scheduled later (step {produced_at}); "
                    f"the orchestrator would read an absent value",
                    op=anchor, placement=role,
                ))
    return diagnostics


def _check_boundary_straddle(
    comp: Computation,
    schedules: Dict[str, RoleSchedule],
) -> List[Diagnostic]:
    """MSA504: a jit-candidate segment consumes values produced by
    always-eager sliver segments — every such input crosses the
    host/device boundary per evaluation."""
    diagnostics: List[Diagnostic] = []
    for role in sorted(schedules):
        sched = schedules[role]
        produced_in: Dict[str, SegmentPlan] = {}
        for seg in sched.segments:
            for n in seg.names:
                produced_in[n] = seg
        for seg in sched.segments:
            if not seg.validatable:
                continue
            eager_inputs = [
                i for i in seg.in_names
                if i in produced_in and not produced_in[i].validatable
            ]
            if eager_inputs:
                diagnostics.append(Diagnostic(
                    "MSA504", Severity.INFO,
                    f"segment {seg.index} ({len(seg.names)} ops) is a "
                    f"jit candidate but {len(eager_inputs)} of its "
                    f"inputs come from always-eager sliver segments "
                    f"(first: {eager_inputs[0]!r}); each crosses the "
                    f"host/device boundary every evaluation",
                    op=seg.names[0], placement=role,
                ))
    return diagnostics


def analyze_fabric_schedules(
    comp: Computation,
    schedules: Dict[str, RoleSchedule],
    fabric_parties: FrozenSet[str],
) -> List[Diagnostic]:
    """MSA505: deadlock-freedom of the FABRIC-lowered schedule.

    When both endpoints of an edge are members of one
    :class:`~moose_tpu.distributed.fabric.FabricDomain`, the transfer is
    a collective permute on a shared device fabric instead of a buffered
    wire frame.  That is a stronger execution model than the one MSA501
    proves: collectives on one fabric edge retire in launch order, a
    coalesced flush group is ONE batched program (all payloads or
    none), and under the ``colocated_tee`` trust model both endpoint
    parties must issue matching collectives in the same order.  The
    fabric therefore refuses any schedule it cannot prove under three
    rules, each reported as an ``MSA505`` error (the runtime falls back
    to the wire on rejection — fallback is graceful, entering an
    unprovable collective schedule is not):

    1. the MSA501 wait-graph fixed point must already hold (a schedule
       the wire would hang on is certainly not fabric-safe);
    2. no two intra-fabric Sends may share a rendezvous key — a second
       permute program racing into a consumed rendezvous cell is a
       silent payload loss, where the wire's duplicate frame is merely
       dropped;
    3. per fabric edge (sender party -> receiver party), the receiver's
       wait order must not invert the sender's flush order for any key
       pair — inverted collectives on one ordered channel are the
       classic issue-order deadlock.

    Public and pure over explicit ``schedules`` so tests can hand the
    rule schedules the by-construction-safe reconstruction could never
    produce (the plan-build-time gate in ``FabricNetworking.
    prepare_fabric`` calls this with the worker's reconstructed
    schedules)."""
    if not _analyzable(comp):
        return []
    fabric_parties = frozenset(fabric_parties)
    ops = comp.operations
    diagnostics: List[Diagnostic] = []

    def _receiver_of(name: str) -> Optional[str]:
        return ops[name].attributes.get("receiver")

    # rule 1: the wire fixed point, re-coded — the fabric gate runs at
    # plan-build time per session and must reject on its own authority
    for d in _check_wait_graph(comp, schedules):
        diagnostics.append(Diagnostic(
            "MSA505", Severity.ERROR,
            "fabric lowering refused: the underlying wait graph is "
            f"already unsatisfiable — {d.message}",
            op=d.op, placement=d.placement,
        ))

    # rule 2: duplicate intra-fabric sends on one rendezvous key
    fabric_sends: Dict[str, List[str]] = {}
    for role, sched in schedules.items():
        if role not in fabric_parties:
            continue
        for name in sched.exec_step:
            op = ops[name]
            if op.kind != "Send":
                continue
            key = op.attributes.get("rendezvous_key")
            receiver = _receiver_of(name)
            if isinstance(key, str) and receiver in fabric_parties:
                fabric_sends.setdefault(key, []).append(name)
    for key, names in sorted(fabric_sends.items()):
        if len(names) > 1:
            diagnostics.append(Diagnostic(
                "MSA505", Severity.ERROR,
                f"rendezvous key {key!r} has {len(names)} intra-fabric "
                f"Sends ({sorted(names)}); a second collective permute "
                "racing into a consumed rendezvous cell is a silent "
                "payload loss on the fabric",
                op=sorted(names)[1],
            ))

    # rule 3: per-edge launch-order consistency.  Flush order = the
    # order send steps complete in the sender's schedule ("sends"
    # groups flush in payload order); wait order = the receiver's
    # receive steps in step order.
    flush_order: Dict[Tuple[str, str], List[str]] = {}
    wait_order: Dict[Tuple[str, str], List[str]] = {}
    for role, sched in schedules.items():
        if role not in fabric_parties:
            continue
        for kind, payload in sched.steps:
            names: Sequence[str]
            if kind == "sends":
                names = [str(n) for n in payload]
            elif kind == "op" and ops[str(payload)].kind in (
                "Send", "Receive"
            ):
                names = [str(payload)]
            else:
                continue
            for name in names:
                op = ops[name]
                key = op.attributes.get("rendezvous_key")
                if not isinstance(key, str):
                    continue
                if op.kind == "Send":
                    receiver = _receiver_of(name)
                    if receiver in fabric_parties:
                        flush_order.setdefault(
                            (role, str(receiver)), []
                        ).append(key)
                else:
                    sender = op.attributes.get("sender")
                    if sender in fabric_parties:
                        wait_order.setdefault(
                            (str(sender), role), []
                        ).append(key)
    for edge in sorted(set(flush_order) & set(wait_order)):
        flushed = flush_order[edge]
        flush_pos = {k: i for i, k in enumerate(flushed)}
        waited = [k for k in wait_order[edge] if k in flush_pos]
        for a, b in zip(waited, waited[1:]):
            if flush_pos[a] > flush_pos[b]:
                diagnostics.append(Diagnostic(
                    "MSA505", Severity.ERROR,
                    f"fabric edge {edge[0]}->{edge[1]}: receiver waits "
                    f"key {a!r} before {b!r} but the sender launches "
                    f"their permutes in the opposite order; inverted "
                    "collectives on one ordered channel are an "
                    "issue-order deadlock",
                    placement=edge[1],
                ))
                break  # one inversion per edge is enough to reject
    return diagnostics


RULES = {
    "MSA501": "unsatisfiable wait in the segment-level plan (sequential "
              "orchestrator would hang: wait cycle, blocked or missing "
              "sender, or oversubscribed rendezvous key)",
    "MSA502": "deferred-send overflow: >MAX_DEFERRED host ops behind one "
              "segment forced an early split",
    "MSA503": "value consumed at a step before the step that produces "
              "it (receive arrives later than first use)",
    "MSA504": "jit-candidate segment consumes always-eager sliver-"
              "segment outputs (host/device crossing per input)",
    "MSA505": "fabric-lowered schedule not provably deadlock-free "
              "(unsatisfiable wait graph, duplicate intra-fabric "
              "rendezvous key, or inverted per-edge collective launch "
              "order); the fabric transport falls back to the wire",
}
